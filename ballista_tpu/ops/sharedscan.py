"""Shared-scan multi-query execution (ISSUE 13): one upload, one launch,
N queries.

Concurrent DISTINCT queries routinely scan the SAME tables (the multi-
tenant bench's dashboard mix), yet each solo fused-aggregate stage pays its
own parquet decode, its own h2d upload, and its own device program — the
dominant per-query cost at serving scale. The scheduler groups compatible
co-pending stages into one batched task (scheduler/state.py
form_shared_batch); this module is the executor half: it resolves each
member's fused stage (ops/kernels.py resolve_stage), verifies REAL
compatibility, reads the UNION of the members' pruned scan schemas once,
and runs the group as ONE device launch over ONE resident upload — every
member's epilogue (filters + aggregate emission) traced into a single
combined program over the shared scanned tensors. Each member's readback
decodes through its own stage's machinery, so the spliced table is EXACTLY
what that member's solo stage.run would have produced — bit identity to
solo execution is the invariant at every decision point, and any doubt
(string-coded device columns, cardinality past the unrolled ceiling,
un-lowerable columns, budget overruns, plain exceptions) degrades the
member — or the whole group — to solo execution, never to a different
answer.

Why the union read is solo-identical: a member's solo scan reads its
pruned column list from the same parquet files, combine_chunks()es, and
slices into ctx.batch_size row batches — row boundaries depend only on the
row count and the batch size, never on which columns ride along. Selecting
the member's schema columns by name out of the union batch therefore
yields byte-identical member batches, and every shared column is lowered
by the same column_to_numpy/_lower_planes the member's solo prepare uses.

Two launch shapes, one invariant: members whose packed output rows are all
ORDER-INSENSITIVE (int sums, counts, min/max, float-bits min/max) fuse into
the combined one-launch program — integer/lattice folds are exact under any
reassociation, so the combined graph cannot change them. Members with
float-arithmetic sums (f32 sum/avg) run their OWN solo-compiled step over
the same shared upload: XLA may reassociate an f32 reduction differently
inside a different program context, and only the member's own executable on
identical inputs reproduces its solo bits. Cold compositions also take the
own-step path while the combined program warms in the background, so a
serving wave never stalls behind a multi-second trace.

Compatibility (the executor is authoritative; the scheduler's signature is
a cheap heuristic):
- plain FusedAggregateStage (no top-k epilogue, no fact-agg derivations)
  over a Parquet scan — stable content identity, shared decodable read;
- identical (files, mtimes, chunk cover, batch size, HBM budget): members
  must read byte-identical row streams;
- no dictionary-coded (string) device columns: each stage grows its own
  string dictionary, so shared int-code tiles would mean different strings
  to different members (string GROUP BY keys stay host-side and batch
  fine);
- every batch's group count within the unrolled path's MAX_GROUPS ceiling
  (the sorted layout is per-member by construction — its tiles ARE the
  member's group order).

The combined program is AOT-cached like any stage step (ops/aotcache.py),
keyed on the member set's stable stage identities, so repeated batch
compositions skip the trace/compile.
"""

from __future__ import annotations

import hashlib
import logging
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np
import pyarrow as pa

from ballista_tpu.ops.runtime import UnsupportedOnDevice
from ballista_tpu.utils.locks import make_lock

log = logging.getLogger("ballista.sharedscan")

# order for widening int narrow-choice priors across members
_INT_ORDER = {"int8": 0, "int16": 1, "int32": 2}


class SharedResults:
    """Per-batched-task registry of precomputed member tables, keyed on the
    aggregate node OBJECT inside the member's (deserialized, soon to be
    executed) plan tree plus the partition — so the splice in
    kernels.hash_aggregate can only ever hit the exact node this group ran.
    Node references are pinned for the registry's lifetime, so ids are
    never recycled. take() consumes the entry."""

    def __init__(self) -> None:
        self._tables: Dict[Tuple[int, int], pa.Table] = {}
        self._pins: List[object] = []

    def put(self, node, partition: int, table: pa.Table) -> None:
        self._pins.append(node)
        self._tables[(id(node), partition)] = table

    def take(self, node, partition: int) -> Optional[pa.Table]:
        return self._tables.pop((id(node), partition), None)

    def drop(self, node, partition: int) -> None:
        self._tables.pop((id(node), partition), None)

    def __len__(self) -> int:
        return len(self._tables)


class _Member:
    """One batch member: its plan's aggregate node, resolved fused stage,
    stable identity, partition, task context, and scan-compatibility key.

    `exact` marks stages whose every packed output row is order-insensitive
    (int sums, counts, min/max, float-bits min/max): ONLY those may fuse
    into the combined one-launch program — XLA may reassociate an f32 SUM
    differently inside a different program context, so a float-arithmetic
    sum/avg is bit-identical to solo only under the member's OWN compiled
    step (which still runs over the shared upload)."""

    __slots__ = ("node", "stage", "stable", "partition", "ctx", "group_key",
                 "cover", "exact")

    def __init__(self, node, stage, stable, partition, ctx, group_key,
                 cover) -> None:
        self.node = node
        self.stage = stage
        self.stable = stable
        self.partition = partition
        self.ctx = ctx
        self.group_key = group_key
        self.cover = cover
        self.exact = not any(
            (not ix) and a.fn in ("sum", "avg")
            for a, ix in zip(stage.aggs, stage.int_exact)
        )


def _record(event: str, n: int = 1) -> None:
    from ballista_tpu.ops.runtime import record_shared_scan

    record_shared_scan(event, n)


def _find_aggregate(plan):
    """The batchable aggregate node under a stage plan: the FIRST
    HashAggregateExec down the single-child operator spine (stage plans put
    sort/projection/coalesce epilogues ABOVE the aggregate — they consume
    its output per member and never affect what the aggregate computes).
    None when the spine forks or ends before an aggregate, or the mode is
    FINAL (final aggregates read shuffles, not scans)."""
    from ballista_tpu.physical.aggregate import AggregateMode, HashAggregateExec

    node = plan
    while not isinstance(node, HashAggregateExec):
        kids = node.children()
        if len(kids) != 1:
            return None
        node = kids[0]
    if node.mode in (AggregateMode.PARTIAL, AggregateMode.SINGLE):
        return node
    return None


def _member_key_map(stage) -> Dict[object, tuple]:
    """Member cols-dict key -> shared column key. The member's compiled
    cores read columns by PRUNED-schema index (plus the float-bits plane
    keys derived from it); the shared staging is keyed by column NAME so
    members with different pruned schemas share one lowered array."""
    from ballista_tpu.ops.stage import plane_keys

    schema = stage.scan_schema
    out: Dict[object, tuple] = {}
    for idx in stage.compiler.used_columns:
        out[idx] = ("col", schema.field(idx).name)
    for idx, width in stage._bit_planes.items():
        hk, lk = plane_keys(idx)
        out[hk] = ("hi", schema.field(idx).name)
        if width == "f64":
            out[lk] = ("lo", schema.field(idx).name)
    return out


def _member_info(plan, partition: int, ctx) -> Optional[_Member]:
    """Resolve one member's stage and compatibility facts, or None when the
    member cannot ride a shared-scan group (it then executes solo through
    the untouched normal path)."""
    import os

    from ballista_tpu.ops import kernels
    from ballista_tpu.ops.stage import FusedAggregateStage
    from ballista_tpu.physical.scan import ParquetScanExec

    if ctx.backend != "tpu":
        return None
    node = _find_aggregate(plan)
    if node is None:
        return None
    try:
        stage, _key, stable, _units = kernels.resolve_stage(node, ctx)
    except Exception:
        log.debug("shared-scan stage resolution failed", exc_info=True)
        return None
    # plain fused stages only: fact-agg subclasses derive columns and run
    # epilogues this group launcher does not model, and a live top-k spec
    # routes the stage through the sorted layout
    if stage is False or type(stage) is not FusedAggregateStage:
        return None
    if stage.topk is not None or stage.derive_columns:
        return None
    scan = stage.scan
    if not isinstance(scan, ParquetScanExec):
        return None
    if ctx.config.device_cache() and stage._device_cache.get(partition) is not None:
        # the member's columns are already RESIDENT: its solo run skips the
        # scan and the upload entirely, which beats re-scanning it into a
        # batch — shared-scan exists to amortize COLD scans across queries,
        # not to undo the residency tier
        return None
    # persisted-layout-warm members are shared-scan-ELIGIBLE since batch
    # size folded into the stage/persist key (ISSUE 15 satellite, PR 13
    # residue): a warm layout entry is always at THIS dispatch's batch
    # granularity, so the shared batch stream is row-identical to the
    # member's layout-cache solo stream and f32 partials fold identically.
    # (The group key below already carries ctx.batch_size, so members of
    # different granularities never group.)
    if stage.dicts.dicts:
        return None  # string-coded device columns: per-stage dictionaries
    schema = stage.scan_schema
    for idx in stage.compiler.used_columns:
        t = schema.field(idx).type
        if pa.types.is_string(t) or pa.types.is_large_string(t):
            return None
    files = tuple(getattr(scan.source, "files", ()) or ())
    if not files:
        return None
    try:
        mtimes = tuple(str(os.path.getmtime(f)) for f in files)
    except OSError:
        return None
    total = scan.output_partitioning().partition_count()
    stride = stage.scan_stride
    # the chunk cover: exactly which scan partitions this member's task
    # reads (ops/stage.py _scan_batches) — members must match it so the
    # shared batch stream is row-identical to each member's solo stream
    cover = tuple(range(partition, total, stride)) if stride else (partition,)
    if any(p >= len(files) for p in cover):
        return None  # out-of-range partition: let the solo path surface it
    group_key = (
        files, mtimes, cover, ctx.batch_size, ctx.config.tpu_hbm_budget(),
    )
    return _Member(node, stage, stable, partition, ctx, group_key, cover)


def precompute(items, max_batch: int = 8) -> SharedResults:
    """Group compatible members and run each group as one shared-scan
    launch. `items` are (stage plan, partition, TaskContext) triples of a
    batched task's members. Returns the per-member precomputed tables;
    members absent from the result simply execute solo — this function
    NEVER fails a member (exceptions degrade the group and are logged)."""
    res = SharedResults()
    if len(items) < 2:
        return res
    groups: Dict[tuple, List[_Member]] = {}
    for plan, partition, ctx in items:
        m = _member_info(plan, partition, ctx)
        if m is None:
            _record("member_ineligible")
            continue
        groups.setdefault(m.group_key, []).append(m)
    for g in groups.values():
        # canonical member order: the combined program is cached (and AOT-
        # persisted) per ordered member-set composition, and dispatch order
        # varies wave to wave — sorting by stable identity makes repeated
        # compositions hit the same compiled program
        g.sort(key=lambda m: m.stable)
        for lo in range(0, len(g), max(2, max_batch)):
            chunk = g[lo:lo + max(2, max_batch)]
            if len(chunk) < 2:
                continue
            try:
                _run_group(chunk, res)
            except Exception:
                log.warning(
                    "shared-scan group degraded to solo execution",
                    exc_info=True,
                )
                _record("batch_degraded")
                for m in chunk:
                    res.drop(m.node, m.partition)
    return res


def _codes_fingerprint(stage) -> Optional[tuple]:
    """Sharing key for host-side group ranking: members whose group keys
    are the same plain scan COLUMNS rank identical codes from the same
    batch (dense ranking is a pure function of the evaluated key arrays),
    so one member's _group_codes output serves them all. Computed group
    keys return None — those members rank their own."""
    from ballista_tpu.physical import expr as px

    names = []
    for e, _name in stage.group_exprs:
        if not isinstance(e, px.ColumnExpr):
            return None
        names.append(stage.scan_schema.field(e.index).name)
    return tuple(names)


def _merge_prior(a, b):
    """Widest of two narrow-choice priors (never downgrade a member's
    compiled width; the choice only affects residency dtype, never values)."""
    if a is None:
        return b
    if b is None:
        return a
    if a in _INT_ORDER and b in _INT_ORDER:
        return a if _INT_ORDER[a] >= _INT_ORDER[b] else b
    if "wide" in (a, b):
        return "wide"
    return a


def _scan_union_batches(members: List[_Member]):
    """Read the members' shared chunk cover ONCE with the UNION of their
    pruned scan schemas (strings as dictionary columns, exactly like
    FusedAggregateStage._scan_batches' parquet fast path), yielding
    ctx.batch_size row batches. Row boundaries depend only on row count
    and batch size, so each member's name-selected view of every batch is
    identical to its solo scan stream."""
    import pyarrow.parquet as pq

    names: List[str] = []
    strings: List[str] = []
    for m in members:
        for f in m.stage.scan_schema:
            if f.name not in names:
                names.append(f.name)
                if pa.types.is_string(f.type) or pa.types.is_large_string(f.type):
                    strings.append(f.name)
    files = members[0].stage.scan.source.files
    batch_size = members[0].ctx.batch_size
    for p in members[0].cover:
        table = pq.read_table(
            files[p], columns=names, read_dictionary=strings
        ).combine_chunks()
        yield from table.to_batches(max_chunksize=batch_size)


def _run_group(members: List[_Member], res: SharedResults) -> None:
    """Shared prepare + combined launch for one compatible group. Stage
    state (narrow choices, compiled cores) is touched under every member
    stage's prepare lock, acquired in id order (two identical queries can
    resolve to the SAME stage object — locks dedupe by identity)."""
    locks = {}
    for m in members:
        locks[id(m.stage._prepare_lock)] = m.stage._prepare_lock
    ordered = [locks[k] for k in sorted(locks)]
    for lk in ordered:
        lk.acquire()
    try:
        _run_group_locked(members, res)
    finally:
        for lk in reversed(ordered):
            lk.release()


def _run_group_locked(members: List[_Member], res: SharedResults) -> None:
    import jax.numpy as jnp

    from ballista_tpu.ops.runtime import (
        bucket_rows,
        column_to_numpy,
        make_headroom,
        narrow_column,
        pad_to,
        readback,
    )
    from ballista_tpu.ops.stage import MAX_GROUPS, FusedAggregateStage

    budget = min(m.ctx.config.tpu_hbm_budget() for m in members)
    live = list(members)

    def degrade(m: _Member) -> None:
        if m in live:
            live.remove(m)
            _record("member_degraded")

    # negotiated narrow choices for the SHARED staged columns (keyed by
    # shared column key): start from the widest of the members' existing
    # priors (a member that already compiled a width must never see a
    # narrower batch), then carry each batch's choice forward exactly like
    # a solo prepare does
    keymaps = {id(m): _member_key_map(m.stage) for m in members}
    shared_choice: Dict[tuple, object] = {}
    for m in members:
        for mkey, skey in keymaps[id(m)].items():
            shared_choice[skey] = _merge_prior(
                shared_choice.get(skey), m.stage._narrow_choice.get(mkey)
            )
    for m in list(members):
        if not m.exact and any(
            shared_choice.get(skey) != m.stage._narrow_choice.get(mkey)
            for mkey, skey in keymaps[id(m)].items()
        ):
            # an inexact member's own step must compile the EXACT dtype
            # graph its solo run would (f32 sums are reassociation-
            # sensitive): any starting prior that differs from the
            # member's OWN — another member's wider history included,
            # even against a fresh None — breaks that guarantee, so the
            # member runs solo. All-fresh and all-warm-equal groups (the
            # common cases) pass untouched.
            members.remove(m)
            live.remove(m)
            _record("member_degraded")
    if len(live) < 2:
        _record("batch_degraded")
        return

    batches: List[dict] = []
    total_bytes = 0
    for batch in _scan_union_batches(members):
        n = batch.num_rows
        if not n:
            continue
        bucket = bucket_rows(n)
        # per-member group ranking over the member's name-selected VIEW of
        # the shared batch — exactly the member's own host-side work, so
        # codes/keys are solo-identical. Members whose group keys are the
        # same plain columns share ONE ranking (identical by construction:
        # the dense rank is a pure function of the evaluated key arrays).
        per: Dict[int, tuple] = {}  # id(member) -> (codes, key_values, n_groups)
        codes_cache: Dict[tuple, tuple] = {}
        for m in list(live):
            try:
                fp = _codes_fingerprint(m.stage)
                if fp is not None and fp in codes_cache:
                    codes, key_values, n_groups = codes_cache[fp]
                else:
                    view = batch.select(m.stage.scan_schema.names)
                    codes, key_values, n_groups = m.stage._group_codes(view)
                    if fp is not None:
                        codes_cache[fp] = (codes, key_values, n_groups)
            except UnsupportedOnDevice:
                degrade(m)
                continue
            if n_groups > MAX_GROUPS:
                # solo would retry on the sorted layout; that path is
                # per-member by construction — hand the member back
                degrade(m)
                continue
            if n_groups:
                per[id(m)] = (codes, key_values, n_groups)
        if len(live) < 2:
            break
        # lower the UNION of live members' device columns ONCE, keyed by
        # shared column key (name-based: members prune differently)
        needed: Dict[tuple, tuple] = {}  # skey -> ("col", name, dtype) | ("plane", name, width)
        for m in live:
            schema = m.stage.scan_schema
            for idx, dtype in m.stage.compiler.used_columns.items():
                name = schema.field(idx).name
                needed[("col", name)] = ("col", name, dtype)
            for idx, width in m.stage._bit_planes.items():
                name = schema.field(idx).name
                needed[("plane", name)] = ("plane", name, width)
        shared_np: Dict[tuple, np.ndarray] = {}
        bad: set = set()  # shared keys that failed to lower
        for spec in needed.values():
            kind, name = spec[0], spec[1]
            try:
                if kind == "col":
                    shared_np[("col", name)] = column_to_numpy(
                        batch.column(name), spec[2], None
                    )
                else:
                    # plane_keys(0) == (-2, -3): lower once, remap by name
                    d = FusedAggregateStage._lower_planes(
                        batch.column(name), 0, spec[2]
                    )
                    shared_np[("hi", name)] = d[-2]
                    if spec[2] == "f64":
                        shared_np[("lo", name)] = d[-3]
            except UnsupportedOnDevice:
                bad.add(("col", name) if kind == "col" else ("hi", name))
                bad.add(("lo", name))
        if bad:
            # a column that cannot lower declines the members reading it —
            # solo they would decline to the host path on the same batch
            for m in list(live):
                if any(skey in bad for skey in keymaps[id(m)].values()):
                    degrade(m)
        for m in list(live):
            if id(m) not in per:
                continue
            try:
                npview = {
                    mkey: shared_np[skey]
                    for mkey, skey in keymaps[id(m)].items()
                    if skey in shared_np
                }
                m.stage._check_int_ranges(npview, n)
            except UnsupportedOnDevice:
                degrade(m)
        if len(live) < 2:
            break
        # narrow + pad the shared tiles once; keep only columns live
        # members still read
        live_keys: set = set()
        for m in live:
            live_keys |= set(keymaps[id(m)].values())
        staged: Dict[tuple, tuple] = {}
        for skey in sorted(k for k in shared_np if k in live_keys):
            npcol = shared_np[skey]
            fill = False if npcol.dtype == np.bool_ else 0
            narrow, lut, choice = narrow_column(npcol, shared_choice.get(skey))
            shared_choice[skey] = choice
            padded = pad_to(narrow, bucket, fill)
            staged[skey] = (padded, lut, choice)
            total_bytes += padded.nbytes + (0 if lut is None else lut.nbytes)
        row_valid = np.zeros(bucket, dtype=np.bool_)
        row_valid[:n] = True
        recs = []
        for m in live:
            hit = per.get(id(m))
            if hit is None:
                continue  # no groups in this batch (solo skips it too)
            codes, key_values, n_groups = hit
            seg_bucket = bucket_rows(n_groups, 16) + 1  # +1 dump slot
            codes_pad = pad_to(codes.astype(np.int16), bucket, 0)
            total_bytes += codes_pad.nbytes
            recs.append((m, codes_pad, seg_bucket, n_groups, key_values))
        total_bytes += bucket  # shared bool row_valid
        if total_bytes > budget:
            raise UnsupportedOnDevice(
                f"shared-scan batches ({total_bytes >> 20} MiB) exceed the "
                "HBM budget"
            )
        batches.append(
            {"staged": staged, "row_valid": row_valid, "recs": recs}
        )
    if len(live) < 2:
        _record("batch_degraded")
        return
    _record("shared_groups")
    tables: Dict[int, List[pa.Table]] = {id(m): [] for m in live}
    # per-member aux is batch-independent: build + upload once per group
    # (the solo path builds it once per run too)
    aux_by_member = {
        id(m): tuple(jnp.asarray(a) for a in m.stage.compiler.build_aux())
        for m in live
    }
    for rec in batches:
        recs = [r for r in rec["recs"] if r[0] in live]
        if not recs:
            continue
        make_headroom(members[0].stage, total_bytes, budget)
        # ONE upload per shared column — through upload_array, so large
        # tiles keep the chunked double-buffered h2d tier (and its
        # cost-store h2d observations) exactly like the solo path; the
        # members' cols dicts alias the same device buffers under their
        # own pruned-schema keys
        from ballista_tpu.ops.runtime import upload_array

        dev_by_skey: Dict[tuple, object] = {}
        for skey, (padded, lut, _choice) in rec["staged"].items():
            dev = upload_array(padded)
            dev_by_skey[skey] = dev if lut is None else (dev, jnp.asarray(lut))
        rv = jnp.asarray(rec["row_valid"])
        seg_buckets = tuple(sb for _m, _cp, sb, _ng, _kv in recs)
        cols_list = tuple(
            {
                mkey: dev_by_skey[skey]
                for mkey, skey in keymaps[id(m)].items()
                if skey in dev_by_skey
            }
            for m, _cp, _sb, _ng, _kv in recs
        )
        auxs = tuple(
            aux_by_member[id(m)] for m, _cp, _sb, _ng, _kv in recs
        )
        codes_dev = tuple(
            jnp.asarray(cp) for _m, cp, _sb, _ng, _kv in recs
        )
        from ballista_tpu.ops.runtime import fetch_arrays, record_readback

        # split the wave: only EXACT members (order-insensitive packed
        # rows) may fuse into the combined one-launch program; inexact
        # members (f32 sums) run their OWN solo-compiled step over the
        # same shared upload — identical executable, identical inputs,
        # bit-identical result
        fuse_idx = [i for i, r in enumerate(recs) if r[0].exact]
        own_idx = [i for i, r in enumerate(recs) if not r[0].exact]
        blocks: List[Optional[np.ndarray]] = [None] * len(recs)
        combined_plan = None
        if len(fuse_idx) >= 2:
            stages_f = [recs[i][0].stage for i in fuse_idx]
            stables_f = [recs[i][0].stable for i in fuse_idx]
            seg_f = tuple(seg_buckets[i] for i in fuse_idx)
            args = (
                seg_f,
                tuple(cols_list[i] for i in fuse_idx),
                tuple(auxs[i] for i in fuse_idx),
                tuple(codes_dev[i] for i in fuse_idx),
                rv,
            )
            sig = (tuple(stables_f), seg_f, len(rec["row_valid"]))
            if _combined_ready(sig):
                combined_plan = (stages_f, stables_f, args)
            else:
                # tracing the combined program NOW would stall the wave
                # for seconds: warm it in the background and run this
                # wave's fusible members on their own steps too
                _warm_combined(sig, stages_f, stables_f, args)
                own_idx = own_idx + fuse_idx
                fuse_idx = []
        else:
            own_idx = own_idx + fuse_idx
            fuse_idx = []
        pending = [
            (
                i,
                recs[i][0].stage._step(
                    recs[i][2], cols_list[i], list(auxs[i]), codes_dev[i], rv
                ),
            )
            for i in sorted(own_idx)
        ]
        if combined_plan is not None:
            stages_f, stables_f, args = combined_plan
            step = _combined_step(stages_f, stables_f)
            flat = readback(step(*args))
            with _combined_lock:
                # a successful combined launch marks its signature warm —
                # under SYNC_COMPILE (tests / bench warm rounds) this is
                # what primes the ready set for later async waves
                _combined_warm.add(sig)
            _record("device_launches")
            _record("launches_saved", len(fuse_idx) - 1)
            off = 0
            for i in fuse_idx:
                m, _cp, seg_bucket, _ng, _kv = recs[i]
                r_packed = sum(2 if b else 1 for b in m.stage._int_rows)
                blocks[i] = flat[off:off + r_packed * seg_bucket].reshape(
                    r_packed, seg_bucket
                )
                off += r_packed * seg_bucket
        if pending:
            fetched = fetch_arrays([dev for _i, dev in pending])
            record_readback(
                sum(f.shape[-1] for f in fetched),
                sum(f.nbytes for f in fetched),
            )
            _record("device_launches", len(pending))
            if not combined_plan and len(recs) > 1:
                _record("warm_fallback_launches", len(pending))
            for (i, _dev), arr in zip(pending, fetched):
                blocks[i] = arr
        _record("uploads_saved", len(recs) - 1)
        for block, (m, _cp, seg_bucket, n_groups, key_values) in zip(
            blocks, recs
        ):
            # the member's OWN decode/assembly — the solo readback path
            rows = m.stage._decode_stacked(block)
            counts = rows[0][:n_groups]
            outputs = [o[:n_groups] for o in m.stage._state_outputs(rows)]
            t = m.stage._assemble_partial(
                outputs, counts, key_values, n_groups
            )
            if t.num_rows:
                tables[id(m)].append(t)
    # carry the negotiated narrow choices into each member's own prior map
    # so its later solo runs keep the exact dtypes this group compiled
    for m in live:
        for mkey, skey in keymaps[id(m)].items():
            if skey in shared_choice:
                m.stage._narrow_choice[mkey] = shared_choice[skey]
    for m in live:
        tabs = tables[id(m)]
        table = (
            pa.concat_tables(tabs) if tabs
            else m.stage.partial_schema.empty_table()
        )
        res.put(m.node, m.partition, table)


# combined-step cache: one AOT-wrapped program per member-set composition
# (stable stage identities, in canonical order); wrap_step handles per-shape
# signatures underneath, the XLA/AOT disk tiers amortize across processes.
# `_combined_warm` marks (composition, shape) signatures whose program has
# actually been traced/compiled (by a background warm call or an earlier
# wave), so a serving wave never stalls behind a multi-second trace; the
# in-flight set bounds concurrent background compiles to one per signature.
_combined_lock = make_lock("ops.sharedscan._combined_lock")
_combined_cache: Dict[tuple, object] = {}  # guarded-by: _combined_lock
_combined_warm: set = set()  # guarded-by: _combined_lock
_combined_warming: set = set()  # guarded-by: _combined_lock
# test hook: compile the combined program synchronously on first sight
# instead of warming it in the background (deterministic one-launch waves)
SYNC_COMPILE = False


def _combined_ready(sig: tuple) -> bool:
    if SYNC_COMPILE:
        return True
    with _combined_lock:
        return sig in _combined_warm


def _warm_combined(sig: tuple, stages: list, stables: List[str], args) -> None:
    """Trace + compile the composition's combined program OFF the serving
    path (one background thread per signature; XLA compilation releases
    the GIL). Compile-WITHOUT-execute (ISSUE 19 satellite): the warm goes
    through ``step.warm`` — ``jit(...).lower(...).compile()`` under the
    AOT wrapper — so the program never RUNS during warm-up: no output is
    allocated and the wave's shared device buffers are released as soon
    as the trace finishes, closing the transient-HBM accounting gap the
    execute-to-warm approach had. The signature is marked ready for the
    next wave once the executable exists."""
    with _combined_lock:
        if sig in _combined_warm or sig in _combined_warming:
            return
        _combined_warming.add(sig)

    def run() -> None:
        try:
            step = _combined_step(stages, stables)
            step.warm(*args)
            with _combined_lock:
                _combined_warm.add(sig)
        except Exception:
            log.warning("combined-step warm failed", exc_info=True)
        finally:
            with _combined_lock:
                _combined_warming.discard(sig)

    # non-daemon ON PURPOSE: a daemon compile thread racing interpreter
    # teardown aborts in PJRT ("terminate called without an active
    # exception"); non-daemon threads are joined BEFORE finalization, so a
    # process exits cleanly after at most one in-flight warm compile
    threading.Thread(
        target=run, daemon=False, name="sharedscan-warm"
    ).start()


class _AotOwner:
    """Minimal aot_key carrier for aotcache.wrap_step."""

    def __init__(self, aot_key: str) -> None:
        self.aot_key = aot_key


def _combined_step(stages: list, stables: List[str]):
    """One jitted program running every member's unrolled core with its own
    (seg_bucket, cols view, aux, codes) over the SHARED row_valid — the
    member sub-programs are the EXACT solo cores, so each slice of the
    concatenated f32 output is bit-identical to that member's solo stacked
    readback."""
    key = tuple(stables)
    with _combined_lock:
        fn = _combined_cache.get(key)
    if fn is not None:
        return fn
    import jax.numpy as jnp

    from ballista_tpu.ops import aotcache

    cores = [s._unrolled_core() for s in stages]

    def combined(seg_buckets, cols_list, auxs, codes_list, row_valid):
        outs = []
        for core, sb, cols, aux, codes in zip(
            cores, seg_buckets, cols_list, auxs, codes_list
        ):
            outs.append(core(sb, cols, list(aux), codes, row_valid).reshape(-1))
        return jnp.concatenate(outs)

    owner = _AotOwner(
        "sharedscan|"
        + hashlib.sha1("|".join(stables).encode()).hexdigest()
    )
    fn = aotcache.wrap_step(owner, "sharedscan", combined, static_argnums=(0,))
    with _combined_lock:
        if len(_combined_cache) > 64:
            # evicting compiled programs must also forget their READY
            # marks: a warm sig whose program was evicted would otherwise
            # retrace/recompile synchronously inside a serving wave —
            # exactly the stall the warm set exists to prevent
            _combined_cache.clear()
            _combined_warm.clear()
        return _combined_cache.setdefault(key, fn)

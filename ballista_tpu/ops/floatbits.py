"""Order-preserving IEEE-754 <-> integer bijections.

The device computes in f32, but float MIN/MAX results must sometimes be the
bit-exact stored value (q2's decorrelated MIN(ps_supplycost) is
equality-joined back against the source column — a rounded f32 min matches
nothing). The fix is representational, not arithmetic: map float bits to
integers whose *signed integer order equals the float total order*, run the
existing exact integer min/max machinery on device, and invert on readback.
No rounding exists anywhere in that path.

Key construction (i = raw bits viewed as a signed integer of equal width):

    key(x) = i          if i >= 0     (+0.0, positives, +NaN)
           = INT_MIN-i  if i <  0     (-0.0, negatives, -NaN)

Properties, documented and tested (tests/test_floatbits.py):

- monotone total order: x < y  <=>  key(x) < key(y) for all non-NaN x, y,
  including negatives, subnormals and ±inf;
- ±0 collapse: key(-0.0) == key(+0.0) == 0. MIN/MAX treat the two zeros as
  equal (SQL equality does too) and decode returns +0.0;
- NaN policy: +NaN keys sort above +inf, -NaN keys below -inf. Aggregate
  consumers never rely on this — the stage declines to the host path when a
  min/max input column contains NaN, because Arrow's host min/max SKIPS NaN
  and no single key order can reproduce "never wins min AND never wins max";
- exact round-trip: decode(encode(x)) is bit-identical to x for every value
  except -0.0, which decodes to +0.0 (the documented collapse).

f64 keys additionally split into two int32 planes for the device (TPU has
no native int64): hi = top 32 bits (signed, carries the order's coarse
component), lo = bottom 32 bits biased into int32 so that for equal hi the
signed int32 order of lo matches the key order. Lexicographic (hi, lo)
min/max equals int64 key min/max; the host recombines exactly.
"""

from __future__ import annotations

import numpy as np

_I32_MIN = np.int32(-(2**31))
_I64_MIN = np.int64(-(2**63))


# -- f32 <-> i32 -----------------------------------------------------------
def f32_to_i32(x: np.ndarray) -> np.ndarray:
    """Encode float32 values to order-preserving int32 keys."""
    i = np.asarray(x, dtype=np.float32).view(np.int32)
    return np.where(i >= 0, i, _I32_MIN - i)


def i32_to_f32(k: np.ndarray) -> np.ndarray:
    """Invert f32_to_i32 (key 0 -> +0.0; see module docstring)."""
    k = np.asarray(k, dtype=np.int32)
    return np.where(k >= 0, k, _I32_MIN - k).astype(np.int32).view(np.float32)


# -- f64 <-> i64 -----------------------------------------------------------
def f64_to_i64(x: np.ndarray) -> np.ndarray:
    """Encode float64 values to order-preserving int64 keys."""
    i = np.asarray(x, dtype=np.float64).view(np.int64)
    return np.where(i >= 0, i, _I64_MIN - i)


def i64_to_f64(k: np.ndarray) -> np.ndarray:
    """Invert f64_to_i64 (key 0 -> +0.0; see module docstring)."""
    k = np.asarray(k, dtype=np.int64)
    return np.where(k >= 0, k, _I64_MIN - k).astype(np.int64).view(np.float64)


# -- i64 key <-> two int32 device planes -----------------------------------
def i64_to_planes(k: np.ndarray):
    """Split int64 keys into (hi, lo) int32 planes whose lexicographic
    signed order equals the key order: hi is the arithmetic top half, lo the
    bottom 32 bits re-biased so unsigned lo order becomes signed int32
    order."""
    k = np.asarray(k, dtype=np.int64)
    hi = (k >> 32).astype(np.int32)
    lo = (k & np.int64(0xFFFFFFFF)).astype(np.int64) + np.int64(_I32_MIN)
    return hi, lo.astype(np.int32)


def planes_to_i64(hi: np.ndarray, lo: np.ndarray) -> np.ndarray:
    """Exact inverse of i64_to_planes. Accepts int64 inputs (device rows
    decode through the hi/lo f32-pair packing as int64)."""
    hi64 = np.asarray(hi, dtype=np.int64)
    lo64 = np.asarray(lo, dtype=np.int64) - np.int64(_I32_MIN)  # back to [0, 2^32)
    return hi64 * np.int64(1 << 32) + lo64


# -- in-program (jax) variants ---------------------------------------------
def jnp_f32_to_i32(x):
    """Device-side f32 -> key. Bit reinterpretation plus integer select —
    no float arithmetic, so TPU denormal flushing cannot alter the key."""
    import jax
    import jax.numpy as jnp

    i = jax.lax.bitcast_convert_type(x.astype(jnp.float32), jnp.int32)
    return jnp.where(i >= 0, i, jnp.int32(-(2**31)) - i)


def jnp_i32_to_f32(k):
    """Device-side key -> f32: exact inverse of jnp_f32_to_i32 (bit
    reinterpretation only). The fused epilogue ranks the int key lanes
    directly and never decodes on device — this inverse exists for
    in-program consumers that need the float back without a host
    round-trip, and is pinned by tests/test_floatbits.py."""
    import jax
    import jax.numpy as jnp

    i = jnp.where(k >= 0, k, jnp.int32(-(2**31)) - k).astype(jnp.int32)
    return jax.lax.bitcast_convert_type(i, jnp.float32)

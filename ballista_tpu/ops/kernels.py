"""Device kernel entry points used by operator dispatch.

hash_aggregate is the headline: whole-pipeline fusion via FusedAggregateStage.
filter_batch is a per-batch lowering used when a filter runs outside a
fusable aggregate pipeline; it returns None (host fallback) for shapes the
device path doesn't support. Projections have no stand-alone device path —
they only pay off fused into a stage (FusedAggregateStage / FactAggregateStage).

This module also owns the CANONICAL DECLINE HELPERS (`decline`,
`host_fallback`): device paths bail to host only through
`raise UnsupportedOnDevice("<reason>")` or these — never a silent
`return None` or an ad-hoc exception — so every decline carries a reason
and the kernels ladder stays enumerable. Enforced by dev/analysis's
decline-discipline pass.
"""

from __future__ import annotations

import functools
import logging
from typing import Dict, List, Optional, Tuple

import numpy as np
import pyarrow as pa

from ballista_tpu.ops.jaxexpr import ExprCompiler
from ballista_tpu.ops.runtime import (
    ScanDictionaries,
    UnsupportedOnDevice,
    bucket_rows,
    column_to_numpy,
    pad_to,
    readback,
)
from ballista_tpu.utils.locks import make_lock


def decline(reason: str):
    """Canonical raising decline: identical to raising UnsupportedOnDevice
    directly, kept as the named entry point for the ladder."""
    raise UnsupportedOnDevice(reason)


def host_fallback(reason: str) -> None:
    """Canonical Optional-sentinel decline: logs + counts the reason, then
    returns the None the dispatcher maps to the host Arrow path. Use this
    instead of a bare `return None` inside UnsupportedOnDevice handlers so
    declines stay observable (tracing counter + debug log). Inside a
    routing probe the trace buffers with the decision counters, so a
    speculative attempt that declined leaves no phantom fallback trace."""
    from ballista_tpu.ops.runtime import record_decline_trace

    record_decline_trace("device.host_fallback", f"host fallback: {reason}")
    return None


def step_aside(reason: str) -> None:
    """Canonical MID-LADDER decline: one admission path steps aside but the
    dispatcher tries the next rung (e.g. factagg -> mapped rewrite), so the
    query may still run fully on device. Counted separately from
    host_fallback — conflating them would make the device path look
    disengaged on queries that ran on-chip."""
    from ballista_tpu.ops.runtime import record_decline_trace

    record_decline_trace("device.step_aside", f"ladder step-aside: {reason}")
    return None


# -- M:N join admission ------------------------------------------------------
# Bounded-width gather tiers for the device hash join (ops/join.py and the
# SPMD mesh join, parallel/spmd_join.py): duplicate build keys expand each
# probe into up to max-multiplicity matched rows, and the static gather
# width is the smallest tier covering the observed maximum run-length, so
# XLA compiles a bounded set of gather programs (same recompilation-control
# idea as bucket_rows). Shapes past the top tier — or whose padded [probe
# slots x width] materialization would exceed the element cap — step aside
# to the host sort-merge join with a recorded reason.
JOIN_MULTIPLICITY_TIERS = (1, 4, 16, 64, 256)
# padded gather elements (probe slots x width); past this the bounded-width
# materialization + its d2h readback cost more than the host join it
# replaces (2^26 int32 elements = 256 MiB on the wire)
JOIN_GATHER_CAP = 1 << 26


def join_multiplicity_tier(
    max_mult: int, probe_slots: int
) -> Tuple[Optional[int], Optional[str]]:
    """Admission for the M:N bounded-width gather: (tier, None) with the
    smallest static width covering `max_mult`, or (None, reason) when the
    shape exceeds the ladder — callers record the reason (runtime.
    record_join_path) and step aside to the host join."""
    for tier in JOIN_MULTIPLICITY_TIERS:
        if max_mult <= tier:
            # width 1 transfers exactly the one-int32-per-probe plane the
            # pre-M:N kernel always read back uncapped — the cap guards the
            # bounded-width padding amplification, which only exists past
            # width 1 (capping width 1 would regress large unique-key joins
            # to the host for no readback saving)
            if tier > 1 and probe_slots * tier > JOIN_GATHER_CAP:
                return None, (
                    f"M:N gather {probe_slots}x{tier} exceeds the "
                    f"{JOIN_GATHER_CAP}-element cap"
                )
            return tier, None
    return None, (
        f"build-key multiplicity {max_mult} exceeds top tier "
        f"{JOIN_MULTIPLICITY_TIERS[-1]}"
    )


# -- cost-model tier extension (ISSUE 10) ------------------------------------
# The static ladder above stays the cold-start prior AND the hard safety
# cap: a shape it declines may still run on device, but ONLY when the
# measured cost store (ops/costmodel.py) has enough evidence that the
# device gather beats the host join for that shape — and never past the
# hard caps below, which bound the worst case a wrong store can cost.
JOIN_EXTENDED_TIERS = (512, 1024)
JOIN_GATHER_HARD_CAP = JOIN_GATHER_CAP * 4
# predicted device cost must beat the host prediction by this margin:
# close calls stay on the proven static routing
_EXT_MARGIN = 0.75


def join_extended_tier(
    max_mult: int, probe_slots: int, host_units: int
) -> Optional[Tuple[int, float, float]]:
    """Evidence-gated admission past the static ladder: (tier, predicted
    device seconds, predicted host seconds) when the warm cost store says
    the bounded-width gather beats the host join by _EXT_MARGIN — None
    when cold (no evidence = static prior stands), unfavorable, or past
    the hard cap. The static widths are candidates too: a join declined
    purely on the ELEMENT cap (max_mult inside the ladder) re-admits at
    its natural width under the hard cap, not at a 2x-wasteful extended
    width. `host_units` is the host join's work measure (build + probe
    rows)."""
    from ballista_tpu.ops import costmodel

    for tier in JOIN_MULTIPLICITY_TIERS + JOIN_EXTENDED_TIERS:
        if max_mult <= tier:
            if probe_slots * tier > JOIN_GATHER_HARD_CAP:
                return None
            dev = costmodel.predict("join.gather", probe_slots * tier)
            host = costmodel.predict("join.host", host_units, engine="host")
            if dev is None or host is None:
                return None  # cold store: the static ladder is the prior
            if dev < _EXT_MARGIN * host:
                return tier, dev, host
            return None
    return None

# executor task threads run concurrently: lookup/evict/insert must be one
# atomic section or two threads can each build (and pin) the same stage.
# (Tests reach in to clear these between cases — cross-file accesses are
# outside the file-scoped guarded-by check by design.)

_stage_cache_lock = make_lock("ops.kernels._stage_cache_lock")
_stage_cache: Dict[str, object] = {}  # guarded-by: _stage_cache_lock
# pins each cached stage's table source so its id() (part of the cache key
# for memory scans) can never be recycled by a different object
_stage_cache_pins: Dict[str, object] = {}  # guarded-by: _stage_cache_lock
# stable plan identity -> the latest full (mtime-bearing) cache key, so a
# rewritten file's superseded entry can be evicted and its reservations freed
_stage_latest: Dict[str, str] = {}  # guarded-by: _stage_cache_lock
_filter_cache: Dict[tuple, object] = {}
_cache_configured = False


def _configure_jax_cache() -> None:
    """Persistent XLA compilation cache: repeated queries (and repeated
    bench/driver processes) skip recompilation — essential when the chip is
    behind a remote-compile relay."""
    global _cache_configured
    if _cache_configured:
        return
    import pathlib

    import jax

    cache_dir = pathlib.Path(__file__).resolve().parents[2] / ".jax_cache"
    try:
        cache_dir.mkdir(exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", str(cache_dir))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
    except Exception:
        pass
    _cache_configured = True


def resolve_stage(exec_node, ctx) -> Tuple[object, str, str, float]:
    """Build-or-fetch the fused device stage for one aggregate node WITHOUT
    running it: the structural-cache half of hash_aggregate, factored out so
    the shared-scan batch executor (ops/sharedscan.py, ISSUE 13) can resolve
    member stages up front and group compatible ones into one launch.

    Returns (stage, key, stable_key, unit_size): `stage` is False when the
    shape permanently declined to the host path (cached verdict included),
    `key` the full mtime-bearing cache key, `stable_key` the mtime-free
    stage identity (the AOT/cost-store key half), and `unit_size` the
    stage's input size in leaf-file bytes or memory-scan rows (the
    stage.run cost-observation units)."""
    from ballista_tpu.ops.stage import FusedAggregateStage

    _configure_jax_cache()
    # AOT program-cache wiring (ISSUE 8): bind the disk tier's directory +
    # chaos injector from this dispatch's config so the stage steps built
    # below resolve through it. The cost model (ISSUE 10) binds beside it:
    # stage runs/compiles/readbacks observed below feed tier selection.
    from ballista_tpu.ops import aotcache, costmodel

    aotcache.configure(ctx.config)
    costmodel.configure(ctx.config)
    # structural cache: identical plan shapes (the common case for repeated
    # queries) share one stage — and with it the jit trace/compile cache.
    # Memory scans carry no identity in their display: include source ids so
    # two in-memory tables with the same shape never collide.
    import os

    from ballista_tpu.physical.scan import MemoryScanExec

    def leaves(node):
        if not node.children():
            yield node
        for c in node.children():
            yield from leaves(c)

    parts = []
    mtimes = []
    pinned = []
    # input-size units for the stage.run cost observation (ISSUE 11
    # satellite): leaf-file bytes (or memory-scan rows). units=1 made the
    # whole-stage rate scale-blind — the first run after a file grew in
    # place predicted the OLD size's seconds and counted one guaranteed
    # gross mispredict; a per-byte rate predicts correctly at any scale.
    unit_size = 0.0
    # persisted-layout eligibility: every leaf's data identity must be a
    # file set with covering mtimes. A shuffle-reader-fed (or otherwise
    # non-file) leaf contributes nothing to the mtime component, so the key
    # would stay constant across data changes and the layout cache could
    # return stale tiles — those stages must never persist.
    file_backed = True
    for leaf in leaves(exec_node):
        if isinstance(leaf, MemoryScanExec):
            parts.append(str(id(leaf.source)))
            pinned.append(leaf.source)
            unit_size += float(sum(
                b.num_rows
                for part in getattr(leaf.source, "partitions", ())
                for b in part
            ))
        elif hasattr(leaf, "source") and hasattr(leaf.source, "files"):
            # file mtimes invalidate the cached stage (and its
            # device-resident columns) when a file is rewritten; they live
            # in a separate key component so the superseded entry can be
            # found and its HBM reservations released
            parts.extend(leaf.source.files)
            for f in leaf.source.files:
                if os.path.exists(f):
                    mtimes.append(str(os.path.getmtime(f)))
                    try:
                        unit_size += float(os.path.getsize(f))
                    except OSError:
                        pass
                else:
                    mtimes.append("0")
                    file_backed = False  # mtime does not cover this leaf
        else:
            file_backed = False
    # config flags participate in the key: a run-time decline under one
    # config must not pin the device path off for another (ADVICE r1). The
    # top-k annotation does too — it changes what a fact-agg stage returns,
    # and it is not part of the aggregate subtree's display.
    flags = (
        f"fv={ctx.config.tpu_fuse_volatile()},dc={ctx.config.device_cache()},"
        f"sk={ctx.config.tpu_sorted_kernel()},"
        f"topk={getattr(exec_node, '_topk_pushdown', None)}"
    )
    # append-only-when-set: ef=False on every key would invalidate every
    # persisted layout entry written before the flag existed
    if getattr(exec_node, "exact_floats", False):
        flags += ",ef=True"
    # batch.size folds into the key the same append-only way (ISSUE 15
    # satellite, PR 13 residue): the persisted layout's tile granularity
    # follows batch size, and keying on it means a warm layout entry is
    # ALWAYS at this dispatch's granularity — which is what makes
    # layout-warm members shared-scan-eligible (the shared batch stream is
    # then row-identical to the member's warm solo stream). The guarantee
    # only holds for entries written under THIS keying scheme, so the
    # layout-cache _FORMAT bump to 5 orphans every pre-keying store (a
    # suffix-less v4 entry could have been written at any batch size).
    from ballista_tpu.config import BALLISTA_BATCH_SIZE, DEFAULT_SETTINGS

    if ctx.batch_size != int(DEFAULT_SETTINGS[BALLISTA_BATCH_SIZE]):
        flags += f",bs={ctx.batch_size}"
    # decorrelated scalar subqueries equality-compare the aggregate result
    # against source values (q2: ps_supplycost = MIN(...)): float MIN/MAX
    # must be the bit-exact stored value. The fused stage delivers exactly
    # that for plain columns via the order-preserving IEEE-754<->int
    # bijection (ops/floatbits.py) — integer min/max on device, inverted on
    # readback, zero rounding — so the ladder runs; the paths that cannot
    # be exact decline individually (factagg.try_build steps aside, the
    # fused stage rejects exact min/max over computed expressions).
    stable = exec_node.display_indent() + "|" + ",".join(parts) + "|" + flags
    key = stable + "|" + ",".join(mtimes)
    with _stage_cache_lock:
        stage = _stage_cache.get(key)
        if stage is None:
            # evict a superseded entry for the same stable plan (file
            # rewritten: new mtimes) and release its HBM-budget reservations
            # — otherwise a long-lived executor leaks budget until
            # everything streams. release marks the old stage retired, so a
            # task thread still inside its run() cannot re-reserve.
            old_key = _stage_latest.get(stable)
            if old_key is not None and old_key != key:
                old = _stage_cache.pop(old_key, None)
                _stage_cache_pins.pop(old_key, None)
                if old not in (None, False):
                    from ballista_tpu.ops.runtime import release_stage_residency

                    release_stage_residency(old)
            _stage_latest[stable] = key
    if stage is None:
        # build OUTSIDE the lock — a slow stage build must not block cache
        # hits for unrelated queries. First insert wins on a racing build.
        try:
            from ballista_tpu.ops.factagg import FactAggregateStage

            from ballista_tpu.ops.mappedscan import try_rewrite_mapped

            # aggregate over a join: try the fact-side pushdown first
            built = FactAggregateStage.try_build(exec_node)
            if (
                built is not None
                and getattr(built, "topk", None) is None
                and getattr(exec_node, "_topk_pushdown", None) is not None
            ):
                # factagg admitted the shape but its epilogue cannot fuse
                # (dim-only grouping, q10: output groups are not fact keys,
                # so its per-key top-k would rank the wrong thing and the
                # member-select readback pays O(members) d2h). A mapped
                # rewrite groups directly by the OUTPUT keys, so the fused
                # stage's lexicographic top-k applies — prefer it when its
                # spec is live, keeping the O(limit) readback.
                rewritten = try_rewrite_mapped(exec_node)
                if rewritten is not None:
                    try:
                        alt = FusedAggregateStage(rewritten)
                        if alt.topk is not None:
                            built = alt
                    except UnsupportedOnDevice:
                        pass
            if built is None:
                # shapes factagg excludes (multi-key fact joins, dim-valued
                # aggregate inputs, fact-column group keys — q7-q9/q12):
                # rewrite the join tree to a mapped fact scan and fuse that
                rewritten = try_rewrite_mapped(exec_node)
                if rewritten is not None:
                    built = FusedAggregateStage(rewritten)
            if built is None:
                built = FusedAggregateStage(exec_node)
        except UnsupportedOnDevice:
            built = False
        # persisted-layout eligibility: only fully file-backed stages
        # (memory-scan keys embed id(), which another process could recycle
        # for different data, and shuffle-fed stages carry no mtimes at all
        # — a false disk hit either way would be silent corruption)
        if built is not False and not pinned and file_backed:
            built.persist_key = key
            # chunk-set delta identity (ISSUE 19): the plan display names the
            # scan DIRECTORY, not the file list, so display+flags is stable
            # across appends — each prepared chunk keys itself under this
            # base plus its own (path, mtime, size, chunk_index), letting a
            # grown file set reuse every existing chunk byte-for-byte.
            chunk_base = exec_node.display_indent() + "|" + flags
            built.chunk_key_base = chunk_base
            inner = getattr(built, "inner", None)
            if inner is not None:
                inner.persist_key = key
                inner.chunk_key_base = chunk_base
        if built is not False:
            # AOT program identity is the STABLE key half (no mtimes):
            # compiled programs depend on plan structure + shapes only
            # (literal codes/tables ride as runtime aux), so a rewritten
            # input file keeps its warm programs; memory-scan id() reuse is
            # harmless here for the same reason (worst case a false hit
            # serves the identical program)
            built.aot_key = stable
            inner = getattr(built, "inner", None)
            if inner is not None:
                inner.aot_key = stable
        with _stage_cache_lock:
            stage = _stage_cache.get(key)
            if stage is None:
                _stage_cache[key] = built
                _stage_cache_pins[key] = pinned
                stage = built
    return stage, key, stable, unit_size


def hash_aggregate(exec_node, partition: int, ctx) -> Optional[pa.Table]:
    # bind the AOT disk tier + cost model from THIS dispatch's config
    # BEFORE any path that compiles or observes (the countjoin prescreen
    # included — resolve_stage rebinds idempotently for the ladder below)
    from ballista_tpu.ops import aotcache, costmodel

    _configure_jax_cache()
    aotcache.configure(ctx.config)
    costmodel.configure(ctx.config)
    # shared-scan splice (ISSUE 13): the batched-task executor already ran
    # this node's partition inside one combined device launch — hand its
    # table straight back. The precompute produced EXACTLY what stage.run
    # below would (bit-identity is the batching invariant), so nothing
    # downstream can tell. Checked before the countjoin prescreen on
    # purpose: only scan-rooted stages (join-free row sources) are ever
    # precomputed, and countjoin only matches join shapes, so the two can
    # never claim the same node.
    shared = getattr(ctx, "shared_scan", None)
    if shared is not None:
        hit = shared.take(exec_node, partition)
        if hit is not None:
            from ballista_tpu.ops.runtime import record_routing

            record_routing("batch", "stage")
            return hit
    # COUNT-over-LEFT-join as device membership counting (q13): the
    # per-probe counts plane replaces the join expansion entirely. A cheap
    # shape prescreen — non-matching aggregates fall through to the ladder
    if ctx.config.tpu_device_join():
        from ballista_tpu.ops.countjoin import try_count_left_join

        counted = try_count_left_join(exec_node, partition, ctx)
        if counted is not None:
            return counted
    stage, key, stable, unit_size = resolve_stage(exec_node, ctx)
    if stage is False:
        return None
    try:
        # the run cost is a cost-store observation keyed on stable stage
        # identity (like the AOT cache), and the success is a recorded
        # routing decision — predicted from the stage's own history, so the
        # bench mispredict rate covers the aggregate path too. Units are
        # the stage's input size (file bytes / memory rows), so the learned
        # rate scales with the data instead of memorizing one run's seconds
        # (ISSUE 11 satellite — units=1 mispredicted once per data growth).
        import hashlib

        op = "stage.run|" + hashlib.sha1(stable.encode()).hexdigest()[:12]
        with costmodel.timed(op, units=max(1.0, unit_size), routing_op="stage"):
            out = stage.run(partition, ctx)
        return out
    except UnsupportedOnDevice:
        # permanently declined: free its pinned device entries and their
        # HBM-budget reservations before dropping the stage. Log WHY once —
        # a silent decline (e.g. tiles just past the HBM budget) reads as
        # "device path ran" in benchmarks when it did not.
        import sys

        reason = f"stage permanently declined: {sys.exc_info()[1]}"
        logging.getLogger("ballista.tpu").warning(
            "device stage permanently declined to host: %s", sys.exc_info()[1]
        )
        from ballista_tpu.ops.runtime import (
            record_routing,
            release_stage_residency,
        )

        release_stage_residency(stage)
        with _stage_cache_lock:
            _stage_cache[key] = False
        record_routing("host", "stage")
        return host_fallback(reason)


def _compile_predicate(predicate, schema: pa.Schema):
    # structural key (an id() key could be recycled after GC and serve a
    # stale compiled predicate)
    key = (str(predicate), tuple(schema.names), tuple(str(t) for t in schema.types))
    hit = _filter_cache.get(key)
    if hit is not None:
        return hit
    try:
        dicts = ScanDictionaries()
        compiler = ExprCompiler(schema, dicts)
        cv = compiler.compile(predicate)
        if cv.kind != "bool":
            decline("non-boolean predicate")  # cold-path: compile-time shape check; the routing decision is recorded where the cached verdict is consumed (filter_batch)
        import jax

        from ballista_tpu.ops.jaxexpr import predicate_fn

        mask_fn = predicate_fn(cv)  # WHERE collapse: NULL -> excluded

        @jax.jit
        def run(cols, aux):
            return mask_fn(cols, aux)

        hit = (compiler, run)
    except UnsupportedOnDevice:
        hit = False
    _filter_cache[key] = hit
    return hit


def filter_batch(batch: pa.RecordBatch, predicate) -> Optional[pa.RecordBatch]:
    """Evaluate the predicate on device, compact on host."""
    import jax.numpy as jnp

    schema = batch.schema
    hit = _compile_predicate(predicate, schema)
    if hit is False:
        return None
    compiler, run = hit
    n = batch.num_rows
    bucket = bucket_rows(n)
    try:
        cols = {}
        for idx, dtype in compiler.used_columns.items():
            d = compiler.dicts.dicts.get(idx)
            npcol = column_to_numpy(batch.column(idx), dtype, d)
            fill = False if npcol.dtype == np.bool_ else 0
            cols[idx] = jnp.asarray(pad_to(npcol, bucket, fill))
    except UnsupportedOnDevice as e:
        from ballista_tpu.ops.runtime import record_routing

        record_routing("host", "filter")
        return host_fallback(f"filter batch lowering: {e}")
    aux = [jnp.asarray(a) for a in compiler.build_aux()]
    # the full boolean mask rides d2h once per batch — account for it
    mask = readback(run(cols, aux))[:n]
    return batch.filter(pa.array(mask))



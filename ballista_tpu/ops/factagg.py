"""Fact-side aggregation pushdown: Aggregate over a PK-FK join tree.

The reference executes Aggregate(Join(dim, fact)) by materializing the join
then hash-aggregating the joined rows (DataFusion HashJoinExec +
HashAggregateExec; serde rust/core/src/serde/physical_plan/from_proto.rs:
176-214, 370-384). On a relay-attached TPU that shape loses: the join output
is volatile, so every query pays encode + transfer for 6M+ joined rows.

TPU-first redesign (eager-aggregation + semi-join membership):

  host      dim side of the join (small) executes as-is; its join-key
            column must be unique (checked) -> the join attaches at most
            one dim row per fact row, so aggregates distribute over the
            join. Build a per-rank membership vector over the fact table's
            cached sorted-key layout.
  device    ONE jit call over the resident fact layout: fused filters +
            per-key partial aggregates (ops/stage.py sorted core), mask by
            membership, and — when the planner annotated a Sort+Limit
            epilogue — lax.top_k over the score column so the readback is
            K rows, not G. d2h latency (~65ms) + 28MB/s bandwidth make
            readback size the whole game.
  host      attach dim attribute columns to the selected keys, emit the
            aggregate's partial-state rows; the ordinary Final merge, Sort
            and Limit operators above run unchanged on K rows.

Pattern matched: HashAggregateExec[single|partial] over
 [Filter/Projection/Coalesce]* -> a hash-join tree in which the largest
file-backed scan chain (the fact) sits anywhere reachable through INNER
joins and the LEFT side of SEMI/ANTI joins — directly (q3: orders x
lineitem), nested (q10: ((customer x orders) x lineitem) x nation), or
under a semi filter (q18: the "orderkey IN (big orders)" build side folds
whole into the dim-plan membership). The fact's own join must be INNER,
single equi-key, no residual filter. Joins between it and the root are
normally host-side over the dim plan and must not be keyed on fact columns
— with ONE exception: a coupled secondary dim (q5: supplier joined on
l_suppkey with c_nationkey = s_nationkey coupling) runs per-S_ATTR-class
on device via a static mapped column (_detect_secondary). Fact-side group
keys must be the join key; dim-side group keys are attached
post-aggregation (secondary mode: group keys attach per class); all
aggregate inputs must be fact-side expressions. The device top-k epilogue
additionally requires the fact key among the group keys (one output group
per key); dim-only grouping (q10) uses the member-select readback and the
ordinary final merge re-groups.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from ballista_tpu.ops.runtime import (
    UnsupportedOnDevice,
    bucket_rows,
    pad_to,
    record_readback,
    widen_cols,
)
from ballista_tpu.ops.stage import (
    FusedAggregateStage,
    _SCAN_TYPES,
    decode_packed_rows,
    jnp_unpack_i32,
    packed_positions,
    state_column,
    substitute_columns,
)
from ballista_tpu.physical import expr as px
from ballista_tpu.physical.basic import (
    CoalesceBatchesExec,
    FilterExec,
    MergeExec,
    ProjectionExec,
)

# dim sides larger than this are not "dimension tables"; let the host join
# handle them. The ceiling is host-side cost only (one cached collect +
# sort + unique check; the device never sees dim rows, just fact-rank
# membership bits), so it is sized for SF=100 TPC-H dim shapes: q3's
# filtered customer x orders side is ~15M rows, q10's window ~6M. A 4M
# ceiling silently pushed exactly those queries back onto the host path at
# the scale the ≥5x target is defined on.
MAX_DIM_ROWS = 32_000_000

# the non-topk member-select epilogue reads back one column per member and
# re-groups on host; that path keeps the old tighter ceiling (the raised
# MAX_DIM_ROWS is sized for the topk epilogue, whose readback is O(k))
MAX_SELECT_MEMBERS = 4_000_000

# group_layout marker for "this output column is the fact join key" — a
# sentinel object so it can never collide with a real dim column name
FACT_KEY = object()
# candidate multiplier for the top-k epilogue: secondary sort keys and f32
# score ties are resolved host-side within this pool
TOPK_POOL = 64


def _scan_chain_leaf(node):
    while isinstance(node, (FilterExec, ProjectionExec, CoalesceBatchesExec)):
        node = node.input
    return node if isinstance(node, _SCAN_TYPES) else None


def _chain_bytes(leaf) -> int:
    files = getattr(getattr(leaf, "source", None), "files", None)
    if not files:
        return 0
    return sum(os.path.getsize(f) for f in files if os.path.exists(f))


def _columns_of(e: px.PhysicalExpr, acc: List[int]) -> None:
    if isinstance(e, px.ColumnExpr):
        acc.append(e.index)
    for name in ("left", "right", "expr", "low", "high", "base", "else_expr"):
        c = getattr(e, name, None)
        if isinstance(c, px.PhysicalExpr):
            _columns_of(c, acc)
    for a in getattr(e, "args", []) or []:
        _columns_of(a, acc)
    for w, t in getattr(e, "when_then", []) or []:
        _columns_of(w, acc)
        _columns_of(t, acc)


class FactAggregateStage:
    """Device pipeline for one aggregate-over-join. Built via try_build."""

    @staticmethod
    def try_build(agg) -> Optional["FactAggregateStage"]:
        from ballista_tpu.physical.aggregate import needs_exact_float_minmax

        if needs_exact_float_minmax(agg):
            # equality-consumed float MIN/MAX (q2): the fact-agg inner runs
            # with float_bits=False (its per-field row math can't carry the
            # two-row f64 key planes), so its f32 min/max would round the
            # result to match nothing. Step aside: the mapped-scan rewrite
            # below this in the ladder lowers plain-column MIN/MAX through
            # the order-preserving bijection instead (ops/floatbits.py).
            return None
        try:
            return FactAggregateStage(agg)
        except UnsupportedOnDevice as e:
            from ballista_tpu.ops.kernels import step_aside
            from ballista_tpu.ops.runtime import record_routing_event

            # not the end of the ladder: hash_aggregate tries the mapped
            # rewrite next (the query may still run fully on device), but
            # the reason why factagg stepped aside must stay observable
            record_routing_event("factagg.step_aside")
            return step_aside(f"factagg admission: {e}")

    def __init__(self, agg) -> None:
        from ballista_tpu.logical.plan import JoinType
        from ballista_tpu.physical.aggregate import AggregateFunc, HashAggregateExec
        from ballista_tpu.physical.join import HashJoinExec

        if agg.mode.value not in ("single", "partial"):
            raise UnsupportedOnDevice("fact-agg needs single/partial mode")

        # -- walk down to the join ------------------------------------
        node = agg.input
        stack: List[Tuple[str, object]] = []
        # partitions the framework will actually drive this aggregate with
        # (1 for SINGLE mode / over MergeExec). The fact scan's own count
        # can differ — e.g. a single-partition probe side with a
        # multi-partition fact build side — so fact reads stripe over the
        # driven count (inner.scan_stride below); a 1:1 partition map there
        # would silently aggregate only a fraction of the fact rows.
        n_driven = agg.input.output_partitioning().partition_count()
        while isinstance(node, (FilterExec, ProjectionExec, CoalesceBatchesExec, MergeExec)):
            if isinstance(node, FilterExec):
                stack.append(("filter", node.predicate))
            elif isinstance(node, ProjectionExec):
                stack.append(("project", node.exprs))
            node = node.input
        _WALKABLE = (JoinType.INNER, JoinType.SEMI, JoinType.ANTI)
        if not isinstance(node, HashJoinExec) or node.join_type not in _WALKABLE:
            raise UnsupportedOnDevice("row source is not a foldable hash join")
        if node.filter is not None:
            # a residual filter is an index-based expr over concat(left,
            # right); rebuilding the dim plan with the fact block removed
            # would silently shift what it reads
            raise UnsupportedOnDevice("root join has a residual filter")
        root = node

        # -- locate the fact scan chain anywhere in the join tree -------
        # Paths may cross INNER HashJoinExec nodes (their output schema is
        # the concatenation of their children, so removing the fact block
        # keeps every other column's relative order) and the LEFT side of
        # SEMI/ANTI joins (their output schema IS the left schema; the
        # filtering build side stays whole inside the dim plan — q18's
        # "orderkey IN (big-quantity orders)" folds into the membership
        # this way). The fact is the largest file-backed scan chain
        # reachable that way (q10 nests lineitem two joins deep).
        candidates: List[Tuple[list, HashJoinExec, str, int]] = []

        def dfs(j, path):
            sides = ("left",) if j.join_type != JoinType.INNER else ("left", "right")
            for side in sides:
                child = getattr(j, side)
                leaf = _scan_chain_leaf(child)
                if leaf is not None:
                    b = _chain_bytes(leaf)
                    if b > 0:
                        candidates.append((list(path), j, side, b))
                elif (
                    isinstance(child, HashJoinExec)
                    and child.join_type in _WALKABLE
                    and child.filter is None
                ):
                    dfs(child, path + [(j, side)])

        dfs(root, [])
        if not candidates:
            raise UnsupportedOnDevice("no file-backed scan side")
        path, join, fact_side, _ = max(candidates, key=lambda c: c[3])
        if join.join_type != JoinType.INNER:
            # aggregates distribute over the fact's own join only when it
            # attaches at most one dim row per fact row (INNER + unique key)
            raise UnsupportedOnDevice("fact join is not inner")
        if join.filter is not None or len(join.on) != 1:
            raise UnsupportedOnDevice("fact join shape (residual filter / multi-key)")
        self.fact_plan = getattr(join, fact_side)
        fact_n = len(self.fact_plan.schema())
        # joins between the root and the fact join normally run on the host
        # over the dim plan, so they must not need fact columns. ONE shape
        # of fact-column-keyed upper join is supported: the coupled
        # secondary dim (q5 joins supplier on l_suppkey, coupled through
        # c_nationkey = s_nationkey) — see _detect_secondary.
        fact_names = set(self.fact_plan.schema().names)
        offending = [
            i for i, (j, _side) in enumerate(path)
            if any(ln in fact_names or rn in fact_names for ln, rn in j.on)
        ]
        self.secondary: Optional[dict] = None
        if offending:
            self._detect_secondary(path, offending, join, fact_side, fact_names)
        # offset of the fact block within the root's flattened schema
        fact_offset = 0
        for j, side in path + [(join, fact_side)]:
            if side == "right":
                fact_offset += len(j.left.schema())
        lkey, rkey = join.on[0]
        self.fact_key = lkey if fact_side == "left" else rkey
        self.dim_key = rkey if fact_side == "left" else lkey
        fact_key_idx = self.fact_plan.schema().names.index(self.fact_key)

        # -- dim plan: the join tree with the fact subtree removed ------
        # In secondary mode every path join belongs to the SECONDARY plan
        # (built in _detect_secondary); the primary dim plan is just the
        # fact join's other side.
        replacement = join.left if fact_side == "right" else join.right
        if self.secondary is None:
            for j, side in reversed(path):
                children = [j.left, j.right]
                children[0 if side == "left" else 1] = replacement
                replacement = j.with_children(children)
        self.dim_plan = replacement

        # -- re-express aggregate exprs over the root join schema -------
        join_schema = root.schema()
        mapping: List[px.PhysicalExpr] = [
            px.ColumnExpr(f.name, i) for i, f in enumerate(join_schema)
        ]
        above_filters: List[px.PhysicalExpr] = []
        for kind, payload in reversed(stack):
            if kind == "project":
                mapping = [substitute_columns(e, mapping) for e, _ in payload]
            else:
                above_filters.append(substitute_columns(payload, mapping))

        def side_of(e: px.PhysicalExpr) -> str:
            cols: List[int] = []
            _columns_of(e, cols)
            in_fact = [fact_offset <= c < fact_offset + fact_n for c in cols]
            if all(in_fact):
                return "fact"
            if not any(in_fact):
                return "dim"
            return "mixed"

        # fact-index remap: join-schema column -> fact-plan column
        fact_map: List[px.PhysicalExpr] = []
        for i, f in enumerate(join_schema):
            if fact_offset <= i < fact_offset + fact_n:
                fact_map.append(px.ColumnExpr(f.name, i - fact_offset))
            else:
                fact_map.append(px.LiteralExpr(None, pa.null()))

        def to_fact(e: px.PhysicalExpr) -> px.PhysicalExpr:
            return substitute_columns(e, fact_map)

        # group keys: the fact side may contribute only the join key; dim
        # keys become post-aggregation attachments. Secondary mode instead
        # requires every group key to be a secondary-plan column (q5 groups
        # by n_name): values attach per allowed S_ATTR class.
        self.group_layout: List[Tuple[str, Optional[str]]] = []
        sec_group_cols: List[Tuple[str, str]] = []
        for e, name in [(substitute_columns(e, mapping), n) for e, n in agg.group_exprs]:
            s = side_of(e)
            if self.secondary is not None:
                if not (
                    isinstance(e, px.ColumnExpr)
                    and e.index >= self.secondary["sec_start"]
                    and e.name in self.secondary["plan"].schema().names
                ):
                    raise UnsupportedOnDevice(
                        "secondary mode requires secondary-side group keys"
                    )
                sec_group_cols.append((e.name, name))
                continue
            if s == "fact":
                if not (isinstance(e, px.ColumnExpr) and e.index - fact_offset == fact_key_idx):
                    raise UnsupportedOnDevice("fact-side group key is not the join key")
                self.group_layout.append((FACT_KEY, name))
            elif s == "dim" and isinstance(e, px.ColumnExpr):
                ri = e.index if e.index < fact_offset else e.index - fact_n
                dim_name = self.dim_plan.schema().names[ri]
                if dim_name != e.name:
                    raise UnsupportedOnDevice("dim column remap mismatch")
                self.group_layout.append((dim_name, name))
            else:
                raise UnsupportedOnDevice("unsupported group key shape")
        if self.secondary is not None:
            self.secondary["group_cols"] = sec_group_cols

        fact_filters = []
        for f in above_filters:
            if side_of(f) != "fact":
                raise UnsupportedOnDevice("non-fact filter above the join")
            fact_filters.append(to_fact(f))

        syn_aggs = []
        for a in agg.aggr_funcs:
            e = substitute_columns(a.expr, mapping)
            if side_of(e) not in ("fact",):
                raise UnsupportedOnDevice("aggregate input not on the fact side")
            syn_aggs.append(
                AggregateFunc(a.fn, to_fact(e), a.name, a.dtype, a.input_type)
            )
        self.aggs = agg.aggr_funcs

        # -- synthetic partial aggregate over the fact chain -----------
        from ballista_tpu.physical.aggregate import AggregateMode

        fact_input = self.fact_plan
        for f in fact_filters:
            fact_input = FilterExec(fact_input, f)
        syn = HashAggregateExec(
            AggregateMode.PARTIAL,
            fact_input,
            [(px.ColumnExpr(self.fact_key, fact_key_idx), self.fact_key)],
            syn_aggs,
        )
        # float_bits=False: the fact-agg readback/row math addresses one row
        # per state FIELD (_score_row, _decode); the bijected f64 min/max
        # states occupy two key-plane rows, which this path cannot carry.
        # Float min/max here keeps the documented f32 semantics.
        self.inner = FusedAggregateStage(syn, float_bits=False)
        # chunk partials must BE group partials (member mask / top-k index
        # group space); widen L1 to the longest key run
        self.inner.sorted_cover_max = True
        n_fact = self.fact_plan.output_partitioning().partition_count()
        if n_driven != n_fact:
            # stripe fact partitions over the driven partitions so every
            # fact row is read exactly once (n_driven=1: read them all)
            self.inner.scan_stride = n_driven
        if not self.inner.cacheable:
            raise UnsupportedOnDevice("fact side not cacheable")
        if self.secondary is not None:
            # F2 (the secondary fact key, e.g. l_suppkey) as a SCAN-space
            # column: compiling it registers it with the column loader, and
            # the derived-column hook materializes the static mapped S_ATTR
            # per row alongside the resident tiles
            sec = self.secondary
            f2_fact_idx = self.fact_plan.schema().names.index(sec["f2"])
            f2_scan = substitute_columns(
                px.ColumnExpr(sec["f2"], f2_fact_idx), self.inner.input_to_scan
            )
            if not isinstance(f2_scan, px.ColumnExpr):
                raise UnsupportedOnDevice("secondary fact key is not a column")
            cv = self.inner.compiler.compile(f2_scan)
            if cv.kind == "code":
                raise UnsupportedOnDevice("string secondary fact key")
            sec["f2_scan_idx"] = f2_scan.index
            self._sec_map = None  # (sorted base S_KEYs, their S_ATTRs)
            self.inner.derive_columns["sec_attr"] = self._derive_sec_attr
        self.partial_schema = FusedAggregateStage._partial_schema(agg)
        # planner-provided Sort+Limit epilogue (physical/planner.py)
        self.topk = getattr(agg, "_topk_pushdown", None)
        self.partitions = n_driven
        if self.topk is not None and (
            self.partitions != 1
            or self.aggs[self.topk["agg_index"]].fn != "sum"
            or self.topk["k"] > (1 << 16)
            or all(src is not FACT_KEY for src, _ in self.group_layout)
        ):
            # per-partition partial sums cannot drive a global top-k, the
            # score must be a plain SUM state, the candidate pool is capped
            # at 64k groups, and — critically — the output groups must BE
            # the fact keys: when the query groups by dim attributes only
            # (q10 groups by customer), many keys fold into one group in the
            # final merge and a per-key top-k ranks the wrong thing. Fall
            # back to the member-select readback (still correct, larger d2h)
            self.topk = None
        self._dim_cache: Optional[dict] = None
        self._prepared: Dict[int, dict] = {}
        self._fact_step = None
        self._sec_cache: Optional[dict] = None
        self._sec_step = None
        if self.secondary is not None and any(self.inner.int_exact):
            # secondary-mode reductions span the whole partition in one
            # jnp.sum; int32 accumulation could overflow silently
            raise UnsupportedOnDevice("int-exact aggregate in secondary mode")

    # ------------------------------------------------------------------
    def _detect_secondary(self, path, offending, join, fact_side, fact_names):
        """q5 shape: ONE upper join keyed on a fact column, adjacent to the
        fact join, whose other side is an unfiltered scan chain (the
        secondary dim), with exactly one extra key pair coupling a PRIMARY
        column to a secondary column:

            J2: [fact.F2 = sec.S_KEY, prim.P = sec.S_ATTR]

        The aggregation then runs per S_ATTR value on device: a STATIC
        mapped column M[row] = S_ATTR of row's F2 (valid because the
        secondary base is unfiltered) compared against the per-rank primary
        coupling value and the query-time allowed S_ATTR set. Joins above
        J2 fold into the secondary plan (supplier * nation * region for q5)
        and must not touch fact or primary columns. Raises to fall back."""
        from ballista_tpu.logical.plan import JoinType

        if offending != [len(path) - 1]:
            raise UnsupportedOnDevice("fact-column upper join not adjacent")
        j2, side2 = path[-1]
        if j2.join_type != JoinType.INNER or j2.filter is not None:
            raise UnsupportedOnDevice("secondary join shape")
        if side2 != "left" or any(s != "left" for _j, s in path):
            # fact+primary under j2.left keeps the secondary block a suffix
            # of the flattened schema
            raise UnsupportedOnDevice("secondary fold needs left-leaning joins")
        sec_base = j2.right
        if _scan_chain_leaf(sec_base) is None:
            raise UnsupportedOnDevice("secondary side is not a scan chain")
        node = sec_base
        while isinstance(node, (ProjectionExec, CoalesceBatchesExec, FilterExec)):
            if isinstance(node, FilterExec):
                # the static map must not depend on query-time predicates
                raise UnsupportedOnDevice("filtered secondary base")
            node = node.input
        sec_names = set(sec_base.schema().names)
        prim_plan = join.left if fact_side == "right" else join.right
        prim_names = set(prim_plan.schema().names)
        f2 = s_key = p = s_attr = None
        for ln, rn in j2.on:
            lef, rig = (ln, rn) if rn in sec_names else (rn, ln)
            if rig not in sec_names:
                raise UnsupportedOnDevice("secondary join key resolution")
            if lef in fact_names:
                if f2 is not None:
                    raise UnsupportedOnDevice("two fact-keyed pairs")
                f2, s_key = lef, rig
            elif lef in prim_names:
                if p is not None:
                    raise UnsupportedOnDevice("two coupling pairs")
                p, s_attr = lef, rig
            else:
                raise UnsupportedOnDevice("secondary join key from unknown side")
        if f2 is None or p is None:
            raise UnsupportedOnDevice("secondary join missing fact key or coupling")
        if not pa.types.is_integer(prim_plan.schema().field(p).type):
            raise UnsupportedOnDevice("coupling column must be integer")
        for j, _s in path[:-1]:
            for ln, rn in j.on:
                if {ln, rn} & (fact_names | prim_names):
                    raise UnsupportedOnDevice("upper join not secondary-only")
        sec_plan = sec_base
        for j, s in reversed(path[:-1]):
            children = [j.left, j.right]
            children[0 if s == "left" else 1] = sec_plan
            sec_plan = j.with_children(children)
        self.secondary = {
            "plan": sec_plan,
            "base": sec_base,
            "f2": f2,
            "s_key": s_key,
            "p": p,
            "s_attr": s_attr,
            "sec_start": len(j2.left.schema()),
        }

    # ------------------------------------------------------------------
    def _ensure_sec_map(self, ctx) -> None:
        """Static secondary mapping: sorted base S_KEYs and their S_ATTRs.
        Valid across queries because the base chain is unfiltered."""
        if self._sec_map is not None:
            return
        from ballista_tpu.physical.plan import collect_all

        sec = self.secondary
        base = collect_all(sec["base"], ctx)
        if base.num_rows > MAX_DIM_ROWS:
            raise UnsupportedOnDevice("secondary base too large")
        k = base.column(sec["s_key"]).to_numpy(zero_copy_only=False)
        a = base.column(sec["s_attr"]).to_numpy(zero_copy_only=False)
        if not (np.issubdtype(k.dtype, np.integer) and np.issubdtype(a.dtype, np.integer)):
            raise UnsupportedOnDevice("secondary keys must be integers")
        if len(a) and int(a.min()) < 0:
            raise UnsupportedOnDevice("negative secondary attribute")
        order = np.argsort(k, kind="stable")
        ks = k[order]
        if len(np.unique(ks)) != len(ks):
            raise UnsupportedOnDevice("secondary key not unique")
        self._sec_map = (ks.astype(np.int64), a[order].astype(np.int32))

    def _derive_sec_attr(self, npcols) -> np.ndarray:
        """Row-space static mapped column: S_ATTR of each row's F2 value
        (-1 when the base holds no such key — the row can never qualify)."""
        keys, attrs = self._sec_map
        f2 = npcols[self.secondary["f2_scan_idx"]].astype(np.int64)
        if len(keys) == 0:
            return np.full(len(f2), -1, dtype=np.int32)
        pos = np.clip(np.searchsorted(keys, f2), 0, len(keys) - 1)
        matched = keys[pos] == f2
        return np.where(matched, attrs[pos], -1).astype(np.int32)

    def _sec_side(self, ctx) -> dict:
        """Query-time secondary plan: allowed S_ATTR classes and the group
        key values attached to each. Declines when qualification is not a
        pure function of S_ATTR (the static map cannot express per-key
        filtering) or when group values are not unique per class."""
        if self._sec_cache is not None:
            return self._sec_cache
        with self.inner._prepare_lock:
            return self._sec_side_locked(ctx)

    def _sec_side_locked(self, ctx) -> dict:
        if self._sec_cache is not None:
            return self._sec_cache
        from ballista_tpu.physical.plan import collect_all

        sec = self.secondary
        self._ensure_sec_map(ctx)
        base_keys, base_attrs = self._sec_map
        table = collect_all(sec["plan"], ctx)
        attrs = table.column(sec["s_attr"]).to_numpy(zero_copy_only=False)
        keys = table.column(sec["s_key"]).to_numpy(zero_copy_only=False)
        pairs = np.unique(np.stack([attrs.astype(np.int64), keys.astype(np.int64)]), axis=1)
        if pairs.shape[1] != len(attrs):
            # duplicate (attr, key) rows: an upper secondary join multiplies
            # supplier rows, so each fact row should count more than once —
            # the per-class device mask cannot express that
            raise UnsupportedOnDevice("secondary plan multiplies rows")
        allowed, sec_counts = np.unique(pairs[0], return_counts=True)
        b_allowed, b_counts = np.unique(
            base_attrs[np.isin(base_attrs, allowed.astype(np.int32))],
            return_counts=True,
        )
        if not (
            len(allowed) == len(b_allowed)
            and (allowed == b_allowed).all()
            and (sec_counts == b_counts).all()
        ):
            raise UnsupportedOnDevice("secondary qualification not attr-pure")
        if len(allowed) > 256:
            raise UnsupportedOnDevice("too many secondary classes")
        # group values: unique per class, gathered in `allowed` order.
        # First-occurrence rows come from np.unique (a per-row Python loop
        # here would take ~10s on an SF=100-sized secondary table).
        group_values = {}
        uniq_attrs, first_idx = np.unique(attrs.astype(np.int64), return_index=True)
        first_row_for_attr = dict(
            zip(uniq_attrs.tolist(), first_idx.tolist())
        )
        for name, _out in sec["group_cols"]:
            col = table.column(name)
            enc = pc.dictionary_encode(col.combine_chunks() if isinstance(col, pa.ChunkedArray) else col)
            codes = enc.indices.to_numpy(zero_copy_only=False)
            if len(np.unique(np.stack([attrs.astype(np.int64), codes.astype(np.int64)]), axis=1)[0]) != len(allowed):
                raise UnsupportedOnDevice("group key not unique per secondary class")
            take = pa.array([first_row_for_attr[int(v)] for v in allowed], type=pa.int64())
            group_values[name] = col.take(take) if not isinstance(col, pa.ChunkedArray) else col.combine_chunks().take(take)
        out = {"allowed": allowed.astype(np.int32), "group_values": group_values}
        if ctx.config.device_cache():
            self._sec_cache = out
        return out

    def _build_sec_step(self):
        """Per-class masked full reductions: ONE jit call computes every
        aggregate state for every allowed S_ATTR class. GA is padded to a
        power of two (sentinel -2 never matches) to bound retracing."""
        import jax
        import jax.numpy as jnp

        inner = self.inner
        filter_masks = inner.filter_masks

        from ballista_tpu.ops.stage import jnp_expand_clen

        def step_sec(L1, cols, aux, clen, m_tiles, p_rank, allowed):
            cols = widen_cols(cols)  # narrow residency -> canonical dtypes
            m_tiles = m_tiles.astype(jnp.int32)  # derived tiles ride narrow
            mask0 = jnp_expand_clen(clen, L1)
            for fm in filter_masks:
                mask0 = jnp.logical_and(mask0, fm(cols, aux))
            outs = []
            for g in range(allowed.shape[0]):
                a = allowed[g]
                m = jnp.logical_and(mask0, m_tiles == a)
                # coupling: the rank's primary value must equal the class
                # (non-member ranks carry -1 and never match)
                m = jnp.logical_and(m, (p_rank == a)[:, None])
                outs.append(
                    inner._emit_rows(
                        cols, aux, m,
                        counts=jnp.sum(m, dtype=jnp.int32),
                        reduce_sum=lambda v, zero: jnp.sum(v),
                        reduce_extreme=lambda v, fill, red: red(v),
                    )
                )
            return jnp.stack(outs, axis=1)  # [R_packed, GA_pad]

        # AOT disk tier (ISSUE 10 satellite, PR 8 residue): factagg steps
        # reload as compile_hit_disk in a cold process instead of retracing
        from ballista_tpu.ops import aotcache

        return aotcache.wrap_step(self, "factagg_sec", step_sec,
                                  static_argnums=(0,))

    def _run_secondary(self, ent: dict, ctx) -> pa.Table:
        import jax.numpy as jnp

        sec = self.secondary
        info = self._sec_side(ctx)
        prim = self._dim_side(ctx)
        if (
            ent["kind"] == "empty"
            or len(info["allowed"]) == 0
            or prim["table"].num_rows == 0
        ):
            return self.partial_schema.empty_table()
        # per-rank coupling value from the primary side (-1 = no match)
        p_col = prim["table"].column(sec["p"]).to_numpy(zero_copy_only=False)
        if not np.issubdtype(p_col.dtype, np.integer):
            raise UnsupportedOnDevice("coupling column must be integer")
        rank_keys = ent["rank_keys"]
        pos = np.clip(
            np.searchsorted(prim["keys_sorted"], rank_keys),
            0, max(0, len(prim["keys_sorted"]) - 1),
        )
        matched = prim["keys_sorted"][pos] == rank_keys
        p_sorted = p_col[prim["order"]]
        p_rank = np.where(matched, p_sorted[pos], -1).astype(np.int32)

        GA = len(info["allowed"])
        ga_pad = 1
        while ga_pad < GA:
            ga_pad <<= 1
        allowed_pad = np.full(ga_pad, -2, dtype=np.int32)
        allowed_pad[:GA] = info["allowed"]
        if self._sec_step is None:
            self._sec_step = self._build_sec_step()
        aux = [jnp.asarray(a) for a in self.inner.compiler.build_aux()]
        packed = np.asarray(
            self._sec_step(
                ent["layout"].L1, ent["cols"], aux, ent["clen"],
                ent["derived"]["sec_attr"],
                jnp.asarray(p_rank), jnp.asarray(allowed_pad),
            )
        )
        record_readback(packed.shape[-1], packed.nbytes)
        rows = self._decode(packed)
        counts = rows[0][:GA]
        keep = counts > 0
        fields = list(self.partial_schema)
        arrays: List[pa.Array] = []
        fi = 0
        keep_idx = pa.array(np.flatnonzero(keep).astype(np.int64))
        for name, _out in sec["group_cols"]:
            f = fields[fi]
            arr = info["group_values"][name].take(keep_idx)
            if arr.type != f.type:
                arr = pc.cast(arr, f.type)
            arrays.append(arr)
            fi += 1
        state_rows = rows[1:]
        ri = 0
        nonempty = counts[keep]
        for a in self.aggs:
            for _sf in a.state_fields():
                f = fields[fi]
                raw = state_rows[ri][:GA][keep]
                arrays.append(state_column(a, raw, f.type, nonempty == 0))
                ri += 1
                fi += 1
        return pa.table(arrays, schema=self.partial_schema)

    # ------------------------------------------------------------------
    def _score_row(self) -> int:
        """Logical result-row index of the top-k score column (the j-th
        aggregate's first state row; row 0 is counts)."""
        row = 1
        for a in self.aggs[: self.topk["agg_index"]]:
            row += len(a.state_fields())
        return row

    def _build_fact_step(self):
        import jax
        import jax.numpy as jnp

        core = self.inner._sorted_core()
        # positions of each logical result row inside the packed f32 stack
        # (int32 rows occupy two hi/lo rows, see stage.py::_stack_rows)
        pos = packed_positions(self.inner._int_rows)

        if self.topk is not None:
            score_logical = self._score_row()
            score_row = pos[score_logical]
            score_is_int = self.inner._int_rows[score_logical]
            descending = self.topk["descending"]
            k = min(max(4 * self.topk["k"], TOPK_POOL), 1 << 16)

            def two_stage_topk(masked, kk):
                """Exact top-k via block maxima: a block holding a true
                top-k element must rank in the top k blocks by max (k
                distinct larger elements would otherwise exist). Avoids
                lax.top_k over the full G (measured ~70ms at G=1.5M; this
                is ~2ms)."""
                n = masked.shape[0]
                B = 128
                if n < kk * B:
                    return jax.lax.top_k(masked, kk)
                npad = -(-n // B) * B
                m2 = jnp.pad(masked, (0, npad - n),
                             constant_values=-jnp.inf).reshape(-1, B)
                bmax = jnp.max(m2, axis=1)
                _, bidx = jax.lax.top_k(bmax, kk)
                cand = m2[bidx].reshape(-1)  # [kk * B]
                vals, ci = jax.lax.top_k(cand, kk)
                gidx = bidx[ci // B] * B + ci % B
                return vals, gidx

            def step_topk(L1, cols, aux, clen, member_bits):
                stacked = core(L1, cols, aux, clen)  # [R_packed, G]
                G = stacked.shape[1]
                # little-endian bit unpack (host: np.packbits bitorder="little")
                bits = (member_bits[:, None] >> jnp.arange(8, dtype=jnp.uint8)) & 1
                member = bits.reshape(-1)[:G]
                counts = jnp_unpack_i32(stacked[pos[0]], stacked[pos[0] + 1])
                valid = jnp.logical_and(member > 0, counts > 0)
                if score_is_int:
                    # decode BOTH halves — ranking by the hi row alone would
                    # collapse every sum within a 65536 bucket into a tie
                    score = jnp_unpack_i32(
                        stacked[score_row], stacked[score_row + 1]
                    ).astype(jnp.float32)
                else:
                    score = stacked[score_row]
                if not descending:
                    score = -score
                masked = jnp.where(valid, score, -jnp.inf)
                kk = min(k, G)
                _, idx = two_stage_topk(masked, kk)
                sel = jnp.take(stacked, idx, axis=1)
                # single readback: [R_packed + 4, kk] (d2h latency is ~65ms
                # per transfer on the relay — never return multiple arrays).
                # idx travels as two exact f32 halves: a plain f32 cast loses
                # exactness above 2^24 groups.
                idx32 = idx.astype(jnp.int32)
                return jnp.concatenate(
                    [
                        sel,
                        jnp.take(masked, idx)[None, :],
                        (idx32 >> 16).astype(jnp.float32)[None, :],
                        (idx32 & 0xFFFF).astype(jnp.float32)[None, :],
                        jnp.take(valid, idx).astype(jnp.float32)[None, :],
                    ]
                )

            from ballista_tpu.ops import aotcache

            return aotcache.wrap_step(self, "factagg_topk", step_topk,
                                      static_argnums=(0,))

        def step_select(L1, cols, aux, clen, positions):
            stacked = core(L1, cols, aux, clen)
            return jnp.take(stacked, positions, axis=1)

        from ballista_tpu.ops import aotcache

        return aotcache.wrap_step(self, "factagg_select", step_select,
                                  static_argnums=(0,))

    # ------------------------------------------------------------------
    def _dim_side(self, ctx) -> dict:
        """Execute (+ cache, if enabled) the dim side; build key->row index.
        Serialized with the stage's prepare lock: concurrent first-touch
        partitions must not each collect the dim plan."""
        if self._dim_cache is not None:
            return self._dim_cache
        with self.inner._prepare_lock:
            return self._dim_side_locked(ctx)

    def _dim_side_locked(self, ctx) -> dict:
        if self._dim_cache is not None:
            return self._dim_cache
        from ballista_tpu.physical.plan import collect_all

        table = collect_all(self.dim_plan, ctx)
        if table.num_rows > MAX_DIM_ROWS:
            raise UnsupportedOnDevice("dim side too large")
        keys = table.column(self.dim_key)
        if keys.null_count:
            mask = pc.is_valid(keys)
            table = table.filter(mask)
            keys = table.column(self.dim_key)
        kn = keys.to_numpy(zero_copy_only=False)
        if len(np.unique(kn)) != len(kn):
            raise UnsupportedOnDevice("dim join key not unique")
        order = np.argsort(kn, kind="stable")
        out = {"table": table, "keys_sorted": kn[order], "order": order}
        if ctx.config.device_cache():
            self._dim_cache = out
        return out

    def _prepare(self, partition: int, ctx) -> dict:
        ent = self._prepared.get(partition)
        if ent is not None:
            from ballista_tpu.ops.runtime import touch_residency

            touch_residency(self, partition)  # LRU recency for eviction
            return ent
        # concurrent executor task threads: serialize prepare (shared
        # growing dictionaries / compiled-step slots), same as the inner
        # stage's own lock
        with self.inner._prepare_lock:
            return self._prepare_locked(partition, ctx)

    def _prepare_locked(self, partition: int, ctx) -> dict:
        ent = self._prepared.get(partition)
        if ent is not None:
            return ent
        if self.secondary is not None:
            self._ensure_sec_map(ctx)  # the derived column needs the map
        ent = self.inner._prepare_partition_sorted(partition, ctx)
        use_cache = ctx.config.device_cache()
        if ent["kind"] == "sorted":
            layout = ent["layout"]
            if not layout.one_chunk_per_group:
                raise UnsupportedOnDevice("fact key runs exceed one chunk")
            kv = ent["key_values"][0]
            kv_np = (kv.to_numpy(zero_copy_only=False)
                     if isinstance(kv, (pa.Array, pa.ChunkedArray)) else np.asarray(kv))
            ent["rank_keys"] = kv_np
            ent["rank_order"] = np.argsort(kv_np, kind="stable")
        if self._fact_step is None:
            self._fact_step = self._build_fact_step()
        if use_cache:
            from ballista_tpu.ops.runtime import (
                entry_device_bytes,
                reserve_and_pin,
            )

            # ballista.tpu.device_cache=false: recompute per query instead
            # of pinning the [V, L1] tiles in HBM. Cached entries also count
            # against the global HBM budget; beyond it, stream per query.
            reserve_and_pin(
                self,
                partition,
                ent,
                self._prepared,
                entry_device_bytes(ent),
                ctx.config.tpu_hbm_budget(),
            )
        return ent

    # ------------------------------------------------------------------
    def run(self, partition: int, ctx) -> pa.Table:
        import jax.numpy as jnp

        if self.secondary is not None:
            return self._run_secondary(self._prepare(partition, ctx), ctx)
        dim = self._dim_side(ctx)
        if self.topk is None and dim["table"].num_rows > MAX_SELECT_MEMBERS:
            # members <= dim rows: decline BEFORE prepare pays the fact
            # upload (the per-query check below would fire after it)
            raise UnsupportedOnDevice("member-select dim side too large")
        ent = self._prepare(partition, ctx)
        if ent["kind"] == "empty" or dim["table"].num_rows == 0:
            return self.partial_schema.empty_table()

        rank_keys, rank_order = ent["rank_keys"], ent["rank_order"]
        sorted_keys = rank_keys[rank_order]
        pos = np.searchsorted(sorted_keys, dim["keys_sorted"])
        pos = np.clip(pos, 0, len(sorted_keys) - 1)
        matched = sorted_keys[pos] == dim["keys_sorted"]
        member_ranks = rank_order[pos[matched]]
        # dim row index (into the collected dim table) per matched rank
        dim_rows_for_rank = dim["order"][matched]

        aux = [jnp.asarray(a) for a in self.inner.compiler.build_aux()]
        G = ent["n_groups"]
        if self.topk is not None:
            member = np.zeros(G, dtype=bool)
            member[member_ranks] = True
            bits = np.packbits(member, bitorder="little")
            packed = np.asarray(
                self._fact_step(ent["layout"].L1, ent["cols"], aux,
                                ent["clen"], jnp.asarray(bits))
            )
            record_readback(packed.shape[-1], packed.nbytes)
            sel, scores, valid = packed[:-4], packed[-4], packed[-1] > 0
            idx = (
                packed[-3].astype(np.int64) * 65536
                + packed[-2].astype(np.int64)
            )
            sel, idx, scores = sel[:, valid], idx[valid], scores[valid]
            # A tie at the k-th score reaching the candidate-pool edge means
            # the pool may not contain every qualifying group. Two causes:
            # - strict (secondary sort keys): groups outside the pool could
            #   legitimately outrank pool members on the tie-breakers.
            # - integer SUM scores (ADVICE r2): ranking casts the exact int
            #   to f32; above 2^24 distinct sums collapse into FALSE ties.
            #   f32 rounding is monotone, so a wrongly-excluded group forces
            #   f32(kth) <= f32(pool edge) — exactly this condition. Within
            #   the pool the upper Sort re-orders on exact decoded ints, so
            #   only pool exclusion needs the fallback.
            k = self.topk["k"]
            tie_val = scores[min(k - 1, len(scores) - 1)] if len(scores) else 0.0
            # int scores below 2^24 are exact in f32: a boundary tie there
            # is GENUINE, and non-strict genuine ties may break arbitrarily
            # — only the collapse-prone magnitudes force the fallback
            score_exact_risk = (
                self.inner._int_rows[self._score_row()]
                and abs(float(tie_val)) >= float(1 << 24)
            )
            if (
                (self.topk.get("strict") or score_exact_risk)
                and valid.all()
                and len(scores) > k
                and tie_val <= scores[-1]
            ):
                raise UnsupportedOnDevice("top-k tie at candidate boundary")
            # map selected ranks back to dim rows
            rank_to_dim = np.full(G, -1, dtype=np.int64)
            rank_to_dim[member_ranks] = dim_rows_for_rank
            dim_idx = rank_to_dim[idx]
            return self._assemble(sel, idx, dim_idx, dim["table"], ent)
        positions = member_ranks.astype(np.int64)
        if len(positions) == 0:
            return self.partial_schema.empty_table()
        if len(positions) > MAX_SELECT_MEMBERS:
            # the non-topk epilogue reads back [state_rows, members] — at
            # dim cardinalities past this the transfer (and per-query host
            # re-group) costs more than the host path; decline
            raise UnsupportedOnDevice("member-select readback too large")
        # bucket the gather width: an exact-length positions array would
        # recompile step_select for every distinct member count
        n_pos = len(positions)
        pos_pad = pad_to(
            positions.astype(np.int32), bucket_rows(n_pos, 16), 0
        )
        sel = np.asarray(
            self._fact_step(ent["layout"].L1, ent["cols"], aux, ent["clen"],
                            jnp.asarray(pos_pad))
        )[:, :n_pos]
        record_readback(sel.shape[-1], sel.nbytes)
        rows = self._decode(sel)
        keep = rows[0] > 0
        return self._assemble_decoded(
            [r[keep] for r in rows], positions[keep], dim_rows_for_rank[keep],
            dim["table"], ent,
        )

    def _decode(self, stacked: np.ndarray) -> List[np.ndarray]:
        return [
            r if r.dtype == np.int64 else r.astype(np.float64)
            for r in decode_packed_rows(stacked, self.inner._int_rows)
        ]

    def _assemble(self, sel, ranks, dim_idx, dim_table, ent) -> pa.Table:
        rows = self._decode(sel)
        counts = rows[0]
        keep = counts > 0
        return self._assemble_decoded(
            [r[keep] for r in rows], ranks[keep], dim_idx[keep], dim_table, ent
        )

    def _assemble_decoded(self, rows, ranks, dim_idx, dim_table, ent) -> pa.Table:
        """Partial-state table for the selected groups: group keys in the
        original order (fact key value / dim attachments), then states."""
        counts, states = rows[0], rows[1:]
        fields = list(self.partial_schema)
        arrays: List[pa.Array] = []
        take_dim = pa.array(dim_idx.astype(np.int64))
        fi = 0
        for src, _name in self.group_layout:
            f = fields[fi]
            if src is FACT_KEY:
                arr = pa.array(ent["rank_keys"][ranks])
            else:
                arr = dim_table.column(src).take(take_dim)
                if isinstance(arr, pa.ChunkedArray):
                    arr = arr.combine_chunks()
            if arr.type != f.type:
                arr = pc.cast(arr, f.type)
            arrays.append(arr)
            fi += 1
        si = 0
        nonempty = counts > 0  # all true post-filter; kept for min/max nulls
        for a in self.aggs:
            for _ in a.state_fields():
                f = fields[fi]
                raw = states[si]
                arrays.append(state_column(a, raw, f.type, ~nonempty))
                si += 1
                fi += 1
        return pa.table(arrays, schema=self.partial_schema)

"""Fact-side aggregation pushdown: Aggregate over a PK-FK join tree.

The reference executes Aggregate(Join(dim, fact)) by materializing the join
then hash-aggregating the joined rows (DataFusion HashJoinExec +
HashAggregateExec; serde rust/core/src/serde/physical_plan/from_proto.rs:
176-214, 370-384). On a relay-attached TPU that shape loses: the join output
is volatile, so every query pays encode + transfer for 6M+ joined rows.

TPU-first redesign (eager-aggregation + semi-join membership):

  host      dim side of the join (small) executes as-is; its join-key
            column must be unique (checked) -> the join attaches at most
            one dim row per fact row, so aggregates distribute over the
            join. Build a per-rank membership vector over the fact table's
            cached sorted-key layout.
  device    ONE jit call over the resident fact layout: fused filters +
            per-key partial aggregates (ops/stage.py sorted core), mask by
            membership, and — when the planner annotated a Sort+Limit
            epilogue — lax.top_k over the score column so the readback is
            K rows, not G. d2h latency (~65ms) + 28MB/s bandwidth make
            readback size the whole game.
  host      attach dim attribute columns to the selected keys, emit the
            aggregate's partial-state rows; the ordinary Final merge, Sort
            and Limit operators above run unchanged on K rows.

Pattern matched: HashAggregateExec[single|partial] over
 [Filter/Projection/Coalesce]* -> an INNER hash-join tree in which the
largest file-backed scan chain (the fact) sits anywhere reachable through
inner joins — directly (q3: orders x lineitem) or nested (q10:
((customer x orders) x lineitem) x nation). The fact's own join must be a
single equi-key with no residual filter; joins between it and the root must
not be keyed on fact columns (q5 joins supplier on l_suppkey — host path).
Fact-side group keys must be the join key; dim-side group keys are attached
post-aggregation; all aggregate inputs must be fact-side expressions. The
device top-k epilogue additionally requires the fact key among the group
keys (one output group per key); dim-only grouping (q10) uses the
member-select readback and the ordinary final merge re-groups.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from ballista_tpu.ops.runtime import UnsupportedOnDevice
from ballista_tpu.ops.stage import (
    FusedAggregateStage,
    _SCAN_TYPES,
    decode_packed_rows,
    jnp_unpack_i32,
    packed_positions,
    substitute_columns,
)
from ballista_tpu.physical import expr as px
from ballista_tpu.physical.basic import (
    CoalesceBatchesExec,
    FilterExec,
    ProjectionExec,
)

# dim sides larger than this are not "dimension tables"; let the host join
# handle them
MAX_DIM_ROWS = 4_000_000

# group_layout marker for "this output column is the fact join key" — a
# sentinel object so it can never collide with a real dim column name
FACT_KEY = object()
# candidate multiplier for the top-k epilogue: secondary sort keys and f32
# score ties are resolved host-side within this pool
TOPK_POOL = 64


def _scan_chain_leaf(node):
    while isinstance(node, (FilterExec, ProjectionExec, CoalesceBatchesExec)):
        node = node.input
    return node if isinstance(node, _SCAN_TYPES) else None


def _chain_bytes(leaf) -> int:
    files = getattr(getattr(leaf, "source", None), "files", None)
    if not files:
        return 0
    return sum(os.path.getsize(f) for f in files if os.path.exists(f))


def _columns_of(e: px.PhysicalExpr, acc: List[int]) -> None:
    if isinstance(e, px.ColumnExpr):
        acc.append(e.index)
    for name in ("left", "right", "expr", "low", "high", "base", "else_expr"):
        c = getattr(e, name, None)
        if isinstance(c, px.PhysicalExpr):
            _columns_of(c, acc)
    for a in getattr(e, "args", []) or []:
        _columns_of(a, acc)
    for w, t in getattr(e, "when_then", []) or []:
        _columns_of(w, acc)
        _columns_of(t, acc)


class FactAggregateStage:
    """Device pipeline for one aggregate-over-join. Built via try_build."""

    @staticmethod
    def try_build(agg) -> Optional["FactAggregateStage"]:
        try:
            return FactAggregateStage(agg)
        except UnsupportedOnDevice:
            return None

    def __init__(self, agg) -> None:
        from ballista_tpu.logical.plan import JoinType
        from ballista_tpu.physical.aggregate import AggregateFunc, HashAggregateExec
        from ballista_tpu.physical.join import HashJoinExec

        if agg.mode.value not in ("single", "partial"):
            raise UnsupportedOnDevice("fact-agg needs single/partial mode")

        # -- walk down to the join ------------------------------------
        node = agg.input
        stack: List[Tuple[str, object]] = []
        while isinstance(node, (FilterExec, ProjectionExec, CoalesceBatchesExec)):
            if isinstance(node, FilterExec):
                stack.append(("filter", node.predicate))
            elif isinstance(node, ProjectionExec):
                stack.append(("project", node.exprs))
            node = node.input
        if not isinstance(node, HashJoinExec) or node.join_type != JoinType.INNER:
            raise UnsupportedOnDevice("row source is not an inner hash join")
        root = node

        # -- locate the fact scan chain anywhere in the inner-join tree --
        # Paths may only cross INNER HashJoinExec nodes (their output schema
        # is the concatenation of their children, so removing the fact block
        # keeps every other column's relative order); the fact is the
        # largest file-backed scan chain reachable that way (q10 nests
        # lineitem two joins deep).
        candidates: List[Tuple[list, HashJoinExec, str, int]] = []

        def dfs(j, path):
            for side in ("left", "right"):
                child = getattr(j, side)
                leaf = _scan_chain_leaf(child)
                if leaf is not None:
                    b = _chain_bytes(leaf)
                    if b > 0:
                        candidates.append((list(path), j, side, b))
                elif (
                    isinstance(child, HashJoinExec)
                    and child.join_type == JoinType.INNER
                    and child.filter is None
                ):
                    dfs(child, path + [(j, side)])

        dfs(root, [])
        if not candidates:
            raise UnsupportedOnDevice("no file-backed scan side")
        path, join, fact_side, _ = max(candidates, key=lambda c: c[3])
        if join.filter is not None or len(join.on) != 1:
            raise UnsupportedOnDevice("fact join shape (residual filter / multi-key)")
        self.fact_plan = getattr(join, fact_side)
        fact_n = len(self.fact_plan.schema())
        # joins between the root and the fact join run on the host over the
        # dim plan; they must not need fact columns (q5 joins supplier on
        # l_suppkey — that shape stays on the host path)
        fact_names = set(self.fact_plan.schema().names)
        for j, _side in path:
            for ln, rn in j.on:
                if ln in fact_names or rn in fact_names:
                    raise UnsupportedOnDevice("upper join keyed on a fact column")
        # offset of the fact block within the root's flattened schema
        fact_offset = 0
        for j, side in path + [(join, fact_side)]:
            if side == "right":
                fact_offset += len(j.left.schema())
        lkey, rkey = join.on[0]
        self.fact_key = lkey if fact_side == "left" else rkey
        self.dim_key = rkey if fact_side == "left" else lkey
        fact_key_idx = self.fact_plan.schema().names.index(self.fact_key)

        # -- dim plan: the join tree with the fact subtree removed ------
        replacement = join.left if fact_side == "right" else join.right
        for j, side in reversed(path):
            children = [j.left, j.right]
            children[0 if side == "left" else 1] = replacement
            replacement = j.with_children(children)
        self.dim_plan = replacement

        # -- re-express aggregate exprs over the root join schema -------
        join_schema = root.schema()
        mapping: List[px.PhysicalExpr] = [
            px.ColumnExpr(f.name, i) for i, f in enumerate(join_schema)
        ]
        above_filters: List[px.PhysicalExpr] = []
        for kind, payload in reversed(stack):
            if kind == "project":
                mapping = [substitute_columns(e, mapping) for e, _ in payload]
            else:
                above_filters.append(substitute_columns(payload, mapping))

        def side_of(e: px.PhysicalExpr) -> str:
            cols: List[int] = []
            _columns_of(e, cols)
            in_fact = [fact_offset <= c < fact_offset + fact_n for c in cols]
            if all(in_fact):
                return "fact"
            if not any(in_fact):
                return "dim"
            return "mixed"

        # fact-index remap: join-schema column -> fact-plan column
        fact_map: List[px.PhysicalExpr] = []
        for i, f in enumerate(join_schema):
            if fact_offset <= i < fact_offset + fact_n:
                fact_map.append(px.ColumnExpr(f.name, i - fact_offset))
            else:
                fact_map.append(px.LiteralExpr(None, pa.null()))

        def to_fact(e: px.PhysicalExpr) -> px.PhysicalExpr:
            return substitute_columns(e, fact_map)

        # group keys: the fact side may contribute only the join key; dim
        # keys become post-aggregation attachments
        self.group_layout: List[Tuple[str, Optional[str]]] = []
        for e, name in [(substitute_columns(e, mapping), n) for e, n in agg.group_exprs]:
            s = side_of(e)
            if s == "fact":
                if not (isinstance(e, px.ColumnExpr) and e.index - fact_offset == fact_key_idx):
                    raise UnsupportedOnDevice("fact-side group key is not the join key")
                self.group_layout.append((FACT_KEY, name))
            elif s == "dim" and isinstance(e, px.ColumnExpr):
                ri = e.index if e.index < fact_offset else e.index - fact_n
                dim_name = self.dim_plan.schema().names[ri]
                if dim_name != e.name:
                    raise UnsupportedOnDevice("dim column remap mismatch")
                self.group_layout.append((dim_name, name))
            else:
                raise UnsupportedOnDevice("unsupported group key shape")

        fact_filters = []
        for f in above_filters:
            if side_of(f) != "fact":
                raise UnsupportedOnDevice("non-fact filter above the join")
            fact_filters.append(to_fact(f))

        syn_aggs = []
        for a in agg.aggr_funcs:
            e = substitute_columns(a.expr, mapping)
            if side_of(e) not in ("fact",):
                raise UnsupportedOnDevice("aggregate input not on the fact side")
            syn_aggs.append(
                AggregateFunc(a.fn, to_fact(e), a.name, a.dtype, a.input_type)
            )
        self.aggs = agg.aggr_funcs

        # -- synthetic partial aggregate over the fact chain -----------
        from ballista_tpu.physical.aggregate import AggregateMode

        fact_input = self.fact_plan
        for f in fact_filters:
            fact_input = FilterExec(fact_input, f)
        syn = HashAggregateExec(
            AggregateMode.PARTIAL,
            fact_input,
            [(px.ColumnExpr(self.fact_key, fact_key_idx), self.fact_key)],
            syn_aggs,
        )
        self.inner = FusedAggregateStage(syn)
        # chunk partials must BE group partials (member mask / top-k index
        # group space); widen L1 to the longest key run
        self.inner.sorted_cover_max = True
        if not self.inner.cacheable:
            raise UnsupportedOnDevice("fact side not cacheable")
        self.partial_schema = FusedAggregateStage._partial_schema(agg)
        # planner-provided Sort+Limit epilogue (physical/planner.py)
        self.topk = getattr(agg, "_topk_pushdown", None)
        self.partitions = self.fact_plan.output_partitioning().partition_count()
        if self.topk is not None and (
            self.partitions != 1
            or self.aggs[self.topk["agg_index"]].fn != "sum"
            or self.topk["k"] > (1 << 16)
            or all(src is not FACT_KEY for src, _ in self.group_layout)
        ):
            # per-partition partial sums cannot drive a global top-k, the
            # score must be a plain SUM state, the candidate pool is capped
            # at 64k groups, and — critically — the output groups must BE
            # the fact keys: when the query groups by dim attributes only
            # (q10 groups by customer), many keys fold into one group in the
            # final merge and a per-key top-k ranks the wrong thing. Fall
            # back to the member-select readback (still correct, larger d2h)
            self.topk = None
        self._dim_cache: Optional[dict] = None
        self._prepared: Dict[int, dict] = {}
        self._fact_step = None

    # ------------------------------------------------------------------
    def _score_row(self) -> int:
        """Logical result-row index of the top-k score column (the j-th
        aggregate's first state row; row 0 is counts)."""
        row = 1
        for a in self.aggs[: self.topk["agg_index"]]:
            row += len(a.state_fields())
        return row

    def _build_fact_step(self):
        import jax
        import jax.numpy as jnp

        core = self.inner._sorted_core()
        # positions of each logical result row inside the packed f32 stack
        # (int32 rows occupy two hi/lo rows, see stage.py::_stack_rows)
        pos = packed_positions(self.inner._int_rows)

        if self.topk is not None:
            score_logical = self._score_row()
            score_row = pos[score_logical]
            score_is_int = self.inner._int_rows[score_logical]
            descending = self.topk["descending"]
            k = min(max(4 * self.topk["k"], TOPK_POOL), 1 << 16)

            def two_stage_topk(masked, kk):
                """Exact top-k via block maxima: a block holding a true
                top-k element must rank in the top k blocks by max (k
                distinct larger elements would otherwise exist). Avoids
                lax.top_k over the full G (measured ~70ms at G=1.5M; this
                is ~2ms)."""
                n = masked.shape[0]
                B = 128
                if n < kk * B:
                    return jax.lax.top_k(masked, kk)
                npad = -(-n // B) * B
                m2 = jnp.pad(masked, (0, npad - n),
                             constant_values=-jnp.inf).reshape(-1, B)
                bmax = jnp.max(m2, axis=1)
                _, bidx = jax.lax.top_k(bmax, kk)
                cand = m2[bidx].reshape(-1)  # [kk * B]
                vals, ci = jax.lax.top_k(cand, kk)
                gidx = bidx[ci // B] * B + ci % B
                return vals, gidx

            @jax.jit
            def step_topk(cols, aux, pad, member_bits):
                stacked = core(cols, aux, pad)  # [R_packed, G]
                G = stacked.shape[1]
                # little-endian bit unpack (host: np.packbits bitorder="little")
                bits = (member_bits[:, None] >> jnp.arange(8, dtype=jnp.uint8)) & 1
                member = bits.reshape(-1)[:G]
                counts = jnp_unpack_i32(stacked[pos[0]], stacked[pos[0] + 1])
                valid = jnp.logical_and(member > 0, counts > 0)
                if score_is_int:
                    # decode BOTH halves — ranking by the hi row alone would
                    # collapse every sum within a 65536 bucket into a tie
                    score = jnp_unpack_i32(
                        stacked[score_row], stacked[score_row + 1]
                    ).astype(jnp.float32)
                else:
                    score = stacked[score_row]
                if not descending:
                    score = -score
                masked = jnp.where(valid, score, -jnp.inf)
                kk = min(k, G)
                _, idx = two_stage_topk(masked, kk)
                sel = jnp.take(stacked, idx, axis=1)
                # single readback: [R_packed + 4, kk] (d2h latency is ~65ms
                # per transfer on the relay — never return multiple arrays).
                # idx travels as two exact f32 halves: a plain f32 cast loses
                # exactness above 2^24 groups.
                idx32 = idx.astype(jnp.int32)
                return jnp.concatenate(
                    [
                        sel,
                        jnp.take(masked, idx)[None, :],
                        (idx32 >> 16).astype(jnp.float32)[None, :],
                        (idx32 & 0xFFFF).astype(jnp.float32)[None, :],
                        jnp.take(valid, idx).astype(jnp.float32)[None, :],
                    ]
                )

            return step_topk

        @jax.jit
        def step_select(cols, aux, pad, positions):
            stacked = core(cols, aux, pad)
            return jnp.take(stacked, positions, axis=1)

        return step_select

    # ------------------------------------------------------------------
    def _dim_side(self, ctx) -> dict:
        """Execute (+ cache, if enabled) the dim side; build key->row index."""
        if self._dim_cache is not None:
            return self._dim_cache
        from ballista_tpu.physical.plan import collect_all

        table = collect_all(self.dim_plan, ctx)
        if table.num_rows > MAX_DIM_ROWS:
            raise UnsupportedOnDevice("dim side too large")
        keys = table.column(self.dim_key)
        if keys.null_count:
            mask = pc.is_valid(keys)
            table = table.filter(mask)
            keys = table.column(self.dim_key)
        kn = keys.to_numpy(zero_copy_only=False)
        if len(np.unique(kn)) != len(kn):
            raise UnsupportedOnDevice("dim join key not unique")
        order = np.argsort(kn, kind="stable")
        out = {"table": table, "keys_sorted": kn[order], "order": order}
        if ctx.config.device_cache():
            self._dim_cache = out
        return out

    def _prepare(self, partition: int, ctx) -> dict:
        ent = self._prepared.get(partition)
        if ent is not None:
            return ent
        ent = self.inner._prepare_partition_sorted(partition, ctx)
        use_cache = ctx.config.device_cache()
        if ent["kind"] == "sorted":
            layout = ent["layout"]
            if not layout.one_chunk_per_group:
                raise UnsupportedOnDevice("fact key runs exceed one chunk")
            kv = ent["key_values"][0]
            kv_np = (kv.to_numpy(zero_copy_only=False)
                     if isinstance(kv, (pa.Array, pa.ChunkedArray)) else np.asarray(kv))
            ent["rank_keys"] = kv_np
            ent["rank_order"] = np.argsort(kv_np, kind="stable")
        if self._fact_step is None:
            self._fact_step = self._build_fact_step()
        if use_cache:
            # ballista.tpu.device_cache=false: recompute per query instead
            # of pinning the [V, L1] tiles in HBM
            self._prepared[partition] = ent
        return ent

    # ------------------------------------------------------------------
    def run(self, partition: int, ctx) -> pa.Table:
        import jax.numpy as jnp

        dim = self._dim_side(ctx)
        ent = self._prepare(partition, ctx)
        if ent["kind"] == "empty" or dim["table"].num_rows == 0:
            return self.partial_schema.empty_table()

        rank_keys, rank_order = ent["rank_keys"], ent["rank_order"]
        sorted_keys = rank_keys[rank_order]
        pos = np.searchsorted(sorted_keys, dim["keys_sorted"])
        pos = np.clip(pos, 0, len(sorted_keys) - 1)
        matched = sorted_keys[pos] == dim["keys_sorted"]
        member_ranks = rank_order[pos[matched]]
        # dim row index (into the collected dim table) per matched rank
        dim_rows_for_rank = dim["order"][matched]

        aux = [jnp.asarray(a) for a in self.inner.compiler.build_aux()]
        G = ent["n_groups"]
        if self.topk is not None:
            member = np.zeros(G, dtype=bool)
            member[member_ranks] = True
            bits = np.packbits(member, bitorder="little")
            packed = np.asarray(
                self._fact_step(ent["cols"], aux, ent["pad"], jnp.asarray(bits))
            )
            sel, scores, valid = packed[:-4], packed[-4], packed[-1] > 0
            idx = (
                packed[-3].astype(np.int64) * 65536
                + packed[-2].astype(np.int64)
            )
            sel, idx, scores = sel[:, valid], idx[valid], scores[valid]
            # A tie at the k-th score reaching the candidate-pool edge means
            # the pool may not contain every qualifying group. Two causes:
            # - strict (secondary sort keys): groups outside the pool could
            #   legitimately outrank pool members on the tie-breakers.
            # - integer SUM scores (ADVICE r2): ranking casts the exact int
            #   to f32; above 2^24 distinct sums collapse into FALSE ties.
            #   f32 rounding is monotone, so a wrongly-excluded group forces
            #   f32(kth) <= f32(pool edge) — exactly this condition. Within
            #   the pool the upper Sort re-orders on exact decoded ints, so
            #   only pool exclusion needs the fallback.
            k = self.topk["k"]
            tie_val = scores[min(k - 1, len(scores) - 1)] if len(scores) else 0.0
            # int scores below 2^24 are exact in f32: a boundary tie there
            # is GENUINE, and non-strict genuine ties may break arbitrarily
            # — only the collapse-prone magnitudes force the fallback
            score_exact_risk = (
                self.inner._int_rows[self._score_row()]
                and abs(float(tie_val)) >= float(1 << 24)
            )
            if (
                (self.topk.get("strict") or score_exact_risk)
                and valid.all()
                and len(scores) > k
                and tie_val <= scores[-1]
            ):
                raise UnsupportedOnDevice("top-k tie at candidate boundary")
            # map selected ranks back to dim rows
            rank_to_dim = np.full(G, -1, dtype=np.int64)
            rank_to_dim[member_ranks] = dim_rows_for_rank
            dim_idx = rank_to_dim[idx]
            return self._assemble(sel, idx, dim_idx, dim["table"], ent)
        positions = member_ranks.astype(np.int64)
        if len(positions) == 0:
            return self.partial_schema.empty_table()
        sel = np.asarray(
            self._fact_step(
                ent["cols"], aux, ent["pad"], jnp.asarray(positions.astype(np.int32))
            )
        )
        rows = self._decode(sel)
        keep = rows[0] > 0
        return self._assemble_decoded(
            [r[keep] for r in rows], positions[keep], dim_rows_for_rank[keep],
            dim["table"], ent,
        )

    def _decode(self, stacked: np.ndarray) -> List[np.ndarray]:
        return [
            r if r.dtype == np.int64 else r.astype(np.float64)
            for r in decode_packed_rows(stacked, self.inner._int_rows)
        ]

    def _assemble(self, sel, ranks, dim_idx, dim_table, ent) -> pa.Table:
        rows = self._decode(sel)
        counts = rows[0]
        keep = counts > 0
        return self._assemble_decoded(
            [r[keep] for r in rows], ranks[keep], dim_idx[keep], dim_table, ent
        )

    def _assemble_decoded(self, rows, ranks, dim_idx, dim_table, ent) -> pa.Table:
        """Partial-state table for the selected groups: group keys in the
        original order (fact key value / dim attachments), then states."""
        counts, states = rows[0], rows[1:]
        fields = list(self.partial_schema)
        arrays: List[pa.Array] = []
        take_dim = pa.array(dim_idx.astype(np.int64))
        fi = 0
        for src, _name in self.group_layout:
            f = fields[fi]
            if src is FACT_KEY:
                arr = pa.array(ent["rank_keys"][ranks])
            else:
                arr = dim_table.column(src).take(take_dim)
                if isinstance(arr, pa.ChunkedArray):
                    arr = arr.combine_chunks()
            if arr.type != f.type:
                arr = pc.cast(arr, f.type)
            arrays.append(arr)
            fi += 1
        si = 0
        nonempty = counts > 0  # all true post-filter; kept for min/max nulls
        for a in self.aggs:
            for _ in a.state_fields():
                f = fields[fi]
                raw = states[si]
                if a.fn in ("min", "max"):
                    arr = pa.array(raw.astype(np.float64), mask=~nonempty)
                else:
                    arr = pa.array(raw.astype(np.float64))
                if arr.type != f.type:
                    arr = pc.cast(arr, f.type)
                arrays.append(arr)
                si += 1
                fi += 1
        return pa.table(arrays, schema=self.partial_schema)

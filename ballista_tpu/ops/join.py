"""Device join kernel: sort + vectorized binary search.

The TPU-native lowering of the PK-FK hash join (every TPC-H join): build-side
key codes are sorted on device, probe keys binary-search them
(jnp.searchsorted is branch-free and vectorizes on the VPU), equality checks
produce a match mask, and the matched build-row indices gather the build
columns. Requires unique build keys (primary keys) — the probe side keeps its
cardinality, so output shapes stay static. Duplicate build keys fall back to
the host sort-merge join (physical/joinutil.py), which shares the same key
normalization.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import numpy as np
import pyarrow as pa

from ballista_tpu.ops.runtime import bucket_rows, pad_to, readback


@functools.lru_cache(maxsize=None)
def _kernel():
    import jax
    import jax.numpy as jnp

    @jax.jit
    def join(build_codes, probe_codes, n_build):
        order = jnp.argsort(build_codes)
        sorted_b = build_codes[order]
        pos = jnp.searchsorted(sorted_b, probe_codes)
        pos_c = jnp.clip(pos, 0, build_codes.shape[0] - 1)
        match = jnp.logical_and(
            sorted_b[pos_c] == probe_codes, pos < n_build
        )
        build_idx = jnp.where(match, order[pos_c], -1)
        return build_idx

    return join


def device_join_indices(
    build_codes: np.ndarray, probe_codes: np.ndarray
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Per-probe matched build index (-1 = no match) computed on device.

    Returns (build_idx, match_mask) or None when the device path declines
    (duplicate build keys, code range too wide for int32).
    """
    import jax.numpy as jnp

    nb, np_ = len(build_codes), len(probe_codes)
    if nb == 0 or np_ == 0:
        return None
    if len(np.unique(build_codes)) != nb:
        return None  # duplicate build keys -> expansion needs dynamic shapes
    hi = max(int(build_codes.max()), int(probe_codes.max()) if np_ else 0)
    if hi >= 2**31 - 2:
        return None
    pad_code = np.int32(2**31 - 1)  # sorts last, never matches a probe
    b = jnp.asarray(
        pad_to(build_codes.astype(np.int32), bucket_rows(nb, 16), pad_code)
    )
    # null probe keys (-1) must not match; -1 would binary-search below all
    # valid codes and compare unequal, which is already a non-match
    p = jnp.asarray(pad_to(probe_codes.astype(np.int32), bucket_rows(np_, 16), -1))
    out = readback(_kernel()(b, p, nb))[:np_]
    return out, out >= 0


def try_device_inner_join(
    build: pa.Table,
    probe: pa.Table,
    build_keys: list,
    probe_keys: list,
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Returns (build_idx, probe_idx) row selections realizing the inner
    join, or None if the device path declines."""
    from ballista_tpu.physical.joinutil import combined_key_codes

    bcodes, pcodes = combined_key_codes(
        [build.column(k) for k in build_keys],
        [probe.column(k) for k in probe_keys],
    )
    res = device_join_indices(bcodes, pcodes)
    if res is None:
        return None
    build_idx, mask = res
    probe_rows = np.nonzero(mask)[0].astype(np.int64)
    return build_idx[mask].astype(np.int64), probe_rows

"""Device join kernel: sort + paired binary search with M:N multiplicity.

The TPU-native lowering of the hash join (every TPC-H join, primary-key or
not): build-side key codes are sorted on device ONCE (stable, so equal keys
keep build-row order), each probe key binary-searches the sorted plane twice
(jnp.searchsorted side='left'/'right' — branch-free, vectorizes on the VPU)
and the difference is that probe's match run-length. Duplicate build keys no
longer decline: run-lengths exclusive-scan into per-probe output offsets on
the host flatten, and matches materialize through a bounded-width gather
whose static width is the smallest admission tier
(ops/kernels.py::JOIN_MULTIPLICITY_TIERS) covering the observed maximum
multiplicity, keeping every program shape static. Shapes past the top tier
(or past the gather element cap) step aside to the host sort-merge join
(physical/joinutil.py) with a recorded reason; both paths share the same
key normalization and emit matches in the same order — probe-major, build
rows in stable sorted order within a probe key — so device results are
bit-identical to the host oracle, multiplicity and order included.

Every decline flows through the canonical kernels helpers AND
runtime.record_join_path, so bench.py's per-config join-path counters
(device / step_aside / host_fallback, with reasons) stay truthful.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import numpy as np
import pyarrow as pa

from ballista_tpu.ops.runtime import (
    bucket_rows,
    pad_to,
    readback,
    record_join_path,
)

_PAD_CODE = np.int32(2**31 - 1)  # sorts last, never matches a valid probe


def match_runs(sorted_codes, probe_codes):
    """Per-probe match run over a sorted build-code plane (traced):
    paired searchsorted left/right -> (starts, counts), both int32. Null
    probe codes (-1) and probe pad slots yield count 0; null build codes
    sort below every valid probe code and build pad codes above, so
    [starts, ends) never spans either. ONE source of truth shared by the
    single-chip kernel below and the SPMD mesh program (spmd_join.py) —
    the two device join paths must never drift."""
    import jax.numpy as jnp

    starts = jnp.searchsorted(sorted_codes, probe_codes, side="left")
    ends = jnp.searchsorted(sorted_codes, probe_codes, side="right")
    counts = jnp.where(probe_codes >= 0, ends - starts, 0)
    return starts.astype(jnp.int32), counts.astype(jnp.int32)


def gather_matches(values, starts, counts, width: int):
    """Bounded-width gather (traced): [P, width] of values[starts + j],
    masked to -1 past each probe's run length. Shared with the mesh
    program, like match_runs."""
    import jax.numpy as jnp

    n = values.shape[0]
    j = jnp.arange(width, dtype=jnp.int32)
    idx = jnp.clip(starts[:, None] + j[None, :], 0, n - 1)
    return jnp.where(j[None, :] < counts[:, None], values[idx], -1)


@functools.lru_cache(maxsize=None)
def _runs_kernel():
    import jax
    import jax.numpy as jnp

    @jax.jit
    def runs(build_codes, probe_codes):
        # stable: equal build keys keep original row order, matching the
        # host oracle's kind="stable" argsort (bit-equal output order)
        order = jnp.argsort(build_codes, stable=True)
        starts, counts = match_runs(build_codes[order], probe_codes)
        return order, starts, counts

    return runs


@functools.lru_cache(maxsize=None)
def _gather_kernel(width: int):
    import jax

    @jax.jit
    def gather(order, starts, counts):
        return gather_matches(order, starts, counts, width)

    return gather


def _decline(kind: str, reason: str) -> None:
    """Join decline: record the path for bench's per-config join counters
    (`kind` distinguishes admission-tier "step_aside" declines from other
    "host_fallback" declines), then route through the canonical
    host_fallback helper — either way the join leaves the device entirely,
    so tracing must count a fallback, not a mid-ladder step-aside."""
    from ballista_tpu.ops.kernels import host_fallback

    record_join_path(kind, reason)
    return host_fallback(reason)


def _counts_plane(build_codes: np.ndarray, probe_codes: np.ndarray):
    """Shared admission + padding + run-length pass for BOTH device join
    entries: (order, starts, counts [device], counts_h [host, unpadded],
    n_probe), or None after a recorded decline (empty side, code range
    past int32). One implementation so the full-join and counts-only
    planes can never diverge on sentinels, bucketing, or admission."""
    import jax.numpy as jnp

    nb, np_ = len(build_codes), len(probe_codes)
    if nb == 0 or np_ == 0:
        return _decline("host_fallback", "empty join side")
    hi = max(int(build_codes.max()), int(probe_codes.max()))
    if hi >= 2**31 - 2:
        return _decline("host_fallback", "join key codes exceed int32")
    b = jnp.asarray(
        pad_to(build_codes.astype(np.int32), bucket_rows(nb, 16), _PAD_CODE)
    )
    # null probe keys (-1) binary-search below all valid codes and compare
    # unequal — already a non-match; pads reuse the same sentinel
    p = jnp.asarray(pad_to(probe_codes.astype(np.int32), bucket_rows(np_, 16), -1))
    order, starts, counts = _runs_kernel()(b, p)
    counts_h = readback(counts)[:np_]
    return order, starts, counts, counts_h, np_


def device_join_indices(
    build_codes: np.ndarray, probe_codes: np.ndarray
) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """M:N inner-join row selections computed on device.

    Returns (build_idx, probe_idx, counts): flat int64 selections realizing
    every (build, probe) key match — probe-major, build rows in stable
    sorted order within a probe key, bit-identical to the host oracle's
    ``join_indices(..., "inner")`` — plus per-probe match run-lengths
    (LEFT-join and membership-count consumers read unmatched probes off
    ``counts == 0``). None when the device path declines (empty side, code
    range too wide for int32, multiplicity past the top admission tier);
    every decline carries a recorded reason.
    """
    from ballista_tpu.ops.kernels import join_multiplicity_tier

    plane = _counts_plane(build_codes, probe_codes)
    if plane is None:
        return None  # reason recorded by _counts_plane's decline
    order, starts, counts, counts_h, np_ = plane
    max_mult = int(counts_h.max())
    tier, why = join_multiplicity_tier(max_mult, int(counts.shape[0]))
    if tier is None:
        return _decline("step_aside", why)
    mat = readback(_gather_kernel(tier)(order, starts, counts), rows=np_)[:np_]
    # host flatten: the run-length exclusive scan is implicit in the
    # row-major compaction (probe-major, slot order within each probe)
    keep = np.arange(tier, dtype=np.int32)[None, :] < counts_h[:, None]
    build_idx = mat[keep].astype(np.int64)
    probe_idx = np.repeat(np.arange(np_, dtype=np.int64), counts_h)
    record_join_path("device")
    return build_idx, probe_idx, counts_h.astype(np.int64)


def device_membership_counts(
    build_codes: np.ndarray, probe_codes: np.ndarray
) -> Optional[np.ndarray]:
    """Per-probe match run-lengths (membership counts) computed on device —
    the counts-only entry of device_join_indices (ISSUE 7 satellite: the
    q13/q22 wiring). LEFT-join COUNT aggregates and SEMI/ANTI membership
    need ONLY the counts plane: no gather, so no multiplicity tier applies
    — the readback is the one-int32-per-probe plane, the same cap-exempt
    width-1 transfer the pre-M:N kernel always made. Returns int64 counts
    (null probe codes yield 0, matching SQL never-match semantics and the
    host oracle's ``join_indices`` counts bit-for-bit), or None when the
    device declines (empty side, code range past int32) — every decline
    carries a recorded reason."""
    plane = _counts_plane(build_codes, probe_codes)
    if plane is None:
        return None  # reason recorded by _counts_plane's decline
    _order, _starts, _counts, counts_h, _np = plane
    record_join_path("device")
    return counts_h.astype(np.int64)


def try_device_inner_join(
    build: pa.Table,
    probe: pa.Table,
    build_keys: list,
    probe_keys: list,
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Returns (build_idx, probe_idx) row selections realizing the inner
    join — duplicate build keys expand to their full multiplicity — or None
    if the device path declines."""
    from ballista_tpu.physical.joinutil import combined_key_codes

    bcodes, pcodes = combined_key_codes(
        [build.column(k) for k in build_keys],
        [probe.column(k) for k in probe_keys],
    )
    res = device_join_indices(bcodes, pcodes)
    if res is None:
        return None
    build_idx, probe_idx, _counts = res
    return build_idx, probe_idx

"""Device join kernel: sort + paired binary search with M:N multiplicity.

The TPU-native lowering of the hash join (every TPC-H join, primary-key or
not): build-side key codes are sorted on device ONCE (stable, so equal keys
keep build-row order), each probe key binary-searches the sorted plane twice
(jnp.searchsorted side='left'/'right' — branch-free, vectorizes on the VPU)
and the difference is that probe's match run-length. Duplicate build keys no
longer decline: run-lengths exclusive-scan into per-probe output offsets on
the host flatten, and matches materialize through a bounded-width gather
whose static width is the smallest admission tier
(ops/kernels.py::JOIN_MULTIPLICITY_TIERS) covering the observed maximum
multiplicity, keeping every program shape static.

Adaptive execution (ISSUE 10) replaces the wholesale decline past the
static ladder with three measured-cost escapes, every one bit-identical to
the host oracle:

- **extended tiers** — with a warm cost store whose evidence says the
  device gather beats the host join (kernels.join_extended_tier), widths
  512/1024 admit under hard caps; a gross mispredict re-tiers the store so
  the next decision falls back.
- **partial offload** — a batch past a tier boundary SPLITS at the
  boundary: probes whose run-length fits the boundary tier gather on
  device, the few dominant (skewed) keys past it join on the host oracle,
  and the two selections merge probe-major — bit-identical to the
  wholesale host join by construction, asserted against the oracle's own
  run-lengths before merging.
- **cold paths unchanged** — no config / cost model off / no structural
  skew reproduces the pre-adaptive step-aside exactly.

Both compiled programs (the runs kernel and each gather width) ride the
persistent AOT disk tier (ops/aotcache.py) under a stable plan-independent
key, so a cold process reloads them as compile_hit_disk instead of fresh
traces (ISSUE 10 satellite; PR 8 residue).

Shapes past every escape step aside to the host sort-merge join
(physical/joinutil.py) with a recorded reason; both paths share the same
key normalization and emit matches in the same order — probe-major, build
rows in stable sorted order within a probe key — so device results are
bit-identical to the host oracle, multiplicity and order included.

Every decline flows through the canonical kernels helpers AND
runtime.record_join_path, so bench.py's per-config join-path counters
(device / split / step_aside / host_fallback, with reasons) stay truthful;
every engine choice additionally lands in the routing accumulator
(runtime.record_routing) with its predicted-vs-observed cost.
"""

from __future__ import annotations

import functools
import time
from typing import Optional, Tuple

import numpy as np
import pyarrow as pa

from ballista_tpu.ops.runtime import (
    bucket_rows,
    pad_to,
    readback,
    record_join_path,
    record_routing,
    record_routing_event,
    routing_probe,
)

_PAD_CODE = np.int32(2**31 - 1)  # sorts last, never matches a valid probe

# partial offload engages only for the skew shape it is built for: at most
# this many DISTINCT keys past the tier boundary go to the host remainder
# (a broadly-duplicated build is not a split candidate — host-wholesale or
# an evidence-backed extended tier handles it)
_SPLIT_MAX_HOT_KEYS = 16
# planned-build-side row excess past which the observed cardinalities are
# treated as a plan-time misestimate and the build side switches
_BUILD_SWAP_RATIO = 4


class _JoinProgramOwner:
    """AOT-cache identity for the device-join programs. They are pure
    shape functions — no plan structure, no literals — so one stable key
    serves every join and a cold executor reloads them from disk
    (compile_hit_disk) instead of retracing."""

    aot_key = "ops.join"


_AOT_OWNER = _JoinProgramOwner()


def match_runs(sorted_codes, probe_codes):
    """Per-probe match run over a sorted build-code plane (traced):
    paired searchsorted left/right -> (starts, counts), both int32. Null
    probe codes (-1) and probe pad slots yield count 0; null build codes
    sort below every valid probe code and build pad codes above, so
    [starts, ends) never spans either. ONE source of truth shared by the
    single-chip kernel below and the SPMD mesh program (spmd_join.py) —
    the two device join paths must never drift."""
    import jax.numpy as jnp

    starts = jnp.searchsorted(sorted_codes, probe_codes, side="left")
    ends = jnp.searchsorted(sorted_codes, probe_codes, side="right")
    counts = jnp.where(probe_codes >= 0, ends - starts, 0)
    return starts.astype(jnp.int32), counts.astype(jnp.int32)


def gather_matches(values, starts, counts, width: int):
    """Bounded-width gather (traced): [P, width] of values[starts + j],
    masked to -1 past each probe's run length. Shared with the mesh
    program, like match_runs."""
    import jax.numpy as jnp

    n = values.shape[0]
    j = jnp.arange(width, dtype=jnp.int32)
    idx = jnp.clip(starts[:, None] + j[None, :], 0, n - 1)
    return jnp.where(j[None, :] < counts[:, None], values[idx], -1)


@functools.lru_cache(maxsize=None)
def _runs_kernel():
    from ballista_tpu.ops import aotcache

    def runs(build_codes, probe_codes):
        import jax.numpy as jnp

        # stable: equal build keys keep original row order, matching the
        # host oracle's kind="stable" argsort (bit-equal output order)
        order = jnp.argsort(build_codes, stable=True)
        starts, counts = match_runs(build_codes[order], probe_codes)
        return order, starts, counts

    return aotcache.wrap_step(_AOT_OWNER, "join_runs", runs, static_argnums=())


@functools.lru_cache(maxsize=None)
def _gather_kernel(width: int):
    from ballista_tpu.ops import aotcache

    def gather(order, starts, counts):
        return gather_matches(order, starts, counts, width)

    # width is baked into the closure, not an argument: the program name
    # carries it so each width keys its own AOT artifact
    return aotcache.wrap_step(
        _AOT_OWNER, f"join_gather_w{width}", gather, static_argnums=()
    )


def _decline(kind: str, reason: str) -> None:
    """Join decline: record the path for bench's per-config join counters
    (`kind` distinguishes admission-tier "step_aside" declines from other
    "host_fallback" declines), then route through the canonical
    host_fallback helper — either way the join leaves the device entirely,
    so tracing must count a fallback, not a mid-ladder step-aside."""
    from ballista_tpu.ops.kernels import host_fallback

    record_join_path(kind, reason)
    record_routing("host", "join")
    return host_fallback(reason)


def _counts_plane(build_codes: np.ndarray, probe_codes: np.ndarray):
    """Shared admission + padding + run-length pass for BOTH device join
    entries: (order, starts, counts [device], counts_h [host, unpadded],
    n_probe), or None after a recorded decline (empty side, code range
    past int32). One implementation so the full-join and counts-only
    planes can never diverge on sentinels, bucketing, or admission."""
    import jax.numpy as jnp

    nb, np_ = len(build_codes), len(probe_codes)
    if nb == 0 or np_ == 0:
        return _decline("host_fallback", "empty join side")
    hi = max(int(build_codes.max()), int(probe_codes.max()))
    if hi >= 2**31 - 2:
        return _decline("host_fallback", "join key codes exceed int32")
    b = jnp.asarray(
        pad_to(build_codes.astype(np.int32), bucket_rows(nb, 16), _PAD_CODE)
    )
    # null probe keys (-1) binary-search below all valid codes and compare
    # unequal — already a non-match; pads reuse the same sentinel
    p = jnp.asarray(pad_to(probe_codes.astype(np.int32), bucket_rows(np_, 16), -1))
    order, starts, counts = _runs_kernel()(b, p)
    counts_h = readback(counts)[:np_]
    return order, starts, counts, counts_h, np_


def _run_gather(order, starts, counts, tier: int, np_: int) -> Tuple[np.ndarray, float]:
    """Execute the bounded-width gather at `tier` and feed the cost store:
    (matched-plane [np_, tier], observed seconds)."""
    from ballista_tpu.ops import costmodel

    t0 = time.perf_counter()
    mat = readback(_gather_kernel(tier)(order, starts, counts), rows=np_)[:np_]
    dt = time.perf_counter() - t0
    costmodel.observe("join.gather", int(counts.shape[0]) * tier, dt)
    return mat, dt


def _flatten_matched(mat: np.ndarray, counts_h: np.ndarray, np_: int):
    """Host flatten of the gathered match plane: probe-major (build, probe)
    selections — the run-length exclusive scan is implicit in the
    row-major compaction (probe-major, slot order within each probe)."""
    tier = mat.shape[1]
    keep = np.arange(tier, dtype=np.int32)[None, :] < counts_h[:, None]
    build_idx = mat[keep].astype(np.int64)
    probe_idx = np.repeat(np.arange(np_, dtype=np.int64), counts_h)
    return build_idx, probe_idx


def _within_runs(counts: np.ndarray) -> np.ndarray:
    """[0..c) position index for each run of a counts vector, flattened."""
    total = int(counts.sum())
    if total == 0:
        return np.empty(0, dtype=np.int64)
    cum = np.cumsum(counts, dtype=np.int64)
    return np.arange(total, dtype=np.int64) - np.repeat(cum - counts, counts)


def _split_offload(
    order, starts, counts, counts_h, np_,
    build_codes: np.ndarray, probe_codes: np.ndarray,
) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """Partial offload (ISSUE 10): split the batch at the tier boundary
    instead of declining it wholesale. Probes whose run-length fits the
    largest cap-admissible tier gather on device; the dominant keys past it
    (at most _SPLIT_MAX_HOT_KEYS distinct — the skew shape) join on the
    host oracle; selections merge probe-major. Bit-identity with the
    wholesale host join holds by construction — both sides emit build rows
    in stable sorted order within a probe key — and the host remainder's
    run-lengths are asserted against the device counts plane before the
    merge. Returns None when the shape is not a split candidate."""
    from ballista_tpu.ops import costmodel
    from ballista_tpu.ops.kernels import (
        JOIN_GATHER_CAP,
        JOIN_MULTIPLICITY_TIERS,
        join_multiplicity_tier,
    )
    from ballista_tpu.physical.joinutil import join_indices

    probe_slots = int(counts.shape[0])
    boundary = JOIN_MULTIPLICITY_TIERS[0]
    for t in JOIN_MULTIPLICITY_TIERS:
        if t == 1 or probe_slots * t <= JOIN_GATHER_CAP:
            boundary = t
    hot = counts_h > boundary
    if not hot.any():
        return None  # nothing past the boundary: not this escape's shape
    if len(np.unique(probe_codes[hot])) > _SPLIT_MAX_HOT_KEYS:
        return None  # broad duplication, not skew — splitting buys nothing
    cold = ~hot
    cold_max = int(counts_h[cold].max()) if cold.any() else 0
    cold_tier, _why = join_multiplicity_tier(cold_max, probe_slots)
    if cold_tier is None or cold_tier > boundary:
        return None
    # input-row units, like every other join.host site (the op-global rate
    # is shared; match-count units would dilute it and skew the extended-
    # tier gate's host predictions)
    host_units = len(build_codes) + int(hot.sum())
    predicted = None
    dev_pred = costmodel.predict("join.gather", probe_slots * cold_tier)
    host_pred = costmodel.predict("join.host", host_units, engine="host")
    if dev_pred is not None and host_pred is not None:
        predicted = dev_pred + host_pred

    mat, dt_dev = _run_gather(order, starts, counts, cold_tier, np_)
    # host remainder: the oracle on the hot probes only
    hot_sel = np.flatnonzero(hot)
    t_host = time.perf_counter()
    bi_hot, pi_hot = join_indices(build_codes, probe_codes[hot_sel], "inner")
    dt_host = time.perf_counter() - t_host
    costmodel.observe("join.host", host_units, dt_host, engine="host")
    # per-op re-tiering on gross mispredicts (either direction): without
    # it a first-call trace/compile outlier inflates the gather rate for
    # _FORGET_AT observations and the composite prediction stays wrong
    costmodel.check_mispredict(
        "join.gather", probe_slots * cold_tier, dev_pred, dt_dev
    )
    costmodel.check_mispredict(
        "join.host", host_units, host_pred, dt_host, engine="host"
    )
    # decision-point oracle assertion: the host remainder's run-lengths
    # must equal the device counts plane for those probes — a mismatch
    # means the two engines disagree about the data and the split must not
    # merge (fall back to the wholesale host join instead)
    hot_counts = counts_h[hot_sel].astype(np.int64)
    if len(bi_hot) != int(hot_counts.sum()) or not np.array_equal(
        np.bincount(pi_hot, minlength=len(hot_sel)), hot_counts
    ):
        record_routing_event("split_oracle_mismatch")
        return None

    offsets = np.concatenate(
        ([0], np.cumsum(counts_h, dtype=np.int64)[:-1])
    )
    total = int(counts_h.sum())
    build_idx = np.empty(total, dtype=np.int64)
    cold_sel = np.flatnonzero(cold)
    cold_counts = counts_h[cold_sel].astype(np.int64)
    keep_cold = (
        np.arange(cold_tier, dtype=np.int32)[None, :] < counts_h[:, None]
    ) & cold[:, None]
    build_idx[
        np.repeat(offsets[cold_sel], cold_counts) + _within_runs(cold_counts)
    ] = mat[keep_cold].astype(np.int64)
    build_idx[
        np.repeat(offsets[hot_sel], hot_counts) + _within_runs(hot_counts)
    ] = bi_hot
    probe_idx = np.repeat(np.arange(np_, dtype=np.int64), counts_h)
    record_join_path("split", "partial offload at the tier boundary")
    # observed = the modeled work (gather + host join); the merge scatter
    # and oracle assertion are not part of the prediction, so timing them
    # would bill measurement scope as model error in the mispredict rate
    record_routing("split", "join", predicted, dt_dev + dt_host)
    record_routing_event("split")
    return build_idx, probe_idx, counts_h.astype(np.int64)


def _extended_gather(
    order, starts, counts, counts_h, np_,
    max_mult: int, host_units: int,
) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """Evidence-gated gather at an extended tier (past the static ladder).
    A gross mispredict re-tiers the cost store so the next decision for
    this shape bucket falls back to the static prior."""
    from ballista_tpu.ops import costmodel
    from ballista_tpu.ops.kernels import join_extended_tier

    probe_slots = int(counts.shape[0])
    ext = join_extended_tier(max_mult, probe_slots, host_units)
    if ext is None:
        return None
    tier, dev_pred, _host_pred = ext
    mat, dt = _run_gather(order, starts, counts, tier, np_)
    record_routing("device", "join.extended", dev_pred, dt)
    costmodel.check_mispredict("join.gather", probe_slots * tier, dev_pred, dt)
    build_idx, probe_idx = _flatten_matched(mat, counts_h, np_)
    record_join_path("device", "extended tier past the static ladder")
    return build_idx, probe_idx, counts_h.astype(np.int64)


def device_join_indices(
    build_codes: np.ndarray, probe_codes: np.ndarray, config=None
) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """M:N inner-join row selections computed on device.

    Returns (build_idx, probe_idx, counts): flat int64 selections realizing
    every (build, probe) key match — probe-major, build rows in stable
    sorted order within a probe key, bit-identical to the host oracle's
    ``join_indices(..., "inner")`` — plus per-probe match run-lengths
    (LEFT-join and membership-count consumers read unmatched probes off
    ``counts == 0``). None when the device path declines (empty side, code
    range too wide for int32, multiplicity past the top admission tier);
    every decline carries a recorded reason.

    With a config whose ``ballista.tpu.cost_model`` is on, shapes the
    static ladder declines first try the measured-cost escapes (extended
    tier, partial-offload split — see the module docstring); without one
    the static ladder is the whole story, so direct callers keep the
    pre-adaptive contract exactly.
    """
    from ballista_tpu.ops import costmodel
    from ballista_tpu.ops.kernels import join_multiplicity_tier

    plane = _counts_plane(build_codes, probe_codes)
    if plane is None:
        return None  # reason recorded by _counts_plane's decline
    order, starts, counts, counts_h, np_ = plane
    max_mult = int(counts_h.max())
    probe_slots = int(counts.shape[0])
    tier, why = join_multiplicity_tier(max_mult, probe_slots)
    if tier is not None:
        predicted = costmodel.predict("join.gather", probe_slots * tier)
        mat, dt = _run_gather(order, starts, counts, tier, np_)
        build_idx, probe_idx = _flatten_matched(mat, counts_h, np_)
        record_join_path("device")
        record_routing("device", "join", predicted, dt)
        # gross mispredict either way re-tiers the bucket: a first-call
        # trace/compile outlier otherwise inflates the rate for _FORGET_AT
        # observations, steering extended admission and the split decision
        # off steady-state reality
        costmodel.check_mispredict(
            "join.gather", probe_slots * tier, predicted, dt
        )
        return build_idx, probe_idx, counts_h.astype(np.int64)
    if config is not None and config.tpu_cost_model():
        costmodel.configure(config)
        host_units = len(build_codes) + len(probe_codes)
        res = _extended_gather(
            order, starts, counts, counts_h, np_, max_mult, host_units
        )
        if res is None:
            res = _split_offload(
                order, starts, counts, counts_h, np_, build_codes, probe_codes
            )
        if res is not None:
            return res
    return _decline("step_aside", why)


def device_membership_counts(
    build_codes: np.ndarray, probe_codes: np.ndarray
) -> Optional[np.ndarray]:
    """Per-probe match run-lengths (membership counts) computed on device —
    the counts-only entry of device_join_indices (ISSUE 7 satellite: the
    q13/q22 wiring). LEFT-join COUNT aggregates and SEMI/ANTI membership
    need ONLY the counts plane: no gather, so no multiplicity tier applies
    — the readback is the one-int32-per-probe plane, the same cap-exempt
    width-1 transfer the pre-M:N kernel always made. Returns int64 counts
    (null probe codes yield 0, matching SQL never-match semantics and the
    host oracle's ``join_indices`` counts bit-for-bit), or None when the
    device declines (empty side, code range past int32) — every decline
    carries a recorded reason."""
    plane = _counts_plane(build_codes, probe_codes)
    if plane is None:
        return None  # reason recorded by _counts_plane's decline
    _order, _starts, _counts, counts_h, _np = plane
    record_join_path("device")
    record_routing("device", "join.counts")
    return counts_h.astype(np.int64)


def try_device_inner_join(
    build: pa.Table,
    probe: pa.Table,
    build_keys: list,
    probe_keys: list,
    config=None,
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Returns (build_idx, probe_idx) row selections realizing the inner
    join — duplicate build keys expand to their full multiplicity — or None
    if the device path declines.

    Runtime re-planning (ISSUE 10): when the cost model is on and the
    observed row counts say the planner picked the wrong build side (build
    more than _BUILD_SWAP_RATIO times the probe), the sides swap — the
    device sorts the smaller plane — and the canonical probe-major order
    is restored host-side. Within a probe key every matched build row
    carries the SAME key code, so the oracle's "stable sorted build order"
    is simply build-row-ascending; a stable sort of the swapped result by
    probe row reproduces it exactly, keeping bit-identity."""
    from ballista_tpu.physical.joinutil import combined_key_codes

    bcodes, pcodes = combined_key_codes(
        [build.column(k) for k in build_keys],
        [probe.column(k) for k in probe_keys],
    )
    if (
        config is not None
        and config.tpu_cost_model()
        and len(bcodes) > _BUILD_SWAP_RATIO * max(1, len(pcodes))
    ):
        # probe scope: the swapped shape may decline (its multiplicity
        # profile differs), in which case the planned-side attempt below
        # records the real decision — without the probe one join would
        # count BOTH the probe's host decline and the planned outcome
        with routing_probe() as rp:
            swapped = device_join_indices(pcodes, bcodes, config)
        if swapped is not None:
            rp.commit()
            record_routing_event("join_build_swapped")
            p_rows, b_rows, _counts = swapped
            perm = np.argsort(p_rows, kind="stable")
            return b_rows[perm], p_rows[perm]
        # fall through to the planned sides before giving up on the device
    res = device_join_indices(bcodes, pcodes, config)
    if res is None:
        return None
    build_idx, probe_idx, _counts = res
    return build_idx, probe_idx

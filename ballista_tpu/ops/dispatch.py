"""Backend dispatch: route operator compute to JAX/XLA kernels.

Each hook returns None when the JAX kernel set is unavailable (or declines
the shape); operators then fall back to the host Arrow path.
"""

from __future__ import annotations

from typing import Optional

import pyarrow as pa


def _kernels():
    try:
        from ballista_tpu.ops import kernels

        return kernels
    except ImportError:
        return None


def tpu_filter(batch: pa.RecordBatch, predicate) -> Optional[pa.RecordBatch]:
    k = _kernels()
    return k.filter_batch(batch, predicate) if k else None


def tpu_hash_aggregate(exec_node, partition: int, ctx) -> Optional[pa.Table]:
    k = _kernels()
    return k.hash_aggregate(exec_node, partition, ctx) if k else None

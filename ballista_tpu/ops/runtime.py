"""Arrow <-> device runtime: column transfer, dictionary encoding, padding.

TPU-first data discipline (SURVEY §7 "TPU operator lowering"):
- strings never reach the device as bytes: each string column is encoded to
  int32 codes against a per-scan growing dictionary; predicates on strings
  become code comparisons / table gathers; group keys aggregate over codes
  and decode at the end
- float64 narrows to float32 (TPU vector unit native; f64 is emulated and
  slow), int64 narrows to int32 after a range check, date32 is int32 days
- batches are padded to power-of-two row buckets so XLA compiles a bounded
  set of program shapes (recompilation control)
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from ballista_tpu.errors import ExecutionError
from ballista_tpu.utils.locks import make_lock


class UnsupportedOnDevice(Exception):
    """Raised when a column/expr can't lower to the device path; callers
    fall back to the host Arrow kernels."""


class ColumnDictionary:
    """Growing per-column dictionary mapping values -> stable int32 codes.

    Thread-safe: executor task threads can run different partitions of one
    cached stage concurrently, and both prepare-time encode() and
    aux-build-time code_of() extend the dictionary (read-modify-write on
    `values`); an unguarded interleaving would silently re-assign codes
    already baked into pinned device tiles."""

    def __init__(self) -> None:
        self.values: Optional[pa.Array] = None  # distinct values; guarded-by: self._lock
        self._lock = make_lock("ops.runtime._lock")

    def encode(self, arr: pa.Array) -> np.ndarray:
        with self._lock:
            return self._encode(arr)

    # holds-lock: self._lock
    def _encode(self, arr: pa.Array) -> np.ndarray:
        """Encode an Arrow array to codes against this dictionary, extending
        it with novel values. Nulls -> -1."""
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        if isinstance(arr, pa.DictionaryArray):
            d = arr  # parquet dictionary pages: codes come for free
        else:
            d = pc.dictionary_encode(arr)
        if isinstance(d, pa.ChunkedArray):
            d = d.combine_chunks()
        local_values = d.dictionary
        # nulls -> -1 BEFORE the numpy conversion (a null-carrying indices
        # array converts via float NaN, whose int cast is undefined)
        local_codes = (
            pc.fill_null(d.indices, -1)
            .to_numpy(zero_copy_only=False)
            .astype(np.int64)
        )
        if self.values is None:
            self.values = local_values
            remap = np.arange(len(local_values), dtype=np.int64)
        else:
            idx = pc.index_in(local_values, value_set=self.values)
            idx_np = idx.to_numpy(zero_copy_only=False).astype(np.float64)
            missing = np.isnan(idx_np)
            if missing.any():
                novel = local_values.filter(pa.array(missing))
                base = len(self.values)
                self.values = pa.concat_arrays(
                    [self.values.cast(novel.type), novel]
                )
                idx_np = np.where(
                    missing, base + np.cumsum(missing) - 1, idx_np
                )
            remap = idx_np.astype(np.int64)
        out = np.where(local_codes >= 0, remap[np.maximum(local_codes, 0)], -1)
        return out.astype(np.int32)

    def snapshot(self) -> Optional[pa.Array]:
        """Consistent point-in-time view of the accumulated values (a
        concurrent encode may grow the dictionary; callers must not read
        `values` twice)."""
        with self._lock:
            return self.values

    def code_of(self, value) -> int:
        """Code for a literal, extending the dictionary so it always exists."""
        with self._lock:
            if self.values is None:
                self.values = pa.array([value])
                return 0
            idx = pc.index_in(pa.scalar(value, type=self.values.type), value_set=self.values)
            if idx.as_py() is None:
                self.values = pa.concat_arrays([self.values, pa.array([value], type=self.values.type)])
                return len(self.values) - 1
            return int(idx.as_py())

    def __len__(self) -> int:
        with self._lock:
            return 0 if self.values is None else len(self.values)


class ScanDictionaries:
    """Per-scan registry of ColumnDictionary keyed by column index."""

    def __init__(self) -> None:
        self.dicts: Dict[int, ColumnDictionary] = {}

    def for_column(self, index: int) -> ColumnDictionary:
        if index not in self.dicts:
            self.dicts[index] = ColumnDictionary()
        return self.dicts[index]


# -- device residency accounting -------------------------------------------
# One chip's HBM is shared by every cached stage; when a new partition would
# push the total past the configured budget, other stages' least-recently
# used pins are evicted to make room (re-prepared on their next touch), and
# only an entry that cannot fit even after eviction streams per query. A
# stage invalidated by the kernel dispatcher releases its reservations.
import threading
import time

_res_lock = make_lock("ops.runtime._res_lock")
_resident_bytes = 0  # guarded-by: _res_lock
_reservations: dict = {}  # token -> bytes; guarded-by: _res_lock
_pinned: dict = {}  # token -> (stage, partition), for LRU; guarded-by: _res_lock
_last_used: dict = {}  # token -> monotonic last-run time; guarded-by: _res_lock


def entry_device_bytes(obj) -> int:
    """Recursive nbytes of the DEVICE (jax) arrays inside a prepared cache
    entry. Host-side metadata (numpy rank orders, arrow key values) rides in
    the same dicts but does not occupy HBM, so it is not counted."""
    try:
        import jax

        if isinstance(obj, jax.Array):
            return int(obj.nbytes)
    except ImportError:
        pass
    if isinstance(obj, dict):
        return sum(entry_device_bytes(v) for v in obj.values())
    if isinstance(obj, (list, tuple)):
        return sum(entry_device_bytes(v) for v in obj)
    return 0


def reserve_and_pin(stage, partition: int, entry, cache: dict, nbytes: int, budget: int) -> bool:
    """Atomically reserve HBM budget AND insert the prepared entry into the
    stage's cache dict, refusing retired stages.

    When the budget is full, OTHER stages' pinned partitions are evicted
    least-recently-used-first until the new entry fits (touch_residency
    maintains recency). First-come residency would make every query after
    the budget fills stream per-iteration forever — fatal at SF=100, where
    q1's lineitem residency alone is most of a 16 GB chip and the suite
    visits many stages. With LRU, the working set follows the query mix and
    an evicted stage simply re-prepares on its next touch. Eviction is safe
    mid-run: a task thread inside the victim's step holds Python references
    to its device arrays, so compute completes; only the cache entry goes.

    A task thread may still be inside stage.run() when another thread
    evicts that stage (superseded mtimes) and releases its reservations.
    The retired check, the reservation, and the dict insert all happen
    under the same lock release_stage_residency holds for the flag write
    and the cache sweep — so there is no window where a reservation exists
    for a partition the sweep cannot see (which would leak budget
    permanently once the stage is unreachable)."""
    global _resident_bytes
    token = (id(stage), partition)
    with _res_lock:
        if getattr(stage, "_retired", False):
            return False
        if token not in _reservations:
            if nbytes > budget:
                return False  # can never fit; do NOT disturb other pins
            if _resident_bytes + nbytes > budget:
                _evict_lru_locked(stage, nbytes, budget)
            if _resident_bytes + nbytes > budget:
                return False
            _reservations[token] = nbytes
            _resident_bytes += nbytes
            _pinned[token] = (stage, partition)
        _last_used[token] = time.monotonic()
        cache[partition] = entry
        return True


# refuse an eviction plan that frees more than this multiple of the bytes
# requested: re-uploading a 15 GB pin to admit a 2 GB one costs more relay
# time than the newcomer streaming ever would, and two such stages
# alternating would thrash the whole budget every query
_EVICT_COST_RATIO = 4
# a stage evicted within this window is immune from re-eviction: in an
# A,B,A,B access pattern where A and B fit alone but not together, plain
# LRU would make EVERY query a full re-prepare (both stages thrash); after
# one thrash cycle the cooldown pins the survivor and the other streams —
# the same steady state first-come residency gave that pattern, while
# sequential workloads (the bench / the 22-query suite) still evict freely
_EVICT_COOLDOWN_S = 60.0
_evicted_at: dict = {}  # id(stage) -> last eviction time; guarded-by: _res_lock


# holds-lock: _res_lock
def _evict_lru_locked(requesting_stage, nbytes: int, budget: int) -> None:
    """Evict other stages' pinned partitions, oldest touch first, until
    `nbytes` fits. Caller holds _res_lock. The requesting stage's own
    entries are never victims (evicting them to fit a sibling partition of
    the same stage would thrash a multi-partition prepare loop), recently
    evicted stages are immune (thrash cooldown), and the whole plan is
    abandoned — nothing evicted — when it cannot fit the request or would
    free more than _EVICT_COST_RATIO times the request."""
    global _resident_bytes
    now = time.monotonic()
    for sid in [s for s, ts in _evicted_at.items() if now - ts > _EVICT_COOLDOWN_S]:
        del _evicted_at[sid]
    candidates = sorted(
        (
            t
            for t, (s, _p) in _pinned.items()
            if s is not requesting_stage and id(s) not in _evicted_at
        ),
        key=lambda t: _last_used.get(t, 0.0),
    )
    need = _resident_bytes + nbytes - budget
    chosen, freed = [], 0
    for t in candidates:
        if freed >= need:
            break
        size = _reservations.get(t, 0)
        if size > _EVICT_COST_RATIO * nbytes:
            continue  # huge victim for a small need: leave it resident
        chosen.append(t)
        freed += size
    if freed < need or freed > _EVICT_COST_RATIO * nbytes:
        return  # plan doesn't fit or costs more than it buys — evict nothing
    for t in chosen:
        victim_stage, p = _pinned.pop(t)
        _evicted_at[id(victim_stage)] = now
        _last_used.pop(t, None)
        _resident_bytes -= _reservations.pop(t, 0)
        for attr in ("_device_cache", "_prepared"):
            c = getattr(victim_stage, attr, None)
            if c is not None:
                c.pop(p, None)


def make_headroom(stage, nbytes: int, budget: int) -> None:
    """Best-effort LRU eviction BEFORE a large upload. reserve_and_pin only
    evicts at pin time — after the transfer — which is too late to save the
    chip when other stages' pins plus the incoming tiles would exceed HBM."""
    with _res_lock:
        if _resident_bytes + nbytes > budget:
            _evict_lru_locked(stage, nbytes, budget)


def touch_residency(stage, partition: int) -> None:
    """Record a cache hit for LRU ordering. Only refreshes live pins: a
    racing eviction may have dropped the token already, and re-inserting
    _last_used for it would leak bookkeeping no release path sweeps."""
    token = (id(stage), partition)
    with _res_lock:
        if token in _pinned:
            _last_used[token] = time.monotonic()


_stack_jit = None


def fetch_arrays(arrs: list) -> list:
    """Materialize a list of device arrays to numpy with ONE d2h transfer
    per distinct (shape, dtype) group instead of one per array.

    Through the relay every transfer pays the full round-trip latency
    (~65 ms measured), so a partition split into k row buckets costs
    k*RTT if fetched array-by-array. Same-shaped outputs are stacked
    on-device (async dispatch, no extra sync) and fetched as one array.
    """
    global _stack_jit
    if len(arrs) <= 1:
        return [np.asarray(a) for a in arrs]
    import jax
    import jax.numpy as jnp

    if _stack_jit is None:
        _stack_jit = jax.jit(lambda *xs: jnp.stack(xs))
    out: list = [None] * len(arrs)
    groups: Dict[tuple, list] = {}
    for i, a in enumerate(arrs):
        groups.setdefault((tuple(a.shape), str(a.dtype)), []).append(i)
    for idxs in groups.values():
        if len(idxs) == 1:
            out[idxs[0]] = np.asarray(arrs[idxs[0]])
            continue
        # bounded stack arities {2,4,8}: jit caches per (arity, shape), and
        # the batch count is data-dependent — unpadded arities would compile
        # a fresh trivial stack program per distinct count (expensive
        # through the remote-compile relay). Short chunks pad by repeating
        # the first member; the duplicate rows are dropped on unpack.
        for lo in range(0, len(idxs), 8):
            chunk = idxs[lo:lo + 8]
            arity = 2 if len(chunk) <= 2 else (4 if len(chunk) <= 4 else 8)
            padded = chunk + [chunk[0]] * (arity - len(chunk))
            # ballista-lint: disable=readback-discipline -- transport-layer batching: callers (stage.run) record the result-readback rows/bytes with aggregate semantics; recording here too would double-count
            stacked = np.asarray(_stack_jit(*[arrs[i] for i in padded]))
            for j, i in enumerate(chunk):
                out[i] = stacked[j]
    return out


def release_residency(token) -> None:
    global _resident_bytes
    with _res_lock:
        _resident_bytes -= _reservations.pop(token, 0)
        _pinned.pop(token, None)
        _last_used.pop(token, None)


def release_stage_residency(stage) -> None:
    """Drop a stage's cached device entries and their reservations (the
    dispatcher calls this when it permanently declines or evicts a stage).
    Runs entirely under the residency lock: the retired flag and the cache
    sweep are one atomic step against reserve_and_pin."""
    global _resident_bytes
    with _res_lock:
        stage._retired = True
        for attr in ("_device_cache", "_prepared"):
            cache = getattr(stage, attr, None)
            if cache:
                for p in list(cache):
                    token = (id(stage), p)
                    _resident_bytes -= _reservations.pop(token, 0)
                    _pinned.pop(token, None)
                    _last_used.pop(token, None)
                cache.clear()


def resident_bytes() -> int:
    with _res_lock:
        return _resident_bytes


def reset_residency() -> None:
    global _resident_bytes
    with _res_lock:
        _resident_bytes = 0
        _reservations.clear()
        _pinned.clear()
        _last_used.clear()
        _evicted_at.clear()


def bucket_rows(n: int, minimum: int = 1024) -> int:
    """Pad row counts to power-of-two buckets to bound XLA recompilation."""
    b = minimum
    while b < n:
        b <<= 1
    return b


_INT32_MIN = -(2**31)
_INT32_MAX = 2**31 - 1


def column_to_numpy(
    arr: pa.Array, dtype: pa.DataType, dictionary: Optional[ColumnDictionary]
) -> np.ndarray:
    """Lower one Arrow column to a device-ready numpy array.

    String columns tolerate nulls: they ride as -1 dictionary codes, and
    every compiled code predicate (eq/neq/LIKE/IN/IS NULL) applies SQL
    three-valued logic to code -1. Group keys are guarded separately
    (_group_codes declines null keys host-side) and code-typed aggregate
    inputs decline at compile, so predicates are the only device consumers.
    Numeric/date/bool columns with nulls decline (no null representation)."""
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    if pa.types.is_string(dtype) or pa.types.is_large_string(dtype):
        assert dictionary is not None
        return dictionary.encode(arr)
    if arr.null_count:
        raise UnsupportedOnDevice("null values in device column")
    if pa.types.is_floating(dtype):
        return arr.to_numpy(zero_copy_only=False).astype(np.float32)
    if pa.types.is_date(dtype):
        return arr.cast(pa.int32()).to_numpy(zero_copy_only=False)
    if pa.types.is_integer(dtype):
        vals = arr.to_numpy(zero_copy_only=False)
        if vals.dtype.itemsize > 4:
            if len(vals) and (vals.min() < _INT32_MIN or vals.max() > _INT32_MAX):
                raise UnsupportedOnDevice("int64 values exceed int32 range")
            vals = vals.astype(np.int32)
        return vals
    if pa.types.is_boolean(dtype):
        return arr.to_numpy(zero_copy_only=False).astype(np.bool_)
    raise UnsupportedOnDevice(f"unsupported device dtype {dtype}")


_LUT_MIN_ROWS = 4096
_LUT_MAX_VALUES = 256
_LUT_SAMPLE = 65536


def narrow_column(
    npcol: np.ndarray, prior: Optional[str] = None
) -> Tuple[np.ndarray, Optional[np.ndarray], str]:
    """Narrow a device-bound column for residency: (narrow array, optional
    f32 LUT, choice tag).

    HBM capacity and host->device bandwidth — not FLOPs — bound SF=100 on a
    16 GB chip (q1's lineitem columns alone are ~17 GB as int32/f32), so
    columns are stored narrow and widened in-program (widen_cols): int32
    whose range fits goes int8/int16; a float32 column with <=256 distinct
    values (TPC-H quantity/discount/tax are decimal grids) becomes uint8
    codes plus an f32 lookup table gathered on device. Compute dtypes after
    widening are exactly the canonical int32/f32, so results are bit-equal.

    `prior` is the choice a previous batch of the SAME column made; passing
    it back keeps the narrow dtype stable across batches so the jitted step
    compiles once (a per-batch min/max decision would retrace per width).
    A batch the prior no longer fits escalates to the next wider choice —
    one bounded retrace, never a flap back. LUTs are padded to a fixed
    _LUT_MAX_VALUES length for the same reason.
    """
    if npcol.dtype == np.int32:
        if not len(npcol):
            return npcol, None, prior or "int32"
        mn, mx = int(npcol.min()), int(npcol.max())
        choice = "int32"
        if -128 <= mn and mx <= 127:
            choice = "int8"
        elif -32768 <= mn and mx <= 32767:
            choice = "int16"
        # never narrow below what an earlier batch needed
        order = {"int8": 0, "int16": 1, "int32": 2}
        if prior in order and order[prior] > order[choice]:
            choice = prior
        if choice == "int32":
            return npcol, None, choice
        return npcol.astype(choice), None, choice
    if npcol.dtype == np.float32 and prior in (None, "lut"):
        if len(npcol) < _LUT_MIN_ROWS and prior != "lut":
            # too small to judge; stay UNDECIDED — a "wide" verdict here
            # would be sticky and lock a large later batch (prepare order
            # across partitions is arbitrary) out of LUT narrowing
            return npcol, None, prior
        # cheap sample gate first: a high-cardinality column (extendedprice
        # at SF=100 is ~1M distinct floats) must not pay a full
        # dictionary_encode just to discover it cannot LUT-encode
        sample = npcol[:: max(1, len(npcol) // _LUT_SAMPLE)][:_LUT_SAMPLE]
        if len(np.unique(sample)) <= _LUT_MAX_VALUES:
            d = pc.dictionary_encode(pa.array(npcol))
            if isinstance(d, pa.ChunkedArray):
                d = d.combine_chunks()
            if len(d.dictionary) <= _LUT_MAX_VALUES:
                lut = np.zeros(_LUT_MAX_VALUES, dtype=np.float32)
                vals = d.dictionary.to_numpy(zero_copy_only=False)
                lut[: len(vals)] = vals.astype(np.float32)
                codes = d.indices.to_numpy(zero_copy_only=False).astype(np.uint8)
                return codes, lut, "lut"
    return npcol, None, "wide"




def widen_cols(cols: dict) -> dict:
    """In-program inverse of narrow_column, applied at the top of every
    jitted device step: sub-4-byte ints widen to int32, (codes, lut) pairs
    gather back to float32. Wide inputs pass through untouched, so callers
    that never narrow (the SPMD mesh programs, filter_batch) share the same
    cores, and XLA reads the narrow representation from HBM while all
    arithmetic stays int32/f32."""
    import jax.numpy as jnp

    out = {}
    for idx, v in cols.items():
        if isinstance(v, tuple):
            codes, lut = v
            out[idx] = jnp.take(lut, codes.astype(jnp.int32))
        elif np.issubdtype(v.dtype, np.integer) and v.dtype.itemsize < 4:
            out[idx] = v.astype(jnp.int32)
        else:
            out[idx] = v
    return out


def pad_to(arr: np.ndarray, n: int, fill=0) -> np.ndarray:
    if len(arr) == n:
        return arr
    pad = np.full(n - len(arr), fill, dtype=arr.dtype)
    return np.concatenate([arr, pad])


# -- pipelined ingestion ----------------------------------------------------
# The per-partition hot path used to be one serial thread: at SF=100 the
# host-side parquet decode costs ~400 s while the device aggregate takes
# ~100 ms, so the chip idled >99% of first-touch wall-clock. These two
# helpers are the bounded producer/consumer shapes the ingest pipeline is
# built from (ops/stage.py scan/decode vs encode/upload; distributed
# shuffle-piece fetches). Both preserve input order exactly — the consume
# side of a stage prepare MUST stay ordered because each batch's narrow
# choice feeds the next batch's narrow_column prior — and both bound the
# number of results in flight so host RSS stays ~depth decoded items.


def ordered_map(fn, items, workers: int, depth: int = 2):
    """Concurrent map over a finite, independent item list, yielding
    results in input order with at most `depth` in flight — depth is the
    host-RSS cap and wins over workers (extra threads beyond it idle).
    workers <= 0 (or a single item) degenerates to the serial loop."""
    items = list(items)
    if workers <= 0 or len(items) <= 1:
        for it in items:
            yield fn(it)
        return
    import collections
    from concurrent.futures import ThreadPoolExecutor

    inflight = max(1, depth)
    ex = ThreadPoolExecutor(max_workers=workers)
    pending: collections.deque = collections.deque()
    i = 0
    try:
        while pending or i < len(items):
            while i < len(items) and len(pending) < inflight:
                pending.append(ex.submit(fn, items[i]))
                i += 1
            yield pending.popleft().result()
    finally:
        for f in pending:
            f.cancel()
        ex.shutdown(wait=True)


def pipelined_map(src, fn, workers: int, depth: int = 2, on_src_time=None):
    """Ordered streaming producer/consumer over an iterator.

    A reader thread pulls items from `src` serially (the pull itself may be
    expensive IO — e.g. a parquet read inside a generator), submits
    fn(item) to a `workers`-thread pool, and the caller consumes results in
    input order. At most `depth` results exist beyond the one being
    consumed. Exceptions from `src` or `fn` re-raise at the consumption
    point in order, so decline signals (UnsupportedOnDevice, TooManyGroups)
    keep their serial-path semantics. `on_src_time(seconds)` is called from
    the reader thread with each pull's duration (ingest scan timing).

    workers <= 0 degenerates to the serial in-thread map."""
    if workers <= 0:
        it = iter(src)
        while True:
            t0 = time.perf_counter()
            try:
                item = next(it)
            except StopIteration:
                return
            if on_src_time is not None:
                on_src_time(time.perf_counter() - t0)
            yield fn(item)
    import queue as _queue
    from concurrent.futures import ThreadPoolExecutor

    done = object()
    stop = threading.Event()
    slots = threading.Semaphore(max(1, depth))
    out_q: "_queue.Queue" = _queue.Queue()
    ex = ThreadPoolExecutor(max_workers=workers)

    def _reader() -> None:
        it = iter(src)
        while not stop.is_set():
            # bounded wait so a consumer that stopped early (exception,
            # generator close) can never strand this thread on the semaphore
            if not slots.acquire(timeout=0.05):
                continue
            t0 = time.perf_counter()
            try:
                item = next(it)
            except StopIteration:
                slots.release()
                break
            except BaseException as e:  # src failure surfaces in order
                slots.release()
                out_q.put(("err", e))
                return
            if on_src_time is not None:
                on_src_time(time.perf_counter() - t0)
            try:
                out_q.put(("fut", ex.submit(fn, item)))
            except RuntimeError:
                # consumer exited early and its finally shut the pool down
                # while we were blocked in a long pull — nobody is reading
                # out_q anymore, just exit quietly
                return
        out_q.put(done)

    reader = threading.Thread(target=_reader, name="ingest-reader", daemon=True)
    reader.start()
    try:
        while True:
            msg = out_q.get()
            if msg is done:
                break
            tag, val = msg
            if tag == "err":
                raise val
            yield val.result()
            slots.release()
    finally:
        stop.set()
        # on normal completion the reader has already exited and the pool is
        # drained, so these return immediately. On early consumer exit (a
        # TooManyGroups retry, an exception) do NOT block behind a multi-
        # second in-flight parquet pull or ranking task: the reader is a
        # daemon guarded against post-shutdown submits, in-flight fn work is
        # pure per-batch compute, and the caller (e.g. the sorted-layout
        # retry) should not stall on work it is about to throw away.
        reader.join(timeout=0.2)
        ex.shutdown(wait=False)


# accumulated ingest timings across stage prepares (bench.py reports them):
# scan_s = prefetch-stage work (parquet read + dictionary decode + group
# ranking), encode_s = host narrow/encode, upload_s = h2d transfer, wall_s =
# end-to-end prepare. overlap_frac = 1 - wall / (scan + encode + upload):
# 0 on the serial path, > 0 when the pipeline actually hid host work.
_ingest_lock = make_lock("ops.runtime._ingest_lock")
# guarded-by: _ingest_lock
_ingest_totals = {
    "scan_s": 0.0, "encode_s": 0.0, "upload_s": 0.0, "wall_s": 0.0,
    "prepares": 0,
}


def record_ingest(scan_s: float, encode_s: float, upload_s: float,
                  wall_s: float) -> None:
    with _ingest_lock:
        _ingest_totals["scan_s"] += scan_s
        _ingest_totals["encode_s"] += encode_s
        _ingest_totals["upload_s"] += upload_s
        _ingest_totals["wall_s"] += wall_s
        _ingest_totals["prepares"] += 1


def ingest_stats(reset: bool = False) -> Dict[str, float]:
    """Snapshot of accumulated ingest timings plus the derived overlap
    fraction."""
    with _ingest_lock:
        out = dict(_ingest_totals)
        if reset:
            for k in _ingest_totals:
                _ingest_totals[k] = 0.0 if k != "prepares" else 0
    stages = out["scan_s"] + out["encode_s"] + out["upload_s"]
    out["overlap_frac"] = (
        max(0.0, 1.0 - out["wall_s"] / stages) if stages > 0 else 0.0
    )
    return out


# accumulated device->host result readback across stage runs (bench.py
# reports rows/bytes per config): every aggregate-result d2h transfer on
# the device paths — full-column, fused top-k, fact-agg member/top-k —
# records its width here. rows = trailing-axis length of each fetched
# result (groups or selected candidates), bytes = the packed f32 transfer
# size. The fused Sort+Limit epilogue's whole point is to shrink these to
# O(limit); readbacks is the transfer count.
_readback_lock = make_lock("ops.runtime._readback_lock")
_readback_totals = {"rows": 0, "bytes": 0, "readbacks": 0}  # guarded-by: _readback_lock


def record_readback(rows: int, nbytes: int) -> None:
    with _readback_lock:
        _readback_totals["rows"] += int(rows)
        _readback_totals["bytes"] += int(nbytes)
        _readback_totals["readbacks"] += 1


def readback(x, rows: Optional[int] = None) -> np.ndarray:
    """Canonical device->host result materialization: np.asarray + the
    readback accounting in one step. `rows` defaults to the trailing-axis
    length (group/candidate count in the packed [R, G] result convention);
    pass it explicitly when the row axis is not the trailing one. Every
    device-path np.asarray of a compiled-program result must go through
    here (or pair with record_readback) — enforced by
    dev/analysis's readback-discipline pass.

    With the cost model enabled (ISSUE 10), the transfer's wall time lands
    in the cost store as a per-byte readback observation (bench
    observability + groundwork for transfer-aware admission; no predictor
    consults it yet). The producing computation is synced FIRST so the
    timer measures the d2h transfer, not whatever async dispatch happens
    to still be in flight."""
    from ballista_tpu.ops import costmodel

    t0 = None
    if costmodel.enabled():
        if hasattr(x, "block_until_ready"):
            x.block_until_ready()
        t0 = time.perf_counter()
    arr = np.asarray(x)
    if t0 is not None and arr.nbytes:
        costmodel.observe("readback", arr.nbytes, time.perf_counter() - t0)
    record_readback(
        rows if rows is not None else (arr.shape[-1] if arr.ndim else 1),
        arr.nbytes,
    )
    return arr


def readback_stats(reset: bool = False) -> Dict[str, int]:
    """Snapshot of accumulated result-readback totals."""
    with _readback_lock:
        out = dict(_readback_totals)
        if reset:
            for k in _readback_totals:
                _readback_totals[k] = 0
    return out


# accumulated join-path outcomes across join executions (bench.py reports
# them per config): every device-join attempt lands in exactly one bucket —
# "device" (the M:N kernel or the mesh program produced the result),
# "step_aside" (the multiplicity/gather admission tier declined, host join
# ran instead), or "host_fallback" (any other decline or error). Reasons are
# counted verbatim so a bench row says WHY a join left the device path.
_join_lock = make_lock("ops.runtime._join_lock")
# guarded-by: _join_lock
_join_paths: Dict[str, int] = {}  # path -> count
# guarded-by: _join_lock
_join_reasons: Dict[str, int] = {}  # "path: reason" -> count


def record_join_path(path: str, reason: Optional[str] = None) -> None:
    probe = getattr(_probe_tls, "probe", None)
    if probe is not None:
        probe.buf.append(("join_path", (path, reason)))
        return
    with _join_lock:
        _join_paths[path] = _join_paths.get(path, 0) + 1
        if reason:
            key = f"{path}: {reason}"
            _join_reasons[key] = _join_reasons.get(key, 0) + 1


def join_path_stats(reset: bool = False) -> Dict[str, Dict[str, int]]:
    """Snapshot of accumulated join-path counters: {"paths": {path: n},
    "reasons": {"path: reason": n}}."""
    with _join_lock:
        out = {"paths": dict(_join_paths), "reasons": dict(_join_reasons)}
        if reset:
            _join_paths.clear()
            _join_reasons.clear()
    return out


# accumulated failure-recovery events across scheduler/executor/client
# (bench.py reports them per config beside readback/join_paths): every
# retry, lineage recompute, stale-report drop, transient-RPC retry, and
# chaos injection lands in exactly one named bucket, so a bench row under
# `ballista.chaos.rate` > 0 shows both the injected faults AND the recovery
# work they triggered. In-process accumulator like the readback totals —
# the standalone cluster (scheduler + executors in one process) is where
# chaos runs live; separate daemons each report their own share.
_recovery_lock = make_lock("ops.runtime._recovery_lock")
# guarded-by: _recovery_lock
_recovery: Dict[str, int] = {}  # event -> count


def record_recovery(event: str, n: int = 1) -> None:
    with _recovery_lock:
        _recovery[event] = _recovery.get(event, 0) + int(n)


def recovery_stats(reset: bool = False) -> Dict[str, int]:
    """Snapshot of accumulated recovery-event counters."""
    with _recovery_lock:
        out = dict(_recovery)
        if reset:
            _recovery.clear()
    return out


# accumulated multi-tenant serving events (ISSUE 7): result-cache hits /
# misses / puts / invalidations, plan-cache hits, and admission quota
# deferrals. Same in-process accumulator pattern as the recovery counters;
# bench.py's multi-tenant scenario reports cache-hit rate and per-tenant
# fairness off these plus the scheduler's per-tenant assignment ledger.
_tenancy_lock = make_lock("ops.runtime._tenancy_lock")
# guarded-by: _tenancy_lock
_tenancy: Dict[str, int] = {}  # event -> count


def record_tenancy(event: str, n: int = 1) -> None:
    with _tenancy_lock:
        _tenancy[event] = _tenancy.get(event, 0) + int(n)


def tenancy_stats(reset: bool = False) -> Dict[str, int]:
    """Snapshot of accumulated multi-tenant serving counters."""
    with _tenancy_lock:
        out = dict(_tenancy)
        if reset:
            _tenancy.clear()
    return out


# accumulated low-latency serving-tier events (ISSUE 8): dispatch-path
# counts (dispatch_push vs dispatch_poll — the latency harness asserts a
# warm push-enabled cluster runs with ZERO poll-dispatched tasks),
# compiled-program cache outcomes (compile_trace = a fresh Python trace +
# XLA compile happened; compile_hit_memory / compile_hit_disk /
# compile_prewarmed = the AOT tier served it; aot_load_error = corrupt or
# version-mismatched artifact fell back, with the reason recorded by the
# caller's log), push-stream health (push_subscribed counts every
# successful stream open — re-subscribes included — and push_stream_drop
# every loss), and streaming-collect progress (stream_partition_early = a result
# partition fetched before the job completed). Same in-process accumulator
# pattern as readback/join_paths/recovery/tenancy above.
_serving_lock = make_lock("ops.runtime._serving_lock")
# guarded-by: _serving_lock
_serving: Dict[str, int] = {}  # event -> count


def record_serving(event: str, n: int = 1) -> None:
    with _serving_lock:
        _serving[event] = _serving.get(event, 0) + int(n)


def serving_stats(reset: bool = False) -> Dict[str, int]:
    """Snapshot of accumulated serving-tier counters."""
    with _serving_lock:
        out = dict(_serving)
        if reset:
            _serving.clear()
    return out


# accumulated speculative-execution events (ISSUE 11): duplicate-attempt
# launches and their outcomes ("launched" / "won" = the duplicate finished
# first / "lost" = the primary beat it / "failed" = the duplicate itself
# died / "promoted" = the primary died and the in-flight duplicate became
# the current attempt / "orphaned" / "executor_lost"), the duplicated
# compute discarded when a pair resolves ("wasted_seconds", a float), and
# per-tenant SLO outcomes ("slo_misses" / "slo_met" — jobs completing past
# or within their ballista.tenant.slo_ms deadline). Same in-process
# accumulator pattern as recovery/tenancy/serving above; bench.py reports a
# per-config `speculation` block off this beside `recovery`/`routing`.
_speculation_lock = make_lock("ops.runtime._speculation_lock")
# guarded-by: _speculation_lock
_speculation: Dict[str, float] = {}  # event -> count/seconds


def record_speculation(event: str, n: float = 1) -> None:
    with _speculation_lock:
        _speculation[event] = _speculation.get(event, 0) + n


def speculation_stats(reset: bool = False) -> Dict[str, float]:
    """Snapshot of accumulated speculation counters (wasted_seconds is a
    float total; everything else is an integral count)."""
    with _speculation_lock:
        out = dict(_speculation)
        if reset:
            _speculation.clear()
    return out


# accumulated shared-scan events (ISSUE 13): scheduler-side batch formation
# (batches_formed = batched dispatches minted, batched_stages = member tasks
# riding them, batch_gate_solo = evidence-gate declines, batch_chaos_solo =
# scheduler.batch-torn formations degraded to solo) and executor-side group
# execution (shared_groups = groups that actually launched shared,
# uploads_saved / launches_saved = per-batch member-transfers and
# member-launches avoided vs solo, device_launches = combined launches run,
# member_degraded / batch_degraded = members or whole groups that fell back
# to solo execution — bit-identical either way). Same in-process accumulator
# pattern as recovery/tenancy/serving above; bench.py reports a per-scenario
# `shared_scan` block off this.
_shared_scan_lock = make_lock("ops.runtime._shared_scan_lock")
# guarded-by: _shared_scan_lock
_shared_scan: Dict[str, int] = {}  # event -> count


def record_shared_scan(event: str, n: int = 1) -> None:
    with _shared_scan_lock:
        _shared_scan[event] = _shared_scan.get(event, 0) + int(n)


def shared_scan_stats(reset: bool = False) -> Dict[str, int]:
    """Snapshot of accumulated shared-scan counters."""
    with _shared_scan_lock:
        out = dict(_shared_scan)
        if reset:
            _shared_scan.clear()
    return out


# accumulated disaggregated-shuffle-tier events (ISSUE 15): where pieces
# were published (storage_publish vs local_publish) and how readers
# resolved them — storage_fetch = read straight from the shared dir,
# peer_fetch = the Flight path (the local tier, and the fallback when a
# storage-homed piece is unreadable, counted storage_fallback_peer beside
# it), storage_publish_torn = a shuffle.store-chaos-torn publish (the task
# failed and retried). bench.py's elastic scenario reports
# storage-vs-peer fetch mix off this. Same in-process accumulator pattern
# as recovery/tenancy/serving above.
_shuffle_tier_lock = make_lock("ops.runtime._shuffle_tier_lock")
# guarded-by: _shuffle_tier_lock
_shuffle_tier: Dict[str, int] = {}  # event -> count


def record_shuffle_tier(event: str, n: int = 1) -> None:
    with _shuffle_tier_lock:
        _shuffle_tier[event] = _shuffle_tier.get(event, 0) + int(n)


def shuffle_tier_stats(reset: bool = False) -> Dict[str, int]:
    """Snapshot of accumulated shuffle-tier counters."""
    with _shuffle_tier_lock:
        out = dict(_shuffle_tier)
        if reset:
            _shuffle_tier.clear()
    return out


# accumulated HBM-resident exchange events (ISSUE 16): published /
# publish_bytes = pieces registered in the residency registry after their
# authoritative disk publish, reupload_skipped / h2d_bytes_saved = consumer
# resolutions served straight from the registry (no decode, no re-upload),
# served_from_registry / d2h_bytes_saved = Flight FetchPartition streams
# served from memory instead of re-reading the piece off disk,
# skipped_budget / evicted_budget = budget pressure outcomes at publish,
# evicted_chaos = exchange.evict verdicts, locality_preferred = scheduler
# assignments reordered toward the executor advertising residency, miss =
# registry probes that fell through to the piece ladder. Same in-process
# accumulator pattern as recovery/shuffle-tier above.
_exchange_lock = make_lock("ops.runtime._exchange_lock")
# guarded-by: _exchange_lock
_exchange: Dict[str, int] = {}  # event -> count


def record_exchange(event: str, n: int = 1) -> None:
    with _exchange_lock:
        _exchange[event] = _exchange.get(event, 0) + int(n)


def exchange_stats(reset: bool = False) -> Dict[str, int]:
    """Snapshot of accumulated exchange-tier counters."""
    with _exchange_lock:
        out = dict(_exchange)
        if reset:
            _exchange.clear()
    return out


# accumulated incremental-execution events (ISSUE 19): chunks_reused =
# prepared chunks served byte-for-byte from the chunk-set delta store,
# chunks_prepared = chunks that paid the scan/encode pipeline,
# bytes_reprepared_saved = staged bytes those reused chunks would have
# re-encoded, save_declined_midappend = chunk saves refused because the
# file's identity moved between the stat and the read (fail-closed bugfix),
# advance_hits = cached results advanced by a delta fold instead of a full
# recompute, advance_declined = advancement attempts that fell back to the
# full run (ineligible shape, torn advance, delta-job failure — recorded,
# never silent). Same in-process accumulator pattern as the counters above.
_delta_lock = make_lock("ops.runtime._delta_lock")
# guarded-by: _delta_lock
_delta: Dict[str, int] = {}  # event -> count


def record_delta(event: str, n: int = 1) -> None:
    with _delta_lock:
        _delta[event] = _delta.get(event, 0) + int(n)


def delta_stats(reset: bool = False) -> Dict[str, int]:
    """Snapshot of accumulated incremental-execution counters."""
    with _delta_lock:
        out = dict(_delta)
        if reset:
            _delta.clear()
    return out


# accumulated elastic-fleet events (ISSUE 15): autoscaler evaluations and
# the scale actions they took (scale_up / scale_down by executor count,
# scale_chaos_skipped = fleet.scale-torn decisions, drain_completed /
# drain_timeout = graceful scale-in outcomes), plus the running gauges the
# bench scenario samples (fleet_size = last observed size, backlog_ms =
# last predicted backlog, peaks kept as fleet_size_peak / backlog_ms_peak).
# Same in-process accumulator pattern as the counters above; gauges
# overwrite instead of accumulate.
_fleet_lock = make_lock("ops.runtime._fleet_lock")
# guarded-by: _fleet_lock
_fleet: Dict[str, float] = {}  # event -> count (or gauge value)


def record_fleet(event: str, n: float = 1) -> None:
    with _fleet_lock:
        _fleet[event] = _fleet.get(event, 0) + n


def record_fleet_gauge(gauge: str, value: float) -> None:
    """Overwrite a fleet gauge, keeping its `_peak` sibling."""
    with _fleet_lock:
        _fleet[gauge] = value
        peak = f"{gauge}_peak"
        _fleet[peak] = max(_fleet.get(peak, value), value)


def fleet_stats(reset: bool = False) -> Dict[str, float]:
    """Snapshot of accumulated elastic-fleet counters and gauges."""
    with _fleet_lock:
        out = dict(_fleet)
        if reset:
            _fleet.clear()
    return out


# accumulated adaptive-routing decisions (ISSUE 10): every engine choice
# the cost-model-aware ladder makes — device / host / split — lands here
# with its predicted-vs-observed cost when a prediction existed, plus named
# events (partial-offload splits, skew re-plans, build-side swaps, cost-
# store health). bench.py reports the per-config `routing` block off this.
# A decision whose observed cost deviates from its prediction by more than
# costmodel.MISPREDICT_FACTOR either way counts as a mispredict; the
# mispredict rate is the model's running honesty meter.
_routing_lock = make_lock("ops.runtime._routing_lock")
# guarded-by: _routing_lock
_routing = {
    "engines": {},  # engine -> decision count
    "events": {},  # event -> count (op:engine decision detail + named events)
    "predicted_s": 0.0,
    "observed_s": 0.0,
    "predictions": 0,
    "mispredicts": 0,
    # last tuned h2d chunk size (ISSUE 13 satellite): a VALUE, not a count —
    # what _h2d_chunk_bytes() chose for the most recent chunked upload
    "h2d_chunk_bytes": 0,
}


# speculative-attempt scope: the build-swap re-plan (ops/join.py) probes
# the swapped shape by running the full device ladder on it, and only a
# probe that produced a result becomes the decision — a failed probe is
# followed by the planned-shape attempt, which records the real outcome.
# Decision counters made inside a probe (record_routing / record_join_path)
# therefore buffer in the probe and land only on commit; without this one
# join would count a host decline AND the planned-side decision. Named
# events (record_routing_event: retier, split_oracle_mismatch, ...) pass
# through — they describe work/store mutations that genuinely happened.
_probe_tls = threading.local()


class _RoutingProbe:
    def __init__(self) -> None:
        self.buf: List[tuple] = []

    def commit(self) -> None:
        """Land the buffered decisions (call AFTER the with-block: the
        probe's records ARE the decision). Replays through the public
        recorders, so a still-active outer probe keeps buffering them."""
        buf, self.buf = self.buf, []
        for kind, args in buf:
            if kind == "routing":
                record_routing(*args)
            elif kind == "trace":
                record_decline_trace(*args)
            else:
                record_join_path(*args)


def record_decline_trace(counter: str, message: str) -> None:
    """Decline observability (tracing counter + debug log) that respects an
    active routing probe: a decline inside a speculative attempt buffers
    like the decision counters, so an uncommitted probe leaves no phantom
    host-fallback trace for a join that actually ran on device."""
    probe = getattr(_probe_tls, "probe", None)
    if probe is not None:
        probe.buf.append(("trace", (counter, message)))
        return
    import logging

    from ballista_tpu.utils import tracing

    tracing.incr(counter)
    logging.getLogger("ballista.tpu").debug("%s", message)


@contextmanager
def routing_probe() -> Iterator[_RoutingProbe]:
    """Buffer routing/join-path decision counters recorded in the body.
    The caller commits them only when the probed attempt became the real
    decision; an uncommitted probe's records are dropped."""
    prev = getattr(_probe_tls, "probe", None)
    probe = _RoutingProbe()
    _probe_tls.probe = probe
    try:
        yield probe
    finally:
        _probe_tls.probe = prev


def record_routing(engine: str, op: str = "",
                   predicted_s: Optional[float] = None,
                   observed_s: Optional[float] = None) -> None:
    """Record one routing decision: which engine ran `op`, and (when the
    cost model predicted) how the prediction held up. Cost totals
    accumulate only when BOTH sides exist, so predicted_s and observed_s
    stay comparable sums over the same decision set."""
    from ballista_tpu.ops.costmodel import gross_mispredict

    probe = getattr(_probe_tls, "probe", None)
    if probe is not None:
        probe.buf.append(("routing", (engine, op, predicted_s, observed_s)))
        return
    with _routing_lock:
        _routing["engines"][engine] = _routing["engines"].get(engine, 0) + 1
        if op:
            k = f"{op}:{engine}"
            _routing["events"][k] = _routing["events"].get(k, 0) + 1
        if predicted_s is not None and observed_s is not None:
            _routing["predictions"] += 1
            _routing["predicted_s"] += float(predicted_s)
            _routing["observed_s"] += float(observed_s)
            if gross_mispredict(predicted_s, observed_s):
                _routing["mispredicts"] += 1


def record_routing_event(event: str, n: int = 1) -> None:
    """Count a named routing event (split, skew_replan, join_build_swapped,
    retier, cost_store_corrupt, ...)."""
    with _routing_lock:
        _routing["events"][event] = _routing["events"].get(event, 0) + int(n)


def routing_stats(reset: bool = False) -> Dict[str, object]:
    """Snapshot of accumulated routing decisions + events. mispredict_rate
    is derived here so every consumer sums the accounting identically."""
    with _routing_lock:
        out = {
            "engines": dict(_routing["engines"]),
            "events": dict(_routing["events"]),
            "predicted_s": _routing["predicted_s"],
            "observed_s": _routing["observed_s"],
            "predictions": _routing["predictions"],
            "mispredicts": _routing["mispredicts"],
            "h2d_chunk_bytes": _routing["h2d_chunk_bytes"],
        }
        if reset:
            _routing["engines"] = {}
            _routing["events"] = {}
            _routing["predicted_s"] = 0.0
            _routing["observed_s"] = 0.0
            _routing["predictions"] = 0
            _routing["mispredicts"] = 0
            _routing["h2d_chunk_bytes"] = 0
    out["mispredict_rate"] = (
        out["mispredicts"] / out["predictions"] if out["predictions"] else 0.0
    )
    return out


# -- chunked double-buffered h2d upload (ISSUE 10 satellite) ----------------
# A persisted-layout warm start used to move each staged column to the
# device as ONE bulk transfer: nothing overlaps a 9.6 GB h2d the way the
# ingest pipeline overlaps prepare. Large arrays now go up in bounded
# chunks with exactly one transfer in flight while the previous one is
# timed to completion — later chunks (and the next column's host staging)
# overlap earlier transfers, and the per-chunk timings land in the cost
# store as the h2d observations (observe-only today, like readback: no
# predictor consults the h2d rate yet).

_H2D_CHUNK_BYTES = 64 << 20  # static per-chunk default (cold store)
_H2D_MIN_CHUNKED = 256 << 20  # arrays below this go as one piece
# tuned-chunk candidates (ISSUE 13 satellite): the power-of-two bucket
# sizes the picker compares against the cost store's observed per-chunk
# h2d rates — 16 MB .. 256 MB around the static 64 MB default
_H2D_CHUNK_CANDIDATES = tuple(1 << p for p in range(24, 29))


def _h2d_chunk_bytes() -> int:
    """Per-chunk h2d transfer size, tuned from the cost store (ISSUE 13
    satellite, PR 10 residue): among the power-of-two candidates, pick the
    bucket whose OBSERVED per-chunk h2d rate (seconds per byte, exact
    bucket only — the op-global fallback rate would make every candidate
    tie) is best; buckets without enough observations don't compete, and a
    fully cold store keeps the static 64 MB default. Chunking never
    changes the concatenated bytes, so the choice is bit-identical by
    construction. The pick is surfaced as `h2d_chunk_bytes` in
    routing_stats."""
    from ballista_tpu.ops import costmodel

    best, best_rate = _H2D_CHUNK_BYTES, None
    for cand in _H2D_CHUNK_CANDIDATES:
        r = costmodel.bucket_rate("h2d", cand)
        if r is None:
            continue
        if best_rate is None or r < best_rate:
            best, best_rate = cand, r
    with _routing_lock:
        _routing["h2d_chunk_bytes"] = best
    return best


def upload_array(arr: np.ndarray):
    """Host->device transfer of one numpy array. Arrays past
    _H2D_MIN_CHUNKED split along axis 0 into _h2d_chunk_bytes() chunks
    (the cost store's observed h2d rates pick the chunk size; 64 MB when
    cold), double-buffered (dispatch chunk j, then block on chunk j-1 and
    record its h2d cost), and concatenate on device — bit-identical to the
    single put, with a transient 2x HBM peak for this one array. Small
    arrays — and every array while the cost model is off (the chunked
    path's extra device copy and HBM peak are part of the adaptive tier,
    and its observations would be discarded anyway) — keep the plain async
    jnp.asarray dispatch."""
    import jax.numpy as jnp

    from ballista_tpu.ops import costmodel

    nbytes = arr.nbytes
    rows = arr.shape[0] if arr.ndim else 0
    if not costmodel.enabled() or nbytes < _H2D_MIN_CHUNKED or rows < 2:
        return jnp.asarray(arr)
    row_bytes = max(1, nbytes // rows)
    chunk_rows = max(1, _h2d_chunk_bytes() // row_bytes)
    if chunk_rows >= rows:
        return jnp.asarray(arr)
    chunks = []
    prev = prev_t0 = None
    for lo in range(0, rows, chunk_rows):
        t0 = time.perf_counter()
        c = jnp.asarray(np.ascontiguousarray(arr[lo:lo + chunk_rows]))
        if prev is not None:
            prev.block_until_ready()
            costmodel.observe("h2d", prev.nbytes,
                              time.perf_counter() - prev_t0)
        prev, prev_t0 = c, t0
        chunks.append(c)
    prev.block_until_ready()
    costmodel.observe("h2d", prev.nbytes, time.perf_counter() - prev_t0)
    record_routing_event("h2d_chunked")
    return jnp.concatenate(chunks, axis=0)

"""Chunked segment layout: cardinality-independent grouped aggregation.

The device path's round-1 ceiling was group count: XLA lowers segment_* to
scatter (serializes on TPU) and unrolled per-group reductions are O(G)
passes. This module removes the ceiling with a cache-time data layout
instead of a clever kernel:

  host, once per (partition, group-key set):
    sort rows by group key, assign dense ranks, split every rank's run of
    rows into chunks of L1 (L1 = power of two covering the 90th-percentile
    run length), and materialize the used columns as [V, L1] tiles (zero
    padded). V = number of chunks; chunks are emitted in rank order, so the
    chunk->rank "owner" array is sorted.

  device, per query (ONE call, one readback):
    evaluate filter masks / value expressions elementwise on the [V, L1]
    tiles, reduce axis 1 -> per-chunk partials [n_out, V]. Pure VPU work,
    no scatter, no matmul: O(N) regardless of G, and f32 sums reduce in
    tree order (better than sequential accumulation).

  host, per query:
    fold chunk partials to groups with np.*.reduceat over the sorted owner
    array (identity when every rank has one chunk, the common case).

Reference equivalent: the hash-aggregate kernels DataFusion provides under
HashAggregateExec (rust/core/proto/ballista.proto:370-384); the redesign
trades their per-row hash table for sorted residency + static shapes, which
is what XLA/TPU wants.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def _chunk_spans(starts: np.ndarray, lens: np.ndarray, L: int,
                 min_one_chunk: bool = True):
    """Split each group's [start, start+len) row range into chunks of <= L
    rows. Vectorized. Returns (chunk start rows [V], chunk lengths [V],
    owner group of each chunk [V], all in group order)."""
    nchunks = -(-lens // L)
    if min_one_chunk:
        nchunks = np.maximum(nchunks, 1)
    V = int(nchunks.sum())
    owner = np.repeat(np.arange(len(lens), dtype=np.int64), nchunks)
    offs = np.repeat(np.cumsum(nchunks) - nchunks, nchunks)
    chunk_pos = np.arange(V, dtype=np.int64) - offs
    cstart = starts[owner] + chunk_pos * L
    clen = np.clip(lens[owner] - chunk_pos * L, 0, L)
    return cstart, clen, owner


class SortedSegmentLayout:
    """Host-side artifact built once per partition per group-key set."""

    def __init__(self, codes: np.ndarray, n_groups: int,
                 cover_max: bool = False, force_L1: Optional[int] = None,
                 min_one_chunk: bool = True) -> None:
        order = np.argsort(codes, kind="stable")
        sorted_codes = codes[order]
        grid = np.arange(n_groups, dtype=np.int64)
        starts = np.searchsorted(sorted_codes, grid)
        ends = np.searchsorted(sorted_codes, grid, side="right")
        lens = ends - starts

        # cover_max: one chunk per group whenever the longest run fits 1024
        # (fact-agg needs chunk partials == group partials); default: cover
        # the 90th percentile and let fold_* handle the tail.
        # force_L1: mesh shards must share one tile width so their [V, L1]
        # tiles stack into a single sharded array.
        if force_L1 is not None:
            L1 = force_L1
        else:
            target = int(lens.max()) if (cover_max and n_groups) else (
                int(np.percentile(lens, 90)) if n_groups else 1
            )
            L1 = 8
            while L1 < target and L1 < 1024:
                L1 <<= 1
        # min_one_chunk=False: groups with no rows here get NO chunk (mesh
        # shards fold to dense [G] with in-program segment ops, which supply
        # the identity for absent groups; the host fold_* path needs the
        # dense chunk cover instead)
        cstart, clen, owner = _chunk_spans(
            starts, lens, L1, min_one_chunk=min_one_chunk
        )

        V = len(owner)
        # int32 index math: at SF=100 these transients are the prepare's
        # host-memory peak (600M rows: int64 idx alone was 9.6 GB; the
        # whole prepare OOM-killed a 125 GB host before this). Oversized
        # partitions must DECLINE to the host path, not wrap indices.
        if len(codes) >= (1 << 31):
            from ballista_tpu.ops.runtime import UnsupportedOnDevice

            raise UnsupportedOnDevice(
                f"partition of {len(codes)} rows exceeds int32 row indexing"
            )
        idx = cstart.astype(np.int32)[:, None] + np.arange(L1, dtype=np.int32)[None, :]
        idx = np.where(
            np.arange(L1, dtype=np.int32)[None, :] < clen[:, None], idx, 0
        )

        self.n_groups = n_groups
        self.L1 = L1
        self.V = V
        # valid-row count per chunk; the [V, L1] boolean mask it implies is
        # expanded IN-PROGRAM (arange(L1) < clen[:, None]) — shipping the
        # bool tiles cost 1 byte/slot of HBM (1.05 GB at SF=100, exactly
        # the margin that pushed q5 past the budget)
        self.clen = clen.astype(np.int16)
        # take-index into ORIGINAL row positions
        self.row_take = order.astype(np.int32)[idx.reshape(-1)].reshape(V, L1)
        del idx
        self.owner = owner  # sorted [V]
        # fold_*'s reduceat bookkeeping assumes every group owns >=1 chunk;
        # min_one_chunk=False layouts fold in-program instead (mesh path)
        self._host_folds = min_one_chunk
        self.one_chunk_per_group = min_one_chunk and V == n_groups
        if self._host_folds and not self.one_chunk_per_group:
            self._fold_starts = np.searchsorted(owner, grid)

    # ------------------------------------------------------------------
    def state(self) -> dict:
        """Persistable post-materialize scalars (ops/layout_cache.py).
        row_take is intentionally absent: it is only needed to materialize,
        and persisted entries carry already-materialized tiles."""
        return {
            "n_groups": int(self.n_groups),
            "L1": int(self.L1),
            "V": int(self.V),
            "host_folds": bool(self._host_folds),
            "one_chunk_per_group": bool(self.one_chunk_per_group),
        }

    @classmethod
    def from_state(cls, meta: dict, owner: np.ndarray, clen: np.ndarray):
        """Rehydrate a layout from persisted state; supports every
        post-materialize consumer (fold_*, one_chunk_per_group checks) but
        not materialize()."""
        self = cls.__new__(cls)
        self.n_groups = int(meta["n_groups"])
        self.L1 = int(meta["L1"])
        self.V = int(meta["V"])
        self.owner = owner
        self.clen = clen.astype(np.int16)
        self.row_take = None  # materialize() unsupported after rehydration
        self._host_folds = bool(meta["host_folds"])
        self.one_chunk_per_group = bool(meta["one_chunk_per_group"])
        if self._host_folds and not self.one_chunk_per_group:
            self._fold_starts = np.searchsorted(
                owner, np.arange(self.n_groups, dtype=np.int64)
            )
        return self

    def materialize(self, col: np.ndarray) -> np.ndarray:
        """Lay a row-space column out as [V, L1] tiles (pad slots carry row
        0's value; every consumer masks with the clen-derived pad)."""
        return col[self.row_take.reshape(-1)].reshape(self.V, self.L1)

    # ------------------------------------------------------------------
    def fold_sum(self, chunk_partials: np.ndarray) -> np.ndarray:
        assert self._host_folds, "min_one_chunk=False layouts fold in-program"
        if self.one_chunk_per_group:
            return chunk_partials
        # widen before folding: float for accuracy, int so exact chunk sums
        # stay exact across groups of any size
        if chunk_partials.dtype == np.float32:
            cp = chunk_partials.astype(np.float64)
        elif chunk_partials.dtype == np.int32:
            cp = chunk_partials.astype(np.int64)
        else:
            cp = chunk_partials
        return np.add.reduceat(cp, self._fold_starts)

    def fold_min(self, chunk_partials: np.ndarray) -> np.ndarray:
        assert self._host_folds, "min_one_chunk=False layouts fold in-program"
        if self.one_chunk_per_group:
            return chunk_partials
        return np.minimum.reduceat(chunk_partials, self._fold_starts)

    def fold_max(self, chunk_partials: np.ndarray) -> np.ndarray:
        assert self._host_folds, "min_one_chunk=False layouts fold in-program"
        if self.one_chunk_per_group:
            return chunk_partials
        return np.maximum.reduceat(chunk_partials, self._fold_starts)

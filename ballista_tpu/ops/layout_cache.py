"""Persisted device-layout cache: warm starts for expensive stage prepares.

The cache-time host work behind the device path — parquet decode, string
dictionary encoding, dense ranking of group keys, the chunked-segment sort,
tile materialization, narrowing — is O(N log N) host work that dominates
cold-start latency at scale (measured 600s of the 737s TPC-H q3 SF=100 cold
query on one core; the warm query is 9s). It is also a pure function of
(stage plan fingerprint, input file mtimes). This module persists the
staged host-side artifacts (narrow numpy tiles, LUTs, group key values,
layout metadata, string dictionary snapshots) so a NEW process skips
straight to the h2d transfer: cold q3 SF=100 drops to roughly disk-read +
transfer time.

This is the scan-side analog of the reference's shuffle materialization
(rust/executor/src/flight_service.rs:104-126 persists every stage output
before downstream consumption); here the persisted artifact is the
device-ready input layout rather than a stage result.

Storage layout (one directory per (stage fingerprint, partition)):
  meta.json          versioned manifest: kind, scalars, array manifest
  a<i>.npy           numpy arrays (cols, luts, pad bits, codes, key values)
  (dictionary snapshots ride as string-array .npy)

Keys hash the kernel dispatcher's stage cache key (plan display + scan
files + mtimes + config flags), so a rewritten input file or changed config
misses cleanly. Writes are capped by ballista.tpu.layout_cache_cap_bytes
(oldest-mtime directories evicted first) and are atomic (tmpdir + rename).
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import tempfile
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

# bump to invalidate all persisted entries. v4: float-bits key planes join
# the staged column set and the fused top-k epilogue forces a
# one-chunk-per-group cover — entries written by v3 lack both. v5
# (ISSUE 15 satellite): batch.size folds into the stage/persist key
# (append-only when non-default), and shared-scan eligibility RELIES on a
# warm entry being at the dispatching batch granularity — a v4 store may
# hold suffix-less entries written at ANY batch size, so it is orphaned
# wholesale rather than trusted. v6 (ISSUE 19): parquet-backed batch
# entries move from one-blob-per-(file set, partition) to one entry per
# (path, mtime, size, chunk_index) so appends re-prepare only new chunks;
# whole-set v5 blobs would shadow the chunk store, so they are orphaned.
_FORMAT = 6


def cache_dir_for(base: str, stage_key: str, partition: int) -> str:
    h = hashlib.sha256(f"v{_FORMAT}|{stage_key}|p{partition}".encode()).hexdigest()
    return os.path.join(base, h[:2], h)


def _write_arrays(d: str, arrays: List[np.ndarray]) -> List[int]:
    ids = []
    for i, a in enumerate(arrays):
        np.save(os.path.join(d, f"a{i}.npy"), a, allow_pickle=False)
        ids.append(i)
    return ids


# in-flight write dirs carry this prefix so eviction never deletes them
# while live; ones untouched this long are crashed writers' orphans
_TMP_PREFIX = ".wip-"
_WIP_ORPHAN_S = 6 * 3600.0  # > any plausible single-entry write


def _dir_bytes(base: str) -> int:
    """Committed bytes under base. In-flight .wip- writer dirs are excluded:
    they are not evictable, so counting them against the cap would let one
    concurrent large write force eviction of every committed entry and still
    decline the incoming save (the cap is best-effort and transient
    overshoot while writers finish is the lesser harm)."""
    total = 0
    for root, dirs, files in os.walk(base):
        dirs[:] = [d for d in dirs if not d.startswith(_TMP_PREFIX)]
        for f in files:
            try:
                total += os.path.getsize(os.path.join(root, f))
            except OSError:
                pass
    return total


# per-process running total per cache base: a full os.walk of a ~48 GiB tree
# per save is O(entries^2) stat traffic as the cache fills. The estimate is
# refreshed with a real walk only when it says the cap is exceeded (other
# processes' writes are invisible until then — the cap stays best-effort).
from ballista_tpu.utils.locks import make_lock

_size_lock = make_lock("ops.layout_cache._size_lock")
# guarded-by: _size_lock
_size_cache: Dict[str, int] = {}  # base dir -> bytes


def _size_note(base: str, delta: int) -> None:
    with _size_lock:
        if base in _size_cache:
            _size_cache[base] = max(0, _size_cache[base] + delta)


def _evict_to_cap(base: str, incoming: int, cap: int) -> bool:
    """Evict oldest entry dirs until `incoming` fits under `cap`.
    Returns False when it cannot fit (entry bigger than the whole cap)."""
    if incoming > cap:
        return False
    with _size_lock:
        total = _size_cache.get(base)
    if total is not None and total + incoming <= cap:
        return True
    total = _dir_bytes(base)  # estimate says over-cap (or unknown): re-walk
    with _size_lock:
        _size_cache[base] = total
    if total + incoming <= cap:
        return True
    entries = []
    for shard in os.listdir(base):
        sp = os.path.join(base, shard)
        if not os.path.isdir(sp):
            continue
        for name in os.listdir(sp):
            p = os.path.join(sp, name)
            if not os.path.isdir(p):
                continue
            if name.startswith(_TMP_PREFIX):
                # a LIVE writer's in-flight tmpdir must not be evicted —
                # rmtree mid-write would silently drop the ~600s prepare it
                # is persisting. A crashed writer's orphan, however, would
                # hold disk forever; reclaim once clearly abandoned. (wip
                # bytes are excluded from `total`, so no cap adjustment.)
                try:
                    if time.time() - os.path.getmtime(p) > _WIP_ORPHAN_S:
                        shutil.rmtree(p, ignore_errors=True)
                except OSError:
                    pass
                continue
            try:
                entries.append((os.path.getmtime(p), p, _dir_bytes(p)))
            except OSError:
                pass
    entries.sort()
    for _mtime, p, nbytes in entries:
        if total + incoming <= cap:
            break
        shutil.rmtree(p, ignore_errors=True)
        total -= nbytes
        _size_note(base, -nbytes)
    return total + incoming <= cap


def save_entry(
    base: str,
    stage_key: str,
    partition: int,
    meta: dict,
    arrays: List[np.ndarray],
    cap_bytes: int,
) -> None:
    """Atomically persist one prepared-partition artifact. `meta` must be
    JSON-serializable and reference arrays by index into `arrays`.
    Best-effort: any failure leaves no partial entry and never raises."""
    try:
        target = cache_dir_for(base, stage_key, partition)
        if os.path.isdir(target):
            return
        incoming = sum(a.nbytes for a in arrays)
        os.makedirs(os.path.dirname(target), exist_ok=True)
        if not _evict_to_cap(base, incoming, cap_bytes):
            return
        tmp = tempfile.mkdtemp(dir=os.path.dirname(target), prefix=_TMP_PREFIX)
        try:
            _write_arrays(tmp, arrays)
            with open(os.path.join(tmp, "meta.json"), "w") as f:
                json.dump({"format": _FORMAT, **meta}, f)
            try:
                os.rename(tmp, target)
                _size_note(base, incoming)
            except OSError:  # raced with another writer: keep theirs
                shutil.rmtree(tmp, ignore_errors=True)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
    except Exception:
        return


def load_entry(
    base: str, stage_key: str, partition: int
) -> Optional[Tuple[dict, List[np.ndarray]]]:
    """Load a persisted artifact; None on miss or any corruption."""
    d = cache_dir_for(base, stage_key, partition)
    try:
        with open(os.path.join(d, "meta.json")) as f:
            meta = json.load(f)
        if meta.get("format") != _FORMAT:
            return None
        arrays = []
        i = 0
        while os.path.exists(os.path.join(d, f"a{i}.npy")):
            arrays.append(np.load(os.path.join(d, f"a{i}.npy"), allow_pickle=False))
            i += 1
        if i != meta.get("n_arrays", i):
            return None
        try:
            os.utime(d)  # LRU recency for _evict_to_cap
        except OSError:
            pass  # read-only cache: the hit still counts
        return meta, arrays
    except Exception:
        return None


# -- (de)hydration helpers for the stage entry shapes -----------------------

def pack_arrow_arrays(arrays_pa) -> np.ndarray:
    """Serialize a list of equal-length Arrow arrays (group key values — any
    Arrow type: strings, dates, decimals) as one uint8 IPC-file buffer, so
    they ride the numpy-only entry format unchanged."""
    import pyarrow as pa

    cols = {}
    for i, kv in enumerate(arrays_pa):
        if isinstance(kv, pa.ChunkedArray):
            kv = kv.combine_chunks()
        elif not isinstance(kv, pa.Array):
            kv = pa.array(kv)
        cols[f"k{i}"] = kv
    table = pa.table(cols) if cols else pa.table({})
    sink = pa.BufferOutputStream()
    with pa.ipc.new_file(sink, table.schema) as w:
        w.write_table(table)
    return np.frombuffer(sink.getvalue(), dtype=np.uint8).copy()


def unpack_arrow_arrays(buf: np.ndarray) -> List:
    import pyarrow as pa

    table = pa.ipc.open_file(pa.BufferReader(buf.tobytes())).read_all()
    return [table.column(i).combine_chunks() for i in range(table.num_columns)]


def pack_dict_snapshot(dicts) -> Tuple[dict, List[np.ndarray]]:
    """Snapshot a ScanDictionaries registry as (meta, arrays). String codes
    are baked into the persisted tiles; a fresh process must adopt the SAME
    value->code mapping or compiled predicates (built from the live
    dictionary at run time) would compare against different codes."""
    meta = {}
    arrays: List[np.ndarray] = []
    for idx, d in dicts.dicts.items():
        snap = d.snapshot()
        if snap is None:
            continue
        meta[str(idx)] = len(arrays)
        arrays.append(np.asarray(snap.to_pylist(), dtype=object).astype(str))
    return meta, arrays


def adopt_dict_snapshot(dicts, meta: dict, arrays: List[np.ndarray]) -> bool:
    """Restore dictionary state. Refuses (False) when a live dictionary is
    NOT a prefix of the snapshot — codes would be inconsistent with the
    persisted tiles. (Growth is append-only, so a same-plan process that
    compiled the same literals first always passes.)"""
    import pyarrow as pa
    import pyarrow.compute as pc

    for key, ai in meta.items():
        idx = int(key)
        values = pa.array(list(arrays[ai]))
        d = dicts.for_column(idx)
        with d._lock:
            cur = d.values
            if cur is not None:
                if len(cur) > len(values):
                    return False
                if len(cur) and not pc.all(
                    pc.equal(cur, values.slice(0, len(cur)))
                ).as_py():
                    return False
            d.values = values
    return True

"""COUNT-over-LEFT-join as device membership counting (q13/q22 wiring).

TPC-H q13's inner aggregate —

    SELECT c_custkey, COUNT(o_orderkey) FROM customer
    LEFT OUTER JOIN orders ON c_custkey = o_custkey [AND <orders filter>]
    GROUP BY c_custkey

— materializes the whole joined table on the host path just to count
matches per customer. But COUNT(<right column>) grouped by left-side keys
IS the per-probe match run-length the PR 4 device join already computes:
``ops/join.py device_membership_counts`` (the counts-only entry of
``device_join_indices``) returns exactly one int64 count per LEFT row, with
NULL keys and NULL counted values excluded the way SQL COUNT demands. The
join's M:N expansion never happens — no gather, no multiplicity tier, one
int32-per-probe readback — and the aggregate reduces to summing counts per
group key over the LEFT table alone.

``try_count_left_join`` routes a matching HashAggregateExec through that
plane and returns the aggregated table (bit-identical to the host path:
counts are exact integers and the group-by reduction is the same pyarrow
hash aggregation the host runs, just over left rows + counts instead of
the expanded join); None hands the shape back to the normal kernel ladder.
The ANTI-join half of the carry-over (q22's NOT EXISTS) lives in
physical/join.py, which keeps rows off the same counts plane.

Admitted shape (everything else returns None — a prescreen, not a decline):

- mode SINGLE or PARTIAL;
- input chain of schema-preserving passthroughs (Merge/CoalesceBatches)
  over a LEFT HashJoinExec without residual filter;
- every group key a plain column of the join's LEFT side;
- every aggregate COUNT over a plain column of the join's RIGHT side.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np
import pyarrow as pa

from ballista_tpu.physical import expr as px
from ballista_tpu.physical.basic import CoalesceBatchesExec, MergeExec
from ballista_tpu.physical.plan import TaskContext, collect_partition

_PASSTHROUGH = (MergeExec, CoalesceBatchesExec)


def _match_shape(agg):
    """(join, merges_input) for an admissible aggregate, else None."""
    from ballista_tpu.logical.plan import JoinType
    from ballista_tpu.physical.aggregate import AggregateMode
    from ballista_tpu.physical.join import HashJoinExec

    if agg.mode not in (AggregateMode.SINGLE, AggregateMode.PARTIAL):
        return None
    node = agg.input
    merged = False
    while isinstance(node, _PASSTHROUGH):
        merged = merged or isinstance(node, MergeExec)
        node = node.input
    if (
        not isinstance(node, HashJoinExec)
        or node.join_type != JoinType.LEFT
        or node.filter is not None
    ):
        return None
    n_left = len(node.left.schema())
    for e, _name in agg.group_exprs:
        if not isinstance(e, px.ColumnExpr) or e.index >= n_left:
            return None
    if not agg.aggr_funcs:
        return None
    for a in agg.aggr_funcs:
        if (
            a.fn != "count"
            or not isinstance(a.expr, px.ColumnExpr)
            or a.expr.index < n_left
        ):
            return None
    return node, merged


def _partition_counts(
    left: pa.Table, right: pa.Table, join, counted: List[int]
) -> Optional[List[np.ndarray]]:
    """One int64 counts array per counted right column, for this partition's
    (left, right) pair. A counted column with nulls gets its own device
    pass over the null-filtered build rows (COUNT skips nulls); null-free
    columns (the common case — join/count keys are usually primary keys)
    share one pass."""
    import pyarrow.compute as pc

    from ballista_tpu.ops.join import device_membership_counts
    from ballista_tpu.physical.joinutil import combined_key_codes

    left_keys = [n for n, _ in join.on]
    right_keys = [n for _, n in join.on]
    n_left_rows = left.num_rows
    shared: Optional[np.ndarray] = None
    out: List[Optional[np.ndarray]] = []
    for idx in counted:
        col = right.column(idx - len(join.left.schema()))
        if col.null_count == 0:
            out.append(None)  # filled from the shared pass below
            continue
        valid = right.filter(pc.is_valid(col))
        if valid.num_rows == 0 or n_left_rows == 0:
            out.append(np.zeros(n_left_rows, dtype=np.int64))
            continue
        bcodes, pcodes = combined_key_codes(
            [valid.column(k) for k in right_keys],
            [left.column(k) for k in left_keys],
        )
        counts = device_membership_counts(bcodes, pcodes)
        if counts is None:
            return None
        out.append(counts)
    if any(c is None for c in out):
        if right.num_rows == 0 or n_left_rows == 0:
            shared = np.zeros(n_left_rows, dtype=np.int64)
        else:
            bcodes, pcodes = combined_key_codes(
                [right.column(k) for k in right_keys],
                [left.column(k) for k in left_keys],
            )
            shared = device_membership_counts(bcodes, pcodes)
            if shared is None:
                return None
    return [shared if c is None else c for c in out]


def try_count_left_join(agg, partition: int, ctx: TaskContext) -> Optional[pa.Table]:
    """Aggregated output table (partial-state shape: group columns then one
    int64 count column per aggregate) for an admissible COUNT-over-LEFT-join,
    or None to fall through to the normal ladder."""
    m = _match_shape(agg)
    if m is None:
        return None
    join, merged = m
    n_join_parts = join.output_partitioning().partition_count()
    # a MergeExec in the chain merges EVERY join partition into this one
    # call; without it the aggregate drives exactly one join partition
    parts = range(n_join_parts) if merged else [partition]
    counted = [a.expr.index for a in agg.aggr_funcs]
    key_chunks: List[List[pa.Array]] = [[] for _ in agg.group_exprs]
    count_chunks: List[List[np.ndarray]] = [[] for _ in counted]
    for p in parts:
        if join.partitioned:
            left = collect_partition(join.left, p, ctx)
        else:
            left = join._collect_build(join.left, ctx)
        right = collect_partition(join.right, p, ctx)
        counts = _partition_counts(left, right, join, counted)
        if counts is None:
            return None  # device declined (reason already recorded)
        for i, (e, _name) in enumerate(agg.group_exprs):
            key_chunks[i].append(left.column(e.index))
        for i, c in enumerate(counts):
            count_chunks[i].append(c)
    cols = {}
    keys = []
    for i, chunks in enumerate(key_chunks):
        kn = f"__g{i}"
        cols[kn] = pa.chunked_array(chunks).combine_chunks()
        keys.append(kn)
    for i, chunks in enumerate(count_chunks):
        cols[f"__c{i}"] = pa.array(np.concatenate(chunks), type=pa.int64())
    t = pa.table(cols)
    from ballista_tpu.physical.aggregate import HashAggregateExec, _cast_to_schema

    specs = [(f"__c{i}", "sum", None) for i in range(len(counted))]
    key_tbl, agg_arrays = HashAggregateExec._group_aggregate(t, keys, specs)
    out_cols = [key_tbl.column(i) for i in range(len(keys))]
    # COUNT is never NULL: summing zero count rows (empty input) yields
    # null from pyarrow; the host path's count produces 0
    import pyarrow.compute as pc

    out_cols += [pc.fill_null(a, 0) for a in agg_arrays]
    from ballista_tpu.utils import tracing

    tracing.incr("device.count_join")
    # partial-state shape (group cols, then one int64 per count): SINGLE
    # callers run _final over it (a per-group identity fold), PARTIAL
    # callers ship it as the partial state — count's state IS the count
    state_schema = pa.schema(
        [pa.field(n, cols[k].type) for k, (_, n) in zip(keys, agg.group_exprs)]
        + [f for a in agg.aggr_funcs for f in a.state_fields()]
    )
    return _cast_to_schema(out_cols, state_schema)

"""HBM-resident cross-stage exchange registry (ISSUE 16).

When a shuffle-write task completes, the executor ALSO registers the piece
batches it just published in this in-process, byte-budgeted registry — the
Arrow piece on disk/shared storage remains the authoritative fault-tolerant
home, written exactly as before. A consuming shuffle reader on the SAME
executor then resolves the piece straight from the registry: zero IPC
decode, zero h2d re-upload. Anything else — eviction, budget pressure, a
chaos verdict, executor death (the registry dies with the process) — falls
through silently to the existing storage -> Flight peer -> lineage ladder,
so bit-identity to the un-exchanged pipeline holds at every decision point.

On this (CPU) image the registered entries are the host-side Arrow batches
the piece holds; on a device image the entry would additionally pin the
stage's device tiles (pod/ICI exchange is the ROADMAP residue). Entries are
keyed by (executor_id, job, stage, map partition, piece) — executor_id
because a StandaloneCluster runs several executors in one process, and a
piece is only "local" to the executor that produced it. The newest attempt
wins on re-publish: every attempt of a task produces bit-identical output
(the repo-wide invariant speculation already relies on), so any attempt's
entry is a valid serve.

Eviction under ``ballista.tpu.residency_budget_bytes`` is cost-model-gated
(ISSUE 16 tentpole): an incomer only displaces colder entries when its
predicted transfer saving — bytes priced at the OBSERVED h2d + readback
rates (ops/costmodel.py), bytes-proportional when cold — exceeds what the
evicted victims would have saved. Rates are read BEFORE the registry lock
is taken, so ``ops.exchange._reg_lock`` stays a leaf lock.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import pyarrow as pa

from ballista_tpu.utils.locks import make_lock

_reg_lock = make_lock("ops.exchange._reg_lock")
# (executor_id, job_id, stage_id, map_partition, piece) -> _Entry
_entries: Dict[Tuple[str, str, int, int, int], "_Entry"] = {}  # guarded-by: _reg_lock
# published piece path -> entry key, for the Flight service's path-keyed
# FetchPartition lookups; guarded-by: _reg_lock
_by_path: Dict[str, Tuple[str, str, int, int, int]] = {}
_total_bytes: int = 0  # guarded-by: _reg_lock
# tenant -> resident bytes (ISSUE 19 satellite): the per-tenant half of
# the budget ledger, kept exactly in sync with _entries by every mutation
_tenant_bytes: Dict[str, int] = {}  # guarded-by: _reg_lock


class _Entry:
    __slots__ = ("batches", "schema", "nbytes", "attempt", "path",
                 "saving_s", "last_used", "tenant")

    def __init__(self, batches: List[pa.RecordBatch], schema: pa.Schema,
                 nbytes: int, attempt: int, path: str,
                 saving_s: float, tenant: str = "") -> None:
        self.batches = batches
        self.schema = schema
        self.nbytes = nbytes
        self.attempt = attempt
        self.path = path
        # predicted transfer seconds a serve of this entry avoids, priced
        # at publish time (entries carry it so eviction never has to call
        # into the cost model while holding the leaf _reg_lock)
        self.saving_s = saving_s
        self.last_used = time.monotonic()
        self.tenant = tenant


# holds-lock: _reg_lock
def _drop_entry_locked(key: Tuple[str, str, int, int, int]) -> "_Entry":
    """Remove one entry and settle BOTH byte ledgers (global + tenant)."""
    global _total_bytes
    e = _entries.pop(key)
    _by_path.pop(e.path, None)
    _total_bytes -= e.nbytes
    if e.tenant in _tenant_bytes:
        _tenant_bytes[e.tenant] -= e.nbytes
        if _tenant_bytes[e.tenant] <= 0:
            del _tenant_bytes[e.tenant]
    return e


def predicted_transfer_saving_s(nbytes: int) -> float:
    """Seconds of transfer a registry serve of `nbytes` avoids: one decode+
    re-upload (h2d-shaped) on the consumer plus one readback-shaped re-read
    on the producer side, priced at the cost model's OBSERVED per-bucket
    rates (ops/costmodel.py, bytes units — the same store upload_array and
    readback feed). Cold model: a nominal bytes-proportional rate (10 GB/s)
    so the keep/evict and locality decisions still order by size instead of
    collapsing to zero."""
    from ballista_tpu.ops import costmodel

    fallback = float(nbytes) / (10 * 1024**3)
    h2d = costmodel.predict("h2d", float(nbytes))
    rb = costmodel.predict("readback", float(nbytes))
    return (h2d if h2d is not None else fallback) + (
        rb if rb is not None else fallback
    )


def publish(executor_id: str, job_id: str, stage_id: int, map_partition: int,
            piece: int, batches: List[pa.RecordBatch], schema: pa.Schema,
            attempt: int, path: str, budget: int,
            tenant: str = "", tenant_budget: int = 0) -> bool:
    """Register one published piece's batches; returns whether it was kept.

    Called only AFTER the authoritative os.replace publish, so the registry
    never advertises bytes the piece ladder cannot also produce. Under
    budget pressure the incomer displaces least-recently-used entries only
    when its predicted transfer saving exceeds the victims' combined saving
    — otherwise it is skipped and the consumer pays the ordinary ladder.

    ``tenant_budget`` > 0 caps this TENANT's resident bytes (ISSUE 19
    satellite), enforced BEFORE the global budget with the same
    cost-gated LRU policy restricted to the tenant's own entries — one
    tenant's giant shuffle evicts its own cold pieces first and can
    never displace another tenant's to fit itself.
    """
    from ballista_tpu.ops.runtime import record_exchange

    nbytes = sum(b.nbytes for b in batches)
    if nbytes <= 0 or nbytes > budget or (
        0 < tenant_budget < nbytes
    ):
        record_exchange("skipped_budget")
        return False
    # price the incomer BEFORE the lock: _reg_lock is a leaf and must not
    # reach into the cost model while held
    saving = predicted_transfer_saving_s(nbytes)
    key = (executor_id, job_id, int(stage_id), int(map_partition), int(piece))
    evicted = 0
    tenant_evicted = 0
    kept = True
    with _reg_lock:
        # leaf lock: nothing else (counters included) is taken while held
        global _total_bytes
        if key in _entries:
            # re-publish (retry/speculative duplicate): newest attempt wins
            _drop_entry_locked(key)

        def lru_plan(pool, need):
            """(victim keys, freed, their saving) — LRU-first over pool."""
            victims = sorted(pool, key=lambda kv: kv[1].last_used)
            freed, victim_saving, victim_keys = 0, 0.0, []
            for vk, ve in victims:
                if freed >= need:
                    break
                victim_keys.append(vk)
                freed += ve.nbytes
                victim_saving += ve.saving_s
            return victim_keys, freed, victim_saving

        # per-tenant cap first: the tenant may only displace ITSELF
        if tenant_budget > 0:
            t_need = _tenant_bytes.get(tenant, 0) + nbytes - tenant_budget
            if t_need > 0:
                own = [kv for kv in _entries.items() if kv[1].tenant == tenant]
                victim_keys, freed, victim_saving = lru_plan(own, t_need)
                if freed < t_need or victim_saving > saving:
                    kept = False
                else:
                    for vk in victim_keys:
                        _drop_entry_locked(vk)
                        tenant_evicted += 1
        if kept:
            need = _total_bytes + nbytes - budget
            if need > 0:
                victim_keys, freed, victim_saving = lru_plan(
                    _entries.items(), need
                )
                if freed < need or victim_saving > saving:
                    # cannot fit, or the victims' predicted transfer saving
                    # (priced at the observed h2d/readback rates when they
                    # published) exceeds the incomer's: keep what is warm
                    kept = False
                else:
                    for vk in victim_keys:
                        _drop_entry_locked(vk)
                        evicted += 1
        if kept:
            entry = _Entry(list(batches), schema, nbytes, attempt, path,
                           saving, tenant)
            _entries[key] = entry
            _by_path[path] = key
            _total_bytes += nbytes
            _tenant_bytes[tenant] = _tenant_bytes.get(tenant, 0) + nbytes
    if not kept:
        record_exchange("skipped_budget")
        return False
    if tenant_evicted:
        record_exchange("evicted_tenant_budget", tenant_evicted)
    if evicted:
        record_exchange("evicted_budget", evicted)
    record_exchange("published")
    record_exchange("publish_bytes", nbytes)
    return True


def resolve(executor_id: str, job_id: str, stage_id: int, map_partition: int,
            piece: int) -> Optional[Tuple[List[pa.RecordBatch], int]]:
    """(batches, nbytes) when this executor holds the piece, else None.
    Counters are the CALLER's job — the consumer and the Flight service
    account a hit differently (h2d vs d2h saved)."""
    key = (executor_id, job_id, int(stage_id), int(map_partition), int(piece))
    with _reg_lock:
        e = _entries.get(key)
        if e is None:
            return None
        e.last_used = time.monotonic()
        return list(e.batches), e.nbytes


def resolve_path(path: str) -> Optional[Tuple[pa.Schema, List[pa.RecordBatch], int]]:
    """(schema, batches, nbytes) for a published piece path, else None —
    the Flight service's FetchPartition fast path (tickets carry paths,
    not plan coordinates)."""
    with _reg_lock:
        key = _by_path.get(path)
        if key is None:
            return None
        e = _entries[key]
        e.last_used = time.monotonic()
        return e.schema, list(e.batches), e.nbytes


def evict(executor_id: str, job_id: str, stage_id: int, map_partition: int,
          piece: int) -> bool:
    """Drop one entry (the exchange.evict chaos seam); True if it existed."""
    key = (executor_id, job_id, int(stage_id), int(map_partition), int(piece))
    with _reg_lock:
        if key not in _entries:
            return False
        _drop_entry_locked(key)
    return True


def evict_job(job_id: str) -> int:
    """Drop every entry of one job (the executor's TTL sweep rides this
    when it removes the job's work dir)."""
    removed = 0
    with _reg_lock:
        for key in [k for k in _entries if k[1] == job_id]:
            _drop_entry_locked(key)
            removed += 1
    return removed


def attempt_of(executor_id: str, job_id: str, stage_id: int,
               map_partition: int, piece: int) -> Optional[int]:
    """The registered attempt for one entry (tests pin newest-attempt-wins
    across speculation promotion)."""
    key = (executor_id, job_id, int(stage_id), int(map_partition), int(piece))
    with _reg_lock:
        e = _entries.get(key)
        return None if e is None else e.attempt


def stage_resident(executor_id: str, job_id: str, stage_id: int,
                   map_partition: int) -> bool:
    """Whether ANY piece of this map task's output is registered here —
    the `resident` hint the executor advertises on its CompletedTask."""
    with _reg_lock:
        return any(
            k[0] == executor_id and k[1] == job_id
            and k[2] == int(stage_id) and k[3] == int(map_partition)
            for k in _entries
        )


def resident_bytes() -> int:
    with _reg_lock:
        return _total_bytes


def tenant_resident_bytes(tenant: str) -> int:
    """One tenant's share of the registry (tests + budget observability)."""
    with _reg_lock:
        return _tenant_bytes.get(tenant, 0)


def reset() -> None:
    """Drop everything (tests)."""
    with _reg_lock:
        global _total_bytes
        _entries.clear()
        _by_path.clear()
        _tenant_bytes.clear()
        _total_bytes = 0

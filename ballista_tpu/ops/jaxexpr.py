"""PhysicalExpr -> JAX compiler.

Lowers an expression tree to a function evaluated inside jit over device
columns. String semantics run over dictionary codes: equality against a
literal becomes a code comparison, LIKE / IN become boolean table gathers
where the table is computed host-side over the (small) dictionary and passed
as a runtime argument (so a growing dictionary never retraces the program —
tables are padded to power-of-two sizes).

This is where the reference's per-row Arrow compute kernels (DataFusion
PhysicalExpr) become branch-free vectorized TPU code.
"""

from __future__ import annotations

import datetime
from typing import Callable, Dict, List, Optional

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from ballista_tpu.ops.runtime import ColumnDictionary, ScanDictionaries, UnsupportedOnDevice
from ballista_tpu.physical import expr as px

# cols: Dict[int, jnp.ndarray]; aux: List[jnp.ndarray]
EvalFn = Callable[[Dict[int, "jnp.ndarray"], List["jnp.ndarray"]], "jnp.ndarray"]


class CompiledValue:
    """A lowered expression: fn computes the value; null_fn (when set)
    computes the rows where the SQL value is NULL — three-valued logic
    over dictionary codes, where -1 marks a NULL string. Predicates with
    null_fn carry an UNDEFINED fn value on null rows; consumers must mask
    (predicate_fn collapses to WHERE semantics: NULL -> excluded)."""

    def __init__(self, kind: str, fn: EvalFn,
                 dictionary: Optional[ColumnDictionary] = None,
                 null_fn: Optional[EvalFn] = None) -> None:
        assert kind in ("num", "bool", "code")
        self.kind = kind
        self.fn = fn
        self.dictionary = dictionary
        self.null_fn = null_fn


def predicate_fn(cv: CompiledValue) -> EvalFn:
    """WHERE-clause collapse of a compiled boolean: rows whose predicate is
    NULL are excluded (SQL three-valued logic)."""
    if cv.null_fn is None:
        return cv.fn
    import jax.numpy as jnp

    def collapsed(cols, aux, v=cv.fn, n=cv.null_fn):
        return jnp.logical_and(v(cols, aux), jnp.logical_not(n(cols, aux)))

    return collapsed


class ExprCompiler:
    """Compiles expressions; records which column indices are needed and the
    aux providers (host-side per-batch table builders)."""

    def __init__(self, schema: pa.Schema, dicts: ScanDictionaries) -> None:
        self.schema = schema
        self.dicts = dicts
        self.used_columns: Dict[int, pa.DataType] = {}
        self.aux_providers: List[Callable[[], np.ndarray]] = []

    # ------------------------------------------------------------------
    def _add_aux(self, provider: Callable[[], np.ndarray]) -> int:
        self.aux_providers.append(provider)
        return len(self.aux_providers) - 1

    def build_aux(self) -> List[np.ndarray]:
        return [p() for p in self.aux_providers]

    # ------------------------------------------------------------------
    def compile(self, e: px.PhysicalExpr) -> CompiledValue:
        import jax.numpy as jnp

        if isinstance(e, px.ColumnExpr):
            idx = e.index
            dtype = self.schema.field(idx).type
            if pa.types.is_dictionary(dtype):
                dtype = dtype.value_type
            self.used_columns[idx] = dtype
            if pa.types.is_string(dtype) or pa.types.is_large_string(dtype):
                d = self.dicts.for_column(idx)
                return CompiledValue("code", lambda cols, aux, i=idx: cols[i], d)
            if pa.types.is_boolean(dtype):
                return CompiledValue("bool", lambda cols, aux, i=idx: cols[i])
            return CompiledValue("num", lambda cols, aux, i=idx: cols[i])

        if isinstance(e, px.LiteralExpr):
            v = e.value
            if isinstance(v, bool):
                return CompiledValue("bool", lambda cols, aux, c=v: jnp.asarray(c))
            if isinstance(v, (int, float)):
                dt = np.float32 if isinstance(v, float) else np.int32
                return CompiledValue(
                    "num", lambda cols, aux, c=dt(v): jnp.asarray(c)
                )
            if isinstance(v, datetime.date) and not isinstance(v, datetime.datetime):
                days = np.int32((v - datetime.date(1970, 1, 1)).days)
                return CompiledValue("num", lambda cols, aux, c=days: jnp.asarray(c))
            if isinstance(v, str):
                # bare string literal: only meaningful inside comparisons,
                # which intercept it before compiling this node
                raise UnsupportedOnDevice("free-standing string literal")
            raise UnsupportedOnDevice(f"literal {v!r}")

        if isinstance(e, px.BinaryPhysicalExpr):
            return self._compile_binary(e)

        if isinstance(e, px.NotExpr):
            inner = self.compile(e.expr)
            # Kleene NOT: value flips, NULL stays NULL (NOT over a code
            # predicate must not turn excluded NULL rows into included ones)
            return CompiledValue(
                "bool",
                lambda cols, aux, f=inner.fn: jnp.logical_not(f(cols, aux)),
                null_fn=inner.null_fn,
            )

        if isinstance(e, px.NegativeExpr):
            inner = self.compile(e.expr)
            return CompiledValue("num", lambda cols, aux, f=inner.fn: -f(cols, aux))

        if isinstance(e, px.IsNullExpr):
            inner = self.compile(e.expr)
            if inner.kind == "code":
                # dictionary-encoded string columns carry nulls as -1 codes
                # (ColumnDictionary.encode) — IS NULL is a code test
                def isnull_code_fn(cols, aux, f=inner.fn, neg=e.negated):
                    r = f(cols, aux) < 0
                    return jnp.logical_not(r) if neg else r

                return CompiledValue("bool", isnull_code_fn)
            # numeric/date/bool device columns are null-free by construction
            # (column_to_numpy rejects nullable batches)
            const = bool(e.negated)  # IS NOT NULL -> True, IS NULL -> False

            def isnull_fn(cols, aux, c=const):
                return jnp.asarray(c)

            return CompiledValue("bool", isnull_fn)

        if isinstance(e, px.BetweenExpr):
            v = self.compile(e.expr)
            lo = self.compile(e.low)
            hi = self.compile(e.high)
            if v.kind != "num":
                raise UnsupportedOnDevice("BETWEEN on non-numeric")

            def between_fn(cols, aux, vf=v.fn, lf=lo.fn, hf=hi.fn, neg=e.negated):
                x = vf(cols, aux)
                r = jnp.logical_and(x >= lf(cols, aux), x <= hf(cols, aux))
                return jnp.logical_not(r) if neg else r

            return CompiledValue("bool", between_fn)

        if isinstance(e, px.InListExpr):
            if e.value_exprs is not None:
                # per-row membership: equality OR-chain on device
                probe = self.compile(e.expr)
                members = [self.compile(ve) for ve in e.value_exprs]
                if probe.kind == "code" or any(m.kind == "code" for m in members):
                    raise UnsupportedOnDevice("expression IN over strings")

                def inlist_expr_fn(cols, aux, pf=probe.fn, ms=members, neg=e.negated):
                    x = pf(cols, aux)
                    r = None
                    for m in ms:
                        eq = x == m.fn(cols, aux)
                        r = eq if r is None else jnp.logical_or(r, eq)
                    return jnp.logical_not(r) if neg else r

                return CompiledValue("bool", inlist_expr_fn)
            v = self.compile(e.expr)
            if v.kind == "code":
                d = v.dictionary
                values = list(e.values)

                def in_table() -> np.ndarray:
                    from ballista_tpu.ops.runtime import bucket_rows

                    # one consistent view: a concurrent encode() may grow
                    # the dictionary between torn len/values reads
                    vals = d.snapshot()
                    n = max(1, 0 if vals is None else len(vals))
                    table = np.zeros(bucket_rows(n, 16), dtype=np.bool_)
                    if vals is not None:
                        member = pc.is_in(vals, value_set=pa.array(values))
                        table[: len(vals)] = member.to_numpy(zero_copy_only=False)
                    return table

                slot = self._add_aux(in_table)

                def inlist_code_fn(cols, aux, vf=v.fn, s=slot, neg=e.negated):
                    r = aux[s][vf(cols, aux)]
                    return jnp.logical_not(r) if neg else r

                def inlist_null(cols, aux, vf=v.fn):
                    # NULL IN / NOT IN a non-empty literal list is NULL
                    return vf(cols, aux) < 0

                return CompiledValue("bool", inlist_code_fn, null_fn=inlist_null)
            # numeric IN list -> chained equality
            consts = [self.compile(px.LiteralExpr(x, pa.float64() if isinstance(x, float) else pa.int64())) for x in e.values]

            def inlist_num_fn(cols, aux, vf=v.fn, cs=consts, neg=e.negated):
                x = vf(cols, aux)
                r = jnp.zeros(x.shape, dtype=bool)
                for c in cs:
                    r = jnp.logical_or(r, x == c.fn(cols, aux))
                return jnp.logical_not(r) if neg else r

            return CompiledValue("bool", inlist_num_fn)

        if isinstance(e, px.CaseExpr):
            arms = []
            for w, t in e.when_then:
                cw = self.compile(w)
                ct = self.compile(t)
                if e.base is not None:
                    raise UnsupportedOnDevice("CASE base form")
                arms.append((cw, ct))
            celse = self.compile(e.else_expr) if e.else_expr is not None else None

            def case_fn(cols, aux, arms=arms, celse=celse):
                out = (
                    celse.fn(cols, aux)
                    if celse is not None
                    else jnp.asarray(np.float32(0))
                )
                for cw, ct in reversed(arms):
                    # a NULL condition does not match its arm (3VL)
                    out = jnp.where(predicate_fn(cw)(cols, aux), ct.fn(cols, aux), out)
                return out

            kind = arms[0][1].kind
            return CompiledValue(kind, case_fn)

        if isinstance(e, px.CastExpr):
            inner = self.compile(e.expr)
            if pa.types.is_floating(e.dtype):
                return CompiledValue(
                    "num",
                    lambda cols, aux, f=inner.fn: f(cols, aux).astype(jnp.float32),
                )
            if pa.types.is_integer(e.dtype):
                return CompiledValue(
                    "num",
                    lambda cols, aux, f=inner.fn: f(cols, aux).astype(jnp.int32),
                )
            raise UnsupportedOnDevice(f"cast to {e.dtype}")

        if isinstance(e, px.ScalarFunctionExpr):
            return self._compile_function(e)

        raise UnsupportedOnDevice(f"expr {type(e).__name__}")

    # ------------------------------------------------------------------
    def _compile_binary(self, e: px.BinaryPhysicalExpr) -> CompiledValue:
        import jax.numpy as jnp

        op = e.op
        # string comparisons / LIKE against literals -> dictionary space
        if op in ("eq", "neq", "like", "not_like"):
            sv = self._try_string_side(e.left, e.right, op)
            if sv is not None:
                return sv
        if op in ("and", "or"):
            l = self.compile(e.left)
            r = self.compile(e.right)
            jop = jnp.logical_and if op == "and" else jnp.logical_or
            if l.null_fn is None and r.null_fn is None:
                return CompiledValue(
                    "bool", lambda cols, aux, lf=l.fn, rf=r.fn, j=jop: j(lf(cols, aux), rf(cols, aux))
                )

            # Kleene: AND is NULL unless a side is definitely FALSE; OR is
            # NULL unless a side is definitely TRUE
            def null3(cols, aux, l=l, r=r, is_and=(op == "and")):
                f = jnp.asarray(False)
                ln = l.null_fn(cols, aux) if l.null_fn else f
                rn = r.null_fn(cols, aux) if r.null_fn else f
                lv, rv = l.fn(cols, aux), r.fn(cols, aux)
                if is_and:
                    decided = jnp.logical_or(
                        jnp.logical_and(jnp.logical_not(lv), jnp.logical_not(ln)),
                        jnp.logical_and(jnp.logical_not(rv), jnp.logical_not(rn)),
                    )
                else:
                    decided = jnp.logical_or(
                        jnp.logical_and(lv, jnp.logical_not(ln)),
                        jnp.logical_and(rv, jnp.logical_not(rn)),
                    )
                return jnp.logical_and(jnp.logical_or(ln, rn), jnp.logical_not(decided))

            return CompiledValue(
                "bool",
                lambda cols, aux, lf=l.fn, rf=r.fn, j=jop: j(lf(cols, aux), rf(cols, aux)),
                null_fn=null3,
            )
        l = self.compile(e.left)
        r = self.compile(e.right)
        if op in ("eq", "neq") and l.kind == "code" and r.kind == "code":
            if l.dictionary is not r.dictionary:
                raise UnsupportedOnDevice("code comparison across dictionaries")
            fn = (lambda a, b: a == b) if op == "eq" else (lambda a, b: a != b)

            def codecmp_fn(cols, aux, lf=l.fn, rf=r.fn, f=fn):
                return f(lf(cols, aux), rf(cols, aux))

            def codecmp_null(cols, aux, lf=l.fn, rf=r.fn):
                # -1 codes are NULLs: NULL = / <> anything is NULL
                return jnp.logical_or(lf(cols, aux) < 0, rf(cols, aux) < 0)

            return CompiledValue("bool", codecmp_fn, null_fn=codecmp_null)
        if l.kind == "code" or r.kind == "code":
            raise UnsupportedOnDevice(f"string operands for {op}")
        cmps = {
            "eq": lambda a, b: a == b,
            "neq": lambda a, b: a != b,
            "lt": lambda a, b: a < b,
            "lteq": lambda a, b: a <= b,
            "gt": lambda a, b: a > b,
            "gteq": lambda a, b: a >= b,
        }
        if op in cmps:
            return CompiledValue(
                "bool",
                lambda cols, aux, lf=l.fn, rf=r.fn, f=cmps[op]: f(lf(cols, aux), rf(cols, aux)),
            )
        ariths = {
            "plus": lambda a, b: a + b,
            "minus": lambda a, b: a - b,
            "multiply": lambda a, b: a * b,
            "divide": lambda a, b: a / b,
            "modulo": lambda a, b: jnp.mod(a, b),
        }
        if op in ariths:
            return CompiledValue(
                "num",
                lambda cols, aux, lf=l.fn, rf=r.fn, f=ariths[op]: f(lf(cols, aux), rf(cols, aux)),
            )
        raise UnsupportedOnDevice(f"binary op {op}")

    def _try_string_side(
        self, left: px.PhysicalExpr, right: px.PhysicalExpr, op: str
    ) -> Optional[CompiledValue]:
        """column-vs-string-literal comparisons in dictionary space."""
        import jax.numpy as jnp

        col, lit = left, right
        if isinstance(left, px.LiteralExpr) and isinstance(left.value, str):
            col, lit = right, left
        if not (isinstance(lit, px.LiteralExpr) and isinstance(lit.value, str)):
            return None
        cv = self.compile(col)
        if cv.kind != "code":
            raise UnsupportedOnDevice("string literal vs non-string column")
        d = cv.dictionary
        pattern = lit.value

        if op in ("eq", "neq"):
            code_slot = self._add_aux(
                lambda d=d, v=pattern: np.asarray(d.code_of(v), dtype=np.int32)
            )

            def eq_fn(cols, aux, f=cv.fn, s=code_slot, neg=(op == "neq")):
                r = f(cols, aux) == aux[s]
                return jnp.logical_not(r) if neg else r

            # NULL (= code -1) compares as NULL, under = and <> alike
            def eq_null(cols, aux, f=cv.fn):
                return f(cols, aux) < 0

            return CompiledValue("bool", eq_fn, null_fn=eq_null)

        # LIKE via host-computed match table over the dictionary
        def like_table(d=d, pattern=pattern) -> np.ndarray:
            from ballista_tpu.ops.runtime import bucket_rows

            # one consistent view (see in_table)
            vals = d.snapshot()
            n = max(1, 0 if vals is None else len(vals))
            table = np.zeros(bucket_rows(n, 16), dtype=np.bool_)
            if vals is not None:
                m = pc.match_like(vals, pattern)
                table[: len(vals)] = pc.fill_null(m, False).to_numpy(zero_copy_only=False)
            return table

        slot = self._add_aux(like_table)

        def like_fn(cols, aux, f=cv.fn, s=slot, neg=(op == "not_like")):
            # the -1 gather wraps to the table's last entry; null rows are
            # UNDEFINED here and masked by null_fn at the consumer
            r = aux[s][f(cols, aux)]
            return jnp.logical_not(r) if neg else r

        def like_null(cols, aux, f=cv.fn):
            # NULL LIKE / NOT LIKE is NULL
            return f(cols, aux) < 0

        return CompiledValue("bool", like_fn, null_fn=like_null)

    # ------------------------------------------------------------------
    def _compile_function(self, e: px.ScalarFunctionExpr) -> CompiledValue:
        import jax.numpy as jnp

        fn = e.fn
        unary = {
            "sqrt": jnp.sqrt, "sin": jnp.sin, "cos": jnp.cos, "tan": jnp.tan,
            "asin": jnp.arcsin, "acos": jnp.arccos, "atan": jnp.arctan,
            "exp": jnp.exp, "ln": jnp.log, "log2": jnp.log2, "log10": jnp.log10,
            "log": jnp.log10, "floor": jnp.floor, "ceil": jnp.ceil,
            "round": jnp.round, "trunc": jnp.trunc, "abs": jnp.abs,
            "signum": jnp.sign,
        }
        if fn in unary:
            inner = self.compile(e.args[0])
            return CompiledValue(
                "num", lambda cols, aux, f=inner.fn, j=unary[fn]: j(f(cols, aux))
            )
        if fn in ("extract", "date_part"):
            part = e.args[0]
            if not isinstance(part, px.LiteralExpr):
                raise UnsupportedOnDevice("extract part must be literal")
            inner = self.compile(e.args[1])
            pname = str(part.value).lower()
            if pname == "year":
                return CompiledValue(
                    "num",
                    lambda cols, aux, f=inner.fn: _civil_from_days(f(cols, aux))[0],
                )
            if pname == "month":
                return CompiledValue(
                    "num",
                    lambda cols, aux, f=inner.fn: _civil_from_days(f(cols, aux))[1],
                )
            if pname == "day":
                return CompiledValue(
                    "num",
                    lambda cols, aux, f=inner.fn: _civil_from_days(f(cols, aux))[2],
                )
            raise UnsupportedOnDevice(f"extract {pname}")
        if fn == "coalesce":
            first = None
            for a in e.args:
                if isinstance(a, px.LiteralExpr) and isinstance(a.value, str):
                    # string-literal fallback: needs the first code arg's dict
                    if first is None or first.kind != "code":
                        raise UnsupportedOnDevice("coalesce string literal first")
                    d = first.dictionary
                    slot = self._add_aux(
                        lambda d=d, v=a.value: np.asarray(d.code_of(v), dtype=np.int32)
                    )

                    def coalesce_lit_fn(cols, aux, f=first.fn, nf=first.null_fn, s=slot):
                        c = f(cols, aux)
                        return jnp.where(c >= 0, c, aux[s])

                    return CompiledValue("code", coalesce_lit_fn, first.dictionary)
                cv = self.compile(a)
                if first is None:
                    first = cv
                    if cv.kind != "code":
                        # numeric/bool device columns are null-free: first
                        # argument wins outright
                        return cv
                    continue
                if cv.kind != "code" or cv.dictionary is not first.dictionary:
                    raise UnsupportedOnDevice("coalesce across dictionaries")

                def coalesce_fn(cols, aux, f=first.fn, g=cv.fn):
                    c = f(cols, aux)
                    return jnp.where(c >= 0, c, g(cols, aux))

                def coalesce_null(cols, aux, f=first.fn, g=cv.fn):
                    return jnp.logical_and(f(cols, aux) < 0, g(cols, aux) < 0)

                first = CompiledValue("code", coalesce_fn, first.dictionary,
                                      null_fn=coalesce_null)
            if first is None:
                raise UnsupportedOnDevice("empty coalesce")
            return first
        raise UnsupportedOnDevice(f"scalar function {fn}")


def _civil_from_days(days):
    """Howard Hinnant's civil_from_days: days since 1970-01-01 -> (y, m, d).
    Pure integer arithmetic — vectorizes cleanly on the VPU."""
    import jax.numpy as jnp

    z = days.astype(jnp.int32) + 719468
    era = jnp.floor_divide(jnp.where(z >= 0, z, z - 146096), 146097)
    doe = z - era * 146097
    yoe = jnp.floor_divide(
        doe - jnp.floor_divide(doe, 1460) + jnp.floor_divide(doe, 36524)
        - jnp.floor_divide(doe, 146096),
        365,
    )
    y = yoe + era * 400
    doy = doe - (365 * yoe + jnp.floor_divide(yoe, 4) - jnp.floor_divide(yoe, 100))
    mp = jnp.floor_divide(5 * doy + 2, 153)
    d = doy - jnp.floor_divide(153 * mp + 2, 5) + 1
    m = mp + jnp.where(mp < 10, 3, -9)
    y = y + (m <= 2)
    return y, m, d

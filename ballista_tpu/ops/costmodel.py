"""Measured cost model for adaptive execution (ISSUE 10).

Device-vs-host routing used to be a cascade of static admission checks
tuned blind (JOIN_MULTIPLICITY_TIERS, gather caps, the decline ladder in
ops/kernels.py). The bench already records the signals needed to do better
— per-config readback/ingest/join-path counters — and this module closes
the loop: observed costs feed back into routing decisions.

The store is a per-shape-bucket cost ledger persisted beside the layout
cache (ballista.tpu.cost_model_dir, default .ballista_cache/costmodel):

  entry key = op | engine | power-of-two units bucket
  entry     = {s: total seconds, units: total work units, n: observations}

ops in use: "join.gather" (units = padded gather elements), "join.host"
(units = build+probe rows), "h2d" / "readback" (units = bytes),
"compile|<step>" (units = 1), "stage.run|<stage id>" (units = the stage's
input size in leaf-file bytes or memory-scan rows, ISSUE 11 — normalized
so a rate learned at one scale predicts another; stage id is the sha1 of
the AOT stable stage key, so the store is keyed like the AOT cache on
stable stage identity), and "task.run|<shape>" under engine "task" (units
= 1; the SCHEDULER's per-stage task durations, keyed on the
job-id-scrubbed stage plan shape via task_run_op below — the rates behind
speculative-execution straggler detection), and "stage.batch" under engine
"task" (units = member count; the SCHEDULER's wall durations of shared-scan
batched tasks, ISSUE 13 — the evidence gate dispatches solo when a batch is
predicted slower than the members' solo task.run sum). Entries carry the
jax/jaxlib/backend
fingerprint of the writer (ops/aotcache.py::fingerprint): a store written
by a different stack is ignored wholesale — costs measured on another
backend must never steer this one.

Prediction is rate-based: predict(op, engine, units) returns
units * (total_s / total_units), preferring the exact units bucket when it
has enough observations and falling back to the op-global rate. Updates
apply exponential forgetting (history halves once an entry saturates) so
the rate tracks the current machine, and a gross mispredict REPLACES the
bucket's history with the observed cost (`retier`) — the
mispredict-driven re-tiering that pulls an over-eager extended admission
back to the static ladder.

Decision discipline (bit-identity is the invariant): the cost model only
changes WHERE a partition runs, never what it returns, and the static
ladder remains both the cold-start prior and the hard safety cap — a cold
or corrupt store reproduces the pre-ISSUE-10 routing exactly.

Persistence is best-effort like the layout cache: atomic tmp+rename
writes, last-writer-wins per key across processes, corrupt or
fingerprint-mismatched files start an empty store (recorded via the
routing accumulator, never raised).
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from contextlib import contextmanager
from typing import Dict, Iterator, Optional, Tuple
from ballista_tpu.utils.locks import make_lock

# bump to orphan every persisted entry (they are re-measured, not migrated).
# 2: stage.run units changed from 1 to input bytes/rows (ISSUE 11) — a
# pre-existing store's unit-less rates would predict file_bytes x
# seconds-per-run, a guaranteed gross mispredict per cached stage shape.
_FORMAT = 2
_STORE_BASENAME = "costs.json"

# minimum observations before a rate is trusted for prediction
MIN_OBSERVATIONS = 4
# entry saturation: past this, history halves before each update so the
# rate follows the current machine instead of the all-time mean
_FORGET_AT = 32
# flush throttle: observe() persists at most this often (atexit + explicit
# flush() cover the tail)
_FLUSH_INTERVAL_S = 5.0
# observed/predicted ratio beyond which a decision counts as a mispredict
MISPREDICT_FACTOR = 3.0

_lock = make_lock("ops.costmodel._lock")
_dir: str = ""  # "" = in-memory only; guarded-by: _lock
# deliberately lock-free: a single bool written by configure()/reset() and
# read on hot paths (readback, h2d) — CPython bool loads are atomic and a
# stale read costs at most one missed/extra observation, never corruption
_enabled: bool = False
_loaded: bool = False  # guarded-by: _lock
_dirty: bool = False  # guarded-by: _lock
# bumped with every mutation; flush() only clears _dirty when the store it
# snapshotted is still current, so observations landing during an in-flight
# flush are never left unpersisted at exit; guarded-by: _lock
_gen: int = 0
_last_flush: float = 0.0  # guarded-by: _lock
# key -> {"s": float, "units": float, "n": int}; guarded-by: _lock
_store: Dict[str, Dict[str, float]] = {}
_atexit_registered = False


def _record_event(event: str, n: int = 1) -> None:
    from ballista_tpu.ops.runtime import record_routing_event

    record_routing_event(event, n)


def enabled() -> bool:
    """Cheap hot-path gate (bool read is atomic; staleness is harmless —
    the worst case is one missed or extra observation around configure)."""
    return _enabled


def configure(config) -> None:
    """Bind directory + enablement from a config, like the AOT cache. The
    last configuration wins; a directory change drops the in-memory store
    (entries lazily reload from the new path)."""
    global _dir, _enabled, _loaded, _dirty, _gen
    d = config.tpu_cost_model_dir()
    en = config.tpu_cost_model()
    global _atexit_registered
    global _last_flush
    with _lock:
        if d != _dir:
            _dir = d
            _store.clear()
            _gen += 1
            _loaded = False
            _dirty = False
            # start the flush throttle NOW: the first observation on a hot
            # path (readback, gather) must not pay a synchronous disk
            # round-trip; atexit + explicit flush() cover the tail
            _last_flush = time.monotonic()
        _enabled = en
        if not _atexit_registered:
            import atexit

            atexit.register(flush)
            _atexit_registered = True


def reset(clear_dir: bool = False) -> None:
    """Test hook: drop the in-memory store (and optionally forget the
    directory) so a fresh process can be simulated."""
    global _dir, _enabled, _loaded, _dirty, _gen
    with _lock:
        _store.clear()
        _gen += 1
        _loaded = False
        _dirty = False
        if clear_dir:
            _dir = ""
            _enabled = False


def _fingerprint() -> str:
    from ballista_tpu.ops import aotcache

    return f"cm{_FORMAT}|{aotcache.fingerprint()}"


def _bucket(units: float) -> int:
    """Power-of-two units bucket (recompilation-control analog: a bounded
    set of entries per op instead of one per distinct shape)."""
    b = 1
    u = max(1, int(units))
    while b < u:
        b <<= 1
    return b


def _key(op: str, engine: str, bucket: int) -> str:
    return f"{op}|{engine}|b{bucket}"


def task_run_op(shape: str) -> str:
    """Cost-store op for scheduler-side task durations of one stage shape
    (ISSUE 11). `shape` must already be job-independent (the caller scrubs
    the job id from the plan display) so repeated queries of the same shape
    share one rate across jobs — which is what lets the straggler monitor
    predict a fresh job's task cost from history."""
    import hashlib

    return "task.run|" + hashlib.sha1(shape.encode()).hexdigest()[:12]


# holds-lock: _lock
def _load_locked() -> None:
    """Lazy-load the persisted store. Corruption or a fingerprint mismatch
    starts empty with the reason recorded — a bad store must reproduce
    cold-start routing, never crash or steer."""
    global _loaded
    if _loaded:
        return
    _loaded = True
    if not _dir:
        return
    path = os.path.join(_dir, _STORE_BASENAME)
    try:
        with open(path) as f:
            blob = json.load(f)
        if blob.get("format") != _FORMAT or blob.get("fingerprint") != _fingerprint():
            _record_event("cost_store_fingerprint_mismatch")
            return
        for k, e in blob.get("entries", {}).items():
            s, units, n = float(e["s"]), float(e["units"]), int(e["n"])
            if s < 0 or units <= 0 or n <= 0:
                raise ValueError(f"bad entry {k}")
            _store[k] = {"s": s, "units": units, "n": n}
    except FileNotFoundError:
        return
    except Exception:
        _store.clear()
        _record_event("cost_store_corrupt")
        return


def flush() -> None:
    """Best-effort atomic persist (tmp+rename). Merge policy is
    last-writer-wins per key: another process's entries for keys we never
    touched survive; shared keys take our value. Never raises."""
    global _dirty, _last_flush
    with _lock:
        if not _dir or not _dirty:
            return
        entries = {k: dict(v) for k, v in _store.items()}
        base = _dir
        gen = _gen
    try:
        os.makedirs(base, exist_ok=True)
        path = os.path.join(base, _STORE_BASENAME)
        merged = dict(entries)
        try:
            with open(path) as f:
                blob = json.load(f)
            if (
                blob.get("format") == _FORMAT
                and blob.get("fingerprint") == _fingerprint()
            ):
                for k, e in blob.get("entries", {}).items():
                    merged.setdefault(k, e)
        except Exception:
            pass
        fd, tmp = tempfile.mkstemp(dir=base, prefix=".wip-")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(
                    {
                        "format": _FORMAT,
                        "fingerprint": _fingerprint(),
                        "entries": merged,
                    },
                    f,
                )
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        with _lock:
            if _gen == gen:
                _dirty = False
            _last_flush = time.monotonic()
    except Exception:
        # still advance the throttle clock: an unwritable dir must not make
        # every subsequent observe() on a hot path re-attempt a full flush
        with _lock:
            _last_flush = time.monotonic()
        return


def observe(op: str, units: float, seconds: float, engine: str = "device") -> None:
    """Record one measured cost. No-op while the model is disabled, so hot
    paths (readback, h2d) can call unconditionally."""
    if not _enabled or seconds < 0 or units <= 0:
        return
    global _dirty, _last_flush, _gen
    k = _key(op, engine, _bucket(units))
    with _lock:
        _load_locked()
        e = _store.get(k)
        if e is None:
            _store[k] = {"s": float(seconds), "units": float(units), "n": 1}
        else:
            if e["n"] >= _FORGET_AT:
                e["s"] *= 0.5
                e["units"] *= 0.5
                e["n"] = e["n"] // 2
            e["s"] += float(seconds)
            e["units"] += float(units)
            e["n"] += 1
        _dirty = True
        _gen += 1
        due = _dir and time.monotonic() - _last_flush > _FLUSH_INTERVAL_S
        if due:
            # claim the flush slot under the lock so a burst of observes
            # spawns ONE writer, then persist off the hot path — a device
            # readback must never wait on a disk round-trip
            _last_flush = time.monotonic()
    if due:
        threading.Thread(
            target=flush, daemon=True, name="costmodel-flush"
        ).start()


def seed(op: str, units: float, seconds: float, engine: str = "device",
         n: int = MIN_OBSERVATIONS) -> None:
    """Directly install a warm entry (tests + the fuzz slice's adversarial
    entries). Replaces any history for the bucket."""
    global _dirty, _gen
    with _lock:
        _load_locked()
        _store[_key(op, engine, _bucket(units))] = {
            "s": float(seconds), "units": float(units), "n": int(n),
        }
        _dirty = True
        _gen += 1


def retier(op: str, units: float, seconds: float, engine: str = "device") -> None:
    """Mispredict-driven re-tiering: REPLACE the bucket's history with the
    observed cost, so the very next prediction reflects reality instead of
    averaging the surprise away."""
    if not _enabled:
        return
    global _dirty, _gen
    with _lock:
        _load_locked()
        _store[_key(op, engine, _bucket(units))] = {
            "s": float(seconds), "units": float(units), "n": MIN_OBSERVATIONS,
        }
        _dirty = True
        _gen += 1
    _record_event("retier")


def gross_mispredict(predicted: float, observed: float) -> bool:
    """True when observed deviates from predicted by MISPREDICT_FACTOR in
    EITHER direction — the one accounting definition shared by the routing
    mispredict counter and the re-tiering below."""
    return (
        observed > MISPREDICT_FACTOR * predicted
        or observed * MISPREDICT_FACTOR < predicted
    )


def check_mispredict(op: str, units: float, predicted: Optional[float],
                     observed: float, engine: str = "device") -> bool:
    """Canonical post-decision check: a gross mispredict (either way)
    re-tiers the bucket so the next prediction reflects reality. Returns
    whether it fired. Every consumer that predicted a cost runs this —
    one implementation, so no site can drift to a one-sided check."""
    if predicted is None or not gross_mispredict(predicted, observed):
        return False
    retier(op, units, observed, engine=engine)
    return True


@contextmanager
def timed(op: str, units: float = 1.0, engine: str = "device",
          routing_op: Optional[str] = None,
          predictive: bool = True) -> Iterator[None]:
    """Time the body as one measured decision — the single implementation
    of the predict/observe/record-routing/re-tier accounting contract, so
    no call site can drift to a partial or one-sided variant. A body
    exception skips the accounting entirely (a failed attempt is not an
    observation of the op's cost). `routing_op` additionally records the
    decision in the routing accumulator under `engine`; predictive=False
    degrades to a plain timed observation (the host-side alternative-cost
    probes, which must not re-tier)."""
    predicted = predict(op, units, engine=engine) if predictive else None
    t0 = time.perf_counter()
    yield
    dt = time.perf_counter() - t0
    observe(op, units, dt, engine=engine)
    if routing_op is not None:
        from ballista_tpu.ops.runtime import record_routing

        record_routing(engine, routing_op, predicted, dt)
    if predictive:
        check_mispredict(op, units, predicted, dt, engine=engine)


def rate(op: str, engine: str = "device") -> Optional[Tuple[float, int]]:
    """Op-global (seconds per unit, observation count) across buckets, or
    None when nothing was observed."""
    prefix = f"{op}|{engine}|b"
    with _lock:
        _load_locked()
        s = units = 0.0
        n = 0
        for k, e in _store.items():
            if k.startswith(prefix):
                s += e["s"]
                units += e["units"]
                n += int(e["n"])
    if n == 0 or units <= 0:
        return None
    return s / units, n


def bucket_rate(op: str, units: float, engine: str = "device") -> Optional[float]:
    """Seconds per unit of the EXACT power-of-two bucket covering `units`,
    or None when the bucket is cold (< MIN_OBSERVATIONS) or the model is
    off. Unlike predict(), never falls back to the op-global rate — the
    h2d chunk picker (ops/runtime.py) compares candidate buckets against
    each other, and the global fallback would make every candidate tie."""
    if not _enabled:
        return None
    k = _key(op, engine, _bucket(units))
    with _lock:
        _load_locked()
        e = _store.get(k)
        if e is None or e["n"] < MIN_OBSERVATIONS or e["units"] <= 0:
            return None
        return e["s"] / e["units"]


def predict(op: str, units: float, engine: str = "device") -> Optional[float]:
    """Predicted seconds for `units` of `op` on `engine`: the exact units
    bucket when it has MIN_OBSERVATIONS, else the op-global rate, else None
    (cold — callers fall back to the static prior)."""
    if not _enabled:
        return None
    k = _key(op, engine, _bucket(units))
    with _lock:
        _load_locked()
        e = _store.get(k)
        if e is not None and e["n"] >= MIN_OBSERVATIONS and e["units"] > 0:
            return units * e["s"] / e["units"]
    r = rate(op, engine)
    if r is None or r[1] < MIN_OBSERVATIONS:
        return None
    return units * r[0]


def snapshot() -> Dict[str, Dict[str, float]]:
    """Copy of the in-memory store (tests/diagnostics)."""
    with _lock:
        _load_locked()
        return {k: dict(v) for k, v in _store.items()}

"""Fused stage execution on the device backend.

The TPU-first restructuring from SURVEY §7: instead of per-operator batch
kernels, the pipeline under an aggregation — scan -> filter* -> projection ->
partial aggregate — compiles into ONE jitted program per batch shape:

    host: Arrow IO, dictionary-encode strings, evaluate group keys,
          rank batch-local group codes (np.unique)
    device (single jit): filter predicates -> mask; aggregate-input
          arithmetic; masked segment_sum/min/max into per-group partials

Per-batch partial states concatenate into a standard partial-aggregate table,
so the surrounding Partial/Final machinery (and the distributed shuffle above
it) is unchanged — the stage is just a faster partial phase. Batches and
group counts pad to power-of-two buckets to bound XLA recompilation.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from ballista_tpu.errors import PlanError
from ballista_tpu.ops.jaxexpr import ExprCompiler
from ballista_tpu.ops.runtime import (
    ScanDictionaries,
    UnsupportedOnDevice,
    bucket_rows,
    column_to_numpy,
    make_headroom,
    narrow_column,
    pad_to,
    widen_cols,
)
from ballista_tpu.physical import expr as px
from ballista_tpu.physical.basic import (
    CoalesceBatchesExec,
    FilterExec,
    MergeExec,
    ProjectionExec,
)
from ballista_tpu.physical.scan import CsvScanExec, MemoryScanExec, ParquetScanExec
from ballista_tpu.utils.locks import make_lock

_SCAN_TYPES = (CsvScanExec, ParquetScanExec, MemoryScanExec)


def plane_keys(idx: int) -> Tuple[int, int]:
    """cols-dict keys for scan column idx's order-preserving int32 key
    planes (ops/floatbits.py). Negative ints: scan columns are keyed by
    their non-negative schema index, so both spaces share one dict through
    the narrow/stage/persist machinery unchanged (layout-cache metas
    stringify keys and re-int them cleanly). f32 columns use the hi slot
    only; f64 columns carry (hi, lo) whose lexicographic signed order is
    the f64 total order."""
    return -2 * idx - 2, -2 * idx - 3

# ceiling for the per-batch unrolled path (G linear passes); beyond it the
# stage switches to the sorted chunked-segment layout (ops/layout.py), which
# is O(N) regardless of group count
MAX_GROUPS = 1024

_INT32_MAX = 2**31 - 1

# widest one-chunk-per-group cover the fused top-k epilogue will force;
# beyond it (or past 4x row padding) the default chunking runs and the
# epilogue's in-program fold variant takes over. HARD CEILING: the layout's
# clen and jnp_expand_clen's arange are int16 (ops/layout.py:113,
# stage.py:142) — an L1 past 2^14 would wrap chunk lengths silently.
TOPK_MAX_L1 = 1 << 14


def _topk_cover_L1(codes: np.ndarray, n_groups: int) -> Optional[int]:
    """L1 giving the one-chunk-per-group cover the fused top-k epilogue
    needs: the chunk fold becomes identity, so the k gathered columns are
    bit-identical to what the full readback would emit. None when the
    longest run exceeds TOPK_MAX_L1 or the cover's zero padding would blow
    past ~4x the real rows (skewed runs) — the caller falls back to the
    default chunking and fusion disables for the partition."""
    if n_groups <= 0:
        return None
    longest = int(np.bincount(codes, minlength=n_groups).max())
    L1 = 8
    while L1 < longest:
        L1 <<= 1
    if L1 > TOPK_MAX_L1 or n_groups * L1 > max(4 * len(codes), 1 << 22):
        return None
    return L1


# the general skew handler splits at most this many dominant groups to the
# in-program segment fold; distributions where more groups blow the cover
# are broad, not skewed, and keep the default chunking
SKEW_MAX_DOMINANT = 64


def skew_split_plan(codes: np.ndarray, n_groups: int) -> Optional[Tuple[int, int]]:
    """General skew handler (ISSUE 10): the q10 monster-group fallback,
    generalized. Called when the one-chunk-per-group cover fails, it
    detects the dominant groups at run time — the few whose runs blow the
    cover bounds — and picks the cover from the TAIL run distribution
    instead: L1 covers every non-dominant run (those groups keep the
    one-chunk fast path, an identity fold), the dominant runs split across
    chunks and segment-fold in program (the existing tstep_fold machinery,
    so bit-identity is the proven contract). Returns (L1, n_dominant) or
    None when the distribution is not skewed (<= SKEW_MAX_DOMINANT
    dominants cannot satisfy the bounds) — the caller then keeps the
    default percentile chunking exactly as before."""
    if n_groups <= 1:
        return None
    lens = np.sort(np.bincount(codes, minlength=n_groups))[::-1]
    budget = max(4 * len(codes), 1 << 22)
    for n_dom in range(1, min(SKEW_MAX_DOMINANT, n_groups - 1) + 1):
        tail_max = int(lens[n_dom])
        L1 = 8
        while L1 < tail_max:
            L1 <<= 1
        if L1 > TOPK_MAX_L1:
            continue  # even the tail needs a wider cover: more dominants
        dom_chunks = int(np.sum(-(-lens[:n_dom] // L1)))
        if (n_groups - n_dom + dom_chunks) * L1 <= budget:
            return L1, n_dom
    return None


class TooManyGroups(UnsupportedOnDevice):
    """Internal signal: per-batch unrolled path declined on cardinality;
    run() retries with the sorted layout before giving up."""


# --- int32 <-> f32-pair packing -------------------------------------------
# Bitcasting int32 to f32 is NOT safe on TPU (small ints are denormal floats
# and get flushed to zero), so int rows travel as two exactly-representable
# halves: hi = v >> 16 (arithmetic), lo = v & 0xFFFF. Encode lives in
# _stack_rows; BOTH decoders below must mirror it.


def decode_packed_rows(stacked: np.ndarray, int_rows) -> List[np.ndarray]:
    """Host-side decode of a packed [R_packed, ...] f32 result: int rows
    come back as int64, float rows as the f32 slices."""
    rows: List[np.ndarray] = []
    i = 0
    for is_int in int_rows:
        if is_int:
            hi = stacked[i].astype(np.int64)
            lo = stacked[i + 1].astype(np.int64)
            rows.append(hi * 65536 + lo)
            i += 2
        else:
            rows.append(stacked[i])
            i += 1
    return rows


def packed_positions(int_rows) -> List[int]:
    """Position of each logical row inside the packed stack."""
    pos, p = [], 0
    for is_int in int_rows:
        pos.append(p)
        p += 2 if is_int else 1
    return pos


def jnp_unpack_i32(hi, lo):
    """In-program decode (exact int32)."""
    import jax.numpy as jnp

    return hi.astype(jnp.int32) * 65536 + lo.astype(jnp.int32)


def jnp_expand_clen(clen, L1: int):
    """In-program [V, L1] valid-slot mask from per-chunk lengths — 16× less
    HBM than shipping the bool tiles (which cost q5 SF=100 its budget)."""
    import jax.numpy as jnp

    return jnp.arange(L1, dtype=jnp.int16)[None, :] < clen[:, None]


def dense_rank(encoded: List[Tuple[np.ndarray, int]]):
    """Combine per-column dictionary codes into dense row ranks.

    encoded: (int64 code array, alphabet size) per key column, all arrays the
    same length. Strides are combined with an overflow guard (repack through
    np.unique before a multiply could overflow int64). Returns
    (rank per row, first row index of each distinct, distinct count)."""
    combined = None
    card = 1
    for codes_i, size in encoded:
        size = max(1, size)
        if combined is None:
            combined, card = codes_i, size
            continue
        if card > (1 << 62) // size:
            _, combined = np.unique(combined, return_inverse=True)
            combined = combined.astype(np.int64)
            card = int(combined.max()) + 1 if len(combined) else 1
        combined = combined * size + codes_i
        card *= size
    uniq, first_idx, inv = np.unique(
        combined, return_index=True, return_inverse=True
    )
    return inv, first_idx, len(uniq)


def substitute_columns(e: px.PhysicalExpr, mapping: List[px.PhysicalExpr]) -> px.PhysicalExpr:
    """Inline projection outputs: ColumnExpr(i) -> mapping[i]."""
    if isinstance(e, px.ColumnExpr):
        return mapping[e.index]
    if isinstance(e, px.LiteralExpr):
        return e
    if isinstance(e, px.BinaryPhysicalExpr):
        return px.BinaryPhysicalExpr(
            substitute_columns(e.left, mapping), e.op, substitute_columns(e.right, mapping)
        )
    if isinstance(e, px.NotExpr):
        return px.NotExpr(substitute_columns(e.expr, mapping))
    if isinstance(e, px.NegativeExpr):
        return px.NegativeExpr(substitute_columns(e.expr, mapping))
    if isinstance(e, px.IsNullExpr):
        return px.IsNullExpr(substitute_columns(e.expr, mapping), e.negated)
    if isinstance(e, px.CastExpr):
        return px.CastExpr(substitute_columns(e.expr, mapping), e.dtype, e.safe)
    if isinstance(e, px.InListExpr):
        return px.InListExpr(
            substitute_columns(e.expr, mapping),
            e.values,
            e.negated,
            None
            if e.value_exprs is None
            else [substitute_columns(v, mapping) for v in e.value_exprs],
        )
    if isinstance(e, px.BetweenExpr):
        return px.BetweenExpr(
            substitute_columns(e.expr, mapping),
            substitute_columns(e.low, mapping),
            substitute_columns(e.high, mapping),
            e.negated,
        )
    if isinstance(e, px.CaseExpr):
        return px.CaseExpr(
            None if e.base is None else substitute_columns(e.base, mapping),
            [
                (substitute_columns(w, mapping), substitute_columns(t, mapping))
                for w, t in e.when_then
            ],
            None if e.else_expr is None else substitute_columns(e.else_expr, mapping),
            e.dtype,
        )
    if isinstance(e, px.ScalarFunctionExpr):
        return px.ScalarFunctionExpr(
            e.fn, [substitute_columns(a, mapping) for a in e.args], e.dtype
        )
    raise UnsupportedOnDevice(f"cannot inline {type(e).__name__}")


def state_column(a, raw: np.ndarray, target: pa.DataType,
                 empty_mask: Optional[np.ndarray]) -> pa.Array:
    """Cast one decoded aggregate-state row to its partial-schema field.
    min/max rows null out empty groups (sentinel fills) via empty_mask;
    date32 states ride as exact int32 day counts (pyarrow has no
    double->date32 cast). Shared by every device assembly path."""
    if a.fn in ("min", "max"):
        if pa.types.is_date32(target):
            arr = pa.array(raw.astype(np.int32), mask=empty_mask)
        else:
            arr = pa.array(raw.astype(np.float64), mask=empty_mask)
    else:
        arr = pa.array(raw.astype(np.float64))
    if arr.type != target:
        arr = pc.cast(arr, target)
    return arr


def _pack_staged(staged: Dict, arrays: List[np.ndarray]) -> Dict[str, dict]:
    """Append a staged {idx: (tiles, lut, choice)} dict's arrays to the
    persistence list, returning the JSON column manifest. Shared by the
    sorted and batches save paths."""
    cols_meta: Dict[str, dict] = {}
    for idx, (tiles, lut, choice) in staged.items():
        spec = {"tiles": len(arrays), "choice": choice, "lut": None}
        arrays.append(tiles)
        if lut is not None:
            spec["lut"] = len(arrays)
            arrays.append(lut)
        cols_meta[str(idx)] = spec
    return cols_meta


def _unpack_staged(cols_meta: Dict[str, dict], arrays: List[np.ndarray],
                   narrow_choice: Dict) -> Optional[Tuple[Dict, int]]:
    """Inverse of _pack_staged: (staged dict, byte total), or None when a
    persisted narrow choice conflicts with one the jitted step already
    compiled against."""
    staged: Dict[int, tuple] = {}
    total = 0
    for k, spec in cols_meta.items():
        idx = int(k)
        tiles = arrays[spec["tiles"]]
        lut = arrays[spec["lut"]] if spec["lut"] is not None else None
        cur = narrow_choice.get(idx)
        if cur is not None and cur != spec["choice"]:
            return None
        staged[idx] = (tiles, lut, spec["choice"])
        total += tiles.nbytes + (0 if lut is None else lut.nbytes)
    return staged, total


def _upload_staged(staged: Dict, choices: Dict) -> Dict:
    """Transfer staged (array, lut, choice) columns, recording the narrow
    choice per key and freeing each host tile right after its device copy
    exists — peak host memory holds one column in flight, not the whole
    stage. The (dev, lut) tuple is the single LUT encoding widen_cols
    understands; both device paths must build it here.

    Large tiles go through runtime.upload_array (ISSUE 10 satellite):
    bounded chunks, double-buffered, so a persisted-layout warm start's
    bulk transfer overlaps the next column's host staging the way the
    ingest pipeline overlaps prepare — and the per-chunk timings land in
    the cost store as h2d observations."""
    import jax.numpy as jnp

    from ballista_tpu.ops.runtime import upload_array

    cols: Dict = {}
    for idx in list(staged):
        arr, lut, choice = staged.pop(idx)
        choices[idx] = choice
        dev = upload_array(arr)
        cols[idx] = dev if lut is None else (dev, jnp.asarray(lut))
    return cols


class FusedAggregateStage:
    """Compiled device pipeline for one HashAggregateExec (partial phase)."""

    def __init__(self, agg, float_bits: bool = True) -> None:
        from ballista_tpu.physical.aggregate import AggregateFunc

        # --- walk the operator chain down to the row source --------------
        # Filters/projections fuse onto the device; whatever sits below them
        # (a scan, or e.g. a host hash join) becomes the row source — so a
        # join-under-aggregate still gets device aggregation.
        node = agg.input
        stack: List[Tuple[str, object]] = []
        # scan_stride: when set to N, this stage's logical partition p reads
        # scan partitions p, p+N, p+2N, ... — used when the partition count
        # the framework drives (aggregate input partitioning) differs from
        # the scan's own count. Crossing a MergeExec (row-transparent; the
        # coalesced SINGLE-mode plan) means ONE driven partition covers
        # every scan partition: stride 1.
        self.scan_stride: Optional[int] = None
        while isinstance(node, (FilterExec, ProjectionExec, CoalesceBatchesExec, MergeExec)):
            if isinstance(node, FilterExec):
                stack.append(("filter", node.predicate))
                node = node.input
            elif isinstance(node, ProjectionExec):
                stack.append(("project", node.exprs))
                node = node.input
            else:
                if isinstance(node, MergeExec):
                    self.scan_stride = 1
                node = node.input
        if self.scan_stride is None:
            # a rewritten aggregate (ops/mappedscan.py) whose driven
            # partition count differs from its scan's: stripe the scan
            hint = getattr(agg, "_scan_stride_hint", None)
            if hint is not None:
                self.scan_stride = int(hint)
        self.scan = node
        # device columns stay resident only for file-backed scans (stable
        # data identity); other sources re-execute per query.
        # ballista_cacheable: composed row sources (ops/mappedscan.py) whose
        # data identity is still file-backed opt in via the class attribute
        self.cacheable = isinstance(node, _SCAN_TYPES) or getattr(
            node, "ballista_cacheable", False
        )
        scan_schema = node.schema()

        # --- re-express every expression against the scan schema --------
        mapping: List[px.PhysicalExpr] = [
            px.ColumnExpr(f.name, i) for i, f in enumerate(scan_schema)
        ]
        filters: List[px.PhysicalExpr] = []
        for kind, payload in reversed(stack):
            if kind == "project":
                mapping = [substitute_columns(e, mapping) for e, _ in payload]
            else:
                filters.append(substitute_columns(payload, mapping))
        # input-schema -> scan-schema expr map, exposed for composers
        # (FactAggregateStage re-expresses extra columns through it)
        self.input_to_scan = mapping

        self.group_exprs = [
            (substitute_columns(e, mapping), name) for e, name in agg.group_exprs
        ]
        self.aggs: List[AggregateFunc] = []
        self.agg_inputs: List[px.PhysicalExpr] = []
        for a in agg.aggr_funcs:
            if a.fn not in ("sum", "min", "max", "avg", "count"):
                raise UnsupportedOnDevice(f"aggregate {a.fn}")
            self.aggs.append(a)
            self.agg_inputs.append(substitute_columns(a.expr, mapping))

        # --- compile device code ----------------------------------------
        self.dicts = ScanDictionaries()
        self.compiler = ExprCompiler(scan_schema, self.dicts)
        self.filter_fns = [self.compiler.compile(f) for f in filters]
        for f in self.filter_fns:
            if f.kind != "bool":
                raise UnsupportedOnDevice("non-boolean filter")
        # WHERE collapse: predicates whose SQL value is NULL exclude the row
        # (three-valued logic over -1 string codes, jaxexpr.predicate_fn)
        from ballista_tpu.ops.jaxexpr import predicate_fn

        self.filter_masks = [predicate_fn(f) for f in self.filter_fns]
        self.value_fns = []
        # integer-typed plain-column inputs accumulate in int32 on device
        # (exact, vs the f32 rounding ADVICE r1 flagged); the value range is
        # bound-checked at prepare time and declines when int32 could
        # overflow a whole-batch masked sum
        self.int_exact: List[bool] = []
        # float MIN/MAX over a plain column routes through the
        # order-preserving bijection (ops/floatbits.py): the column's bits
        # travel as int32 key planes, integer min/max is exact on device,
        # and the readback inverts — bit-exact against the stored f64/f32
        # value, so q2's equality-joined MIN needs no decline. Entries:
        # None (f32 arithmetic path) | "f32" (one plane) | "f64" (hi/lo).
        # The mesh path opts out (float_bits=False): its collectives fold
        # rows independently, which cannot express the hi/lo lexicographic
        # pair, and it keeps its documented f32 min/max semantics.
        self.float_bits: List[Optional[str]] = []
        # scan column index -> "f32" | "f64" (plane columns to materialize)
        self._bit_planes: Dict[int, str] = {}
        exact_required = bool(getattr(agg, "exact_floats", False))
        for a, ie in zip(self.aggs, self.agg_inputs):
            if a.fn == "count":
                # COUNT counts NON-NULL inputs; the device mask-count would
                # count null strings (-1 codes). Wildcard/literal inputs
                # (COUNT(*)) and null-free numeric columns are safe.
                if not isinstance(ie, px.LiteralExpr):
                    probe = self.compiler.compile(ie)
                    if probe.kind == "code":
                        raise UnsupportedOnDevice("COUNT over a string column")
                self.value_fns.append(None)  # mask count only
                self.int_exact.append(False)
                self.float_bits.append(None)
                continue
            if (
                float_bits
                and a.fn in ("min", "max")
                and isinstance(ie, px.ColumnExpr)
                and pa.types.is_floating(scan_schema.field(ie.index).type)
            ):
                # bijected path: do NOT compile the input (that would upload
                # the rounded f32 copy even when nothing else reads it); the
                # planes are materialized directly from the Arrow column
                width = (
                    "f32"
                    if pa.types.is_float32(scan_schema.field(ie.index).type)
                    else "f64"
                )
                prior = self._bit_planes.setdefault(ie.index, width)
                if prior != width:
                    raise UnsupportedOnDevice("conflicting float plane widths")
                self.value_fns.append(None)
                self.int_exact.append(False)
                self.float_bits.append(width)
                continue
            cv = self.compiler.compile(ie)
            if cv.kind == "code":
                raise UnsupportedOnDevice("string aggregate input")
            if (
                exact_required
                and a.fn in ("min", "max")
                and pa.types.is_floating(a.input_type)
            ):
                # equality-consumed float MIN/MAX over a COMPUTED expression:
                # only plain columns carry exact bits; f32 arithmetic would
                # round the result so it matches nothing — host path
                raise UnsupportedOnDevice(
                    "exact float min/max over a computed expression"
                )
            self.value_fns.append(cv)
            # dates lower as int32 day counts: exact int min/max (the
            # f32 route crashed assembling double -> date32, and values
            # past 2^24 days would round)
            self.int_exact.append(
                isinstance(ie, px.ColumnExpr)
                and (
                    pa.types.is_integer(scan_schema.field(ie.index).type)
                    or pa.types.is_date32(scan_schema.field(ie.index).type)
                )
            )
            self.float_bits.append(None)
        self.scan_schema = scan_schema
        self.partial_schema = agg.schema() if agg.mode.value == "partial" else self._partial_schema(agg)
        self._int_rows, self._folds, self._state_specs = self._plan_outputs()
        # planner-annotated Sort+Limit epilogue (physical/planner.py): when
        # eligible, the device step finishes with lax.top_k over the group
        # scores and reads back `limit` rows instead of every group. Only
        # SINGLE-mode aggregates carry the annotation, so one partial IS the
        # final per-group state and on-device selection equals host
        # selection (boundary ties fall back per query, see _topk_tail).
        self.topk: Optional[dict] = self._topk_spec(agg)
        self._topk_step = None  # built on first fused-eligible partition
        self._topk_fold_step = None  # skewed-cover variant (in-program fold)
        self._step = self._build_step()
        self._sorted_step = None  # built on first high-cardinality partition
        self._device_cache: Dict[int, dict] = {}
        # narrow-residency choice of the first batch, keyed by col index
        # (or "derived:<name>" for derived tiles); kept stable across
        # batches/partitions so the jitted step compiles once
        # (mutated only under _prepare_lock)
        self._narrow_choice: Dict[object, str] = {}
        # executor task threads can run different partitions of one cached
        # stage concurrently; prepare mutates shared state (the growing
        # ColumnDictionary, compiled-step slots), so it is serialized
        self._prepare_lock = make_lock("ops.stage._prepare_lock")
        # name -> fn(row-space npcols dict) -> np row array; materialized as
        # [V, L1] tiles alongside the scan columns on the sorted path
        # (FactAggregateStage derives static mapped columns this way)
        self.derive_columns: Dict[str, Callable] = {}
        # stage cache key (plan display + scan files + mtimes + config
        # flags), set by kernels.hash_aggregate for file-backed stages only;
        # keys the persisted layout cache (ops/layout_cache.py)
        self.persist_key: Optional[str] = None
        # chunk-set delta base (ISSUE 19): plan display + config flags with
        # the file list AND mtimes excluded, set beside persist_key by
        # kernels.resolve_stage. Each prepared chunk persists under
        # chunk_key_base + its own (path, mtime, size, chunk_index), so an
        # appended file re-prepares only its own chunks. None = whole-set
        # persistence only.
        self.chunk_key_base: Optional[str] = None
        # STABLE half of the stage cache key (no mtimes — compiled programs
        # are data-independent), set by kernels.hash_aggregate for every
        # dispatched stage; keys the persistent AOT program cache
        # (ops/aotcache.py). None = the AOT tier stays out of the way.
        self.aot_key: Optional[str] = None

    @staticmethod
    def _partial_schema(agg) -> pa.Schema:
        group_fields = []
        in_schema = agg.input.schema()
        for e, name in agg.group_exprs:
            group_fields.append(pa.field(name, e.data_type(in_schema)))
        state_fields = [f for a in agg.aggr_funcs for f in a.state_fields()]
        return pa.schema(group_fields + state_fields)

    # ------------------------------------------------------------------
    def _plan_outputs(self):
        """Stacked-output plan shared by both device steps: row 0 is counts,
        then one row per aggregate state column — except f64-bijected
        min/max states, which occupy TWO int32 rows (hi/lo key planes whose
        lexicographic order is the f64 total order). Returns (is_int flags,
        fold op names) per stacked row, plus one spec per partial-state
        FIELD: (first logical row, kind, fold) with kind in
        {"int", "num", "f32bits", "f64bits"} — the single source of truth
        for row -> state-column mapping (postprocess_state_rows,
        _fold_state_rows, the top-k epilogues, factagg's score row)."""
        int_rows = [True]  # counts
        folds = ["sum"]
        specs: List[Tuple[int, str, str]] = []
        for a, ix, fb in zip(self.aggs, self.int_exact, self.float_bits):
            row = len(int_rows)
            if a.fn == "count":
                int_rows.append(True)
                folds.append("sum")
                specs.append((row, "int", "sum"))
            elif a.fn in ("sum", "avg"):
                int_rows.append(ix)
                folds.append("sum")
                specs.append((row, "int" if ix else "num", "sum"))
                if a.fn == "avg":
                    int_rows.append(True)
                    folds.append("sum")
                    specs.append((row + 1, "int", "sum"))
            elif fb == "f64":
                int_rows.extend([True, True])
                folds.extend([a.fn, a.fn])  # pair; never folded per-row
                specs.append((row, "f64bits", a.fn))
            elif fb == "f32":
                int_rows.append(True)
                folds.append(a.fn)
                specs.append((row, "f32bits", a.fn))
            else:  # min / max, arithmetic path
                int_rows.append(ix)
                folds.append(a.fn)
                specs.append((row, "int" if ix else "num", a.fn))
        return int_rows, folds, specs

    # keys wider than this decline the fusion ("unsupported multi-key
    # widths"): each f64-bijected key spends TWO of the lexicographic
    # int32 lanes the device sort ranks over
    TOPK_MAX_KEY_LANES = 6

    def _topk_spec(self, agg) -> Optional[dict]:
        """Validate the planner's `_topk_pushdown` annotation against this
        stage's output plan. Returns the enriched spec or None (ineligible:
        the normal full-readback path runs unchanged).

        Every sort key lowers to int32 lanes whose signed order equals the
        key's order — exact int states as-is, f32 scores through the
        floatbits bijection, f64-bijected min/max as their (hi, lo) plane
        pair — so the device ranks one lexicographic int tuple. The group
        index joins as the final lane: ties then resolve to the lowest
        group exactly like the host's stable sort over the group-ordered
        aggregate output, which makes the on-device selection identical to
        the host Sort+Limit whenever the annotation covers every sort key."""
        tk = getattr(agg, "_topk_pushdown", None)
        if tk is None:
            return None
        mode = getattr(agg, "mode", None)
        if mode is not None and mode.value != "single":
            return None  # a per-partition partial top-k ranks partial sums
        if not (1 <= tk["k"] <= (1 << 16)):
            return None
        key_dicts = tk.get("keys") or [
            {"agg_index": tk["agg_index"], "descending": tk["descending"]}
        ]
        keyspecs: List[Tuple[int, str, bool]] = []
        for kd in key_dicts:
            j = kd.get("agg_index", -1)
            if not (0 <= j < len(self.aggs)):
                return None
            if self.aggs[j].fn not in ("sum", "count", "min", "max"):
                # avg finalizes to a RATIO of its two state rows; ranking
                # the sum row would order by the wrong quantity
                return None
            field_idx = sum(len(a.state_fields()) for a in self.aggs[:j])
            row, kind, _fold = self._state_specs[field_idx]
            keyspecs.append((row, kind, bool(kd["descending"])))
        n_lanes = sum(2 if kind == "f64bits" else 1 for _r, kind, _d in keyspecs)
        if not keyspecs or n_lanes > self.TOPK_MAX_KEY_LANES:
            return None
        covered = bool(tk.get("covered", not tk.get("strict", False)))
        return {
            "k": int(tk["k"]),
            "keys": keyspecs,
            "covered": covered,
            "n_lanes": n_lanes,
        }

    def _stack_rows(self, rows):
        """Pack mixed int32/f32 result rows into ONE f32 array -> ONE
        device->host transfer (d2h latency dominates on relay-attached
        chips). Bitcasting int32 to f32 is NOT safe on TPU — small ints are
        denormal floats and get flushed to zero — so each int32 row is split
        into two exactly-f32-representable halves (arithmetic-shift hi,
        unsigned lo); _decode_stacked recombines."""
        import jax.numpy as jnp

        out = []
        for r in rows:
            if r.dtype == jnp.int32:
                out.append((r >> 16).astype(jnp.float32))
                out.append((r & 0xFFFF).astype(jnp.float32))
            else:
                out.append(r)
        return jnp.stack(out)

    def _build_step(self):
        from ballista_tpu.ops import aotcache

        # jit with an AOT disk tier underneath (ops/aotcache.py): a cold
        # process reloads the exported program instead of retracing. A
        # stage without an aot_key (built outside the kernel dispatcher)
        # runs the plain jit path inside the wrapper.
        return aotcache.wrap_step(
            self, "unrolled", self._unrolled_core(), static_argnums=(0,)
        )

    def _unrolled_core(self):
        """Unjitted per-batch unrolled-reduction program; SpmdAggregateExec
        wraps it in shard_map + psum for the mesh path."""
        import jax.numpy as jnp

        filter_masks = self.filter_masks

        # XLA lowers segment_* to scatter, which serializes on TPU (measured
        # 460ms vs ~5ms for 6M rows). Group counts are capped at MAX_GROUPS
        # by run(), so every aggregation is an unrolled per-group masked
        # reduction: pure HBM-bandwidth work on the VPU, G linear passes,
        # each a tree reduction (pairwise-summation accuracy). Integer sums
        # accumulate in int32 (exact; range-checked at prepare time).

        def seg_sum(v, safe_codes, num_segments, zero):
            return jnp.stack(
                [
                    jnp.sum(jnp.where(safe_codes == g, v, zero))
                    for g in range(num_segments)
                ]
            )

        def seg_count(safe_codes, num_segments):
            return jnp.stack(
                [
                    jnp.sum(jnp.where(safe_codes == g, 1, 0), dtype=jnp.int32)
                    for g in range(num_segments)
                ]
            )

        def seg_extreme(v, safe_codes, num_segments, fill, red):
            return jnp.stack(
                [
                    red(jnp.where(safe_codes == g, v, fill))
                    for g in range(num_segments)
                ]
            )

        def seg_extreme_pair(hi, lo, safe_codes, num_segments, fill, red):
            # lexicographic (hi, lo) extreme per group: lo competes only
            # among rows whose hi equals the group's hi extreme
            his, los = [], []
            for g in range(num_segments):
                in_g = safe_codes == g
                h = red(jnp.where(in_g, hi, fill))
                l = red(jnp.where(jnp.logical_and(in_g, hi == h), lo, fill))
                his.append(h)
                los.append(l)
            return jnp.stack(his), jnp.stack(los)

        def step(num_segments, cols, aux, codes, row_valid):
            cols = widen_cols(cols)  # narrow residency -> canonical dtypes
            codes = codes.astype(jnp.int32)
            mask = row_valid
            for fm in filter_masks:
                mask = jnp.logical_and(mask, fm(cols, aux))
            safe_codes = jnp.where(mask, codes, num_segments - 1)
            return self._emit_rows(
                cols,
                aux,
                mask,
                counts=seg_count(safe_codes, num_segments),
                reduce_sum=lambda v, zero: seg_sum(
                    v, safe_codes, num_segments, zero
                ),
                reduce_extreme=lambda v, fill, red: seg_extreme(
                    v, safe_codes, num_segments, fill, red
                ),
                reduce_extreme_pair=lambda hi, lo, fill, red: seg_extreme_pair(
                    hi, lo, safe_codes, num_segments, fill, red
                ),
            )

        return step

    def _build_sorted_step(self):
        from ballista_tpu.ops import aotcache

        return aotcache.wrap_step(
            self, "sorted", self._sorted_core(), static_argnums=(0,)
        )

    def _sorted_core(self):
        """Unjitted device program for the chunked-segment layout
        (ops/layout.py): elementwise exprs over [V, L1] tiles, axis-1
        reductions to per-chunk partials. O(N) for any group count. The
        valid-slot mask expands in-program from per-chunk lengths (L1 is
        the static first argument). FactAggregateStage composes this with
        a membership/top-k epilogue inside one jit."""
        import jax.numpy as jnp

        filter_masks = self.filter_masks

        def pair_axis1(hi, lo, fill, red):
            # lexicographic (hi, lo) extreme per chunk: lo competes only
            # among slots whose hi equals the chunk's hi extreme (masked
            # slots carry fill in both planes, so an all-masked chunk
            # yields the (fill, fill) sentinel pair)
            h = red(hi, axis=1)
            l = red(jnp.where(hi == h[:, None], lo, fill), axis=1)
            return h, l

        def sstep(L1, cols, aux, clen):
            cols = widen_cols(cols)  # narrow residency -> canonical dtypes
            mask = jnp_expand_clen(clen, L1)
            for fm in filter_masks:
                mask = jnp.logical_and(mask, fm(cols, aux))
            return self._emit_rows(
                cols,
                aux,
                mask,
                counts=jnp.sum(mask, axis=1, dtype=jnp.int32),
                reduce_sum=lambda v, zero: jnp.sum(v, axis=1),
                reduce_extreme=lambda v, fill, red: red(v, axis=1),
                reduce_extreme_pair=pair_axis1,
            )

        return sstep

    def _emit_rows(self, cols, aux, mask, counts, reduce_sum, reduce_extreme,
                   reduce_extreme_pair=None):
        """Shared per-aggregate emission for both device cores. The row
        order/dtype contract here must stay in sync with _plan_outputs /
        _stack_rows / decode_packed_rows (and FactAggregateStage._score_row
        builds on it). Integer aggregates stay int32 (exact, range-checked
        at prepare time); masked-out slots use 0 for sums and +/-extreme
        fills for min/max. Float-bijected min/max reduces the int32 key
        planes (pure integer select + compare — no float arithmetic exists
        in that path, so the readback inverts to the bit-exact stored
        value). With NaN declined at prepare, real keys never reach the
        int32 extremes, so the +/-INT32_MAX fills stay out-of-band."""
        import jax.numpy as jnp

        maskf = mask.astype(jnp.float32)
        rows = [counts]
        for a, ie, vf, ix, fb in zip(
            self.aggs, self.agg_inputs, self.value_fns, self.int_exact,
            self.float_bits,
        ):
            if a.fn == "count":
                rows.append(counts)
                continue
            if fb is not None:
                largest = a.fn == "max"
                fill = -_INT32_MAX - 1 if largest else _INT32_MAX
                red = jnp.max if largest else jnp.min
                hk, lk = plane_keys(ie.index)
                hi = jnp.where(mask, jnp.broadcast_to(cols[hk], mask.shape), fill)
                if fb == "f32":
                    rows.append(reduce_extreme(hi, fill, red))
                else:
                    lo = jnp.where(
                        mask, jnp.broadcast_to(cols[lk], mask.shape), fill
                    )
                    h, l = reduce_extreme_pair(hi, lo, fill, red)
                    rows.extend([h, l])
                continue
            v = vf.fn(cols, aux)
            v = jnp.broadcast_to(v, mask.shape)
            if a.fn in ("sum", "avg"):
                if ix:
                    rows.append(reduce_sum(jnp.where(mask, v.astype(jnp.int32), 0), 0))
                else:
                    rows.append(reduce_sum(v.astype(jnp.float32) * maskf, 0.0))
                if a.fn == "avg":
                    rows.append(counts)
            elif a.fn in ("min", "max"):
                largest = a.fn == "max"
                if ix:
                    fill = -_INT32_MAX - 1 if largest else _INT32_MAX
                    v2 = jnp.where(mask, v.astype(jnp.int32), fill)
                else:
                    fill = -jnp.inf if largest else jnp.inf
                    v2 = jnp.where(mask, v.astype(jnp.float32), fill)
                rows.append(
                    reduce_extreme(v2, fill, jnp.max if largest else jnp.min)
                )
        return self._stack_rows(rows)

    # ------------------------------------------------------------------
    def _group_codes(self, batch: pa.RecordBatch) -> Tuple[np.ndarray, List[pa.Array], int]:
        """Host side: evaluate group keys, rank to dense batch-local codes."""
        n = batch.num_rows
        if not self.group_exprs:
            return np.zeros(n, dtype=np.int32), [], 1
        key_arrays = []
        for e, _name in self.group_exprs:
            arr = e.evaluate(batch)
            if isinstance(arr, pa.ChunkedArray):
                arr = arr.combine_chunks()
            key_arrays.append(arr)
        encoded = []
        for arr in key_arrays:
            if isinstance(arr, pa.DictionaryArray):
                d = arr
            else:
                d = pc.dictionary_encode(arr)
            if d.indices.null_count:
                raise UnsupportedOnDevice("null group key")
            codes_i = d.indices.to_numpy(zero_copy_only=False).astype(np.int64)
            encoded.append((codes_i, d.dictionary))

        card = 1
        for _c, dv in encoded:
            card *= max(1, len(dv))

        if card <= 1024:
            # dense fast path: combined dictionary code IS the group id — no
            # np.unique pass; empty groups are dropped later (counts == 0)
            combined = np.zeros(n, dtype=np.int64)
            for codes_i, dv in encoded:
                combined = combined * max(1, len(dv)) + codes_i
            # decompose 0..card-1 into per-column dictionary values
            uniq_rows = []
            gids = np.arange(card, dtype=np.int64)
            rem = gids
            parts = []
            for codes_i, dv in reversed(encoded):
                size = max(1, len(dv))
                parts.append(rem % size)
                rem = rem // size
            for (codes_i, dv), pcodes in zip(encoded, reversed(parts)):
                uniq_rows.append(dv.take(pa.array(np.minimum(pcodes, max(0, len(dv) - 1)))))
            return combined.astype(np.int32), uniq_rows, card

        inv, first_idx, n_groups = dense_rank(
            [(codes_i, len(dv)) for codes_i, dv in encoded]
        )
        # key values for each distinct group = the first row bearing it
        take_idx = pa.array(first_idx.astype(np.int64))
        uniq_rows = [
            (arr.dictionary.take(arr.indices.take(take_idx))
             if isinstance(arr, pa.DictionaryArray) else arr.take(take_idx))
            for arr in key_arrays
        ]
        return inv.astype(np.int32), uniq_rows, n_groups

    # ------------------------------------------------------------------
    def _scan_batches(self, partition: int, ctx):
        """Read the scan partition for device consumption. Parquet fast path:
        eager read_table with dictionary columns (dictionary pages map
        straight to codes — ~10x faster than the streaming dictionary read).
        With scan_stride=N, driven partition p covers scan partitions
        p, p+N, p+2N, ... (N=1: SINGLE mode over MergeExec reads them all)."""
        if self.scan_stride is not None:
            total = self.scan.output_partitioning().partition_count()
            parts = range(partition, total, self.scan_stride)
        else:
            parts = [partition]
        if isinstance(self.scan, ParquetScanExec):
            from ballista_tpu.ops.runtime import ordered_map

            def read_one(p: int) -> pa.Table:
                return self._read_scan_file(self.scan.source.files[p], ctx)

            # multi-file (scan_stride) reads are independent: decode up to
            # `workers` files concurrently, yielding tables in file order so
            # the batch stream is identical to the serial read
            for table in ordered_map(
                read_one, parts,
                ctx.config.tpu_ingest_workers(), ctx.config.tpu_ingest_depth(),
            ):
                yield from table.to_batches(max_chunksize=ctx.batch_size)
            return
        for p in parts:
            yield from self.scan.execute(p, ctx)

    def _read_scan_file(self, path: str, ctx) -> pa.Table:
        """Eager parquet read of one scan file (dictionary pages map straight
        to codes). Factored out of _scan_batches so the chunk-delta prepare
        reads per file — and so tests can interpose a mid-append mutation
        between the identity stat and the read (ISSUE 19 bugfix)."""
        import pyarrow.parquet as pq

        names = self.scan.schema().names
        strings = [
            f.name
            for f in self.scan.schema()
            if pa.types.is_string(f.type) or pa.types.is_large_string(f.type)
        ]
        return pq.read_table(
            path, columns=names, read_dictionary=strings
        ).combine_chunks()

    def _check_int_ranges(self, batch_cols, n: int) -> None:
        """Integer sums accumulate in int32 on device; decline when a masked
        sum over n rows could overflow (ADVICE r1: silent f32 rounding of
        integer aggregates). batch_cols: one Dict[int, np.ndarray], or a list
        of them when the sum spans several mesh shards (psum adds across
        shards, so the bound uses the GLOBAL row count)."""
        col_dicts = batch_cols if isinstance(batch_cols, list) else [batch_cols]
        for a, ie, ix in zip(self.aggs, self.agg_inputs, self.int_exact):
            if not ix or a.fn not in ("sum", "avg"):
                continue
            maxabs = 0
            for bc in col_dicts:
                npcol = bc.get(ie.index)
                if npcol is not None and len(npcol):
                    maxabs = max(
                        maxabs, abs(int(npcol.max())), abs(int(npcol.min()))
                    )
            if maxabs * n > _INT32_MAX:
                raise UnsupportedOnDevice(
                    f"int32 sum over column {ie.name!r} may overflow"
                )

    def _lower_columns(self, batch: pa.RecordBatch) -> Dict[int, np.ndarray]:
        cols: Dict[int, np.ndarray] = {}
        for idx, dtype in self.compiler.used_columns.items():
            d = self.dicts.dicts.get(idx)
            cols[idx] = column_to_numpy(batch.column(idx), dtype, d)
        for idx, width in self._bit_planes.items():
            cols.update(self._lower_planes(batch.column(idx), idx, width))
        return cols

    @staticmethod
    def _lower_planes(arr, idx: int, width: str) -> Dict[int, np.ndarray]:
        """Bijected min/max input: lower the RAW Arrow float column to its
        order-preserving int32 key plane(s) — never through the f32 device
        copy, which would round f64 values. Declines on NaN: Arrow's host
        min/max SKIPS NaN, and no single key order can make a value both
        never-min and never-max."""
        from ballista_tpu.ops import floatbits

        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        if arr.null_count:
            raise UnsupportedOnDevice("null values in device column")
        vals = arr.to_numpy(zero_copy_only=False)
        if np.isnan(vals).any():
            raise UnsupportedOnDevice("NaN in float min/max column")
        hk, lk = plane_keys(idx)
        if width == "f32":
            return {hk: floatbits.f32_to_i32(vals.astype(np.float32, copy=False))}
        hi, lo = floatbits.i64_to_planes(floatbits.f64_to_i64(vals))
        return {hk: hi, lk: lo}

    # holds-lock: self._prepare_lock
    def _prepare_partition(self, partition: int, ctx) -> List[dict]:
        """Host work for one partition: scan, encode, pad, transfer. Returns
        per-batch device-input entries (jnp column arrays stay resident).
        Like the sorted path, the staged host artifacts persist through
        ops/layout_cache.py: the low-cardinality shapes (q1/q6) pay the
        same full-scan decode at SF=100 (~400 s measured), so a fresh
        process must skip straight to the h2d transfer too.

        Pipelined (ballista.tpu.ingest_workers > 0): the PREFETCH stage —
        parquet read + dictionary decode (inside _scan_batches) and group
        ranking — runs on a small thread pool with at most ingest_depth
        batches in flight, overlapping the CONSUME stage below. Consume
        (narrow/encode/upload) stays strictly IN-ORDER and in-thread: each
        batch's narrow choice must feed the next batch's narrow_column
        prior (one jitted step), the growing ColumnDictionary must assign
        codes in batch order (bit-identical results at any worker count),
        and the non-persisting host peak stays ~depth batches' tiles. When
        persisting, a host snapshot of every batch's tiles is retained
        until the save at the end — up to the HBM budget of extra host
        RSS, for that one prepare."""
        import time as _time

        import jax.numpy as jnp

        from ballista_tpu.ops.runtime import pipelined_map, record_ingest

        persisting = (
            bool(ctx.config.tpu_layout_cache_dir())
            and self.persist_key is not None
        )
        if (
            persisting
            and getattr(self, "chunk_key_base", None) is not None
            and isinstance(self.scan, ParquetScanExec)
        ):
            # chunk-set delta store (ISSUE 19): persist/reuse per
            # (path, mtime, size, chunk_index) instead of one blob per
            # whole file set — appending a file re-prepares only its own
            # chunks and every existing tile loads byte-for-byte
            return self._prepare_partition_chunks(partition, ctx)
        t_wall0 = _time.perf_counter()
        scan_s = 0.0
        encode_s = 0.0
        upload_s = 0.0
        src_times: List[float] = []  # appended by the reader thread only
        records: List[dict] = []
        entries: List[dict] = []
        # all of a partition's batch entries are live on device at once
        # during run(); past the budget, decline to the host path rather
        # than OOM the chip (mirrors the sorted path's staged check)
        budget = ctx.config.tpu_hbm_budget()
        total_bytes = 0

        def _prefetch(batch: pa.RecordBatch):
            # group codes FIRST: a high-cardinality switch must not pay the
            # column upload. Pure per-batch work (no shared stage state), so
            # batches may rank concurrently; the TooManyGroups decision
            # stays in the ordered consumer below for serial-identical
            # semantics.
            t0 = _time.perf_counter()
            codes, key_values, n_groups = self._group_codes(batch)
            return batch, codes, key_values, n_groups, _time.perf_counter() - t0

        batch_src = (
            b for b in self._scan_batches(partition, ctx) if b.num_rows
        )
        for batch, codes, key_values, n_groups, dt in pipelined_map(
            batch_src, _prefetch,
            ctx.config.tpu_ingest_workers(), ctx.config.tpu_ingest_depth(),
            on_src_time=src_times.append,
        ):
            scan_s += dt
            n = batch.num_rows
            bucket = bucket_rows(n)
            if n_groups == 0:
                continue
            if n_groups > MAX_GROUPS:
                # beyond the unrolled path's ceiling: run() retries with the
                # sorted chunked-segment layout
                raise TooManyGroups(f"{n_groups} groups exceeds unrolled path")
            t_enc0 = _time.perf_counter()
            npcols = self._lower_columns(batch)
            self._check_int_ranges(npcols, n)
            staged: Dict[int, tuple] = {}
            for idx in list(npcols):
                npcol = npcols.pop(idx)
                fill = False if npcol.dtype == np.bool_ else 0
                narrow, lut, choice = narrow_column(
                    npcol, self._narrow_choice.get(idx)
                )
                del npcol
                padded = pad_to(narrow, bucket, fill)
                staged[idx] = (padded, lut, choice)
                total_bytes += padded.nbytes + (0 if lut is None else lut.nbytes)
            total_bytes += 3 * bucket  # int16 codes + bool row_valid
            if total_bytes > budget:
                raise UnsupportedOnDevice(
                    f"stage batches ({total_bytes >> 20} MiB) exceed the HBM budget"
                )
            seg_bucket = bucket_rows(n_groups, 16) + 1  # +1 dump slot
            # group codes fit int16 by construction (n_groups <= MAX_GROUPS)
            codes_pad = pad_to(codes.astype(np.int16), bucket, 0)
            row_valid = np.zeros(bucket, dtype=np.bool_)
            row_valid[:n] = True
            encode_s += _time.perf_counter() - t_enc0
            rec = {
                "n_groups": int(n_groups),
                "seg_bucket": int(seg_bucket),
                "codes_pad": codes_pad,
                "row_valid": row_valid,
                "key_values": key_values,
            }
            if persisting:
                records.append({**rec, "staged": dict(staged)})
            t_up0 = _time.perf_counter()
            make_headroom(self, total_bytes, budget)
            cols = _upload_staged(staged, self._narrow_choice)
            entries.append(
                {
                    "n_groups": rec["n_groups"],
                    "seg_bucket": rec["seg_bucket"],
                    "cols": cols,
                    "codes": jnp.asarray(codes_pad),
                    "row_valid": jnp.asarray(row_valid),
                    "key_values": key_values,
                }
            )
            upload_s += _time.perf_counter() - t_up0
        if persisting and records:
            self._save_batches_layout(partition, ctx, records)
        scan_s += sum(src_times)
        wall_s = _time.perf_counter() - t_wall0
        record_ingest(scan_s, encode_s, upload_s, wall_s)
        return entries

    def _save_batches_layout(self, partition: int, ctx, records: List[dict]) -> None:
        """Best-effort persist of the unrolled path's staged batches."""
        from ballista_tpu.ops import layout_cache as lc

        arrays: List[np.ndarray] = []
        metas = []
        for rec in records:
            m = {
                "n_groups": rec["n_groups"],
                "seg_bucket": rec["seg_bucket"],
                "cols": _pack_staged(rec["staged"], arrays),
                "codes": len(arrays),
            }
            arrays.append(rec["codes_pad"])
            m["row_valid"] = len(arrays)
            arrays.append(rec["row_valid"])
            m["keys"] = len(arrays)
            arrays.append(lc.pack_arrow_arrays(rec["key_values"]))
            metas.append(m)
        dmeta, darrays = lc.pack_dict_snapshot(self.dicts)
        offset = len(arrays)
        meta = {
            "kind": "batches",
            "batches": metas,
            "dicts": {k: v + offset for k, v in dmeta.items()},
        }
        arrays.extend(darrays)
        meta["n_arrays"] = len(arrays)
        lc.save_entry(
            base=ctx.config.tpu_layout_cache_dir(),
            stage_key=self.persist_key,
            partition=partition,
            meta=meta,
            arrays=arrays,
            cap_bytes=ctx.config.tpu_layout_cache_cap(),
        )

    # -- chunk-set delta store (ISSUE 19) -------------------------------
    #
    # The whole-set batches entry above keys on (plan, file set, mtimes):
    # appending ONE parquet file to a growing directory orphans the entry
    # and re-pays the full scan/decode/encode pipeline. The methods below
    # instead persist each prepared chunk under its OWN identity —
    # (path, mtime, size, chunk_index) beneath the mtime-free
    # chunk_key_base — so a query over files ∪ {new} re-prepares only the
    # new file's chunks and loads every existing tile byte-for-byte.

    def _chunk_context(self) -> str:
        """Hash of the cross-file prepare state a chunk's tiles bake in:
        the sticky narrow choices and every string dictionary's code->value
        mapping as they stood when the file's first chunk was consumed.
        Part of the chunk key: a file set whose sort order interleaves a
        NEW file before an old one shifts the old file's dictionary codes,
        and keying on the context makes that a clean miss (one re-prepare,
        re-saved under the new context) instead of a poisoned hit or a
        permanently unloadable entry."""
        import hashlib

        h = hashlib.sha256()
        for k in sorted(self._narrow_choice, key=str):
            h.update(f"n|{k}={self._narrow_choice[k]}\x00".encode())
        for idx in sorted(self.dicts.dicts):
            snap = self.dicts.dicts[idx].snapshot()
            if snap is None:
                continue
            h.update(f"d|{idx}\x00".encode())
            for v in snap.to_pylist():
                h.update(repr(v).encode())
                h.update(b"\x00")
        return h.hexdigest()[:20]

    def _chunk_stage_key(self, ident: Tuple[str, str, int], context: str) -> str:
        path, mtime, size = ident
        return (
            f"chunk|{self.chunk_key_base}|ctx={context}|{path}|{mtime}|{size}"
        )

    # holds-lock: self._prepare_lock
    def _prepare_partition_chunks(self, partition: int, ctx) -> List[dict]:
        """Chunk-granular variant of _prepare_partition for parquet-backed
        stages with a delta identity: walk the partition's files in order,
        loading each file's persisted chunks when its (path, mtime, size)
        identity and prepare context match, preparing (and persisting) only
        the files that miss. Batch order — and therefore dictionary code
        assignment, narrow choices, and the device batch stream — is
        identical to the serial whole-set prepare."""
        import os
        import time as _time

        from ballista_tpu.ops.runtime import record_delta, record_ingest

        t_wall0 = _time.perf_counter()
        if self.scan_stride is not None:
            total = self.scan.output_partitioning().partition_count()
            parts = range(partition, total, self.scan_stride)
        else:
            parts = [partition]
        budget = ctx.config.tpu_hbm_budget()
        entries: List[dict] = []
        # cumulative timings + staged-bytes budget ledger shared with the
        # per-file prepare (mirrors _prepare_partition's accounting)
        totals = {"bytes": 0, "scan_s": 0.0, "encode_s": 0.0, "upload_s": 0.0}
        for p in parts:
            path = self.scan.source.files[p]
            try:
                st = os.stat(path)
                ident = (path, str(st.st_mtime), int(st.st_size))
            except OSError:
                ident = None
            context = self._chunk_context()
            loaded = (
                self._load_file_chunks(ident, context, ctx)
                if ident is not None
                else None
            )
            if loaded is not None:
                records, nbytes = loaded
                totals["bytes"] += nbytes
                if totals["bytes"] > budget:
                    raise UnsupportedOnDevice(
                        f"stage batches ({totals['bytes'] >> 20} MiB) "
                        f"exceed the HBM budget"
                    )
                t_up0 = _time.perf_counter()
                reused = 0
                for rec in records:
                    if rec is None:  # empty-chunk marker
                        continue
                    entries.append(self._upload_record(rec, budget, totals))
                    reused += 1
                totals["upload_s"] += _time.perf_counter() - t_up0
                record_delta("chunks_reused", reused)
                record_delta("bytes_reprepared_saved", nbytes)
                continue
            self._prepare_file_chunks(
                p, ident, context, ctx, entries, totals, budget
            )
        record_ingest(
            totals["scan_s"], totals["encode_s"], totals["upload_s"],
            _time.perf_counter() - t_wall0,
        )
        return entries

    def _upload_record(self, rec: dict, budget: int, totals: dict) -> dict:
        import jax.numpy as jnp

        make_headroom(self, totals["bytes"], budget)
        cols = _upload_staged(rec["staged"], self._narrow_choice)
        return {
            "n_groups": rec["n_groups"],
            "seg_bucket": rec["seg_bucket"],
            "cols": cols,
            "codes": jnp.asarray(rec["codes_pad"]),
            "row_valid": jnp.asarray(rec["row_valid"]),
            "key_values": rec["key_values"],
        }

    def _load_file_chunks(self, ident, context: str, ctx):
        """Load ONE file's persisted chunk set. Returns (records, bytes) —
        records in chunk order, None marking empty chunks — or None on any
        miss. All-or-nothing: every chunk must be present, carry the exact
        identity stamped at save time (a torn mid-append writer is caught
        by the save-side re-stat, this is the load-side belt), and adopt
        its dictionary snapshot cleanly, else the whole file re-prepares."""
        from ballista_tpu.ops import layout_cache as lc

        base = ctx.config.tpu_layout_cache_dir()
        skey = self._chunk_stage_key(ident, context)
        hit = lc.load_entry(base, skey, 0)
        if hit is None:
            return None
        n_chunks = hit[0].get("n_chunks")
        if not isinstance(n_chunks, int) or n_chunks < 1:
            return None
        records: List[Optional[dict]] = []
        total = 0
        for ci in range(n_chunks):
            if hit is None:
                hit = lc.load_entry(base, skey, ci)
            if hit is None:
                return None
            meta, arrays = hit
            hit = None
            if (
                meta.get("kind") != "chunk"
                or meta.get("ident") != list(ident)
                or meta.get("n_chunks") != n_chunks
            ):
                return None
            try:
                if not lc.adopt_dict_snapshot(self.dicts, meta["dicts"], arrays):
                    return None
            except Exception:
                return None
            if meta.get("empty"):
                records.append(None)
                continue
            try:
                unpacked = _unpack_staged(
                    meta["cols"], arrays, self._narrow_choice
                )
                if unpacked is None:
                    return None
                staged, nbytes = unpacked
                rec = {
                    "n_groups": int(meta["n_groups"]),
                    "seg_bucket": int(meta["seg_bucket"]),
                    "staged": staged,
                    "codes_pad": arrays[meta["codes"]],
                    "row_valid": arrays[meta["row_valid"]],
                    "key_values": lc.unpack_arrow_arrays(arrays[meta["keys"]]),
                }
            except Exception:
                return None
            total += nbytes + rec["codes_pad"].nbytes + rec["row_valid"].nbytes
            records.append(rec)
        return records, total

    def _prepare_file_chunks(
        self, p: int, ident, context: str, ctx,
        entries: List[dict], totals: dict, budget: int,
    ) -> None:
        """Prepare one file fresh, persisting each consumed chunk under its
        own (path, mtime, size, chunk_index) entry as it goes. Mid-append
        fail-closed (ISSUE 19 bugfix): the file is re-statted AFTER the
        read — if its identity moved between the stat and the read, the
        bytes just decoded may not be the state `ident` describes, and
        persisting them would poison the entry for every later process
        whose fingerprint resolved at the old mtime. The in-memory prepare
        still uses the data (same exposure as the whole-set path); only
        the save is declined, and recorded."""
        import os
        import time as _time

        from ballista_tpu.ops import layout_cache as lc
        from ballista_tpu.ops.runtime import pipelined_map, record_delta

        path = self.scan.source.files[p]
        t0 = _time.perf_counter()
        table = self._read_scan_file(path, ctx)
        totals["scan_s"] += _time.perf_counter() - t0
        save = ident is not None
        if save:
            try:
                st = os.stat(path)
                if (str(st.st_mtime), int(st.st_size)) != (ident[1], ident[2]):
                    save = False
                    record_delta("save_declined_midappend")
            except OSError:
                save = False
        base = ctx.config.tpu_layout_cache_dir()
        cap = ctx.config.tpu_layout_cache_cap()
        skey = self._chunk_stage_key(ident, context) if save else None
        chunks = table.to_batches(max_chunksize=ctx.batch_size)
        n_chunks = max(len(chunks), 1)

        def _save_chunk(ci: int, body: Optional[dict], staged) -> None:
            if not save:
                return
            arrays: List[np.ndarray] = []
            meta = {
                "kind": "chunk",
                "ident": list(ident),
                "n_chunks": n_chunks,
            }
            if body is None:
                meta["empty"] = True
            else:
                meta["cols"] = _pack_staged(staged, arrays)
                meta["n_groups"] = body["n_groups"]
                meta["seg_bucket"] = body["seg_bucket"]
                meta["codes"] = len(arrays)
                arrays.append(body["codes_pad"])
                meta["row_valid"] = len(arrays)
                arrays.append(body["row_valid"])
                meta["keys"] = len(arrays)
                arrays.append(lc.pack_arrow_arrays(body["key_values"]))
            # cumulative snapshot AFTER this chunk's encode: a loader that
            # adopted every prior chunk in order holds exactly a prefix
            dmeta, darrays = lc.pack_dict_snapshot(self.dicts)
            offset = len(arrays)
            meta["dicts"] = {k: v + offset for k, v in dmeta.items()}
            arrays.extend(darrays)
            meta["n_arrays"] = len(arrays)
            lc.save_entry(base, skey, ci, meta, arrays, cap)

        def _prefetch(item):
            ci, batch = item
            if batch.num_rows == 0:
                return ci, batch, None, None, 0, 0.0
            t0 = _time.perf_counter()
            codes, key_values, n_groups = self._group_codes(batch)
            return (
                ci, batch, codes, key_values, n_groups,
                _time.perf_counter() - t0,
            )

        for ci, batch, codes, key_values, n_groups, dt in pipelined_map(
            iter(enumerate(chunks)), _prefetch,
            ctx.config.tpu_ingest_workers(), ctx.config.tpu_ingest_depth(),
        ):
            totals["scan_s"] += dt
            n = batch.num_rows
            if n == 0 or n_groups == 0:
                _save_chunk(ci, None, None)
                continue
            if n_groups > MAX_GROUPS:
                # partial chunk set stays on disk; the all-chunks-present
                # load check fails it closed
                raise TooManyGroups(f"{n_groups} groups exceeds unrolled path")
            bucket = bucket_rows(n)
            t_enc0 = _time.perf_counter()
            npcols = self._lower_columns(batch)
            self._check_int_ranges(npcols, n)
            staged: Dict[int, tuple] = {}
            for idx in list(npcols):
                npcol = npcols.pop(idx)
                fill = False if npcol.dtype == np.bool_ else 0
                narrow, lut, choice = narrow_column(
                    npcol, self._narrow_choice.get(idx)
                )
                del npcol
                padded = pad_to(narrow, bucket, fill)
                staged[idx] = (padded, lut, choice)
                totals["bytes"] += (
                    padded.nbytes + (0 if lut is None else lut.nbytes)
                )
            totals["bytes"] += 3 * bucket  # int16 codes + bool row_valid
            if totals["bytes"] > budget:
                raise UnsupportedOnDevice(
                    f"stage batches ({totals['bytes'] >> 20} MiB) exceed "
                    f"the HBM budget"
                )
            seg_bucket = bucket_rows(n_groups, 16) + 1  # +1 dump slot
            codes_pad = pad_to(codes.astype(np.int16), bucket, 0)
            row_valid = np.zeros(bucket, dtype=np.bool_)
            row_valid[:n] = True
            rec = {
                "n_groups": int(n_groups),
                "seg_bucket": int(seg_bucket),
                "codes_pad": codes_pad,
                "row_valid": row_valid,
                "key_values": key_values,
            }
            totals["encode_s"] += _time.perf_counter() - t_enc0
            _save_chunk(ci, rec, staged)
            t_up0 = _time.perf_counter()
            rec["staged"] = staged
            entries.append(self._upload_record(rec, budget, totals))
            totals["upload_s"] += _time.perf_counter() - t_up0
            record_delta("chunks_prepared")
        if not chunks:
            _save_chunk(0, None, None)

    def _load_batches_layout(self, meta: dict, arrays: List[np.ndarray],
                             ctx) -> Optional[dict]:
        """Rehydrate a persisted batches entry (meta pre-validated as
        kind=batches with an adopted dictionary snapshot)."""
        import jax.numpy as jnp

        from ballista_tpu.ops import layout_cache as lc

        records: List[dict] = []
        total = 0
        try:
            for m in meta["batches"]:
                unpacked = _unpack_staged(
                    m["cols"], arrays, self._narrow_choice
                )
                if unpacked is None:
                    return None
                staged, nbytes = unpacked
                total += nbytes
                records.append(
                    {
                        "n_groups": int(m["n_groups"]),
                        "seg_bucket": int(m["seg_bucket"]),
                        "staged": staged,
                        "codes_pad": arrays[m["codes"]],
                        "row_valid": arrays[m["row_valid"]],
                        "key_values": lc.unpack_arrow_arrays(arrays[m["keys"]]),
                    }
                )
                total += arrays[m["codes"]].nbytes + arrays[m["row_valid"]].nbytes
        except Exception:
            return None
        budget = ctx.config.tpu_hbm_budget()
        if total > budget:
            raise UnsupportedOnDevice(
                f"stage batches ({total >> 20} MiB) exceed the HBM budget"
            )
        make_headroom(self, total, budget)
        entries: List[dict] = []
        for rec in records:
            cols = _upload_staged(rec["staged"], self._narrow_choice)
            entries.append(
                {
                    "n_groups": rec["n_groups"],
                    "seg_bucket": rec["seg_bucket"],
                    "cols": cols,
                    "codes": jnp.asarray(rec["codes_pad"]),
                    "row_valid": jnp.asarray(rec["row_valid"]),
                    "key_values": rec["key_values"],
                }
            )
        return {"kind": "batches", "entries": entries}

    # holds-lock: self._prepare_lock
    def _prepare_partition_sorted(self, partition: int, ctx) -> dict:
        """High-cardinality path: whole-partition chunked-segment layout
        (ops/layout.py). Sorting/ranking/materialization is cache-time host
        work; per-query device work is O(N) elementwise + axis reductions.
        Config ballista.tpu.sorted_kernel=pallas selects the MXU one-hot
        matmul kernel instead (sum/count/avg only).

        The host work (parquet decode, encode, rank, sort, materialize) is a
        pure function of (persist_key, partition) — persisted via
        ops/layout_cache.py so a fresh process skips straight to the h2d
        transfer (measured: it is ~600 of the 737 s of a cold q3 SF=100).
        The pallas kernel path is not persisted (config-gated, flat layout)."""
        import time as _time

        from ballista_tpu.ops.layout import SortedSegmentLayout
        from ballista_tpu.ops.runtime import record_ingest

        loaded = self._load_layout(partition, ctx, want=("sorted",))
        if loaded is not None:
            return loaded
        # the prefetch/consume split here is inside _scan_batches: multi-file
        # partitions decode up to ingest_workers files concurrently; the
        # whole-partition rank/sort/materialize below is one ordered pass
        t_wall0 = _time.perf_counter()
        batches = [b for b in self._scan_batches(partition, ctx) if b.num_rows]
        if not batches:
            return {"kind": "empty"}
        table = pa.Table.from_batches(batches).combine_chunks()
        batch = table.to_batches(max_chunksize=table.num_rows)[0]
        codes, key_values, n_groups = self._group_codes(batch)
        scan_s = _time.perf_counter() - t_wall0
        if n_groups == 0:
            return {"kind": "empty"}
        if (
            ctx.config.tpu_sorted_kernel() == "pallas"
            and all(a.fn in ("sum", "count", "avg") for a in self.aggs)
            and not any(self.int_exact)
            # fact stages (sorted_cover_max) consume [V, L1] tiles + rank
            # metadata the pallas entry doesn't carry
            and not getattr(self, "sorted_cover_max", False)
            # the fused top-k epilogue composes with the layout core only
            and self.topk is None
            # counts accumulate in f32 inside the kernel: exact only below 2^24
            and batch.num_rows <= (1 << 24)
        ):
            return self._prepare_pallas_sorted(batch, codes, key_values, n_groups, ctx)
        layout = None
        if self.topk is not None and not getattr(self, "sorted_cover_max", False):
            # fused top-k wants the one-chunk-per-group cover: the chunk
            # fold becomes identity, so the gathered k columns are the
            # BIT-IDENTICAL values the full readback would emit. The int
            # range check runs against the cover width (a whole-group sum
            # in one chunk); failing either check falls back to the
            # default chunking below — fusion per-partition degrades to the
            # in-program fold or the full readback, the normal path is
            # untouched. Only THIS branch lowers columns before the layout:
            # the default ordering below (layout first, codes freed, then
            # lower) keeps the documented SF=100 host-memory peak.
            npcols = self._lower_columns(batch)
            cover_L1 = _topk_cover_L1(codes, n_groups)
            if cover_L1 is not None:
                try:
                    self._check_int_ranges(npcols, cover_L1)
                    layout = SortedSegmentLayout(codes, n_groups, force_L1=cover_L1)
                except UnsupportedOnDevice:
                    layout = None
            elif ctx.config.tpu_cost_model():
                # general skew handler (ISSUE 10): the cover failed because
                # a few dominant groups blow its bounds. Split THEM to the
                # in-program segment fold and keep every tail group on the
                # one-chunk fast path, instead of degrading the whole
                # partition to percentile chunking. Counted as a runtime
                # re-plan; bit-identity rides the existing fold machinery.
                skew = skew_split_plan(codes, n_groups)
                if skew is not None:
                    L1_tail, _n_dom = skew
                    try:
                        self._check_int_ranges(npcols, L1_tail)
                        layout = SortedSegmentLayout(
                            codes, n_groups, force_L1=L1_tail
                        )
                        from ballista_tpu.ops.runtime import (
                            record_routing_event,
                        )

                        record_routing_event("skew_replan")
                    except UnsupportedOnDevice:
                        layout = None
            if layout is None:
                layout = SortedSegmentLayout(codes, n_groups)
                self._check_int_ranges(npcols, layout.L1)
            del codes
        else:
            layout = SortedSegmentLayout(
                codes, n_groups, cover_max=getattr(self, "sorted_cover_max", False)
            )
            del codes
            npcols = self._lower_columns(batch)
            self._check_int_ranges(npcols, layout.L1)
        # derived columns read row-space npcols; compute BEFORE the staging
        # loop below starts freeing them
        derived_raw = {name: fn(npcols) for name, fn in self.derive_columns.items()}
        # the Arrow buffers are no longer needed: at SF=100 the combined
        # table is ~25 GB that would otherwise sit under the whole
        # materialization peak (this prepare OOM-killed a 125 GB host)
        del batches, table, batch
        # stage narrow tiles HOST-side and check the HBM budget BEFORE any
        # device allocation: the planner's coalesce cap compares compressed
        # leaf bytes, which under-counts columns that fail to narrow — a
        # too-big stage must fall to the host path, not OOM the chip.
        # Row-space columns free as their tiles materialize: the peak holds
        # one column in row space, not every used column at once.
        staged: Dict[int, tuple] = {}
        total = layout.clen.nbytes
        for idx in list(npcols):
            npcol = npcols.pop(idx)
            narrow, lut, choice = narrow_column(npcol, self._narrow_choice.get(idx))
            del npcol
            tiles = layout.materialize(narrow)
            del narrow
            staged[idx] = (tiles, lut, choice)
            total += tiles.nbytes + (lut.nbytes if lut is not None else 0)
        staged_derived: Dict[str, tuple] = {}
        for name in list(derived_raw):
            raw = derived_raw.pop(name)
            if raw.dtype == np.int32:
                # int-only narrowing: derived tiles travel as standalone
                # step arguments (not through widen_cols), so the consumer
                # widens with a plain astype — no LUT tuples here
                key = f"derived:{name}"
                narrow, _lut, choice = narrow_column(raw, self._narrow_choice.get(key))
                tiles = layout.materialize(narrow)
                staged_derived[name] = (tiles, key, choice)
            else:
                staged_derived[name] = (layout.materialize(raw), None, None)
            del raw
            total += staged_derived[name][0].nbytes
        # the take-index served every materialize; drop it before the h2d
        # staging peak (persisted entries never carry it)
        layout.row_take = None
        budget = ctx.config.tpu_hbm_budget()
        if total > budget:
            # checked BEFORE persisting so an undeployable layout is never
            # written to disk
            raise UnsupportedOnDevice(
                f"stage tiles ({total >> 20} MiB) exceed the HBM budget"
            )
        t_enc_end = _time.perf_counter()
        encode_s = t_enc_end - t_wall0 - scan_s
        # persist BEFORE upload: _upload_staged consumes the host tiles
        self._save_sorted_layout(
            partition, ctx, layout, staged, staged_derived, key_values
        )
        t_up0 = _time.perf_counter()
        # the layout-cache disk write is host-side prepare cost: count it in
        # encode_s so wall_s stays the sum of the components and the derived
        # overlap fraction is not dragged down on persisting prepares
        encode_s += t_up0 - t_enc_end
        out = self._finish_sorted(
            ctx, layout, staged, staged_derived, key_values, total
        )
        t_end = _time.perf_counter()
        record_ingest(scan_s, encode_s, t_end - t_up0, t_end - t_wall0)
        return out

    def _finish_sorted(
        self, ctx, layout, staged: Dict, staged_derived: Dict, key_values,
        total: int,
    ) -> dict:
        """Shared tail of the fresh and disk-loaded sorted prepares: budget
        check, headroom, h2d upload, derived upload, step build, entry."""
        import jax.numpy as jnp

        budget = ctx.config.tpu_hbm_budget()
        if total > budget:
            raise UnsupportedOnDevice(
                f"stage tiles ({total >> 20} MiB) exceed the HBM budget"
            )
        make_headroom(self, total, budget)
        cols = _upload_staged(staged, self._narrow_choice)
        derived = {}
        for name in list(staged_derived):
            tiles, key, choice = staged_derived.pop(name)
            if key is not None:
                self._narrow_choice[key] = choice
            derived[name] = jnp.asarray(tiles)
        if self._sorted_step is None:
            self._sorted_step = self._build_sorted_step()
        return {
            "kind": "sorted",
            "layout": layout,
            "cols": cols,
            "clen": jnp.asarray(layout.clen),
            "key_values": key_values,
            "n_groups": layout.n_groups,
            "derived": derived,
        }

    # -- persisted layout cache (ops/layout_cache.py) -------------------
    def _save_sorted_layout(
        self, partition: int, ctx, layout, staged: Dict, staged_derived: Dict,
        key_values,
    ) -> None:
        """Best-effort persist of one prepared sorted partition: layout
        scalars + owner/pad, narrow tiles + LUTs + choices, derived tiles,
        the string-dictionary snapshot (codes baked into the tiles), and the
        group key values (Arrow IPC bytes). Entries are keyed by the stage
        cache key, so file rewrites and config changes miss cleanly; the
        int-range check is NOT re-run on load because the entry only exists
        if the identical data passed it at save time."""
        base = ctx.config.tpu_layout_cache_dir()
        if not base or self.persist_key is None:
            return
        from ballista_tpu.ops import layout_cache as lc

        arrays: List[np.ndarray] = []
        meta: Dict = {"kind": "sorted", "layout": layout.state()}
        meta["owner"] = len(arrays)
        arrays.append(layout.owner)
        meta["clen"] = len(arrays)
        arrays.append(layout.clen)
        meta["cols"] = _pack_staged(staged, arrays)
        derived_meta = {}
        for name, (tiles, nkey, choice) in staged_derived.items():
            derived_meta[name] = {
                "tiles": len(arrays), "key": nkey, "choice": choice,
            }
            arrays.append(tiles)
        meta["derived"] = derived_meta
        dmeta, darrays = lc.pack_dict_snapshot(self.dicts)
        offset = len(arrays)
        meta["dicts"] = {k: v + offset for k, v in dmeta.items()}
        arrays.extend(darrays)
        meta["keys"] = len(arrays)
        arrays.append(lc.pack_arrow_arrays(key_values))
        meta["n_arrays"] = len(arrays)
        lc.save_entry(
            base, self.persist_key, partition, meta, arrays,
            ctx.config.tpu_layout_cache_cap(),
        )

    # holds-lock: self._prepare_lock
    def _load_layout(self, partition: int, ctx, want=("sorted", "batches")):
        """Rehydrate a persisted partition of either kind: adopt the
        dictionary snapshot (live dicts must be a prefix — codes in the
        persisted arrays must mean the same strings), then go straight to
        the h2d transfer. Returns None on any miss/mismatch."""
        base = ctx.config.tpu_layout_cache_dir()
        if not base or self.persist_key is None:
            return None
        from ballista_tpu.ops import layout_cache as lc

        hit = lc.load_entry(base, self.persist_key, partition)
        if hit is None:
            return None
        meta, arrays = hit
        if meta.get("kind") not in want:
            return None
        try:
            if not lc.adopt_dict_snapshot(self.dicts, meta["dicts"], arrays):
                return None
        except Exception:
            return None
        if meta["kind"] == "batches":
            return self._load_batches_layout(meta, arrays, ctx)
        return self._load_sorted_entry(meta, arrays, ctx)

    def _load_sorted_entry(self, meta: dict, arrays, ctx) -> Optional[dict]:
        from ballista_tpu.ops import layout_cache as lc

        if set(meta.get("derived", {})) != set(self.derive_columns):
            return None
        try:
            from ballista_tpu.ops.layout import SortedSegmentLayout

            owner = arrays[meta["owner"]]
            if "clen" in meta:
                clen = arrays[meta["clen"]]
            else:  # legacy entry: bool [V, L1] pad tiles
                clen = arrays[meta["pad"]].sum(axis=1).astype(np.int16)
            layout = SortedSegmentLayout.from_state(meta["layout"], owner, clen)
            unpacked = _unpack_staged(meta["cols"], arrays, self._narrow_choice)
            if unpacked is None:
                return None  # jitted step already compiled another dtype
            staged, col_bytes = unpacked
            total = clen.nbytes + col_bytes
            staged_derived: Dict[str, tuple] = {}
            for name, spec in meta["derived"].items():
                nkey = spec["key"]
                if nkey is not None:
                    cur = self._narrow_choice.get(nkey)
                    if cur is not None and cur != spec["choice"]:
                        return None
                staged_derived[name] = (arrays[spec["tiles"]], nkey, spec["choice"])
                total += arrays[spec["tiles"]].nbytes
            key_values = lc.unpack_arrow_arrays(arrays[meta["keys"]])
        except Exception:
            return None
        # budget overrun raises (not miss): same disposition as a fresh
        # prepare of this partition
        return self._finish_sorted(
            ctx, layout, staged, staged_derived, key_values, total
        )

    def _prepare_pallas_sorted(self, batch, codes, key_values, n_groups, ctx) -> dict:
        """Flat sorted residency for the pallas MXU kernel
        (ops/pallas_kernels.py::sorted_grouped_sum)."""
        import jax.numpy as jnp

        from ballista_tpu.ops.pallas_kernels import SORT_BLOCK

        order = np.argsort(codes, kind="stable")
        n = len(order)
        pad = (-n) % SORT_BLOCK
        codes_sorted = codes[order].astype(np.int32)
        if pad:
            codes_sorted = np.concatenate(
                [codes_sorted, np.full(pad, codes_sorted[-1], np.int32)]
            )
        npcols = self._lower_columns(batch)
        # same pre-allocation budget discipline as the layout path: this
        # path uploads full-width columns, so a too-big partition must
        # decline to the host, not OOM the chip
        budget = ctx.config.tpu_hbm_budget()
        total = (n + pad) * (4 + 1)  # codes int32 + row_valid bool
        for npcol in npcols.values():
            total += (n + pad) * npcol.dtype.itemsize
        if total > budget:
            raise UnsupportedOnDevice(
                f"pallas stage columns ({total >> 20} MiB) exceed the HBM budget"
            )
        make_headroom(self, total, budget)
        cols: Dict[int, object] = {}
        for idx, npcol in npcols.items():
            flat = npcol[order]
            fill = False if flat.dtype == np.bool_ else 0
            cols[idx] = jnp.asarray(pad_to(flat, n + pad, fill))
        row_valid = np.zeros(n + pad, dtype=np.bool_)
        row_valid[:n] = True
        return {
            "kind": "pallas_sorted",
            "codes": jnp.asarray(codes_sorted),
            "cols": cols,
            "row_valid": jnp.asarray(row_valid),
            "key_values": key_values,
            "n_groups": n_groups,
        }

    def _pallas_masked_rows_step(self):
        """Jitted once per stage (a per-call closure would retrace every
        query)."""
        if getattr(self, "_pallas_step", None) is not None:
            return self._pallas_step
        import jax
        import jax.numpy as jnp

        filter_masks = self.filter_masks
        value_fns = self.value_fns

        @jax.jit
        def masked_rows(cols, aux, row_valid):
            cols = widen_cols(cols)
            mask = row_valid
            for fm in filter_masks:
                mask = jnp.logical_and(mask, fm(cols, aux))
            maskf = mask.astype(jnp.float32)
            rows = [maskf]
            for vf in value_fns:
                if vf is None:
                    continue
                v = jnp.broadcast_to(vf.fn(cols, aux), mask.shape)
                rows.append(v.astype(jnp.float32) * maskf)
            return jnp.stack(rows)

        self._pallas_step = masked_rows
        return masked_rows

    def _run_pallas_sorted(self, ent: dict, aux) -> pa.Table:
        from ballista_tpu.ops.pallas_kernels import sorted_grouped_sum
        from ballista_tpu.ops.runtime import readback

        vals = self._pallas_masked_rows_step()(ent["cols"], aux, ent["row_valid"])
        out = readback(
            sorted_grouped_sum(ent["codes"], vals, ent["n_groups"])
        ).astype(np.float64)
        counts = out[0]
        outputs: List[np.ndarray] = []
        vi = 1
        for a in self.aggs:
            if a.fn == "count":
                outputs.append(counts)
                continue
            outputs.append(out[vi])
            vi += 1
            if a.fn == "avg":
                outputs.append(counts)
        return self._assemble_partial(
            outputs, counts, ent["key_values"], ent["n_groups"]
        )

    def run(self, partition: int, ctx) -> Optional[pa.Table]:
        import jax.numpy as jnp

        use_cache = ctx.config.device_cache() and self.cacheable
        if not self.cacheable and not ctx.config.tpu_fuse_volatile():
            # aggregating over a re-executed source (e.g. a host join) pays
            # encode+transfer per query with no residency payoff — measured a
            # wash-to-loss on relay-attached chips, so it is opt-in
            raise UnsupportedOnDevice("volatile row source (enable ballista.tpu.fuse_volatile_sources)")
        prepared = self._device_cache.get(partition) if use_cache else None
        if prepared is not None:
            from ballista_tpu.ops.runtime import touch_residency

            touch_residency(self, partition)  # LRU recency for eviction
        if prepared is None:
            with self._prepare_lock:
                prepared = self._device_cache.get(partition) if use_cache else None
                freshly_prepared = False
                if prepared is None:
                    # persisted sorted layout first: a hit skips the whole
                    # scan+rank pass (the unrolled path would decode parquet
                    # before discovering the cardinality it declines on)
                    prepared = self._load_layout(partition, ctx)
                    freshly_prepared = prepared is not None
                if prepared is None:
                    if self.topk is not None:
                        # the fused top-k epilogue needs ONE device call
                        # over the whole partition (per-batch group codes
                        # are batch-local); the sorted prepare itself
                        # decides per partition whether fusion is live
                        # (one-chunk cover) or the normal path runs
                        prepared = self._prepare_partition_sorted(partition, ctx)
                    else:
                        try:
                            prepared = {"kind": "batches",
                                        "entries": self._prepare_partition(partition, ctx)}
                        except TooManyGroups:
                            prepared = self._prepare_partition_sorted(partition, ctx)
                    freshly_prepared = True
                if freshly_prepared and use_cache:
                    from ballista_tpu.ops.runtime import (
                        entry_device_bytes,
                        reserve_and_pin,
                    )

                    # pin only within the HBM budget; partitions beyond
                    # it stream per query (how SF=100 fits a 16GB chip).
                    # Disk-loaded entries pin too — an unpinned hit would
                    # re-read the multi-GB entry per query AND hold device
                    # arrays the residency ledger never accounted for.
                    reserve_and_pin(
                        self,
                        partition,
                        prepared,
                        self._device_cache,
                        entry_device_bytes(prepared),
                        ctx.config.tpu_hbm_budget(),
                    )

        aux = [jnp.asarray(a) for a in self.compiler.build_aux()]
        if prepared["kind"] == "empty":
            return self.partial_schema.empty_table()
        if prepared["kind"] == "sorted":
            if self._topk_eligible(prepared):
                out = self._run_topk(prepared, aux)
                if out is not None:
                    return out  # None: boundary tie -> full readback below
            return self._run_sorted(prepared, aux)
        if prepared["kind"] == "pallas_sorted":
            return self._run_pallas_sorted(prepared, aux)

        # dispatch all batches asynchronously, then materialize same-shaped
        # outputs in one stacked d2h transfer — per-batch fetches would pay
        # the relay round-trip k times (runtime.fetch_arrays)
        from ballista_tpu.ops.runtime import fetch_arrays, record_readback

        pending = []
        for ent in prepared["entries"]:
            stacked_dev = self._step(
                ent["seg_bucket"], ent["cols"], aux, ent["codes"], ent["row_valid"]
            )
            pending.append((stacked_dev, ent))
        fetched = fetch_arrays([dev for dev, _ in pending])
        record_readback(
            sum(f.shape[-1] for f in fetched), sum(f.nbytes for f in fetched)
        )

        partial_tables: List[pa.Table] = []
        for stacked_np, (_, ent) in zip(fetched, pending):
            rows = self._decode_stacked(stacked_np)
            n_groups = ent["n_groups"]
            counts_np = rows[0][:n_groups]
            outputs = [o[:n_groups] for o in self._state_outputs(rows)]
            t = self._assemble_partial(outputs, counts_np, ent["key_values"], n_groups)
            if t.num_rows:
                partial_tables.append(t)
        if not partial_tables:
            return self.partial_schema.empty_table()
        return pa.concat_tables(partial_tables)

    def _decode_stacked(self, stacked: np.ndarray) -> List[np.ndarray]:
        """Undo _stack_rows' int32 hi/lo packing."""
        return decode_packed_rows(stacked, self._int_rows)

    def _state_outputs(self, rows: List[np.ndarray]) -> List[np.ndarray]:
        """Decoded logical rows -> one output column per partial-state
        FIELD (spec-driven; bijected min/max states invert through
        ops/floatbits.py, f64 pairs recombining their planes first). Empty
        groups still carry key-space sentinel fills here — every caller
        masks them with counts==0 before assembly."""
        from ballista_tpu.ops import floatbits

        outs: List[np.ndarray] = []
        for row, kind, _fold in self._state_specs:
            if kind == "f64bits":
                outs.append(
                    floatbits.i64_to_f64(
                        floatbits.planes_to_i64(rows[row], rows[row + 1])
                    )
                )
            elif kind == "f32bits":
                outs.append(
                    floatbits.i32_to_f32(rows[row].astype(np.int32)).astype(
                        np.float64
                    )
                )
            else:
                outs.append(rows[row])
        return outs

    def _fold_state_rows(self, layout, rows: List[np.ndarray]) -> List[np.ndarray]:
        """Fold decoded per-chunk partial rows to per-group state columns.
        f64-bijected pairs recombine into int64 keys BEFORE the fold —
        lexicographic (hi, lo) min/max IS int64 key min/max, and reduceat
        over int keys is exact — then invert to the bit-exact float."""
        from ballista_tpu.ops import floatbits

        folds = {"sum": layout.fold_sum, "min": layout.fold_min,
                 "max": layout.fold_max}
        outs: List[np.ndarray] = []
        for row, kind, fold in self._state_specs:
            if kind == "f64bits":
                keys = floatbits.planes_to_i64(rows[row], rows[row + 1])
                outs.append(floatbits.i64_to_f64(folds[fold](keys)))
            elif kind == "f32bits":
                k32 = folds[fold](rows[row]).astype(np.int32)
                outs.append(floatbits.i32_to_f32(k32).astype(np.float64))
            else:
                outs.append(folds[fold](rows[row]))
        return outs

    def _run_sorted(self, ent: dict, aux) -> pa.Table:
        from ballista_tpu.ops.runtime import record_readback

        layout = ent["layout"]
        stacked = np.asarray(
            self._sorted_step(ent["layout"].L1, ent["cols"], aux, ent["clen"])
        )
        record_readback(stacked.shape[-1], stacked.nbytes)
        rows = self._decode_stacked(stacked)
        counts = layout.fold_sum(rows[0])
        outputs = self._fold_state_rows(layout, rows)
        return self._assemble_partial(
            outputs, counts, ent["key_values"], ent["n_groups"]
        )

    # -- fused Sort+Limit epilogue (planner _topk_pushdown) -------------
    def _topk_eligible(self, ent: dict) -> bool:
        """Fusion is live for a partition when the selection can actually
        exclude groups AND the device can produce exact per-group states:
        either the layout carries the one-chunk cover (chunk partials ARE
        the group states, bit-identical to the full readback) or the fold
        variant runs (in-program chunk->group segment fold for skewed
        layouts, e.g. q10's dominant unmatched-row group). The fold variant
        sums int32 in-program where the host fold widens to int64, so
        int-exact SUM aggregates disable it — the normal full readback runs
        instead, same entry, identical values."""
        if (
            self.topk is None
            or ent.get("layout") is None
            or ent["n_groups"] <= self.topk["k"]
        ):
            return False
        if ent["layout"].one_chunk_per_group:
            return True
        return not any(
            ix and a.fn in ("sum", "avg")
            for a, ix in zip(self.aggs, self.int_exact)
        )

    def _build_topk_step(self, fold: bool):
        from ballista_tpu.ops import aotcache

        if fold:
            # (L1, cols, aux, clen, G, owner): G is the segment count
            return aotcache.wrap_step(
                self, "topk_fold", self._topk_core(True), static_argnums=(0, 4)
            )
        return aotcache.wrap_step(
            self, "topk", self._topk_core(False), static_argnums=(0,)
        )

    def _topk_core(self, fold: bool):
        """Device Sort+Limit epilogue composed over the sorted core: lower
        every sort key to int32 lanes whose signed order equals the key
        order (exact int states as-is, f32 scores through the floatbits
        bijection, f64-bijected states as their hi/lo plane pair; bitwise
        NOT flips descending keys without overflow), lexicographically sort
        (validity, key lanes..., group index) and gather the k best columns
        of the packed state stack. The trailing group-index lane makes tie
        order identical to the host's stable sort over the group-ordered
        aggregate output. Readback: [R_packed + E, k] instead of
        [R_packed, G] — E carries the k-th and (k+1)-th lane values (the
        boundary-tie probe) and the selected group indices, all as exact
        f32 halves like _stack_rows.

        fold=False: the one-chunk cover — chunk partials are already group
        states. fold=True: chunk partials segment-fold to group states
        in-program first (sum/min/max per _state_specs; f64-bijected pairs
        fold lexicographically — lo competes only among chunks holding the
        group's hi extreme). min/max folds match the host reduceat exactly;
        f32 sums regroup the accumulation (documented device tolerance);
        int-exact sums never take this variant (_topk_eligible)."""
        import jax
        import jax.numpy as jnp

        from ballista_tpu.ops.floatbits import jnp_f32_to_i32

        core = self._sorted_core()
        pos = packed_positions(self._int_rows)
        int_rows = self._int_rows
        specs = self._state_specs
        k = self.topk["k"]
        keyspecs = self.topk["keys"]

        def split16(x):
            return (x >> 16).astype(jnp.float32), (x & 0xFFFF).astype(jnp.float32)

        def select(G, counts, row_of, gstack):
            """Shared tail over per-group states: row_of(r) is the DECODED
            logical row r ([G] int32, or f32 for num rows); gstack the
            packed [R_packed, G] stack the readback decodes."""
            # validity leads the lexicographic key: empty groups (dropped
            # by the unfused assembly) must never displace a real group
            lanes = [jnp.where(counts > 0, 0, 1).astype(jnp.int32)]
            for row, kind, desc in keyspecs:
                if kind == "num":
                    kv = [jnp_f32_to_i32(row_of(row))]
                elif kind == "f64bits":
                    kv = [row_of(row), row_of(row + 1)]
                else:  # "int" / "f32bits": exact int32 state
                    kv = [row_of(row)]
                lanes.extend(~v if desc else v for v in kv)
            iota = jnp.arange(G, dtype=jnp.int32)
            srt = jax.lax.sort(tuple(lanes) + (iota,), num_keys=len(lanes) + 1)
            sel_idx = srt[-1][:k]
            sel = jnp.take(gstack, sel_idx, axis=1)
            extra = []
            for lane_sorted in srt[:-1]:
                for v in (lane_sorted[k - 1], lane_sorted[k]):
                    hi, lo = split16(v)
                    extra.append(jnp.full((k,), hi, jnp.float32))
                    extra.append(jnp.full((k,), lo, jnp.float32))
            ih, il = split16(sel_idx)
            extra.extend([ih, il])
            return jnp.concatenate([sel, jnp.stack(extra)])

        if not fold:

            def tstep(L1, cols, aux, clen):
                stacked = core(L1, cols, aux, clen)  # [R_packed, G]
                G = stacked.shape[1]

                def row_of(row):
                    p = pos[row]
                    if int_rows[row]:
                        return jnp_unpack_i32(stacked[p], stacked[p + 1])
                    return stacked[p]

                return select(G, row_of(0), row_of, stacked)

            return tstep

        seg = {"sum": jax.ops.segment_sum, "min": jax.ops.segment_min,
               "max": jax.ops.segment_max}

        def tstep_fold(L1, cols, aux, clen, G, owner):
            stacked = core(L1, cols, aux, clen)  # [R_packed, V] chunk partials

            def chunk_row(row):
                p = pos[row]
                if int_rows[row]:
                    return jnp_unpack_i32(stacked[p], stacked[p + 1])
                return stacked[p]

            def red(fop, v):
                return seg[fop](v, owner, num_segments=G,
                                indices_are_sorted=True)

            logical = {0: red("sum", chunk_row(0))}  # counts
            for row, kind, fop in specs:
                if kind == "f64bits":
                    hi, lo = chunk_row(row), chunk_row(row + 1)
                    h = red(fop, hi)
                    fill = jnp.int32(
                        _INT32_MAX if fop == "min" else -_INT32_MAX - 1
                    )
                    l = red(fop, jnp.where(hi == jnp.take(h, owner), lo, fill))
                    logical[row], logical[row + 1] = h, l
                else:
                    logical[row] = red(fop, chunk_row(row))
            packed = []
            for row, is_int in enumerate(int_rows):
                if is_int:
                    packed.extend(split16(logical[row]))
                else:
                    packed.append(logical[row])
            return select(G, logical[0], lambda r: logical[r],
                          jnp.stack(packed))

        return tstep_fold

    def _run_topk(self, ent: dict, aux) -> Optional[pa.Table]:
        """Fused-epilogue readback: k columns + boundary probe. Returns
        None (caller falls back to the full readback, same entry, same
        values) when un-fused trailing sort keys exist AND the k-th and
        (k+1)-th groups tie on every fused lane — the only case where the
        device selection could exclude a group the host order admits."""
        from ballista_tpu.ops.runtime import record_readback

        import jax.numpy as jnp

        spec = self.topk
        k = spec["k"]
        layout = ent["layout"]
        if layout.one_chunk_per_group:
            if self._topk_step is None:
                self._topk_step = self._build_topk_step(fold=False)
            packed = np.asarray(
                self._topk_step(layout.L1, ent["cols"], aux, ent["clen"])
            )
        else:
            # skewed cover: fold chunk partials to group states in-program
            if self._topk_fold_step is None:
                self._topk_fold_step = self._build_topk_step(fold=True)
            owner = ent.get("owner_dev")
            if owner is None:
                owner = ent["owner_dev"] = jnp.asarray(
                    layout.owner.astype(np.int32)
                )
            packed = np.asarray(
                self._topk_fold_step(layout.L1, ent["cols"], aux, ent["clen"],
                                     ent["n_groups"], owner)
            )
        record_readback(packed.shape[-1], packed.nbytes)
        nl = 1 + spec["n_lanes"]
        E = 4 * nl + 2
        sel, tail = packed[:-E], packed[-E:]
        lasts, bounds = [], []
        for i in range(nl):
            b = 4 * i
            lasts.append(int(tail[b][0]) * 65536 + int(tail[b + 1][0]))
            bounds.append(int(tail[b + 2][0]) * 65536 + int(tail[b + 3][0]))
        if not spec["covered"] and lasts == bounds and lasts[0] == 0:
            return None  # boundary tie under un-fused tie-breakers
        idx = tail[-2].astype(np.int64) * 65536 + tail[-1].astype(np.int64)
        order = np.argsort(idx, kind="stable")
        idx = idx[order]
        rows = [r[order] for r in self._decode_stacked(sel)]
        counts = rows[0]
        outputs = self._state_outputs(rows)
        take = pa.array(idx)
        key_values = [
            (kv if isinstance(kv, (pa.Array, pa.ChunkedArray)) else pa.array(kv)).take(take)
            for kv in ent["key_values"]
        ]
        return self._assemble_partial(outputs, counts, key_values, len(idx))

    def _assemble_partial(
        self,
        outputs: List[np.ndarray],
        counts: np.ndarray,
        key_values: List[pa.Array],
        n_groups: int,
    ) -> pa.Table:
        """Build a partial-state Arrow table for one batch's groups."""
        arrays: List[pa.Array] = []
        fields = list(self.partial_schema)
        # group key columns
        if self.group_exprs:
            for kv, f in zip(key_values, fields[: len(key_values)]):
                arr = kv if isinstance(kv, pa.Array) else pa.array(kv)
                if arr.type != f.type:
                    arr = pc.cast(arr, f.type)
                arrays.append(arr)
        # aggregate state columns
        oi = 0
        col_pos = len(key_values)
        nonempty = counts > 0
        for a in self.aggs:
            for _f in a.state_fields():
                f = fields[col_pos]
                raw = outputs[oi]
                # groups with no surviving rows carry sentinel fills in
                # min/max rows; null them out so the merge ignores them
                arrays.append(state_column(a, raw, f.type, ~nonempty))
                oi += 1
                col_pos += 1
        # drop groups where every row was filtered out (counts == 0) to match
        # host-partial semantics (those groups never appear)
        t = pa.table(arrays, schema=self.partial_schema)
        if not nonempty.all():
            t = t.filter(pa.array(nonempty))
        return t

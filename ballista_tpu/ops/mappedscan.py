"""Generalized eager aggregation: Aggregate over a PK-FK join TREE rewritten
to Aggregate over a mapped fact scan.

FactAggregateStage (ops/factagg.py) covers aggregate-over-join shapes whose
group keys are the fact join key and whose aggregate inputs are fact-side —
q3/q5/q10/q18. The shapes it documents as excluded (its own header):
multi-key fact joins (q7-q9) and dim-valued aggregate inputs / fact-column
group keys (q12). This module closes those: the reference executes them by
materializing every join then hash-aggregating the joined rows
(rust/core/src/serde/physical_plan/from_proto.rs:176-214, 370-384); on a
relay-attached TPU that volatile join output pays encode+transfer per query.

Rewrite (device path only; the host path keeps the original plan):

    Aggregate(ops*(Join(Join(...(dim_k, fact)...), dim_1)))
      -> Aggregate(ops*(MappedScanExec(fact_chain, attachments)))

Each INNER equi-join against a unique-keyed dim subtree becomes an
*attachment*: at stage-prepare time the dim subtree executes on the host
(it may carry its own filters/joins — q7's orders x customer x nation leg),
and its columns are gathered per fact row through the key (sorted dim keys
+ searchsorted, the same regular shape the device join kernel uses). The
fact batch comes out extended with the mapped dim columns plus an
``__member`` int8 column (0 where the inner join would drop the row — a
membership filter the stage fuses onto the device). Attachments chain:
a later attachment's fact-side key may itself be a mapped column
(q7: orders attaches o_custkey, customer attaches through it).

After the rewrite the ordinary FusedAggregateStage compiles everything —
mapped columns are just columns: they narrow, dictionary-encode, ride the
persisted layout cache (dim file mtimes are part of the stage key), and
group keys / aggregate inputs / filters may reference them freely
(q12's SUM(CASE over o_orderpriority), q7's n_name cross-filter).
"""

from __future__ import annotations

import threading
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np
import pyarrow as pa

from ballista_tpu.ops.runtime import UnsupportedOnDevice
from ballista_tpu.physical import expr as px
from ballista_tpu.physical.basic import (
    CoalesceBatchesExec,
    FilterExec,
    MergeExec,
    ProjectionExec,
)
from ballista_tpu.physical.plan import (
    ExecutionPlan,
    Partitioning,
    TaskContext,
    collect_all,
)
from ballista_tpu.utils.locks import make_lock

# dim subtrees larger than this are not dimension maps; host joins them.
# Sized for SF=100 TPC-H: q12/q7 attach the whole orders table (~150M rows,
# ~2.4 GB of sorted int64 key + order arrays on a 125 GB host); the DEVICE
# cost is membership bits + narrow mapped columns over the filtered fact,
# which the HBM budget still guards independently
MAX_MAP_ROWS = 200_000_000
_PASSTHROUGH = (FilterExec, ProjectionExec, CoalesceBatchesExec, MergeExec)


class Attachment:
    """One subtree joined to the fact on integer key column(s).

    kind "inner": unique-keyed dim whose columns map onto fact rows.
    kind "semi"/"anti": membership only — no columns attach, no
    uniqueness requirement (EXISTS / NOT EXISTS semantics; q4's shape)."""

    def __init__(self, dim: ExecutionPlan, fact_keys: List[str],
                 dim_keys: List[str], kind: str = "inner") -> None:
        self.dim = dim
        self.fact_keys = fact_keys
        self.dim_keys = dim_keys
        self.kind = kind


def _subtree_scan_bytes(node: ExecutionPlan) -> int:
    import os

    files = getattr(getattr(node, "source", None), "files", None)
    total = sum(
        os.path.getsize(f) for f in (files or []) if os.path.exists(f)
    )
    return total + sum(_subtree_scan_bytes(c) for c in node.children())


def _flatten_join_tree(node: ExecutionPlan):
    """Peel INNER equi-joins off the fact subtree, innermost first.
    Returns (fact_subtree, [Attachment...]) — an empty list means `node`
    has no join to rewrite."""
    from ballista_tpu.logical.plan import JoinType
    from ballista_tpu.physical.join import HashJoinExec

    if (
        not isinstance(node, HashJoinExec)
        or node.join_type not in (JoinType.INNER, JoinType.SEMI, JoinType.ANTI)
        or node.filter is not None
    ):
        return node, []
    if node.join_type in (JoinType.SEMI, JoinType.ANTI):
        # semi/anti preserve the LEFT schema: the fact is always the left
        # side; the right side contributes membership bits only
        fact, atts = _flatten_join_tree(node.left)
        kind = "semi" if node.join_type == JoinType.SEMI else "anti"
        return fact, atts + [
            Attachment(node.right, [l for l, _ in node.on],
                       [r for _, r in node.on], kind=kind)
        ]
    lb = _subtree_scan_bytes(node.left)
    rb = _subtree_scan_bytes(node.right)
    if rb >= lb:
        fact_side, dim_side = node.right, node.left
        fact_keys = [r for _, r in node.on]
        dim_keys = [l for l, _ in node.on]
    else:
        fact_side, dim_side = node.left, node.right
        fact_keys = [l for l, _ in node.on]
        dim_keys = [r for _, r in node.on]
    fact, atts = _flatten_join_tree(fact_side)
    return fact, atts + [Attachment(dim_side, fact_keys, dim_keys)]


class MappedScanExec(ExecutionPlan):
    """Fact chain extended with per-row dim columns and a membership flag.

    Built only inside the device stage builder (never planned, never
    serialized); `ballista_cacheable` marks it a stable file-backed row
    source for FusedAggregateStage residency (the stage cache key already
    carries every underlying file's mtime via the ORIGINAL plan's leaves).
    """

    ballista_cacheable = True

    def __init__(self, fact: ExecutionPlan, attachments: List[Attachment]) -> None:
        self.fact = fact
        self.attachments = attachments
        fields = list(fact.schema())
        for a in attachments:
            if a.kind == "inner":
                fields.extend(list(a.dim.schema()))
        fields.append(pa.field("__member", pa.int8()))
        self._schema = pa.schema(fields)
        self._maps: Optional[List[dict]] = None  # guarded-by: self._lock
        self._lock = make_lock("ops.mappedscan._lock")

    def schema(self) -> pa.Schema:
        return self._schema

    def output_partitioning(self) -> Partitioning:
        return self.fact.output_partitioning()

    def children(self) -> List[ExecutionPlan]:
        return [self.fact] + [a.dim for a in self.attachments]

    def with_children(self, children: List[ExecutionPlan]) -> "MappedScanExec":
        atts = [
            Attachment(d, a.fact_keys, a.dim_keys, kind=a.kind)
            for d, a in zip(children[1:], self.attachments)
        ]
        return MappedScanExec(children[0], atts)

    def fmt(self) -> str:
        parts = ", ".join(
            f"{a.dim_keys} via {a.fact_keys}" for a in self.attachments
        )
        return f"MappedScanExec: {len(self.attachments)} attachments [{parts}]"

    # ------------------------------------------------------------------
    # collects dimension plans while holding the lock (see join.py note)
    # may-acquire: group:exec_substrate
    def _ensure_maps(self, ctx: TaskContext) -> List[dict]:
        with self._lock:
            if self._maps is not None:
                return self._maps
            maps = []
            for a in self.attachments:
                table = collect_all(a.dim, ctx).combine_chunks()
                if table.num_rows > MAX_MAP_ROWS:
                    raise UnsupportedOnDevice(
                        f"dim map {a.dim_keys} has {table.num_rows} rows"
                    )
                if a.kind == "inner" and table.num_rows == 0:
                    # an empty inner dim means zero joined rows; _extend's
                    # gather through an empty order array would IndexError —
                    # decline and let the host path produce the empty result
                    raise UnsupportedOnDevice(
                        f"inner dim map {a.dim_keys} has zero rows"
                    )
                for k in a.dim_keys:
                    if not pa.types.is_integer(table.column(k).type):
                        raise UnsupportedOnDevice(
                            f"non-integer dim key {k!r}"
                        )
                if any(table.column(k).null_count for k in a.dim_keys):
                    # a null key can never match (SQL EXISTS semantics):
                    # drop rows where ANY key is null — filtering the TABLE
                    # keeps composite tuples row-aligned AND converts int64
                    # losslessly (a null-bearing column would round-trip
                    # through float64, corrupting keys above 2^53). Inner
                    # dims must decline instead (a mapped row would vanish).
                    if a.kind == "inner":
                        raise UnsupportedOnDevice(
                            f"null dim key in {a.dim_keys}"
                        )
                    import pyarrow.compute as pc

                    mask = None
                    for k in a.dim_keys:
                        v = pc.is_valid(table.column(k))
                        mask = v if mask is None else pc.and_(mask, v)
                    table = table.filter(mask).combine_chunks()
                key_vals = [
                    table.column(k).to_numpy(zero_copy_only=False)
                    .astype(np.int64)
                    for k in a.dim_keys
                ]
                packed, mins, ranges, strides = _pack_dim_keys(key_vals)
                if a.kind == "inner":
                    order = np.argsort(packed, kind="stable")
                    sorted_keys = packed[order]
                    if len(sorted_keys) and np.any(
                        sorted_keys[1:] == sorted_keys[:-1]
                    ):
                        raise UnsupportedOnDevice(
                            f"dim keys {a.dim_keys} not unique (join multiplies)"
                        )
                else:
                    # membership only: distinct keys suffice, nothing to
                    # gather — no uniqueness requirement, no retained table
                    sorted_keys = np.unique(packed)
                    order = None
                    table = None
                maps.append(
                    {
                        "table": table,
                        "sorted": sorted_keys,
                        "order": order,
                        "mins": mins,
                        "ranges": ranges,
                        "strides": strides,
                        "att": a,
                    }
                )
            self._maps = maps
            return maps

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        maps = self._ensure_maps(ctx)
        for batch in self.fact.execute(partition, ctx):
            if batch.num_rows:
                yield self._extend(batch, maps)

    def _extend(self, batch: pa.RecordBatch, maps: List[dict]) -> pa.RecordBatch:
        n = batch.num_rows
        arrays: List[pa.Array] = list(batch.columns)
        by_name: Dict[str, pa.Array] = {
            f.name: arr for f, arr in zip(batch.schema, arrays)
        }
        member = np.ones(n, dtype=bool)
        for m in maps:
            a: Attachment = m["att"]
            packed = np.zeros(n, dtype=np.int64)
            valid = np.ones(n, dtype=bool)
            for k, mn, rng, stride in zip(
                a.fact_keys, m["mins"], m["ranges"], m["strides"]
            ):
                import pyarrow.compute as pc

                col = by_name[k]
                if isinstance(col, pa.ChunkedArray):
                    col = col.combine_chunks()
                if col.null_count:
                    valid &= col.is_valid().to_numpy(zero_copy_only=False)
                    col = pc.fill_null(col, pa.scalar(0, type=col.type))
                v = col.to_numpy(zero_copy_only=False).astype(np.int64)
                rel = v - mn
                # out-of-range values can never match AND must not pack
                # (an over-range component would alias another tuple)
                in_range = (rel >= 0) & (rel < rng)
                valid &= in_range
                packed = packed + np.where(in_range, rel, 0) * stride
            if len(m["sorted"]) == 0:
                hit = np.zeros(n, dtype=bool)
                idx_c = np.zeros(n, dtype=np.int64)
            else:
                idx = np.searchsorted(m["sorted"], packed)
                idx_c = np.minimum(idx, len(m["sorted"]) - 1)
                hit = valid & (m["sorted"][idx_c] == packed)
            if a.kind == "anti":
                # NOT EXISTS: keep rows with no match (null keys never
                # match, so they are kept — SQL NOT EXISTS semantics)
                member &= ~hit
                continue
            member &= hit
            if a.kind == "semi":
                continue
            # non-member rows gather row 0 (garbage, masked by __member;
            # group codes need non-null values so no null fill here)
            take = m["order"][np.where(hit, idx_c, 0)]
            gathered = m["table"].take(pa.array(take))
            for f, col in zip(gathered.schema, gathered.columns):
                arr = col.combine_chunks()
                arrays.append(arr)
                by_name[f.name] = arr
        arrays.append(pa.array(member.astype(np.int8)))
        return pa.record_batch(arrays, schema=self._schema)


def _pack_dim_keys(key_vals: List[np.ndarray]):
    """Combine multi-column integer keys into one int64 per row by
    range-shifted packing; strides derived from each column's dim range so
    fact values pack consistently. Declines when ranges could overflow."""
    mins = [int(v.min()) if len(v) else 0 for v in key_vals]
    ranges = [
        (int(v.max()) - mn + 1) if len(v) else 1
        for v, mn in zip(key_vals, mins)
    ]
    total = 1
    for r in ranges:
        if r > 0 and total > (1 << 62) // r:
            raise UnsupportedOnDevice("dim key ranges overflow packing")
        total *= r
    strides = []
    acc = 1
    for r in reversed(ranges):
        strides.append(acc)
        acc *= r
    strides = list(reversed(strides))
    packed = np.zeros(len(key_vals[0]), dtype=np.int64)
    for v, mn, s in zip(key_vals, mins, strides):
        packed += (v - mn) * s
    return packed, mins, ranges, strides


# ---------------------------------------------------------------------------
# the rewrite
# ---------------------------------------------------------------------------


def try_rewrite_mapped(agg) -> Optional[object]:
    """Rewrite HashAggregate(ops*(join tree)) to HashAggregate(ops*(
    Filter(__member = 1, MappedScanExec))), or None when the shape doesn't
    match. Expressions referencing the join schema are remapped by name."""
    from ballista_tpu.physical.aggregate import HashAggregateExec
    from ballista_tpu.physical.join import HashJoinExec
    from ballista_tpu.physical.scan import MemoryScanExec
    from ballista_tpu.ops.stage import _SCAN_TYPES, substitute_columns

    node = agg.input
    chain: List[ExecutionPlan] = []
    while isinstance(node, _PASSTHROUGH):
        chain.append(node)
        node = node.input
    if not isinstance(node, HashJoinExec):
        return None
    fact, atts = _flatten_join_tree(node)
    if not atts:
        return None

    # the fact subtree must be a plain scan chain (no memory scans: their
    # id()-keyed identity must not silently gain dim-file dependencies)
    probe = fact
    while isinstance(probe, _PASSTHROUGH):
        probe = probe.input
    if not isinstance(probe, _SCAN_TYPES) or isinstance(probe, MemoryScanExec):
        return None

    # every attachment's fact-side keys must resolve, in order, against the
    # fact schema extended by earlier attachments
    available = set(fact.schema().names)
    for a in atts:
        if not all(k in available for k in a.fact_keys):
            return None
        available |= {f.name for f in a.dim.schema()}

    mapped = MappedScanExec(fact, atts)
    mschema = mapped.schema()
    join_schema = node.schema()
    positions = {f.name: i for i, f in enumerate(mschema)}
    if len(positions) != len(mschema):
        return None  # duplicate names would remap ambiguously
    try:
        mapping = [
            px.ColumnExpr(f.name, positions[f.name]) for f in join_schema
        ]
    except KeyError:
        return None  # a join output column the mapped schema lacks

    member_filter = FilterExec(
        mapped,
        px.BinaryPhysicalExpr(
            px.ColumnExpr("__member", mschema.names.index("__member")),
            "eq",
            px.LiteralExpr(1, pa.int8()),
        ),
    )

    # rebuild the op chain bottom-up; nodes keep referencing the join
    # schema until the first projection redefines it
    cur: ExecutionPlan = member_filter
    needs_remap = True
    for op in reversed(chain):
        if isinstance(op, FilterExec):
            pred = (
                substitute_columns(op.predicate, mapping)
                if needs_remap else op.predicate
            )
            cur = FilterExec(cur, pred)
        elif isinstance(op, ProjectionExec):
            exprs = [
                (
                    substitute_columns(e, mapping) if needs_remap else e,
                    name,
                )
                for e, name in op.exprs
            ]
            cur = ProjectionExec(cur, exprs)
            needs_remap = False
        else:  # Coalesce / Merge: schema-preserving passthrough
            cur = op.with_children([cur])
    group_exprs = [
        (substitute_columns(e, mapping) if needs_remap else e, name)
        for e, name in agg.group_exprs
    ]
    from ballista_tpu.physical.aggregate import AggregateFunc

    aggr_funcs = [
        AggregateFunc(
            a.fn,
            substitute_columns(a.expr, mapping) if needs_remap else a.expr,
            a.name,
            a.dtype,
            a.input_type,
        )
        for a in agg.aggr_funcs
    ]
    try:
        out = HashAggregateExec(agg.mode, cur, group_exprs, aggr_funcs,
                                exact_floats=getattr(agg, "exact_floats", False))
    except Exception:
        return None
    # the rewrite must not change the aggregate's output contract
    if out.schema() != agg.schema():
        return None
    if getattr(agg, "_topk_pushdown", None) is not None:
        out._topk_pushdown = agg._topk_pushdown
    # the framework drives the ORIGINAL aggregate's partition count (the
    # join's probe side); the rewritten stage scans the FACT's partitions.
    # When they differ, the stage must stripe fact partitions over the
    # driven ones or it would silently aggregate a fraction of the fact
    # (same hazard factagg guards at ops/factagg.py:343-347)
    n_driven = agg.input.output_partitioning().partition_count()
    n_fact = mapped.output_partitioning().partition_count()
    if n_driven != n_fact:
        out._scan_stride_hint = n_driven
    return out

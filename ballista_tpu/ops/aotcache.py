"""Persistent AOT compiled-program cache (ISSUE 8).

The in-memory shape-bucketed stage cache (ops/kernels.py) makes REPEATED
queries in one process cheap: jax.jit caches the compiled executable per
(program, shape bucket). A cold process still pays the Python trace + XLA
compile on its first query — which is most of a small query's latency, and
exactly what a serving tier cannot afford. This module adds the disk tier
beside the persisted layout cache (ops/layout_cache.py):

- On a fresh trace/compile, the jitted stage program is EXPORTED
  (jax.export: StableHLO + calling convention), serialized, and persisted
  under sha256(jax/jaxlib/backend fingerprint | stage identity | step name |
  static args | input tree + avals) — the stage-cache key's stable half
  (plan display + scan identity + config flags, no mtimes: programs are
  data-independent) plus the shape bucket.
- A later process's first call LOADS the artifact instead of tracing:
  deserialize + AOT-compile (jax.jit(exported.call).lower(avals).compile()),
  which skips the Python trace entirely and turns the XLA compile into a
  persistent-compilation-cache hit (kernels._configure_jax_cache).
- `prewarm()` walks the manifest at executor start and compiles every
  artifact BEFORE the first task arrives, so a cold executor's first small
  query runs with zero trace and zero compile (the latency harness asserts
  this through the serving counters).

Artifacts are integrity-checked: a corrupt blob, a deserialization failure,
or a fingerprint mismatch (different jax/jaxlib/backend than the writer)
falls back to a fresh trace/compile with the reason recorded
(serving counter `aot_load_error` + a warning log). The `aot.load` chaos
site tears disk loads deterministically to exercise exactly that path.

String-literal predicates are safe to cache across processes: literal codes
and LIKE/IN match tables ride as runtime `aux` arguments (ops/jaxexpr.py),
never as baked constants, so a reloaded program composes with whatever
dictionary state the loading process builds.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import tempfile
import threading
from typing import Dict, List, Optional, Tuple
from ballista_tpu.utils.locks import make_lock

log = logging.getLogger("ballista.tpu.aot")

# bump to orphan every persisted program (they are re-derived, not migrated)
_FORMAT = 1

_lock = make_lock("ops.aotcache._lock")
_dir: str = ""  # "" = disabled; guarded-by: _lock
_chaos = None  # guarded-by: _lock
# full key -> ("fresh", None) | ("disk"|"prewarm", compiled flat callable)
_mem: Dict[str, Tuple[str, object]] = {}  # guarded-by: _lock
_manifest_keys: Optional[set] = None  # lazily loaded; guarded-by: _lock
_fingerprint_cache: Optional[str] = None


def _record(event: str, n: int = 1) -> None:
    from ballista_tpu.ops.runtime import record_serving

    record_serving(event, n)


def fingerprint() -> str:
    """jax/jaxlib/backend identity baked into every key AND every artifact:
    a program compiled by a different stack must never be trusted."""
    global _fingerprint_cache
    if _fingerprint_cache is None:
        import jax
        import jaxlib

        try:
            platform = jax.devices()[0].platform
        except Exception:
            platform = "unknown"
        _fingerprint_cache = (
            f"v{_FORMAT}|jax{jax.__version__}|jaxlib{jaxlib.__version__}"
            f"|{platform}"
        )
    return _fingerprint_cache


def configure(config) -> None:
    """Bind the cache directory + chaos injector from a config. Called on
    every kernel dispatch (cheap once set); the last configuration wins,
    like the layout cache's per-ctx directory resolution."""
    global _dir, _chaos
    d = config.tpu_aot_cache_dir()
    with _lock:
        if d != _dir:
            _dir = d
        from ballista_tpu.utils.chaos import chaos_from_config

        _chaos = chaos_from_config(config)


def reset(clear_disk_dir: bool = False) -> None:
    """Test hook: drop the in-memory program map (and optionally forget the
    configured directory) so a fresh process can be simulated."""
    global _dir, _chaos, _manifest_keys
    with _lock:
        _mem.clear()
        _manifest_keys = None
        if clear_disk_dir:
            _dir = ""
            _chaos = None


def _blob_path(base: str, key: str) -> str:
    return os.path.join(base, key[:2], key + ".jaxprog")


def _manifest_path(base: str) -> str:
    return os.path.join(base, "manifest.jsonl")


# holds-lock: _lock
def _load_manifest_keys_locked(base: str) -> set:
    global _manifest_keys
    if _manifest_keys is None:
        keys = set()
        try:
            with open(_manifest_path(base)) as f:
                for line in f:
                    try:
                        keys.add(json.loads(line)["key"])
                    except (json.JSONDecodeError, KeyError):
                        continue
        except OSError:
            pass
        _manifest_keys = keys
    return _manifest_keys


def manifest_entries(base: str) -> List[dict]:
    """All parseable manifest lines, newest-last, deduped by key."""
    out: Dict[str, dict] = {}
    try:
        with open(_manifest_path(base)) as f:
            for line in f:
                try:
                    e = json.loads(line)
                    out[e["key"]] = e
                except (json.JSONDecodeError, KeyError):
                    continue
    except OSError:
        return []
    return list(out.values())


def _save_artifact(base: str, key: str, name: str, blob: bytes) -> None:
    """Atomically persist one exported program + its manifest line.
    Best-effort: any failure leaves no partial entry and never raises."""
    try:
        target = _blob_path(base, key)
        if os.path.exists(target):
            return
        os.makedirs(os.path.dirname(target), exist_ok=True)
        meta = json.dumps({"fingerprint": fingerprint(), "name": name})
        payload = meta.encode() + b"\n" + blob
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(target), prefix=".wip-")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(payload)
            os.replace(tmp, target)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        with _lock:
            keys = _load_manifest_keys_locked(base)
            if key not in keys:
                with open(_manifest_path(base), "a") as f:
                    f.write(json.dumps({"key": key, "name": name}) + "\n")
                keys.add(key)
        _record("aot_saved")
    except Exception as e:
        log.debug("aot save failed (key=%s...): %s", key[:16], e)


def _read_artifact(base: str, key: str) -> Optional[bytes]:
    """Read + integrity-check one artifact; None (with the reason recorded)
    on corruption or fingerprint mismatch. The `aot.load` chaos site tears
    reads deterministically, keyed on the content-derived program key."""
    from ballista_tpu.utils.chaos import ChaosInjected

    path = _blob_path(base, key)
    if not os.path.exists(path):
        return None
    with _lock:
        chaos = _chaos
    try:
        if chaos is not None:
            chaos.maybe_fail("aot.load", f"prog:{key[:16]}")
        with open(path, "rb") as f:
            payload = f.read()
        header, _, blob = payload.partition(b"\n")
        meta = json.loads(header)
        if meta.get("fingerprint") != fingerprint():
            _record("aot_load_error")
            log.warning(
                "aot artifact %s... rejected: fingerprint %r != %r "
                "(recompiling fresh)", key[:16], meta.get("fingerprint"),
                fingerprint(),
            )
            return None
        if not blob:
            raise ValueError("empty program blob")
        return blob
    except ChaosInjected as e:
        _record("aot_load_error")
        log.warning("aot load torn by chaos (key=%s...): %s — recompiling "
                    "fresh", key[:16], e)
        return None
    except Exception as e:
        _record("aot_load_error")
        log.warning("aot artifact %s... unreadable: %s — recompiling fresh",
                    key[:16], e)
        return None


def _compile_exported(blob: bytes, leaves_avals):
    """Deserialize an exported program and AOT-compile it for the flat
    calling convention. Raises on any mismatch (caller falls back)."""
    import jax
    from jax import export as jax_export

    exported = jax_export.deserialize(bytearray(blob))
    return jax.jit(exported.call).lower(*leaves_avals).compile()


def _leaf_aval(leaf):
    import jax
    import numpy as np

    arr = leaf if hasattr(leaf, "dtype") else np.asarray(leaf)
    return jax.ShapeDtypeStruct(tuple(arr.shape), arr.dtype)


def wrap_step(owner, name: str, core, static_argnums: Tuple[int, ...] = (0,)):
    """Wrap one device-stage core in the AOT tier.

    Returns a callable with jax.jit semantics (same signature, including
    the static leading args). When the owner carries no `aot_key` (stage
    built outside the kernel dispatcher) or no cache dir is configured, the
    plain jitted function runs untouched. Otherwise each distinct
    (program, static args, input shapes) signature resolves through:
    in-memory compiled map -> disk artifact -> fresh trace/compile (which
    exports + persists the artifact for the next process), with the
    serving counters recording which tier served it.

    The returned callable also carries a ``.warm(*args)`` method:
    compile-WITHOUT-execute (ISSUE 19 satellite). It traces and
    XLA-compiles the signature via ``jit(...).lower(...).compile()`` —
    which primes jax's own executable cache, so the next real call is a
    cache hit — and registers/persists the AOT artifact, all without
    running the program: no output buffers are allocated and nothing is
    pinned past the compile. Background warmers (ops/sharedscan.py) use
    it so a warm-up never holds transient HBM outside the residency
    accounting."""
    import jax
    from jax.tree_util import tree_flatten, tree_unflatten

    jitfn = jax.jit(core, static_argnums=static_argnums)
    static_set = frozenset(static_argnums)

    def signature(args):
        """(key, statics, treedef, leaves, avals) for an AOT-cacheable
        call, or None when the AOT tier must be bypassed (no cache dir,
        no owner key, or weak-typed leaves whose promotion semantics an
        exported strong aval could silently change)."""
        key_base = getattr(owner, "aot_key", None)
        with _lock:
            base = _dir
        if not base or key_base is None:
            return None
        statics = [(i, args[i]) for i in sorted(static_set)]
        dynamic = [a for i, a in enumerate(args) if i not in static_set]
        leaves, treedef = tree_flatten(tuple(dynamic))
        if any(bool(getattr(l, "weak_type", False)) for l in leaves):
            return None
        avals = [_leaf_aval(l) for l in leaves]
        sig = (
            f"{name}|s{[(i, repr(v)) for i, v in statics]!r}"
            f"|{treedef}|{[(a.shape, str(a.dtype)) for a in avals]!r}"
        )
        key = hashlib.sha256(
            f"{fingerprint()}|{key_base}|{sig}".encode()
        ).hexdigest()
        return key, statics, treedef, leaves, avals

    def export_and_save(key, statics, treedef, avals, n_args):
        """Export the traced program to StableHLO + persist it for the
        next process. Trace-only (stops at StableHLO — measured ~5% of a
        large unrolled program's XLA compile); never raises."""
        try:
            from jax import export as jax_export

            static_vals = dict(statics)

            def flat_fn(*flat_leaves):
                dyn = tree_unflatten(treedef, flat_leaves)
                full: List[object] = []
                di = 0
                for i in range(n_args):
                    if i in static_vals:
                        full.append(static_vals[i])
                    else:
                        full.append(dyn[di])
                        di += 1
                return core(*full)

            blob = bytes(jax_export.export(jax.jit(flat_fn))(*avals).serialize())
            with _lock:
                base = _dir
            if base:
                _save_artifact(base, key, name, blob)
        except Exception as e:
            log.debug("aot export failed (key=%s...): %s", key[:16], e)

    def wrapped(*args):
        resolved = signature(args)
        if resolved is None:
            return jitfn(*args)
        key, statics, treedef, leaves, avals = resolved
        with _lock:
            base = _dir
            entry = _mem.get(key)
        if entry is not None:
            kind, compiled = entry
            _record("compile_hit_memory")
            if compiled is None:  # freshly traced this process: jit caches
                return jitfn(*args)
            out_flat = compiled(*leaves)
            return out_flat
        blob = _read_artifact(base, key)
        if blob is not None:
            try:
                compiled = _compile_exported(blob, avals)
                out_flat = compiled(*leaves)
            except Exception as e:
                _record("aot_load_error")
                log.warning(
                    "aot artifact %s... failed to compile/run: %s — "
                    "recompiling fresh", key[:16], e,
                )
            else:
                with _lock:
                    _mem[key] = ("disk", compiled)
                _record("compile_hit_disk")
                return out_flat
        # fresh program: run the PLAIN jit first (its persistent-XLA-cache
        # key matches every compile this codebase ever did, so warm
        # deployments hit it), then export + serialize for the disk tier.
        # Compiling THROUGH the exported module here would key the
        # persistent XLA cache differently and recompile from scratch
        # (measured ~15s per big program, a whole-suite stall).
        _record("compile_trace")
        out = jitfn(*args)
        with _lock:
            _mem.setdefault(key, ("fresh", None))
        export_and_save(key, statics, treedef, avals, len(args))
        return out

    def warm(*args):
        """Compile this signature without executing it; True when a
        compile actually happened (False = already resolvable warm)."""
        resolved = signature(args)
        if resolved is None:
            # no AOT tier for this call: still prime jit's executable
            # cache so the next real call neither traces nor compiles
            jitfn.lower(*args).compile()
            _record("compile_warmed")
            return True
        key, statics, treedef, leaves, avals = resolved
        with _lock:
            base = _dir
            if key in _mem:
                return False
        blob = _read_artifact(base, key)
        if blob is not None:
            try:
                compiled = _compile_exported(blob, avals)
            except Exception as e:
                _record("aot_load_error")
                log.warning(
                    "aot artifact %s... failed to compile during warm: %s "
                    "— compiling fresh", key[:16], e,
                )
            else:
                with _lock:
                    _mem.setdefault(key, ("disk", compiled))
                _record("compile_hit_disk")
                return True
        jitfn.lower(*args).compile()
        with _lock:
            _mem.setdefault(key, ("fresh", None))
        _record("compile_warmed")
        export_and_save(key, statics, treedef, avals, len(args))
        return True

    wrapped.warm = warm
    return wrapped


def prewarm(config) -> int:
    """Load + AOT-compile every manifest artifact into the in-memory
    program map — run at executor start (ballista.tpu.prewarm) so the first
    small query's steps are compiled before the first task arrives. Returns
    the number of programs warmed; every failure is recorded and skipped
    (a stale artifact must never block executor start)."""
    configure(config)
    with _lock:
        base = _dir
    if not base:
        return 0
    import jax
    from jax import export as jax_export

    warmed = 0
    for entry in manifest_entries(base):
        key = entry.get("key")
        if not key:
            continue
        with _lock:
            if key in _mem:
                continue
        blob = _read_artifact(base, key)
        if blob is None:
            continue
        try:
            exported = jax_export.deserialize(bytearray(blob))
            compiled = (
                jax.jit(exported.call).lower(*exported.in_avals).compile()
            )
        except Exception as e:
            _record("aot_load_error")
            log.warning("prewarm of %s... failed: %s", key[:16], e)
            continue
        with _lock:
            _mem[key] = ("prewarm", compiled)
        warmed += 1
        _record("compile_prewarmed")
    if warmed:
        log.info("aot prewarm: %d compiled programs ready", warmed)
    return warmed

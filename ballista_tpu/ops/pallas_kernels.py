"""Pallas TPU kernels.

Grouped masked aggregation as a one-hot matmul: for small group counts
(G <= 128 — a dictionary-coded GROUP BY like TPC-H q1), the segment-sum
becomes ``onehot(codes)^T @ values`` which maps directly onto the MXU
systolic array instead of the VPU scatter the XLA segment_sum lowering uses.
One grid pass streams row blocks HBM -> VMEM, accumulating [G, A] partials
in the output block that stays resident in VMEM across grid steps.

Status: a provided, tested alternative kernel (real-chip correctness at
parity with XLA's segment_sum lowering on v5e). The default fused-stage path
(ops/stage.py) keeps the XLA lowering, which also covers min/max and the
hierarchical-accuracy summation; wire-in is a future optimization for
sum/count-only stages.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import numpy as np

BLOCK_ROWS = 1024


def pallas_available() -> bool:
    try:
        from jax.experimental import pallas as pl  # noqa: F401
        from jax.experimental.pallas import tpu as pltpu  # noqa: F401

        return True
    except Exception:
        return False


@functools.lru_cache(maxsize=None)
def _build(num_groups: int, n_values: int, interpret: bool):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    G, A = num_groups, n_values

    def kernel(codes_ref, values_ref, mask_ref, out_ref):
        step = pl.program_id(0)

        @pl.when(step == 0)
        def _init():
            out_ref[:] = jnp.zeros_like(out_ref)

        codes = codes_ref[:]                      # [B]
        maskf = mask_ref[:].astype(jnp.float32)   # [B]
        vals = values_ref[:] * maskf[:, None]     # [B, A] masked values suffice
        onehot = (
            codes[:, None] == jax.lax.broadcasted_iota(jnp.int32, (1, G), 1)
        ).astype(jnp.float32)                     # [B, G]
        # the group-by: [G, B] @ [B, A] on the MXU
        out_ref[:] += jnp.dot(
            onehot.T, vals, preferred_element_type=jnp.float32
        )

    @jax.jit
    def run(codes, values, mask):
        n = codes.shape[0]
        grid = (n // BLOCK_ROWS,)
        return pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec((BLOCK_ROWS,), lambda i: (i,)),
                pl.BlockSpec((BLOCK_ROWS, A), lambda i: (i, 0)),
                pl.BlockSpec((BLOCK_ROWS,), lambda i: (i,)),
            ],
            out_specs=pl.BlockSpec((G, A), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((G, A), jnp.float32),
            interpret=interpret,
        )(codes, values, mask)

    return run


def grouped_aggregate(
    codes: np.ndarray,
    values: np.ndarray,
    mask: np.ndarray,
    num_groups: int,
    interpret: Optional[bool] = None,
) -> Optional[np.ndarray]:
    """Masked per-group sums: out[g, a] = sum(values[i, a] for codes[i]==g and
    mask[i]). Returns None when the kernel declines (no pallas, G too large).

    values: [N, A] float32; codes: [N] int32; mask: [N] bool.
    """
    if not pallas_available() or num_groups > 128:
        return None
    import jax
    import jax.numpy as jnp

    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    n = len(codes)
    if n == 0:
        return np.zeros((num_groups, values.shape[1]), dtype=np.float32)
    pad = (-n) % BLOCK_ROWS
    if pad:
        codes = np.concatenate([codes, np.full(pad, -1, dtype=codes.dtype)])
        values = np.concatenate(
            [values, np.zeros((pad, values.shape[1]), dtype=values.dtype)]
        )
        mask = np.concatenate([mask, np.zeros(pad, dtype=bool)])
    run = _build(num_groups, values.shape[1], interpret)
    out = run(
        jnp.asarray(codes.astype(np.int32)),
        jnp.asarray(values.astype(np.float32)),
        jnp.asarray(mask),
    )
    return np.asarray(out)

"""Pallas TPU kernels.

Grouped masked aggregation as a one-hot matmul: for small group counts
(G <= 128 — a dictionary-coded GROUP BY like TPC-H q1), the segment-sum
becomes ``onehot(codes)^T @ values`` which maps directly onto the MXU
systolic array instead of the VPU scatter the XLA segment_sum lowering uses.
One grid pass streams row blocks HBM -> VMEM, accumulating [G, A] partials
in the output block that stays resident in VMEM across grid steps.

Two kernels: grouped_aggregate (small-G, one-hot matmul with the output
block resident in VMEM) and sorted_grouped_sum (cardinality-independent,
RMW DMA windows over sorted dense ranks). The latter is wired into the
fused stage behind ballista.tpu.sorted_kernel=pallas
(stage.py::_run_pallas_sorted); the chunked-segment layout remains the
default because it measures faster on v5e (see the status note on
_build_sorted and dev/probe_sorted.py).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import numpy as np

BLOCK_ROWS = 1024


def pallas_available() -> bool:
    try:
        from jax.experimental import pallas as pl  # noqa: F401
        from jax.experimental.pallas import tpu as pltpu  # noqa: F401

        return True
    except Exception:
        return False


@functools.lru_cache(maxsize=None)
def _build(num_groups: int, n_values: int, interpret: bool):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    G, A = num_groups, n_values

    def kernel(codes_ref, values_ref, mask_ref, out_ref):
        step = pl.program_id(0)

        @pl.when(step == 0)
        def _init():
            out_ref[:] = jnp.zeros_like(out_ref)

        codes = codes_ref[:]                      # [B]
        maskf = mask_ref[:].astype(jnp.float32)   # [B]
        vals = values_ref[:] * maskf[:, None]     # [B, A] masked values suffice
        onehot = (
            codes[:, None] == jax.lax.broadcasted_iota(jnp.int32, (1, G), 1)
        ).astype(jnp.float32)                     # [B, G]
        # the group-by: [G, B] @ [B, A] on the MXU
        out_ref[:] += jnp.dot(
            onehot.T, vals, preferred_element_type=jnp.float32
        )

    @jax.jit
    def run(codes, values, mask):
        n = codes.shape[0]
        grid = (n // BLOCK_ROWS,)
        return pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec((BLOCK_ROWS,), lambda i: (i,)),
                pl.BlockSpec((BLOCK_ROWS, A), lambda i: (i, 0)),
                pl.BlockSpec((BLOCK_ROWS,), lambda i: (i,)),
            ],
            out_specs=pl.BlockSpec((G, A), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((G, A), jnp.float32),
            interpret=interpret,
        )(codes, values, mask)

    return run


SORT_BLOCK = 1024
_LANE = 128


@functools.lru_cache(maxsize=None)
def _build_sorted(n_values_padded: int, block: int, interpret: bool):
    """Sorted-rank grouped sum: rows are pre-sorted by group key and codes are
    DENSE ranks (consecutive distinct keys differ by exactly 1), so every
    block of B rows spans a rank window of at most B. Each grid step:

        local[v, w] = sum_b vals[v, b] * (codes[b] - base == w)

    — one [AV, B] @ [B, W] one-hot matmul on the MXU — accumulated into the
    HBM output at dynamic offset `base` via a read-modify-write DMA of the
    [AV, W] window. Cost is O(N * B) regardless of the total group count:
    this is what removes the device path's group-cardinality ceiling
    (reference hash aggregate: rust/core/proto/ballista.proto:370-384).

    Precision: one-hot entries are exact in bf16; HIGHEST precision keeps
    value products at effectively f32, accumulation is f32 adds.

    Status: measured ~107ms for 6M rows on v5e (MXU utilization is capped by
    the skinny value dimension, and the RMW DMA serializes the grid). The
    chunked-segment layout (ops/layout.py + stage._sorted_core) does the
    same job in ~0.15ms of device time and is the default; this kernel is
    selectable with ballista.tpu.sorted_kernel=pallas (sum/count/avg
    stages, stage.py::_run_pallas_sorted) and dev/probe_sorted.py keeps the
    perf comparison honest.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B = block
    # window start is aligned down to the 128-lane tile so the dynamic DMA
    # offset is provably tile-divisible for Mosaic; the extra lane covers the
    # alignment slack, one more covers the in-block rank growth
    W = B + 2 * _LANE
    AV = n_values_padded

    def kernel(bases_ref, codes_ref, vals_ref, init_ref, out_ref,
               acc_ref, sem_in, sem_out):
        i = pl.program_id(0)
        base = (bases_ref[i] // _LANE) * _LANE
        window = out_ref.at[:, pl.ds(base, W)]
        copy_in = pltpu.make_async_copy(window, acc_ref, sem_in)
        copy_in.start()
        local = (codes_ref[:] - base)[None, :]
        onehot = (
            local == jax.lax.broadcasted_iota(jnp.int32, (W, 1), 0)
        ).astype(jnp.float32)  # [W, B]
        prod = jax.lax.dot_general(
            vals_ref[:], onehot,
            dimension_numbers=(((1,), (1,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32,
        )  # [AV, W]
        copy_in.wait()
        acc_ref[:] += prod
        copy_out = pltpu.make_async_copy(acc_ref, window, sem_out)
        copy_out.start()
        copy_out.wait()

    @jax.jit
    def run(bases, codes, vals, init):
        nb = codes.shape[0] // B
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(nb,),
            in_specs=[
                pl.BlockSpec((B,), lambda i, bases: (i,)),
                pl.BlockSpec((AV, B), lambda i, bases: (0, i)),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[
                pltpu.VMEM((AV, W), jnp.float32),
                pltpu.SemaphoreType.DMA,
                pltpu.SemaphoreType.DMA,
            ],
        )
        return pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct(init.shape, jnp.float32),
            input_output_aliases={3: 0},
            interpret=interpret,
        )(bases, codes, vals, init)

    return run


def sorted_grouped_sum(
    codes,
    values,
    num_groups: int,
    interpret: Optional[bool] = None,
):
    """Device arrays in, device array out: out[v, g] = sum of values[v, i]
    where codes[i] == g. codes must be sorted dense ranks (int32); values
    rows are pre-masked (a count output is just a mask row). Returns a
    device array [n_values, num_groups]; pure jit-compatible pieces, one
    pallas_call.
    """
    import jax
    import jax.numpy as jnp

    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    nv, n = values.shape
    assert codes.shape == (n,)
    B = SORT_BLOCK
    assert n % B == 0, "pad rows to SORT_BLOCK host-side"
    AV = max(8, -(-nv // 8) * 8)  # sublane-pad the value dim
    if AV != nv:
        values = jnp.concatenate(
            [values, jnp.zeros((AV - nv, n), jnp.float32)], axis=0
        )
    bases = codes[::B]
    gpad = num_groups + B + 2 * _LANE
    init = jnp.zeros((AV, gpad), jnp.float32)
    out = _build_sorted(AV, B, interpret)(bases, codes, values, init)
    return out[:nv, :num_groups]


def grouped_aggregate(
    codes: np.ndarray,
    values: np.ndarray,
    mask: np.ndarray,
    num_groups: int,
    interpret: Optional[bool] = None,
) -> Optional[np.ndarray]:
    """Masked per-group sums: out[g, a] = sum(values[i, a] for codes[i]==g and
    mask[i]). Returns None when the kernel declines (no pallas, G too large).

    values: [N, A] float32; codes: [N] int32; mask: [N] bool.
    """
    if not pallas_available() or num_groups > 128:
        return None
    import jax
    import jax.numpy as jnp

    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    n = len(codes)
    if n == 0:
        return np.zeros((num_groups, values.shape[1]), dtype=np.float32)
    pad = (-n) % BLOCK_ROWS
    if pad:
        codes = np.concatenate([codes, np.full(pad, -1, dtype=codes.dtype)])
        values = np.concatenate(
            [values, np.zeros((pad, values.shape[1]), dtype=values.dtype)]
        )
        mask = np.concatenate([mask, np.zeros(pad, dtype=bool)])
    run = _build(num_groups, values.shape[1], interpret)
    out = run(
        jnp.asarray(codes.astype(np.int32)),
        jnp.asarray(values.astype(np.float32)),
        jnp.asarray(mask),
    )
    from ballista_tpu.ops.runtime import readback

    return readback(out, rows=num_groups)  # [G, A]: the row axis leads

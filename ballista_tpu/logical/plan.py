"""Logical plan nodes.

Mirrors the reference wire contract's 12 LogicalPlanNode variants
(reference rust/core/proto/ballista.proto:164-179: projection, selection,
aggregate, sort, limit, csv/parquet scan, empty relation, create external
table, explain, analyze, join, repartition) plus the nodes full TPC-H planning
needs (cross join, subquery alias, distinct, union, window).
"""

from __future__ import annotations

import enum
from typing import Dict, List, Optional, Sequence, Tuple

import pyarrow as pa

from ballista_tpu.datasource import TableSource
from ballista_tpu.errors import PlanError
from ballista_tpu.logical.expr import (
    AggregateExpr,
    Column,
    Expr,
    SortExpr,
)


class JoinType(enum.Enum):
    INNER = "inner"
    LEFT = "left"
    RIGHT = "right"
    FULL = "full"
    SEMI = "semi"
    ANTI = "anti"


class PartitionScheme(enum.Enum):
    # reference logical Repartition (proto:219-230): round-robin | hash
    ROUND_ROBIN = "round_robin"
    HASH = "hash"


class LogicalPlan:
    """Base logical plan node."""

    def schema(self) -> pa.Schema:
        raise NotImplementedError

    def children(self) -> List["LogicalPlan"]:
        return []

    def with_children(self, children: List["LogicalPlan"]) -> "LogicalPlan":
        """Rebuild this node with new children (optimizer rewrites)."""
        if children:
            raise PlanError(f"{type(self).__name__} takes no children")
        return self

    def expressions(self) -> List[Expr]:
        return []

    # -- display -----------------------------------------------------------
    def fmt(self) -> str:
        raise NotImplementedError

    def display_indent(self, indent: int = 0) -> str:
        lines = ["  " * indent + self.fmt()]
        for c in self.children():
            lines.append(c.display_indent(indent + 1))
        return "\n".join(lines)

    def __str__(self) -> str:
        return self.display_indent()


class TableScan(LogicalPlan):
    def __init__(
        self,
        table_name: str,
        source: TableSource,
        projection: Optional[List[int]] = None,
        filters: Optional[List[Expr]] = None,
    ) -> None:
        self.table_name = table_name
        self.source = source
        self.projection = projection
        self.filters = filters or []

    def schema(self) -> pa.Schema:
        full = self.source.schema()
        if self.projection is None:
            return full
        return pa.schema([full.field(i) for i in self.projection])

    def fmt(self) -> str:
        proj = "" if self.projection is None else f" projection={self.projection}"
        return f"TableScan: {self.table_name}{proj}"


class EmptyRelation(LogicalPlan):
    def __init__(self, produce_one_row: bool = False, schema: Optional[pa.Schema] = None) -> None:
        self.produce_one_row = produce_one_row
        self._schema = schema if schema is not None else pa.schema([])

    def schema(self) -> pa.Schema:
        return self._schema

    def fmt(self) -> str:
        return f"EmptyRelation: produce_one_row={self.produce_one_row}"


class Projection(LogicalPlan):
    def __init__(self, input: LogicalPlan, exprs: List[Expr]) -> None:
        self.input = input
        self.exprs = exprs
        in_schema = input.schema()
        self._schema = pa.schema([e.to_field(in_schema) for e in exprs])

    def schema(self) -> pa.Schema:
        return self._schema

    def children(self) -> List[LogicalPlan]:
        return [self.input]

    def with_children(self, children: List[LogicalPlan]) -> "Projection":
        return Projection(children[0], self.exprs)

    def expressions(self) -> List[Expr]:
        return list(self.exprs)

    def fmt(self) -> str:
        return "Projection: " + ", ".join(str(e) for e in self.exprs)


class Filter(LogicalPlan):
    def __init__(self, input: LogicalPlan, predicate: Expr) -> None:
        self.input = input
        self.predicate = predicate

    def schema(self) -> pa.Schema:
        return self.input.schema()

    def children(self) -> List[LogicalPlan]:
        return [self.input]

    def with_children(self, children: List[LogicalPlan]) -> "Filter":
        return Filter(children[0], self.predicate)

    def expressions(self) -> List[Expr]:
        return [self.predicate]

    def fmt(self) -> str:
        return f"Filter: {self.predicate}"


class Aggregate(LogicalPlan):
    def __init__(
        self,
        input: LogicalPlan,
        group_exprs: List[Expr],
        aggr_exprs: List[Expr],
        exact_floats: bool = False,
    ) -> None:
        self.input = input
        self.group_exprs = group_exprs
        self.aggr_exprs = aggr_exprs
        # a decorrelated scalar subquery's result is compared against
        # source values (q2: ps_supplycost = MIN(ps_supplycost)); float
        # MIN/MAX must then return the bit-exact stored value, which the
        # f32 device paths cannot — they decline when this is set
        self.exact_floats = exact_floats
        in_schema = input.schema()
        fields = [e.to_field(in_schema) for e in group_exprs]
        fields += [e.to_field(in_schema) for e in aggr_exprs]
        self._schema = pa.schema(fields)

    def schema(self) -> pa.Schema:
        return self._schema

    def children(self) -> List[LogicalPlan]:
        return [self.input]

    def with_children(self, children: List[LogicalPlan]) -> "Aggregate":
        return Aggregate(children[0], self.group_exprs, self.aggr_exprs,
                         exact_floats=self.exact_floats)

    def expressions(self) -> List[Expr]:
        return list(self.group_exprs) + list(self.aggr_exprs)

    def fmt(self) -> str:
        return (
            "Aggregate: groupBy=["
            + ", ".join(str(e) for e in self.group_exprs)
            + "], aggr=["
            + ", ".join(str(e) for e in self.aggr_exprs)
            + "]"
        )


class Sort(LogicalPlan):
    def __init__(self, input: LogicalPlan, sort_exprs: List[SortExpr]) -> None:
        self.input = input
        self.sort_exprs = sort_exprs

    def schema(self) -> pa.Schema:
        return self.input.schema()

    def children(self) -> List[LogicalPlan]:
        return [self.input]

    def with_children(self, children: List[LogicalPlan]) -> "Sort":
        return Sort(children[0], self.sort_exprs)

    def expressions(self) -> List[Expr]:
        return list(self.sort_exprs)

    def fmt(self) -> str:
        return "Sort: " + ", ".join(str(e) for e in self.sort_exprs)


class Limit(LogicalPlan):
    def __init__(self, input: LogicalPlan, n: int, skip: int = 0) -> None:
        self.input = input
        self.n = n
        self.skip = skip

    def schema(self) -> pa.Schema:
        return self.input.schema()

    def children(self) -> List[LogicalPlan]:
        return [self.input]

    def with_children(self, children: List[LogicalPlan]) -> "Limit":
        return Limit(children[0], self.n, self.skip)

    def fmt(self) -> str:
        return f"Limit: {self.n}"


def _qualify(schema: pa.Schema, qualifier: Optional[str]) -> pa.Schema:
    if qualifier is None:
        return schema
    return pa.schema(
        [
            pa.field(
                f.name if "." in f.name else f"{qualifier}.{f.name}",
                f.type,
                f.nullable,
            )
            for f in schema
        ]
    )


class Join(LogicalPlan):
    def __init__(
        self,
        left: LogicalPlan,
        right: LogicalPlan,
        on: List[Tuple[Column, Column]],
        join_type: JoinType = JoinType.INNER,
        filter: Optional[Expr] = None,
    ) -> None:
        self.left = left
        self.right = right
        self.on = on
        self.join_type = join_type
        self.filter = filter
        if join_type in (JoinType.SEMI, JoinType.ANTI):
            self._schema = left.schema()
        else:
            left_fields = list(left.schema())
            right_fields = list(right.schema())
            names = {f.name for f in left_fields}
            for f in right_fields:
                if f.name in names:
                    raise PlanError(
                        f"duplicate field {f.name!r} in join output; "
                        "qualify inputs with SubqueryAlias"
                    )
            self._schema = pa.schema(left_fields + right_fields)

    def schema(self) -> pa.Schema:
        return self._schema

    def children(self) -> List[LogicalPlan]:
        return [self.left, self.right]

    def with_children(self, children: List[LogicalPlan]) -> "Join":
        return Join(children[0], children[1], self.on, self.join_type, self.filter)

    def expressions(self) -> List[Expr]:
        out: List[Expr] = []
        for l, r in self.on:
            out.extend([l, r])
        if self.filter is not None:
            out.append(self.filter)
        return out

    def fmt(self) -> str:
        on = ", ".join(f"{l} = {r}" for l, r in self.on)
        return f"Join: type={self.join_type.value}, on=[{on}]"


class CrossJoin(LogicalPlan):
    def __init__(self, left: LogicalPlan, right: LogicalPlan) -> None:
        self.left = left
        self.right = right
        left_fields = list(left.schema())
        right_fields = list(right.schema())
        names = {f.name for f in left_fields}
        for f in right_fields:
            if f.name in names:
                raise PlanError(
                    f"duplicate field {f.name!r} in cross join output; "
                    "qualify inputs with SubqueryAlias"
                )
        self._schema = pa.schema(left_fields + right_fields)

    def schema(self) -> pa.Schema:
        return self._schema

    def children(self) -> List[LogicalPlan]:
        return [self.left, self.right]

    def with_children(self, children: List[LogicalPlan]) -> "CrossJoin":
        return CrossJoin(children[0], children[1])

    def fmt(self) -> str:
        return "CrossJoin"


class Repartition(LogicalPlan):
    def __init__(
        self,
        input: LogicalPlan,
        scheme: PartitionScheme,
        n: int,
        hash_exprs: Optional[List[Expr]] = None,
    ) -> None:
        self.input = input
        self.scheme = scheme
        self.n = n
        self.hash_exprs = hash_exprs or []

    def schema(self) -> pa.Schema:
        return self.input.schema()

    def children(self) -> List[LogicalPlan]:
        return [self.input]

    def with_children(self, children: List[LogicalPlan]) -> "Repartition":
        return Repartition(children[0], self.scheme, self.n, self.hash_exprs)

    def fmt(self) -> str:
        if self.scheme == PartitionScheme.HASH:
            return f"Repartition: hash({', '.join(str(e) for e in self.hash_exprs)}) n={self.n}"
        return f"Repartition: round_robin n={self.n}"


class SubqueryAlias(LogicalPlan):
    """Renames/qualifies an input relation (FROM (…) AS t / table aliases)."""

    def __init__(self, input: LogicalPlan, alias: str) -> None:
        self.input = input
        self.alias = alias
        base = input.schema()
        fields = []
        for f in base:
            bare = f.name.split(".")[-1]
            fields.append(pa.field(f"{alias}.{bare}", f.type, f.nullable))
        self._schema = pa.schema(fields)

    def schema(self) -> pa.Schema:
        return self._schema

    def children(self) -> List[LogicalPlan]:
        return [self.input]

    def with_children(self, children: List[LogicalPlan]) -> "SubqueryAlias":
        return SubqueryAlias(children[0], self.alias)

    def fmt(self) -> str:
        return f"SubqueryAlias: {self.alias}"


class Distinct(LogicalPlan):
    def __init__(self, input: LogicalPlan) -> None:
        self.input = input

    def schema(self) -> pa.Schema:
        return self.input.schema()

    def children(self) -> List[LogicalPlan]:
        return [self.input]

    def with_children(self, children: List[LogicalPlan]) -> "Distinct":
        return Distinct(children[0])

    def fmt(self) -> str:
        return "Distinct"


class Union(LogicalPlan):
    def __init__(self, inputs: List[LogicalPlan], all: bool = True) -> None:
        if not inputs:
            raise PlanError("UNION of zero inputs")
        self.inputs = inputs
        self.all = all

    def schema(self) -> pa.Schema:
        return self.inputs[0].schema()

    def children(self) -> List[LogicalPlan]:
        return list(self.inputs)

    def with_children(self, children: List[LogicalPlan]) -> "Union":
        return Union(children, self.all)

    def fmt(self) -> str:
        return "Union" + ("" if self.all else " Distinct")


class Window(LogicalPlan):
    """Window functions (OVER clauses). Minimal surface for suite parity."""

    def __init__(self, input: LogicalPlan, window_exprs: List[Expr]) -> None:
        self.input = input
        self.window_exprs = window_exprs
        in_schema = input.schema()
        fields = list(in_schema)
        fields += [e.to_field(in_schema) for e in window_exprs]
        self._schema = pa.schema(fields)

    def schema(self) -> pa.Schema:
        return self._schema

    def children(self) -> List[LogicalPlan]:
        return [self.input]

    def with_children(self, children: List[LogicalPlan]) -> "Window":
        return Window(children[0], self.window_exprs)

    def fmt(self) -> str:
        return "Window: " + ", ".join(str(e) for e in self.window_exprs)


class Explain(LogicalPlan):
    def __init__(self, input: LogicalPlan, verbose: bool = False) -> None:
        self.input = input
        self.verbose = verbose
        self._schema = pa.schema(
            [pa.field("plan_type", pa.string()), pa.field("plan", pa.string())]
        )

    def schema(self) -> pa.Schema:
        return self._schema

    def children(self) -> List[LogicalPlan]:
        return [self.input]

    def with_children(self, children: List[LogicalPlan]) -> "Explain":
        return Explain(children[0], self.verbose)

    def fmt(self) -> str:
        return "Explain"


class CreateExternalTable(LogicalPlan):
    """CREATE EXTERNAL TABLE (reference proto CreateExternalTableNode)."""

    def __init__(
        self,
        name: str,
        location: str,
        file_type: str,
        has_header: bool = True,
        schema: Optional[pa.Schema] = None,
    ) -> None:
        self.name = name
        self.location = location
        self.file_type = file_type
        self.has_header = has_header
        self.table_schema = schema

    def schema(self) -> pa.Schema:
        return pa.schema([])

    def fmt(self) -> str:
        return f"CreateExternalTable: {self.name} @ {self.location} ({self.file_type})"

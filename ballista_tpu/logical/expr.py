"""Logical expression tree.

Covers the reference wire contract's expression surface: 17 LogicalExprNode
variants (reference rust/core/proto/ballista.proto:14-45), the scalar function
library (proto:80-114) and the five aggregate functions MIN/MAX/SUM/AVG/COUNT
(proto:121-127), plus subquery expressions needed for full TPC-H.

Arrow types are pyarrow DataTypes throughout — pyarrow is this build's Arrow
substrate, the role arrow-rs plays for the reference.
"""

from __future__ import annotations

import datetime
import decimal
from typing import Any, List, Optional, Sequence, Tuple, TYPE_CHECKING

import pyarrow as pa

from ballista_tpu.errors import PlanError, SchemaError

if TYPE_CHECKING:  # avoid import cycle; LogicalPlan only used in subquery exprs
    from ballista_tpu.logical.plan import LogicalPlan


# ---------------------------------------------------------------------------
# Operators
# ---------------------------------------------------------------------------

COMPARISON_OPS = {"eq", "neq", "lt", "lteq", "gt", "gteq"}
BOOLEAN_OPS = {"and", "or"}
ARITHMETIC_OPS = {"plus", "minus", "multiply", "divide", "modulo"}
STRING_OPS = {"like", "not_like"}

_OP_SYMBOL = {
    "eq": "=",
    "neq": "!=",
    "lt": "<",
    "lteq": "<=",
    "gt": ">",
    "gteq": ">=",
    "and": "AND",
    "or": "OR",
    "plus": "+",
    "minus": "-",
    "multiply": "*",
    "divide": "/",
    "modulo": "%",
    "like": "LIKE",
    "not_like": "NOT LIKE",
}


def _is_numeric(dt: pa.DataType) -> bool:
    return (
        pa.types.is_integer(dt)
        or pa.types.is_floating(dt)
        or pa.types.is_decimal(dt)
    )


_INT_RANK = {
    pa.int8(): 1,
    pa.int16(): 2,
    pa.int32(): 3,
    pa.int64(): 4,
    pa.uint8(): 1,
    pa.uint16(): 2,
    pa.uint32(): 3,
    pa.uint64(): 4,
}


def coerce_numeric(lhs: pa.DataType, rhs: pa.DataType) -> pa.DataType:
    """Numeric type coercion for binary arithmetic/comparison."""
    if lhs == rhs:
        return lhs
    if pa.types.is_decimal(lhs) or pa.types.is_decimal(rhs):
        return pa.float64()
    if pa.types.is_floating(lhs) or pa.types.is_floating(rhs):
        if lhs == pa.float64() or rhs == pa.float64():
            return pa.float64()
        if pa.types.is_integer(lhs) or pa.types.is_integer(rhs):
            return pa.float64()
        return pa.float32()
    if pa.types.is_integer(lhs) and pa.types.is_integer(rhs):
        rank_l = _INT_RANK.get(lhs, 4)
        rank_r = _INT_RANK.get(rhs, 4)
        return lhs if rank_l >= rank_r else rhs
    raise PlanError(f"cannot coerce {lhs} and {rhs}")


# ---------------------------------------------------------------------------
# Expr base
# ---------------------------------------------------------------------------


class Expr:
    """Base logical expression.

    Supports Python operator overloading for DataFrame ergonomics, mirroring
    the reference Python bindings' Expression overloads
    (reference python/src/expression.rs).
    """

    # -- schema-dependent metadata ----------------------------------------
    def data_type(self, schema: pa.Schema) -> pa.DataType:
        raise NotImplementedError(type(self).__name__)

    def nullable(self, schema: pa.Schema) -> bool:
        return True

    def to_field(self, schema: pa.Schema) -> pa.Field:
        return pa.field(self.output_name(), self.data_type(schema), self.nullable(schema))

    def output_name(self) -> str:
        """Column name this expression produces in an output schema."""
        return str(self)

    def children(self) -> List["Expr"]:
        return []

    # -- operator overloads ------------------------------------------------
    def __add__(self, other: Any) -> "BinaryExpr":
        return BinaryExpr(self, "plus", _expr(other))

    def __radd__(self, other: Any) -> "BinaryExpr":
        return BinaryExpr(_expr(other), "plus", self)

    def __sub__(self, other: Any) -> "BinaryExpr":
        return BinaryExpr(self, "minus", _expr(other))

    def __rsub__(self, other: Any) -> "BinaryExpr":
        return BinaryExpr(_expr(other), "minus", self)

    def __mul__(self, other: Any) -> "BinaryExpr":
        return BinaryExpr(self, "multiply", _expr(other))

    def __rmul__(self, other: Any) -> "BinaryExpr":
        return BinaryExpr(_expr(other), "multiply", self)

    def __truediv__(self, other: Any) -> "BinaryExpr":
        return BinaryExpr(self, "divide", _expr(other))

    def __mod__(self, other: Any) -> "BinaryExpr":
        return BinaryExpr(self, "modulo", _expr(other))

    def __eq__(self, other: Any) -> "BinaryExpr":  # type: ignore[override]
        return BinaryExpr(self, "eq", _expr(other))

    def __ne__(self, other: Any) -> "BinaryExpr":  # type: ignore[override]
        return BinaryExpr(self, "neq", _expr(other))

    def __lt__(self, other: Any) -> "BinaryExpr":
        return BinaryExpr(self, "lt", _expr(other))

    def __le__(self, other: Any) -> "BinaryExpr":
        return BinaryExpr(self, "lteq", _expr(other))

    def __gt__(self, other: Any) -> "BinaryExpr":
        return BinaryExpr(self, "gt", _expr(other))

    def __ge__(self, other: Any) -> "BinaryExpr":
        return BinaryExpr(self, "gteq", _expr(other))

    def __and__(self, other: Any) -> "BinaryExpr":
        return BinaryExpr(self, "and", _expr(other))

    def __or__(self, other: Any) -> "BinaryExpr":
        return BinaryExpr(self, "or", _expr(other))

    def __invert__(self) -> "Not":
        return Not(self)

    def __neg__(self) -> "Negative":
        return Negative(self)

    def __hash__(self) -> int:
        return hash(str(self))

    # -- fluent helpers ----------------------------------------------------
    def alias(self, name: str) -> "Alias":
        return Alias(self, name)

    def cast(self, dtype: pa.DataType) -> "Cast":
        return Cast(self, dtype)

    def is_null(self) -> "IsNull":
        return IsNull(self)

    def is_not_null(self) -> "IsNotNull":
        return IsNotNull(self)

    def between(self, low: Any, high: Any, negated: bool = False) -> "Between":
        return Between(self, _expr(low), _expr(high), negated)

    def isin(self, values: Sequence[Any], negated: bool = False) -> "InList":
        return InList(self, [_expr(v) for v in values], negated)

    def like(self, pattern: str) -> "BinaryExpr":
        return BinaryExpr(self, "like", Literal(pattern))

    def not_like(self, pattern: str) -> "BinaryExpr":
        return BinaryExpr(self, "not_like", Literal(pattern))

    def sort(self, ascending: bool = True, nulls_first: bool = False) -> "SortExpr":
        return SortExpr(self, ascending, nulls_first)

    def equals(self, other: "Expr") -> bool:
        """Structural equality (``==`` is overloaded to build BinaryExpr)."""
        return type(self) is type(other) and str(self) == str(other)


def _expr(value: Any) -> Expr:
    return value if isinstance(value, Expr) else Literal(value)


# ---------------------------------------------------------------------------
# Leaf expressions
# ---------------------------------------------------------------------------


class Column(Expr):
    """Column reference, optionally qualified (``l.l_quantity``)."""

    def __init__(self, name: str, relation: Optional[str] = None) -> None:
        self.name = name
        self.relation = relation

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        return self.field(schema).type

    def nullable(self, schema: pa.Schema) -> bool:
        return self.field(schema).nullable

    def field(self, schema: pa.Schema) -> pa.Field:
        idx = self.index_in(schema)
        return schema.field(idx)

    def index_in(self, schema: pa.Schema) -> int:
        # Qualified-name resolution: schemas from joins store fields under
        # "relation.name" flat names; try qualified, then bare.
        candidates = []
        if self.relation is not None:
            candidates.append(f"{self.relation}.{self.name}")
        candidates.append(self.name)
        names = schema.names
        for cand in candidates:
            if cand in names:
                i = names.index(cand)
                if names.count(cand) > 1:
                    raise SchemaError(f"ambiguous column {cand!r}")
                return i
        # unqualified reference to a qualified field, e.g. name "a" matching
        # exactly one "t.a"
        if self.relation is None:
            matches = [i for i, n in enumerate(names) if n.endswith("." + self.name)]
            if len(matches) == 1:
                return matches[0]
            if len(matches) > 1:
                raise SchemaError(f"ambiguous column {self.name!r}")
        raise SchemaError(f"no column named {self.flat_name()!r} in {names}")

    def flat_name(self) -> str:
        return f"{self.relation}.{self.name}" if self.relation else self.name

    def output_name(self) -> str:
        return self.name

    def __str__(self) -> str:
        return f"#{self.flat_name()}"


def infer_literal_type(value: Any) -> pa.DataType:
    if value is None:
        return pa.null()
    if isinstance(value, bool):
        return pa.bool_()
    if isinstance(value, int):
        return pa.int64()
    if isinstance(value, float):
        return pa.float64()
    if isinstance(value, str):
        return pa.string()
    if isinstance(value, bytes):
        return pa.binary()
    if isinstance(value, datetime.datetime):
        return pa.timestamp("us")
    if isinstance(value, datetime.date):
        return pa.date32()
    if isinstance(value, decimal.Decimal):
        return pa.float64()
    raise PlanError(f"unsupported literal {value!r}")


class Literal(Expr):
    def __init__(self, value: Any, dtype: Optional[pa.DataType] = None) -> None:
        self.value = value
        self.dtype = dtype if dtype is not None else infer_literal_type(value)

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        return self.dtype

    def nullable(self, schema: pa.Schema) -> bool:
        return self.value is None

    def __str__(self) -> str:
        if isinstance(self.value, str):
            return f"'{self.value}'"
        return str(self.value)


class Wildcard(Expr):
    """``*`` in ``COUNT(*)`` / ``SELECT *`` (reference proto:44 wildcard)."""

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        return pa.int64()

    def __str__(self) -> str:
        return "*"


# ---------------------------------------------------------------------------
# Compound expressions
# ---------------------------------------------------------------------------


class Alias(Expr):
    def __init__(self, expr: Expr, name: str) -> None:
        self.expr = expr
        self.name = name

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        return self.expr.data_type(schema)

    def nullable(self, schema: pa.Schema) -> bool:
        return self.expr.nullable(schema)

    def children(self) -> List[Expr]:
        return [self.expr]

    def output_name(self) -> str:
        return self.name

    def __str__(self) -> str:
        return f"{self.expr} AS {self.name}"


class BinaryExpr(Expr):
    def __init__(self, left: Expr, op: str, right: Expr) -> None:
        if op not in _OP_SYMBOL:
            raise PlanError(f"unknown binary operator {op!r}")
        self.left = left
        self.op = op
        self.right = right

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        if self.op in COMPARISON_OPS or self.op in BOOLEAN_OPS or self.op in STRING_OPS:
            return pa.bool_()
        lt = self.left.data_type(schema)
        rt = self.right.data_type(schema)
        if pa.types.is_temporal(lt) or pa.types.is_temporal(rt):
            # date +/- interval stays a date; date - date is days
            if pa.types.is_temporal(lt) and pa.types.is_temporal(rt):
                return pa.int32()
            return lt if pa.types.is_temporal(lt) else rt
        if self.op == "divide" and not (
            pa.types.is_floating(lt) or pa.types.is_floating(rt)
        ):
            # integer division keeps integer semantics
            return coerce_numeric(lt, rt)
        return coerce_numeric(lt, rt)

    def nullable(self, schema: pa.Schema) -> bool:
        return self.left.nullable(schema) or self.right.nullable(schema)

    def children(self) -> List[Expr]:
        return [self.left, self.right]

    def __str__(self) -> str:
        return f"({self.left} {_OP_SYMBOL[self.op]} {self.right})"


class Not(Expr):
    def __init__(self, expr: Expr) -> None:
        self.expr = expr

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        return pa.bool_()

    def children(self) -> List[Expr]:
        return [self.expr]

    def __str__(self) -> str:
        return f"NOT {self.expr}"


class Negative(Expr):
    def __init__(self, expr: Expr) -> None:
        self.expr = expr

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        return self.expr.data_type(schema)

    def children(self) -> List[Expr]:
        return [self.expr]

    def __str__(self) -> str:
        return f"(- {self.expr})"


class IsNull(Expr):
    def __init__(self, expr: Expr) -> None:
        self.expr = expr

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        return pa.bool_()

    def nullable(self, schema: pa.Schema) -> bool:
        return False

    def children(self) -> List[Expr]:
        return [self.expr]

    def __str__(self) -> str:
        return f"{self.expr} IS NULL"


class IsNotNull(Expr):
    def __init__(self, expr: Expr) -> None:
        self.expr = expr

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        return pa.bool_()

    def nullable(self, schema: pa.Schema) -> bool:
        return False

    def children(self) -> List[Expr]:
        return [self.expr]

    def __str__(self) -> str:
        return f"{self.expr} IS NOT NULL"


class Between(Expr):
    def __init__(self, expr: Expr, low: Expr, high: Expr, negated: bool = False) -> None:
        self.expr = expr
        self.low = low
        self.high = high
        self.negated = negated

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        return pa.bool_()

    def children(self) -> List[Expr]:
        return [self.expr, self.low, self.high]

    def __str__(self) -> str:
        neg = "NOT " if self.negated else ""
        return f"{self.expr} {neg}BETWEEN {self.low} AND {self.high}"


class InList(Expr):
    def __init__(self, expr: Expr, values: List[Expr], negated: bool = False) -> None:
        self.expr = expr
        self.values = values
        self.negated = negated

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        return pa.bool_()

    def children(self) -> List[Expr]:
        return [self.expr, *self.values]

    def __str__(self) -> str:
        neg = "NOT " if self.negated else ""
        vals = ", ".join(str(v) for v in self.values)
        return f"{self.expr} {neg}IN ({vals})"


# Like exists as a dedicated class for SQL ESCAPE support; plain LIKE uses
# BinaryExpr(op="like") as the reference does.
class Like(Expr):
    def __init__(self, expr: Expr, pattern: Expr, negated: bool = False,
                 escape: Optional[str] = None) -> None:
        self.expr = expr
        self.pattern = pattern
        self.negated = negated
        self.escape = escape

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        return pa.bool_()

    def children(self) -> List[Expr]:
        return [self.expr, self.pattern]

    def __str__(self) -> str:
        neg = "NOT " if self.negated else ""
        return f"{self.expr} {neg}LIKE {self.pattern}"


class Case(Expr):
    """CASE [expr] WHEN .. THEN .. [ELSE ..] END (reference proto CaseNode)."""

    def __init__(
        self,
        expr: Optional[Expr],
        when_then: List[Tuple[Expr, Expr]],
        else_expr: Optional[Expr] = None,
    ) -> None:
        if not when_then:
            raise PlanError("CASE requires at least one WHEN arm")
        self.expr = expr
        self.when_then = when_then
        self.else_expr = else_expr

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        return self.when_then[0][1].data_type(schema)

    def children(self) -> List[Expr]:
        out: List[Expr] = []
        if self.expr is not None:
            out.append(self.expr)
        for w, t in self.when_then:
            out.extend([w, t])
        if self.else_expr is not None:
            out.append(self.else_expr)
        return out

    def __str__(self) -> str:
        parts = ["CASE"]
        if self.expr is not None:
            parts.append(str(self.expr))
        for w, t in self.when_then:
            parts.append(f"WHEN {w} THEN {t}")
        if self.else_expr is not None:
            parts.append(f"ELSE {self.else_expr}")
        parts.append("END")
        return " ".join(parts)


class Cast(Expr):
    def __init__(self, expr: Expr, dtype: pa.DataType) -> None:
        self.expr = expr
        self.dtype = dtype

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        return self.dtype

    def nullable(self, schema: pa.Schema) -> bool:
        return self.expr.nullable(schema)

    def children(self) -> List[Expr]:
        return [self.expr]

    def output_name(self) -> str:
        return self.expr.output_name()

    def __str__(self) -> str:
        return f"CAST({self.expr} AS {self.dtype})"


class TryCast(Cast):
    """Cast returning null on failure instead of raising."""

    def __str__(self) -> str:
        return f"TRY_CAST({self.expr} AS {self.dtype})"


# ---------------------------------------------------------------------------
# Function calls
# ---------------------------------------------------------------------------

# Scalar function library: name -> return-type rule.
# "same" = type of first arg; "float" = float64; "string" = utf8; "int" = int64;
# "bool" = boolean.  Mirrors the reference's 33-function enum (proto:80-114).
SCALAR_FUNCTIONS = {
    "sqrt": "float",
    "sin": "float",
    "cos": "float",
    "tan": "float",
    "asin": "float",
    "acos": "float",
    "atan": "float",
    "exp": "float",
    "log": "float",
    "log2": "float",
    "log10": "float",
    "ln": "float",
    "floor": "float",
    "ceil": "float",
    "round": "float",
    "trunc": "float",
    "abs": "same",
    "signum": "same",
    "octet_length": "int",
    # super-aggregate marker; resolved to 0/1 literals by the grouping-sets
    # planner (only valid with ROLLUP/CUBE/GROUPING SETS)
    "grouping": "int",
    "concat": "string",
    "lower": "string",
    "upper": "string",
    "trim": "string",
    "ltrim": "string",
    "rtrim": "string",
    "btrim": "string",
    "length": "int",
    "char_length": "int",
    "substr": "string",
    "substring": "string",
    "replace": "string",
    "strpos": "int",
    "starts_with": "bool",
    "to_timestamp": "timestamp",
    "array": "same",
    "now": "timestamp",
    "md5": "string",
    "sha224": "string",
    "sha256": "string",
    "sha384": "string",
    "sha512": "string",
    "date_part": "int",
    "date_trunc": "same",
    "extract": "int",
    "coalesce": "same",
    "nullif": "same",
}

_FN_RETURN = {
    "float": pa.float64(),
    "int": pa.int64(),
    "string": pa.string(),
    "bool": pa.bool_(),
    "timestamp": pa.timestamp("us"),
}


class ScalarFunction(Expr):
    def __init__(self, fn: str, args: List[Expr]) -> None:
        fn = fn.lower()
        if fn not in SCALAR_FUNCTIONS:
            raise PlanError(f"unknown scalar function {fn!r}")
        self.fn = fn
        self.args = args

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        rule = SCALAR_FUNCTIONS[self.fn]
        if rule == "same":
            if self.fn in ("date_trunc",):
                return self.args[1].data_type(schema)
            if self.fn in ("coalesce", "nullif"):
                return self.args[0].data_type(schema)
            return self.args[0].data_type(schema)
        return _FN_RETURN[rule]

    def children(self) -> List[Expr]:
        return list(self.args)

    def output_name(self) -> str:
        return str(self)

    def __str__(self) -> str:
        return f"{self.fn}({', '.join(str(a) for a in self.args)})"


AGGREGATE_FUNCTIONS = ("min", "max", "sum", "avg", "count")


class AggregateExpr(Expr):
    """MIN/MAX/SUM/AVG/COUNT (reference proto:121-127), plus COUNT(DISTINCT)."""

    def __init__(self, fn: str, expr: Expr, distinct: bool = False) -> None:
        fn = fn.lower()
        if fn not in AGGREGATE_FUNCTIONS:
            raise PlanError(f"unknown aggregate function {fn!r}")
        self.fn = fn
        self.expr = expr
        self.distinct = distinct

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        if self.fn == "count":
            return pa.int64()
        if self.fn == "avg":
            return pa.float64()
        inner = self.expr.data_type(schema)
        if self.fn == "sum":
            if pa.types.is_integer(inner):
                return pa.int64()
            if pa.types.is_floating(inner) or pa.types.is_decimal(inner):
                return pa.float64()
        return inner

    def children(self) -> List[Expr]:
        return [self.expr]

    def output_name(self) -> str:
        return str(self)

    def __str__(self) -> str:
        d = "DISTINCT " if self.distinct else ""
        return f"{self.fn.upper()}({d}{self.expr})"


WINDOW_FUNCTIONS = ("row_number", "rank", "dense_rank") + AGGREGATE_FUNCTIONS


class WindowExpr(Expr):
    """fn(...) OVER (PARTITION BY ... ORDER BY ... [ROWS|RANGE frame]).

    frame: None = the SQL default (whole partition without ORDER BY;
    RANGE UNBOUNDED PRECEDING..CURRENT ROW with it), else a
    (mode, start, end) triple. mode is "rows" (offsets count rows) or
    "range" (offsets are order-key value deltas; requires one numeric
    order key). None = unbounded on that side, negative = PRECEDING,
    0 = CURRENT ROW, positive = FOLLOWING."""

    def __init__(
        self,
        fn: str,
        arg: Optional["Expr"],
        partition_by: List["Expr"],
        order_by: List["SortExpr"],
        frame: Optional[Tuple[str, Optional[float], Optional[float]]] = None,
    ) -> None:
        fn = fn.lower()
        if fn not in WINDOW_FUNCTIONS:
            raise PlanError(f"unknown window function {fn!r}")
        if frame is not None:
            mode, start, end = frame
            if mode not in ("rows", "range"):
                raise PlanError(f"unknown frame mode {mode!r}")
            if fn in ("row_number", "rank", "dense_rank"):
                raise PlanError(f"{fn} does not accept a frame clause")
            if start is not None and end is not None and start > end:
                raise PlanError("window frame start is after its end")
            if mode == "range" and len(order_by) != 1:
                raise PlanError("RANGE frames require exactly one ORDER BY key")
        self.fn = fn
        self.arg = arg
        self.partition_by = partition_by
        self.order_by = order_by
        self.frame = frame

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        if self.fn in ("row_number", "rank", "dense_rank", "count"):
            return pa.int64()
        if self.fn == "avg":
            return pa.float64()
        assert self.arg is not None
        inner = self.arg.data_type(schema)
        if self.fn == "sum":
            if pa.types.is_integer(inner):
                return pa.int64()
            if pa.types.is_floating(inner) or pa.types.is_decimal(inner):
                return pa.float64()
        return inner

    def children(self) -> List["Expr"]:
        out: List[Expr] = []
        if self.arg is not None:
            out.append(self.arg)
        out.extend(self.partition_by)
        out.extend(self.order_by)
        return out

    def output_name(self) -> str:
        return str(self)

    def __str__(self) -> str:
        arg = str(self.arg) if self.arg is not None else ""
        parts = []
        if self.partition_by:
            parts.append("PARTITION BY " + ", ".join(str(e) for e in self.partition_by))
        if self.order_by:
            parts.append("ORDER BY " + ", ".join(str(e) for e in self.order_by))
        if self.frame is not None:
            mode, start, end = self.frame
            parts.append(f"{mode.upper()} BETWEEN {_bound(start, True)} "
                         f"AND {_bound(end, False)}")
        return f"{self.fn.upper()}({arg}) OVER ({' '.join(parts)})"


def _bound(b, is_start: bool) -> str:
    if b is None:
        return "UNBOUNDED PRECEDING" if is_start else "UNBOUNDED FOLLOWING"
    if b == 0:
        return "CURRENT ROW"
    return f"{-b} PRECEDING" if b < 0 else f"{b} FOLLOWING"


class SortExpr(Expr):
    """Sort key wrapper — only valid inside Sort/TopK nodes (proto sort node)."""

    def __init__(self, expr: Expr, ascending: bool = True, nulls_first: bool = False) -> None:
        self.expr = expr
        self.ascending = ascending
        self.nulls_first = nulls_first

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        return self.expr.data_type(schema)

    def children(self) -> List[Expr]:
        return [self.expr]

    def __str__(self) -> str:
        direction = "ASC" if self.ascending else "DESC"
        nf = " NULLS FIRST" if self.nulls_first else ""
        return f"{self.expr} {direction}{nf}"


# ---------------------------------------------------------------------------
# Subquery expressions (beyond the reference wire contract; needed for the
# full TPC-H suite: q2/q4/q15/q16/q17/q18/q20/q21/q22)
# ---------------------------------------------------------------------------


class ScalarSubquery(Expr):
    def __init__(self, plan: "LogicalPlan") -> None:
        self.plan = plan

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        return self.plan.schema().field(0).type

    def __str__(self) -> str:
        return "(<subquery>)"


class InSubquery(Expr):
    def __init__(self, expr: Expr, plan: "LogicalPlan", negated: bool = False) -> None:
        self.expr = expr
        self.plan = plan
        self.negated = negated

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        return pa.bool_()

    def children(self) -> List[Expr]:
        return [self.expr]

    def __str__(self) -> str:
        neg = "NOT " if self.negated else ""
        return f"{self.expr} {neg}IN (<subquery>)"


class Exists(Expr):
    def __init__(self, plan: "LogicalPlan", negated: bool = False) -> None:
        self.plan = plan
        self.negated = negated

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        return pa.bool_()

    def __str__(self) -> str:
        neg = "NOT " if self.negated else ""
        return f"{neg}EXISTS (<subquery>)"


# ---------------------------------------------------------------------------
# Public constructors (fn library, reference python/src/functions.rs role)
# ---------------------------------------------------------------------------


def col(name: str) -> Column:
    if "." in name:
        relation, _, bare = name.partition(".")
        return Column(bare, relation)
    return Column(name)


def lit(value: Any) -> Literal:
    return Literal(value)


def binary_op(left: Expr, op: str, right: Expr) -> BinaryExpr:
    return BinaryExpr(left, op, right)


class _Functions:
    """``functions.sum(col(...))``-style library."""

    @staticmethod
    def sum(e: Expr) -> AggregateExpr:
        return AggregateExpr("sum", e)

    @staticmethod
    def avg(e: Expr) -> AggregateExpr:
        return AggregateExpr("avg", e)

    @staticmethod
    def min(e: Expr) -> AggregateExpr:
        return AggregateExpr("min", e)

    @staticmethod
    def max(e: Expr) -> AggregateExpr:
        return AggregateExpr("max", e)

    @staticmethod
    def count(e: Optional[Expr] = None, distinct: bool = False) -> AggregateExpr:
        return AggregateExpr("count", e if e is not None else Wildcard(), distinct)

    def __getattr__(self, name: str):
        if name in SCALAR_FUNCTIONS:
            def make(*args: Any) -> ScalarFunction:
                return ScalarFunction(name, [_expr(a) for a in args])
            return make
        raise AttributeError(name)


functions = _Functions()

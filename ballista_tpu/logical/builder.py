"""LogicalPlanBuilder + DataFrame verbs.

The relational-verb surface of the reference client DataFrame
(BallistaDataFrame::{select, filter, aggregate, sort, limit, join,
repartition, explain}, reference rust/client/src/context.rs:241-314).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from ballista_tpu.errors import PlanError
from ballista_tpu.logical import expr as lx
from ballista_tpu.logical import plan as lp


class LogicalPlanBuilder:
    def __init__(self, plan: lp.LogicalPlan) -> None:
        self.plan = plan

    @classmethod
    def scan(cls, table_name: str, source, projection=None) -> "LogicalPlanBuilder":
        return cls(lp.TableScan(table_name, source, projection))

    @classmethod
    def empty(cls, produce_one_row: bool = False) -> "LogicalPlanBuilder":
        return cls(lp.EmptyRelation(produce_one_row))

    def project(self, exprs: Sequence[lx.Expr]) -> "LogicalPlanBuilder":
        return LogicalPlanBuilder(lp.Projection(self.plan, list(exprs)))

    def filter(self, predicate: lx.Expr) -> "LogicalPlanBuilder":
        return LogicalPlanBuilder(lp.Filter(self.plan, predicate))

    def aggregate(
        self, group_exprs: Sequence[lx.Expr], aggr_exprs: Sequence[lx.Expr]
    ) -> "LogicalPlanBuilder":
        return LogicalPlanBuilder(
            lp.Aggregate(self.plan, list(group_exprs), list(aggr_exprs))
        )

    def sort(self, sort_exprs: Sequence[lx.SortExpr]) -> "LogicalPlanBuilder":
        return LogicalPlanBuilder(lp.Sort(self.plan, list(sort_exprs)))

    def limit(self, n: int, skip: int = 0) -> "LogicalPlanBuilder":
        return LogicalPlanBuilder(lp.Limit(self.plan, n, skip))

    def join(
        self,
        right: "LogicalPlanBuilder",
        on: List[Tuple[lx.Column, lx.Column]],
        join_type: lp.JoinType = lp.JoinType.INNER,
        filter: Optional[lx.Expr] = None,
    ) -> "LogicalPlanBuilder":
        return LogicalPlanBuilder(
            lp.Join(self.plan, right.plan, on, join_type, filter)
        )

    def cross_join(self, right: "LogicalPlanBuilder") -> "LogicalPlanBuilder":
        return LogicalPlanBuilder(lp.CrossJoin(self.plan, right.plan))

    def repartition_hash(self, exprs: Sequence[lx.Expr], n: int) -> "LogicalPlanBuilder":
        return LogicalPlanBuilder(
            lp.Repartition(self.plan, lp.PartitionScheme.HASH, n, list(exprs))
        )

    def repartition_round_robin(self, n: int) -> "LogicalPlanBuilder":
        return LogicalPlanBuilder(
            lp.Repartition(self.plan, lp.PartitionScheme.ROUND_ROBIN, n)
        )

    def alias(self, name: str) -> "LogicalPlanBuilder":
        return LogicalPlanBuilder(lp.SubqueryAlias(self.plan, name))

    def distinct(self) -> "LogicalPlanBuilder":
        return LogicalPlanBuilder(lp.Distinct(self.plan))

    def union(self, others: Sequence["LogicalPlanBuilder"], all: bool = True) -> "LogicalPlanBuilder":
        plans = [self.plan] + [o.plan for o in others]
        u: lp.LogicalPlan = lp.Union(plans, all)
        if not all:
            u = lp.Distinct(u)
        return LogicalPlanBuilder(u)

    def build(self) -> lp.LogicalPlan:
        return self.plan

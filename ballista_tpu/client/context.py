"""Distributed client: BallistaContext + BallistaDataFrame.

Mirrors the reference client crate (rust/client/src/context.rs): tables are
registered client-side and plans are built locally; collect() submits the
logical plan to the scheduler (ExecuteQuery), polls GetJobStatus every 100ms
(ref context.rs:183-207), and on completion fetches each result partition
from the executor holding it over Arrow Flight (ref context.rs:218-230).
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional, Sequence

import pyarrow as pa
import pyarrow.flight as flight

from ballista_tpu.config import BallistaConfig
from ballista_tpu.datasource import (
    CsvTableSource,
    MemoryTableSource,
    ParquetTableSource,
    TableSource,
)
from ballista_tpu.engine.context import DataFrame, ExecutionContext
from ballista_tpu.errors import BallistaError, ExecutionError, PlanError
from ballista_tpu.logical import plan as lp
from ballista_tpu.logical.builder import LogicalPlanBuilder
from ballista_tpu.proto import ballista_pb2 as pb
from ballista_tpu.scheduler.rpc import SchedulerGrpcClient
from ballista_tpu.serde.logical import plan_to_proto

POLL_INTERVAL = 0.1  # ref context.rs:195
# status polls start here and double toward POLL_INTERVAL (ISSUE 8): a
# small query completing in a few ms should not pay a fixed 100ms poll
# gap, while long jobs converge to the reference cadence within 5 polls
POLL_INTERVAL_MIN = 0.005


class _CachedResultLost(BallistaError):
    """A result-cache-served job's partitions died before the fetch; the
    scheduler invalidated the entry — collect() resubmits the plan once."""

    def __init__(self, job_id: str) -> None:
        super().__init__(f"cached result partitions of job {job_id} lost")
        self.job_id = job_id


class _StatusWatch:
    """Server-push job-status subscription (ISSUE 11): a reader thread
    drains one SubscribeJobStatus stream into a queue; next() blocks until
    a fresh status lands (or the timeout passes) — which is what removes
    the 5ms-floor polling gap from job completion latency. Degrades
    cleanly: any stream failure (scheduler restart, push disabled,
    pre-ISSUE-11 scheduler answering UNIMPLEMENTED) just flips alive() off
    and the caller's poll loop takes over."""

    def __init__(self, client, job_id: str) -> None:
        import queue as _queue
        import threading

        self._q: "_queue.Queue" = _queue.Queue()
        self._call = None
        self._down = False
        try:
            self._call = client.subscribe_job_status(
                pb.GetJobStatusParams(job_id=job_id)
            )
        except Exception:
            self._down = True
            return
        from ballista_tpu.ops.runtime import record_serving

        record_serving("status_push_subscribed")
        threading.Thread(
            target=self._read, daemon=True, name="status-watch"
        ).start()

    def _read(self) -> None:
        try:
            for res in self._call:
                self._q.put(res.status)
        except Exception:
            pass
        finally:
            self._q.put(None)  # stream over (terminal served, or dropped)

    def next(self, timeout: float):
        """Next pushed JobStatus, or None when the timeout passed (caller
        falls through to a safety poll) or the stream ended (alive() is
        then False and the caller's poll loop owns the job)."""
        import queue as _queue

        if self._down:
            return None
        try:
            st = self._q.get(timeout=max(0.0, timeout))
        except _queue.Empty:
            return None
        if st is None:
            self._down = True
            from ballista_tpu.ops.runtime import record_serving

            record_serving("status_push_closed")
            return None
        from ballista_tpu.ops.runtime import record_serving

        record_serving("status_push")
        return st

    def alive(self) -> bool:
        return not self._down

    def close(self) -> None:
        if self._call is not None:
            try:
                self._call.cancel()
            except Exception:
                pass


class _JobStatusSource:
    """Watch-or-poll job-status acquisition (ISSUE 11): ONE implementation
    of the push/poll contract shared by every status-consuming loop —
    the push subscription (when `ballista.client.push_status` is on), the
    safety-poll fallback, and the adaptive pure-poll pacing. next() blocks
    up to POLL_INTERVAL on a live stream (a pushed transition returns the
    instant the scheduler writes it) and polls otherwise, sleeping the
    adaptive backoff between successive pure polls only."""

    def __init__(self, client, config, job_id: str) -> None:
        self._client = client
        self._config = config
        self._job_id = job_id
        self._watch = (
            _StatusWatch(client, job_id) if config.push_status() else None
        )
        self._interval = POLL_INTERVAL_MIN
        self._polled = False

    def next(self, deadline: float) -> pb.JobStatus:
        """The next JobStatus before `deadline` — pushed when the stream
        is live, polled otherwise (also the safety net when a live stream
        stays silent for a full POLL_INTERVAL)."""
        if self._watch is not None and self._watch.alive():
            status = self._watch.next(
                min(POLL_INTERVAL, max(0.0, deadline - time.time()))
            )
            if status is not None:
                return status
        elif self._polled:
            # pure-poll pacing (push disabled or stream down) between
            # successive polls; with a live watch, next() above already
            # blocked waiting for the change
            time.sleep(self._interval)
            self._interval = min(self._interval * 2, POLL_INTERVAL)
        self._polled = True
        res = self._client.get_job_status(
            pb.GetJobStatusParams(job_id=self._job_id)
        )
        # ownership redirect (ISSUE 20): the polled replica named the
        # job's owner. Status POLLS answer from any replica (shared KV
        # truth), but the push stream only fires on the owner — jump the
        # client there and re-home the subscription once per switch.
        if res.owner_addr and self._client.prefer_endpoint(res.owner_addr):
            if self._watch is not None:
                self._watch.close()
            if self._config.push_status():
                from ballista_tpu.ops.runtime import record_serving

                record_serving("status_push_rehomed")
                self._watch = _StatusWatch(self._client, self._job_id)
        return res.status

    def close(self) -> None:
        if self._watch is not None:
            self._watch.close()


class BallistaContext(ExecutionContext):
    """Client context talking to a remote scheduler (ref BallistaContext::remote)."""

    def __init__(
        self,
        host: str = "localhost",
        port: int = 50050,
        settings: Optional[Dict[str, str]] = None,
        endpoints: Optional[Sequence] = None,
    ) -> None:
        super().__init__(BallistaConfig(settings))
        self.host = host
        self.port = port
        # `endpoints` adds failover scheduler replicas (ISSUE 20): submit,
        # poll and subscribe work against ANY of them — transient failures
        # and ownership redirects rotate the client automatically
        self._client = SchedulerGrpcClient(
            host,
            port,
            retries=self.config.rpc_retries(),
            backoff_s=self.config.rpc_backoff_s(),
            endpoints=endpoints,
        )

    @classmethod
    def remote(cls, host: str, port: int, settings=None) -> "BallistaContext":
        return cls(host, port, settings)

    # DataFrames constructed through the inherited registration/verb surface
    # execute remotely:
    def table(self, name: str) -> "BallistaDataFrame":
        src = self.tables.get(name.lower())
        if src is None:
            raise PlanError(f"no table registered as {name!r}")
        return BallistaDataFrame(self, LogicalPlanBuilder.scan(name, src))

    def sql(self, query: str) -> "BallistaDataFrame":
        from ballista_tpu.sql.planner import plan_sql

        plan = plan_sql(query, self)
        if isinstance(plan, lp.CreateExternalTable):
            self._create_external_table(plan)
            return BallistaDataFrame(self, LogicalPlanBuilder.empty(False))
        return BallistaDataFrame(self, LogicalPlanBuilder(plan))

    # -- execution ---------------------------------------------------------
    def collect(self, plan: lp.LogicalPlan, timeout: float = 300.0) -> pa.Table:
        job_id = self.submit(plan)
        try:
            return self._collect_results(job_id, plan.schema(), timeout)
        except _CachedResultLost:
            # the scheduler served this job from the result cache but the
            # cached partitions died under a live lease; it invalidated the
            # entry and failed the job. ONE resubmission re-executes for
            # real (the fresh submission misses the now-deleted entry).
            from ballista_tpu.ops.runtime import record_tenancy

            record_tenancy("cache_lost_resubmitted")
            job_id = self.submit(plan)
            try:
                return self._collect_results(job_id, plan.schema(), timeout)
            except _CachedResultLost as e:
                # the resubmission ALSO rode a (concurrently re-published)
                # dead entry: the cluster is churning faster than the cache
                # invalidates — surface a public error, not the internal
                # retry marker
                raise ExecutionError(
                    f"job {e.job_id}: cached result partitions lost twice "
                    "in a row (executor churn outpacing cache "
                    "invalidation) — retry the query"
                ) from e

    def submit(self, plan: lp.LogicalPlan) -> str:
        """ExecuteQuery only: returns the job id without waiting for (or
        fetching) results — collect() is submit + _collect_results."""
        params = pb.ExecuteQueryParams()
        params.logical_plan.CopyFrom(plan_to_proto(plan))
        # only non-default settings travel: they override scheduler/executor
        # configs per job without clobbering host-local tuning
        for k, v in self.config.explicit_settings().items():
            params.settings.add(key=k, value=v)
        # tenancy rides first-class fields too (ISSUE 7): admission control
        # must not depend on parsing the settings map
        params.tenant = self.config.tenant()
        params.priority = self.config.tenant_priority()
        return self._client.execute_query(params).job_id

    def collect_stream(self, plan: lp.LogicalPlan, timeout: float = 300.0):
        """Streaming collect (ISSUE 8): yield result RecordBatches in
        final-partition order, starting as soon as the FIRST final-stage
        partition completes (per-partition completion notifications on the
        running job status) instead of after the whole job. Batches are
        committed per partition — a mid-stream fetch loss discards that
        partition's partial batches and routes through ReportLostPartition
        + re-poll, so everything yielded is final. The concatenation of the
        yielded batches is bit-identical to collect()'s table (pre-cast).

        A cache-served job whose partitions died is resubmitted ONCE, like
        collect() — but only while nothing has been yielded yet (yielded
        batches cannot be retracted)."""
        job_id = self.submit(plan)
        yielded = False
        try:
            for batch in self._stream_results(job_id, plan.schema(), timeout):
                yielded = True
                yield batch
        except _CachedResultLost as e:
            if yielded:
                raise ExecutionError(
                    f"job {e.job_id}: cached result partitions lost "
                    "mid-stream — retry the query"
                ) from e
            from ballista_tpu.ops.runtime import record_tenancy

            record_tenancy("cache_lost_resubmitted")
            job_id = self.submit(plan)
            try:
                yield from self._stream_results(job_id, plan.schema(), timeout)
            except _CachedResultLost as e2:
                raise ExecutionError(
                    f"job {e2.job_id}: cached result partitions lost twice "
                    "in a row (executor churn outpacing cache "
                    "invalidation) — retry the query"
                ) from e2

    def _stream_results(self, job_id: str, schema, timeout: float = 300.0):
        """Poll the job status; fetch each final-stage partition the moment
        its completion is published (running.partial_location while the job
        runs, completed.partition_location at the end) and yield its
        batches once the whole partition streamed cleanly, in partition
        order. Fetch failures — including mid-stream drops after the first
        batch — discard the partition's uncommitted batches and report the
        lost location (ReportLostPartition), exactly like the buffered
        path: a restarted job re-polls for fresh locations; a dead cached
        entry surfaces _CachedResultLost for the caller's resubmission."""
        from ballista_tpu.errors import ShuffleFetchError
        from ballista_tpu.ops.runtime import record_recovery, record_serving

        deadline = time.time() + timeout
        # push-status source (ISSUE 11): each status transition — every
        # new partial_location included — arrives the moment the scheduler
        # writes it, with the adaptive poll as the automatic safety net
        # (cooldown re-fetches, stream drops, schedulers without the RPC)
        source = _JobStatusSource(self._client, self.config, job_id)
        committed: Dict[int, list] = {}  # partition -> batches (not yet yielded)
        done: set = set()  # partitions committed (incl. already yielded)
        # partition -> ((executor id, path), failure time) of a location
        # that already failed + was reported: re-fetching the identical
        # location before the scheduler publishes a fresh one would just
        # spin. Cooldown-based, not until-it-changes: a recompute can
        # legitimately land on the same executor AND path (sole survivor).
        failed_locs: Dict[int, tuple] = {}
        FAILED_LOC_COOLDOWN = 0.5
        next_yield = 0
        try:
            while True:
                if time.time() > deadline:
                    raise ExecutionError(
                        f"job {job_id} timed out after {timeout}s"
                    )
                status = source.next(deadline)
                which = status.WhichOneof("status")
                if which == "failed":
                    raise ExecutionError(
                        f"job {job_id} failed: {status.failed.error}"
                    )
                total = None
                if which == "completed":
                    # advanced-entry results ride the status itself (ISSUE
                    # 19): one Arrow IPC stream, checked BEFORE the empty
                    # location list is read as an empty result
                    if status.completed.inline_result:
                        with pa.ipc.open_stream(
                            pa.BufferReader(status.completed.inline_result)
                        ) as r:
                            for batch in r:
                                yield batch
                        return
                    locs = list(status.completed.partition_location)
                    total = len(locs)
                elif which == "running":
                    locs = list(status.running.partial_location)
                else:
                    locs = []
                for loc in locs:
                    p = loc.partition_id.partition_id
                    sig = (loc.executor_meta.id, loc.path)
                    if p in done:
                        continue
                    prior = failed_locs.get(p)
                    if (
                        prior is not None
                        and prior[0] == sig
                        and time.time() - prior[1] < FAILED_LOC_COOLDOWN
                    ):
                        # a known-dead location the scheduler has not
                        # replaced yet (a stale status snapshot can
                        # republish it for a few polls); retried after the
                        # cooldown either way
                        continue
                    try:
                        batches = self._fetch_partition_batches(loc)
                    except ShuffleFetchError as e:
                        result = self._client.report_lost_partition(
                            pb.ReportLostPartitionParams(
                                job_id=job_id,
                                executor_id=e.executor_id,
                                stage_id=e.stage_id,
                                partition_id=e.map_partition,
                                path=e.path,
                            )
                        )
                        if not result.restarted:
                            if which == "completed" and status.completed.cached:
                                raise _CachedResultLost(job_id) from e
                            raise
                        record_recovery("result_fetch_restarted")
                        # keep fetching the OTHER listed partitions this
                        # round (one dead location must not starve the
                        # rest); this one retries after the cooldown / on
                        # a fresh location
                        failed_locs[p] = (sig, time.time())
                        continue
                    failed_locs.pop(p, None)
                    committed[p] = batches
                    done.add(p)
                    if which == "running":
                        record_serving("stream_partition_early")
                while next_yield in committed:
                    for batch in committed.pop(next_yield):
                        yield batch
                    next_yield += 1
                if total is not None and next_yield >= total:
                    return
        finally:
            source.close()

    def _storage_read_table(self, loc: pb.PartitionLocation):
        """Direct shared-storage read of a storage-homed result partition
        (ISSUE 15), or None to use the Flight ladder — the client fetches
        the bytes from the mount instead of round-tripping them through the
        (possibly already retired) producing executor. Confined to this
        client's OWN configured ballista.shuffle.dir: the location path
        came from the scheduler and must not name arbitrary local files.
        Any read failure falls back to Flight, never errors here."""
        if not loc.storage_uri:
            return None
        root = self.config.shuffle_dir()
        if not root:
            return None
        from ballista_tpu.executor.confine import resolve_contained
        from ballista_tpu.ops.runtime import record_shuffle_tier

        resolved = resolve_contained(os.path.join(loc.path, "0.arrow"), root)
        if resolved is None or not os.path.exists(resolved):
            record_shuffle_tier("client_storage_miss")
            return None
        try:
            with pa.ipc.open_file(resolved) as r:
                table = r.read_all()
        except Exception:
            record_shuffle_tier("client_storage_miss")
            return None
        record_shuffle_tier("client_storage_fetch")
        return table

    def _fetch_partition_batches(self, loc: pb.PartitionLocation) -> list:
        """One result partition as a committed batch list — read straight
        from shared storage when the location is storage-homed (ISSUE 15),
        else streamed over Flight (client/flight.py stream_action). Any
        Flight failure — connect, first byte, or mid-stream — surfaces as
        ShuffleFetchError naming the lost location; partial batches are
        dropped by the caller."""
        from ballista_tpu.client.flight import BallistaClient
        from ballista_tpu.errors import RpcError, ShuffleFetchError

        table = self._storage_read_table(loc)
        if table is not None:
            return table.to_batches()
        action = pb.Action()
        action.fetch_partition.path = os.path.join(loc.path, "0.arrow")
        try:
            client = BallistaClient(
                loc.executor_meta.host,
                loc.executor_meta.port,
                retries=self.config.rpc_retries(),
                backoff_s=self.config.rpc_backoff_s(),
            )
        except Exception as e:
            raise ShuffleFetchError(
                f"result partition unreachable: {e}",
                executor_id=loc.executor_meta.id,
                host=loc.executor_meta.host,
                port=loc.executor_meta.port,
                path=loc.path,
                stage_id=loc.partition_id.stage_id,
                map_partition=loc.partition_id.partition_id,
            ) from e
        try:
            return list(client.stream_action(action))
        except RpcError as e:
            raise ShuffleFetchError(
                f"result partition fetch failed: {e}",
                executor_id=loc.executor_meta.id,
                host=loc.executor_meta.host,
                port=loc.executor_meta.port,
                path=loc.path,
                stage_id=loc.partition_id.stage_id,
                map_partition=loc.partition_id.partition_id,
            ) from e
        finally:
            client.close()

    def _collect_results(
        self, job_id: str, schema, timeout: float = 300.0
    ) -> pa.Table:
        """Wait for the job, then fetch each result partition from the
        executor holding it. A fetch failure against the now-TERMINAL job
        (the owner died between completion and this fetch — the scheduler's
        lost-task machinery skips finished jobs, so nobody else notices)
        is reported back via ReportLostPartition: the scheduler requeues
        the lost final-stage tasks through lineage and flips the job back
        to running, and this loop re-polls for the fresh locations instead
        of erroring (ISSUE 6 / PR 5 residue).

        With ballista.client.stream_results on, the same contract runs in
        STREAMING mode: partitions are fetched as they complete and the
        table assembles from the streamed batches — bit-identical to the
        buffered result."""
        from ballista_tpu.errors import ShuffleFetchError

        if self.config.stream_results():
            batches = list(self._stream_results(job_id, schema, timeout))
            if not batches:
                return schema.empty_table()
            return pa.Table.from_batches(
                batches, schema=batches[0].schema
            ).cast(schema)

        deadline = time.time() + timeout
        while True:
            status = self._wait_for_job(job_id, max(0.0, deadline - time.time()))
            if status.completed.inline_result:
                # advanced-entry result (ISSUE 19): the folded table rides
                # the status inline — nothing to fetch, nothing to lose.
                # Checked BEFORE the location list, or an inline result
                # would be misread as an empty table.
                with pa.ipc.open_stream(
                    pa.BufferReader(status.completed.inline_result)
                ) as r:
                    return r.read_all().cast(schema)
            try:
                tables = [
                    self._fetch_partition(loc)
                    for loc in status.completed.partition_location
                ]
            except ShuffleFetchError as e:
                cached = status.completed.cached
                result = self._client.report_lost_partition(
                    pb.ReportLostPartitionParams(
                        job_id=job_id,
                        executor_id=e.executor_id,
                        stage_id=e.stage_id,
                        partition_id=e.map_partition,
                        path=e.path,
                    )
                )
                if not result.restarted:
                    if cached:
                        # cache-served job: the scheduler invalidated the
                        # entry; collect() resubmits the plan once
                        raise _CachedResultLost(job_id) from e
                    # nothing for the scheduler to restart (or the job
                    # already failed for good): surface the fetch error
                    raise
                from ballista_tpu.ops.runtime import record_recovery

                record_recovery("result_fetch_restarted")
                continue
            if not tables:
                return schema.empty_table()
            return pa.concat_tables(tables).cast(schema)

    def _wait_for_job(self, job_id: str, timeout: float) -> pb.JobStatus:
        """Wait for a terminal status — via the SubscribeJobStatus push
        stream when enabled (the completion arrives the instant the
        scheduler writes it, no polling floor), with the adaptive poll as
        the automatic fallback whenever the stream is down or refused."""
        deadline = time.time() + timeout
        source = _JobStatusSource(self._client, self.config, job_id)
        try:
            while time.time() < deadline:
                status = source.next(deadline)
                which = status.WhichOneof("status")
                if which == "completed":
                    return status
                if which == "failed":
                    raise ExecutionError(
                        f"job {job_id} failed: {status.failed.error}"
                    )
            raise ExecutionError(f"job {job_id} timed out after {timeout}s")
        finally:
            source.close()

    def _fetch_partition(self, loc: pb.PartitionLocation) -> pa.Table:
        from ballista_tpu.client.flight import BallistaClient
        from ballista_tpu.errors import RpcError, ShuffleFetchError

        # storage-homed result partitions read straight from the shared
        # mount (ISSUE 15); Flight stays the fallback transport
        table = self._storage_read_table(loc)
        if table is not None:
            return table
        try:
            client = BallistaClient(
                loc.executor_meta.host,
                loc.executor_meta.port,
                retries=self.config.rpc_retries(),
                backoff_s=self.config.rpc_backoff_s(),
            )
        except Exception as e:  # connect failure = same lost location
            raise ShuffleFetchError(
                f"result partition unreachable: {e}",
                executor_id=loc.executor_meta.id,
                host=loc.executor_meta.host,
                port=loc.executor_meta.port,
                path=loc.path,
                stage_id=loc.partition_id.stage_id,
                map_partition=loc.partition_id.partition_id,
            ) from e
        try:
            # the final stage writes piece 0 per input partition
            return client.fetch_partition(os.path.join(loc.path, "0.arrow"))
        except RpcError as e:
            # name the lost location so _collect_results can report it to
            # the scheduler (ReportLostPartition) instead of just erroring
            raise ShuffleFetchError(
                f"result partition fetch failed: {e}",
                executor_id=loc.executor_meta.id,
                host=loc.executor_meta.host,
                port=loc.executor_meta.port,
                path=loc.path,
                stage_id=loc.partition_id.stage_id,
                map_partition=loc.partition_id.partition_id,
            ) from e
        finally:
            client.close()

    # -- cluster info ------------------------------------------------------
    def executors(self) -> List[pb.ExecutorMetadata]:
        return list(self._client.get_executors_metadata().metadata)

    def close(self) -> None:
        self._client.close()


class BallistaDataFrame(DataFrame):
    """DataFrame whose collect() executes on the cluster."""

    def _wrap(self, builder: LogicalPlanBuilder) -> "BallistaDataFrame":
        return BallistaDataFrame(self._ctx, builder)

    # rewrap verbs so chaining stays distributed
    def select(self, *exprs) -> "BallistaDataFrame":
        return self._wrap(self._builder.project(list(exprs)))

    def filter(self, predicate) -> "BallistaDataFrame":
        return self._wrap(self._builder.filter(predicate))

    def aggregate(self, group_by, aggs) -> "BallistaDataFrame":
        return self._wrap(self._builder.aggregate(group_by, aggs))

    def sort(self, *exprs) -> "BallistaDataFrame":
        return self._wrap(self._builder.sort(list(exprs)))

    def limit(self, n: int, skip: int = 0) -> "BallistaDataFrame":
        return self._wrap(self._builder.limit(n, skip))

    def collect(self) -> pa.Table:
        return self._ctx.collect(self.logical_plan())

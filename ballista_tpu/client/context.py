"""Distributed client: BallistaContext + BallistaDataFrame.

Mirrors the reference client crate (rust/client/src/context.rs): tables are
registered client-side and plans are built locally; collect() submits the
logical plan to the scheduler (ExecuteQuery), polls GetJobStatus every 100ms
(ref context.rs:183-207), and on completion fetches each result partition
from the executor holding it over Arrow Flight (ref context.rs:218-230).
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional, Sequence

import pyarrow as pa
import pyarrow.flight as flight

from ballista_tpu.config import BallistaConfig
from ballista_tpu.datasource import (
    CsvTableSource,
    MemoryTableSource,
    ParquetTableSource,
    TableSource,
)
from ballista_tpu.engine.context import DataFrame, ExecutionContext
from ballista_tpu.errors import BallistaError, ExecutionError, PlanError
from ballista_tpu.logical import plan as lp
from ballista_tpu.logical.builder import LogicalPlanBuilder
from ballista_tpu.proto import ballista_pb2 as pb
from ballista_tpu.scheduler.rpc import SchedulerGrpcClient
from ballista_tpu.serde.logical import plan_to_proto

POLL_INTERVAL = 0.1  # ref context.rs:195


class _CachedResultLost(BallistaError):
    """A result-cache-served job's partitions died before the fetch; the
    scheduler invalidated the entry — collect() resubmits the plan once."""

    def __init__(self, job_id: str) -> None:
        super().__init__(f"cached result partitions of job {job_id} lost")
        self.job_id = job_id


class BallistaContext(ExecutionContext):
    """Client context talking to a remote scheduler (ref BallistaContext::remote)."""

    def __init__(
        self,
        host: str = "localhost",
        port: int = 50050,
        settings: Optional[Dict[str, str]] = None,
    ) -> None:
        super().__init__(BallistaConfig(settings))
        self.host = host
        self.port = port
        self._client = SchedulerGrpcClient(
            host,
            port,
            retries=self.config.rpc_retries(),
            backoff_s=self.config.rpc_backoff_s(),
        )

    @classmethod
    def remote(cls, host: str, port: int, settings=None) -> "BallistaContext":
        return cls(host, port, settings)

    # DataFrames constructed through the inherited registration/verb surface
    # execute remotely:
    def table(self, name: str) -> "BallistaDataFrame":
        src = self.tables.get(name.lower())
        if src is None:
            raise PlanError(f"no table registered as {name!r}")
        return BallistaDataFrame(self, LogicalPlanBuilder.scan(name, src))

    def sql(self, query: str) -> "BallistaDataFrame":
        from ballista_tpu.sql.planner import plan_sql

        plan = plan_sql(query, self)
        if isinstance(plan, lp.CreateExternalTable):
            self._create_external_table(plan)
            return BallistaDataFrame(self, LogicalPlanBuilder.empty(False))
        return BallistaDataFrame(self, LogicalPlanBuilder(plan))

    # -- execution ---------------------------------------------------------
    def collect(self, plan: lp.LogicalPlan, timeout: float = 300.0) -> pa.Table:
        job_id = self.submit(plan)
        try:
            return self._collect_results(job_id, plan.schema(), timeout)
        except _CachedResultLost:
            # the scheduler served this job from the result cache but the
            # cached partitions died under a live lease; it invalidated the
            # entry and failed the job. ONE resubmission re-executes for
            # real (the fresh submission misses the now-deleted entry).
            from ballista_tpu.ops.runtime import record_tenancy

            record_tenancy("cache_lost_resubmitted")
            job_id = self.submit(plan)
            try:
                return self._collect_results(job_id, plan.schema(), timeout)
            except _CachedResultLost as e:
                # the resubmission ALSO rode a (concurrently re-published)
                # dead entry: the cluster is churning faster than the cache
                # invalidates — surface a public error, not the internal
                # retry marker
                raise ExecutionError(
                    f"job {e.job_id}: cached result partitions lost twice "
                    "in a row (executor churn outpacing cache "
                    "invalidation) — retry the query"
                ) from e

    def submit(self, plan: lp.LogicalPlan) -> str:
        """ExecuteQuery only: returns the job id without waiting for (or
        fetching) results — collect() is submit + _collect_results."""
        params = pb.ExecuteQueryParams()
        params.logical_plan.CopyFrom(plan_to_proto(plan))
        # only non-default settings travel: they override scheduler/executor
        # configs per job without clobbering host-local tuning
        for k, v in self.config.explicit_settings().items():
            params.settings.add(key=k, value=v)
        # tenancy rides first-class fields too (ISSUE 7): admission control
        # must not depend on parsing the settings map
        params.tenant = self.config.tenant()
        params.priority = self.config.tenant_priority()
        return self._client.execute_query(params).job_id

    def _collect_results(
        self, job_id: str, schema, timeout: float = 300.0
    ) -> pa.Table:
        """Wait for the job, then fetch each result partition from the
        executor holding it. A fetch failure against the now-TERMINAL job
        (the owner died between completion and this fetch — the scheduler's
        lost-task machinery skips finished jobs, so nobody else notices)
        is reported back via ReportLostPartition: the scheduler requeues
        the lost final-stage tasks through lineage and flips the job back
        to running, and this loop re-polls for the fresh locations instead
        of erroring (ISSUE 6 / PR 5 residue)."""
        from ballista_tpu.errors import ShuffleFetchError

        deadline = time.time() + timeout
        while True:
            status = self._wait_for_job(job_id, max(0.0, deadline - time.time()))
            try:
                tables = [
                    self._fetch_partition(loc)
                    for loc in status.completed.partition_location
                ]
            except ShuffleFetchError as e:
                cached = status.completed.cached
                result = self._client.report_lost_partition(
                    pb.ReportLostPartitionParams(
                        job_id=job_id,
                        executor_id=e.executor_id,
                        stage_id=e.stage_id,
                        partition_id=e.map_partition,
                        path=e.path,
                    )
                )
                if not result.restarted:
                    if cached:
                        # cache-served job: the scheduler invalidated the
                        # entry; collect() resubmits the plan once
                        raise _CachedResultLost(job_id) from e
                    # nothing for the scheduler to restart (or the job
                    # already failed for good): surface the fetch error
                    raise
                from ballista_tpu.ops.runtime import record_recovery

                record_recovery("result_fetch_restarted")
                continue
            if not tables:
                return schema.empty_table()
            return pa.concat_tables(tables).cast(schema)

    def _wait_for_job(self, job_id: str, timeout: float) -> pb.JobStatus:
        deadline = time.time() + timeout
        while time.time() < deadline:
            result = self._client.get_job_status(pb.GetJobStatusParams(job_id=job_id))
            status = result.status
            which = status.WhichOneof("status")
            if which == "completed":
                return status
            if which == "failed":
                raise ExecutionError(f"job {job_id} failed: {status.failed.error}")
            time.sleep(POLL_INTERVAL)
        raise ExecutionError(f"job {job_id} timed out after {timeout}s")

    def _fetch_partition(self, loc: pb.PartitionLocation) -> pa.Table:
        from ballista_tpu.client.flight import BallistaClient
        from ballista_tpu.errors import RpcError, ShuffleFetchError

        try:
            client = BallistaClient(
                loc.executor_meta.host,
                loc.executor_meta.port,
                retries=self.config.rpc_retries(),
                backoff_s=self.config.rpc_backoff_s(),
            )
        except Exception as e:  # connect failure = same lost location
            raise ShuffleFetchError(
                f"result partition unreachable: {e}",
                executor_id=loc.executor_meta.id,
                host=loc.executor_meta.host,
                port=loc.executor_meta.port,
                path=loc.path,
                stage_id=loc.partition_id.stage_id,
                map_partition=loc.partition_id.partition_id,
            ) from e
        try:
            # the final stage writes piece 0 per input partition
            return client.fetch_partition(os.path.join(loc.path, "0.arrow"))
        except RpcError as e:
            # name the lost location so _collect_results can report it to
            # the scheduler (ReportLostPartition) instead of just erroring
            raise ShuffleFetchError(
                f"result partition fetch failed: {e}",
                executor_id=loc.executor_meta.id,
                host=loc.executor_meta.host,
                port=loc.executor_meta.port,
                path=loc.path,
                stage_id=loc.partition_id.stage_id,
                map_partition=loc.partition_id.partition_id,
            ) from e
        finally:
            client.close()

    # -- cluster info ------------------------------------------------------
    def executors(self) -> List[pb.ExecutorMetadata]:
        return list(self._client.get_executors_metadata().metadata)

    def close(self) -> None:
        self._client.close()


class BallistaDataFrame(DataFrame):
    """DataFrame whose collect() executes on the cluster."""

    def _wrap(self, builder: LogicalPlanBuilder) -> "BallistaDataFrame":
        return BallistaDataFrame(self._ctx, builder)

    # rewrap verbs so chaining stays distributed
    def select(self, *exprs) -> "BallistaDataFrame":
        return self._wrap(self._builder.project(list(exprs)))

    def filter(self, predicate) -> "BallistaDataFrame":
        return self._wrap(self._builder.filter(predicate))

    def aggregate(self, group_by, aggs) -> "BallistaDataFrame":
        return self._wrap(self._builder.aggregate(group_by, aggs))

    def sort(self, *exprs) -> "BallistaDataFrame":
        return self._wrap(self._builder.sort(list(exprs)))

    def limit(self, n: int, skip: int = 0) -> "BallistaDataFrame":
        return self._wrap(self._builder.limit(n, skip))

    def collect(self) -> pa.Table:
        return self._ctx.collect(self.logical_plan())

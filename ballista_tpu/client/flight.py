"""BallistaClient: the Flight data-plane client wrapper.

Mirrors the reference's BallistaClient (rust/core/src/client.rs:51-208):
connect to an executor's Flight endpoint and
- execute_partition: run plan partitions remotely (push-based path), returns
  per-partition (path, stats) rows
- fetch_partition: stream a materialized partition back
- execute_action: raw Action round-trip (both of the above go through it)
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

import pyarrow as pa
import pyarrow.flight as flight

from ballista_tpu.distributed.stages import PartitionStats
from ballista_tpu.errors import RpcError
from ballista_tpu.proto import ballista_pb2 as pb


class BallistaClient:
    def __init__(
        self, host: str, port: int, retries: int = 3, backoff_s: float = 0.05
    ) -> None:
        # gRPC channels connect lazily; failures surface per-call with the
        # endpoint attached
        self.host = host
        self.port = port
        # transient (UNAVAILABLE/connect) failures retry with jittered
        # exponential backoff; server-side execution errors surface
        # immediately (retrying them would just re-fail slower)
        self.retries = max(0, retries)
        self.backoff_s = backoff_s
        self._client = flight.connect(f"grpc://{host}:{port}")

    @staticmethod
    def _transient(e: flight.FlightError) -> bool:
        # NOT FlightTimedOutError: a deadline expiring says nothing about
        # whether the server stopped working on the request — retrying an
        # execute_partition whose first run is still going duplicates the
        # execution (shuffle writes themselves are atomic, but the wasted
        # work amplifies exactly when the cluster is slowest)
        return isinstance(e, flight.FlightUnavailableError)

    # ------------------------------------------------------------------
    def execute_action(self, action: pb.Action) -> pa.Table:
        """Encode the Action into a Flight ticket, read the result stream
        (schema-first framing is Flight's own, ref client.rs:134-169).
        Whole-call retry is safe: both actions are idempotent (fetch reads
        an immutable piece; execute_partition rewrites the same files)."""
        from ballista_tpu.scheduler.rpc import backoff_delay

        ticket = flight.Ticket(action.SerializeToString())
        attempts = self.retries + 1
        for i in range(attempts):
            try:
                return self._client.do_get(ticket).read_all()
            except flight.FlightError as e:
                if not self._transient(e) or i + 1 >= attempts:
                    raise RpcError(f"executor {self.host}:{self.port}: {e}") from e
                from ballista_tpu.ops.runtime import record_recovery

                record_recovery("rpc_retry")
                import time

                time.sleep(backoff_delay(i, self.backoff_s))
        raise AssertionError("unreachable")

    def stream_action(self, action: pb.Action):
        """Batch-streaming variant of execute_action. Transient failures
        retry only BEFORE the first batch is yielded — a consumer may have
        acted on earlier batches, so a mid-stream drop must surface (the
        task-level retry machinery re-runs the whole task instead)."""
        from ballista_tpu.scheduler.rpc import backoff_delay

        ticket = flight.Ticket(action.SerializeToString())
        attempts = self.retries + 1
        for i in range(attempts):
            yielded = False
            try:
                reader = self._client.do_get(ticket)
                for chunk in reader:
                    yielded = True
                    yield chunk.data
                return
            except flight.FlightError as e:
                if yielded or not self._transient(e) or i + 1 >= attempts:
                    raise RpcError(f"executor {self.host}:{self.port}: {e}") from e
                from ballista_tpu.ops.runtime import record_recovery

                record_recovery("rpc_retry")
                import time

                time.sleep(backoff_delay(i, self.backoff_s))

    def execute_partition(
        self,
        job_id: str,
        stage_id: int,
        partition_ids: List[int],
        plan,
        settings: Optional[dict] = None,
    ) -> List[Tuple[str, PartitionStats]]:
        """Run plan partitions on the remote executor; returns
        [(shuffle dir path, stats)] — the reference's 1-row-per-partition
        result batch (ref client.rs:76-121)."""
        from ballista_tpu.serde.physical import phys_plan_to_proto

        action = pb.Action()
        action.execute_partition.job_id = job_id
        action.execute_partition.stage_id = stage_id
        action.execute_partition.partition_ids.extend(partition_ids)
        action.execute_partition.plan.CopyFrom(phys_plan_to_proto(plan))
        for k, v in (settings or {}).items():
            action.settings.add(key=k, value=v)
        table = self.execute_action(action)
        out = []
        for row in table.to_pylist():
            out.append(
                (
                    row["path"],
                    PartitionStats(
                        row["num_rows"], row["num_batches"], row["num_bytes"]
                    ),
                )
            )
        return out

    def fetch_partition(self, path: str) -> pa.Table:
        """Fetch one materialized shuffle piece (ref client.rs:123-131)."""
        action = pb.Action()
        action.fetch_partition.path = path
        return self.execute_action(action)

    def close(self) -> None:
        self._client.close()

"""DB-API 2.0 (PEP 249) interface — the Python-native counterpart of the
reference's JDBC driver (reference jvm/jdbc/: jdbc:arrow:// over Flight).

    import ballista_tpu.client.dbapi as db
    conn = db.connect(host="localhost", port=50050)
    cur = conn.cursor()
    cur.execute("select l_returnflag, count(*) from lineitem group by 1")
    print(cur.fetchall())

connect(local=True) runs against an in-process engine instead of a cluster.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

apilevel = "2.0"
threadsafety = 1
paramstyle = "qmark"


class Error(Exception):
    pass


class InterfaceError(Error):
    pass


class DatabaseError(Error):
    pass


def connect(host: str = "localhost", port: int = 50050, local: bool = False,
            settings=None) -> "Connection":
    return Connection(host, port, local, settings)


class Connection:
    def __init__(self, host: str, port: int, local: bool, settings) -> None:
        if local:
            from ballista_tpu.config import BallistaConfig
            from ballista_tpu.engine import ExecutionContext

            self._ctx = ExecutionContext(BallistaConfig(settings))
        else:
            from ballista_tpu.client import BallistaContext

            self._ctx = BallistaContext(host, port, settings)
        self._closed = False

    @property
    def context(self):
        """The underlying context (for table registration)."""
        return self._ctx

    def cursor(self) -> "Cursor":
        if self._closed:
            raise InterfaceError("connection is closed")
        return Cursor(self)

    def commit(self) -> None:
        pass  # queries are read-only

    def rollback(self) -> None:
        pass

    def close(self) -> None:
        self._closed = True
        close = getattr(self._ctx, "close", None)
        if close:
            close()


class Cursor:
    arraysize = 1

    def __init__(self, conn: Connection) -> None:
        self._conn = conn
        self._rows: Optional[List[Tuple]] = None
        self._pos = 0
        self.description: Optional[List[Tuple]] = None
        self.rowcount = -1

    def execute(self, operation: str, parameters: Optional[Sequence[Any]] = None) -> "Cursor":
        if parameters:
            for p in parameters:
                operation = operation.replace("?", _quote(p), 1)
        try:
            table = self._conn._ctx.sql(operation).collect()
        except Exception as e:
            raise DatabaseError(str(e)) from e
        self.description = [
            (f.name, str(f.type), None, None, None, None, f.nullable)
            for f in table.schema
        ]
        cols = [c.to_pylist() for c in table.columns]
        self._rows = list(zip(*cols)) if cols else [()] * table.num_rows
        self.rowcount = table.num_rows
        self._pos = 0
        return self

    def executemany(self, operation: str, seq_of_parameters) -> None:
        for p in seq_of_parameters:
            self.execute(operation, p)

    def fetchone(self) -> Optional[Tuple]:
        if self._rows is None:
            raise InterfaceError("no query executed")
        if self._pos >= len(self._rows):
            return None
        row = self._rows[self._pos]
        self._pos += 1
        return row

    def fetchmany(self, size: Optional[int] = None) -> List[Tuple]:
        size = size or self.arraysize
        out = []
        for _ in range(size):
            row = self.fetchone()
            if row is None:
                break
            out.append(row)
        return out

    def fetchall(self) -> List[Tuple]:
        if self._rows is None:
            raise InterfaceError("no query executed")
        out = list(self._rows[self._pos:])
        self._pos = len(self._rows)
        return out

    def close(self) -> None:
        self._rows = None

    def __iter__(self):
        while True:
            row = self.fetchone()
            if row is None:
                return
            yield row


def _quote(v: Any) -> str:
    if v is None:
        return "NULL"
    if isinstance(v, str):
        return "'" + v.replace("'", "''") + "'"
    return str(v)

"""DB-API 2.0 (PEP 249) interface — the Python-native counterpart of the
reference's JDBC driver (reference jvm/jdbc/: jdbc:arrow:// over Flight,
Driver.java:34-47, FlightConnection/FlightStatement/FlightPreparedStatement/
FlightResultSet + FlightResultSetMetaData + ResultSetHelper).

    import ballista_tpu.client.dbapi as db
    conn = db.connect(host="localhost", port=50050)
    cur = conn.cursor()
    cur.execute("select l_returnflag, count(*) from lineitem group by 1")
    print(cur.fetchall())

connect(local=True) runs against an in-process engine instead of a cluster.

Coverage mirrors the JDBC driver's surface: the full PEP 249 exception
hierarchy mapped from engine errors, parameterized statements (qmark style,
literal-safe substitution — the PreparedStatement analog), a result-set
metadata/type-mapping matrix (Arrow type -> DBAPI type object + precision /
scale / size, the FlightResultSetMetaData analog), and catalog metadata
(tables / columns, the DatabaseMetaData analog).
"""

from __future__ import annotations

import datetime
import time as _time
from typing import Any, List, Optional, Sequence, Tuple

import pyarrow as pa

apilevel = "2.0"
threadsafety = 1
paramstyle = "qmark"


# --- PEP 249 exception hierarchy ------------------------------------------


class Warning(Exception):  # noqa: A001  (PEP 249 mandates the name)
    pass


class Error(Exception):
    pass


class InterfaceError(Error):
    pass


class DatabaseError(Error):
    pass


class DataError(DatabaseError):
    pass


class OperationalError(DatabaseError):
    pass


class IntegrityError(DatabaseError):
    pass


class InternalError(DatabaseError):
    pass


class ProgrammingError(DatabaseError):
    pass


class NotSupportedError(DatabaseError):
    pass


def _map_error(e: Exception) -> DatabaseError:
    from ballista_tpu import errors as be

    if isinstance(e, (be.SqlError, be.PlanError, be.SchemaError)):
        return ProgrammingError(str(e))
    if isinstance(e, be.RpcError):
        return OperationalError(str(e))
    if isinstance(e, be.SerdeError):
        return InternalError(str(e))
    return DatabaseError(str(e))


# --- PEP 249 type objects + constructors ----------------------------------


class _DBAPIType(frozenset):
    """A type object equal to any of its member type names."""

    def __eq__(self, other):  # type: ignore[override]
        if isinstance(other, _DBAPIType):
            return frozenset.__eq__(self, other)
        return other in self

    def __ne__(self, other):  # type: ignore[override]
        return not self.__eq__(other)

    def __hash__(self):
        return frozenset.__hash__(self)


STRING = _DBAPIType({"string", "large_string", "utf8"})
BINARY = _DBAPIType({"binary", "large_binary", "fixed_size_binary"})
NUMBER = _DBAPIType(
    {"int8", "int16", "int32", "int64", "uint8", "uint16", "uint32",
     "uint64", "float", "halffloat", "double", "float32", "float64",
     "decimal128", "decimal256", "bool"}
)
DATETIME = _DBAPIType({"date32", "date64", "timestamp", "time32", "time64"})
ROWID = _DBAPIType(set())

Date = datetime.date
Time = datetime.time
Timestamp = datetime.datetime


def DateFromTicks(ticks: float) -> datetime.date:
    return datetime.date(*_time.localtime(ticks)[:3])


def TimeFromTicks(ticks: float) -> datetime.time:
    return datetime.time(*_time.localtime(ticks)[3:6])


def TimestampFromTicks(ticks: float) -> datetime.datetime:
    return datetime.datetime(*_time.localtime(ticks)[:6])


def Binary(data) -> bytes:
    return bytes(data)


def _type_code(t: pa.DataType) -> _DBAPIType:
    if pa.types.is_string(t) or pa.types.is_large_string(t):
        return STRING
    if pa.types.is_binary(t) or pa.types.is_large_binary(t) or pa.types.is_fixed_size_binary(t):
        return BINARY
    if pa.types.is_temporal(t):
        return DATETIME
    return NUMBER


def _describe_field(f: pa.Field) -> Tuple:
    """(name, type_code, display_size, internal_size, precision, scale,
    null_ok) — the Arrow -> DBAPI type-mapping matrix (the JDBC driver's
    FlightResultSetMetaData role)."""
    t = f.type
    precision = scale = None
    try:
        internal = t.bit_width // 8  # fixed-width types only
    except (ValueError, AttributeError):
        internal = None
    if pa.types.is_decimal(t):
        precision, scale = t.precision, t.scale
    elif pa.types.is_floating(t):
        precision = 15 if t == pa.float64() else 7
    elif pa.types.is_integer(t):
        precision = len(str(2 ** (t.bit_width - 1)))
    return (f.name, _type_code(t), None, internal, precision, scale, f.nullable)


# --- statement parameters --------------------------------------------------


def _quote(v: Any) -> str:
    import decimal

    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "TRUE" if v else "FALSE"
    if isinstance(v, str):
        return "'" + v.replace("'", "''") + "'"
    if isinstance(v, (bytes, bytearray)):
        raise NotSupportedError("binary parameters are not supported in SQL text")
    if isinstance(v, datetime.datetime):
        return "timestamp '" + v.isoformat(sep=" ") + "'"
    if isinstance(v, datetime.date):
        return "date '" + v.isoformat() + "'"
    if isinstance(v, decimal.Decimal):
        return str(v)
    if isinstance(v, (int, float)):
        return repr(v)
    raise ProgrammingError(f"unsupported parameter type {type(v).__name__}")


def _bind(operation: str, parameters: Sequence[Any]) -> str:
    """qmark substitution skipping every construct the SQL lexer treats as
    opaque: '...' literals (with '' escapes), "..." identifiers, -- line
    comments, and /* */ block comments — a naive str.replace corrupts
    queries like WHERE c = 'a?b'."""
    out: List[str] = []
    it = iter(parameters)
    i = 0
    n = len(operation)
    while i < n:
        ch = operation[i]
        if ch == "'" or ch == '"':
            quote = ch
            out.append(ch)
            i += 1
            while i < n:
                out.append(operation[i])
                if operation[i] == quote:
                    if quote == "'" and i + 1 < n and operation[i + 1] == "'":
                        out.append("'")
                        i += 2
                        continue
                    i += 1
                    break
                i += 1
            continue
        if ch == "-" and i + 1 < n and operation[i + 1] == "-":
            end = operation.find("\n", i)
            end = n if end == -1 else end
            out.append(operation[i:end])
            i = end
            continue
        if ch == "/" and i + 1 < n and operation[i + 1] == "*":
            end = operation.find("*/", i + 2)
            end = n if end == -1 else end + 2
            out.append(operation[i:end])
            i = end
            continue
        if ch == "?":
            try:
                out.append(_quote(next(it)))
            except StopIteration:
                raise ProgrammingError("not enough parameters for statement")
            i += 1
            continue
        out.append(ch)
        i += 1
    remaining = sum(1 for _ in it)
    if remaining:
        raise ProgrammingError(f"{remaining} unused parameter(s)")
    return "".join(out)


# --- connection / cursor ---------------------------------------------------


def connect(host: str = "localhost", port: int = 50050, local: bool = False,
            settings=None) -> "Connection":
    return Connection(host, port, local, settings)


class Connection:
    def __init__(self, host: str, port: int, local: bool, settings) -> None:
        if local:
            from ballista_tpu.config import BallistaConfig
            from ballista_tpu.engine import ExecutionContext

            self._ctx = ExecutionContext(BallistaConfig(settings))
        else:
            from ballista_tpu.client import BallistaContext

            self._ctx = BallistaContext(host, port, settings)
        self._closed = False

    @property
    def context(self):
        """The underlying context (for table registration)."""
        return self._ctx

    def cursor(self) -> "Cursor":
        if self._closed:
            raise InterfaceError("connection is closed")
        return Cursor(self)

    def commit(self) -> None:
        pass  # queries are read-only

    def rollback(self) -> None:
        pass

    def close(self) -> None:
        self._closed = True
        close = getattr(self._ctx, "close", None)
        if close:
            close()

    def __enter__(self) -> "Connection":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- catalog metadata (the JDBC DatabaseMetaData analog) -----------
    def get_tables(self) -> List[str]:
        return sorted(getattr(self._ctx, "tables", {}).keys())

    def get_columns(self, table: str) -> List[Tuple]:
        src = getattr(self._ctx, "tables", {}).get(table.lower())
        if src is None:
            raise ProgrammingError(f"no table named {table!r}")
        return [_describe_field(f) for f in src.schema()]


class Cursor:
    arraysize = 1

    def __init__(self, conn: Connection) -> None:
        self._conn = conn
        self._rows: Optional[List[Tuple]] = None
        self._pos = 0
        self.description: Optional[List[Tuple]] = None
        self.rowcount = -1
        self.lastrowid = None

    def execute(self, operation: str, parameters: Optional[Sequence[Any]] = None) -> "Cursor":
        if self._conn._closed:
            raise InterfaceError("connection is closed")
        if parameters is not None:
            operation = _bind(operation, list(parameters))
        try:
            table = self._conn._ctx.sql(operation).collect()
        except Error:
            raise
        except Exception as e:
            raise _map_error(e) from e
        self.description = [_describe_field(f) for f in table.schema]
        cols = [c.to_pylist() for c in table.columns]
        self._rows = list(zip(*cols)) if cols else [()] * table.num_rows
        self.rowcount = table.num_rows
        self._pos = 0
        return self

    def executemany(self, operation: str, seq_of_parameters) -> None:
        for p in seq_of_parameters:
            self.execute(operation, p)

    def fetchone(self) -> Optional[Tuple]:
        if self._rows is None:
            raise InterfaceError("no query executed")
        if self._pos >= len(self._rows):
            return None
        row = self._rows[self._pos]
        self._pos += 1
        return row

    def fetchmany(self, size: Optional[int] = None) -> List[Tuple]:
        size = size or self.arraysize
        out = []
        for _ in range(size):
            row = self.fetchone()
            if row is None:
                break
            out.append(row)
        return out

    def fetchall(self) -> List[Tuple]:
        if self._rows is None:
            raise InterfaceError("no query executed")
        out = list(self._rows[self._pos:])
        self._pos = len(self._rows)
        return out

    def nextset(self) -> None:
        return None

    def setinputsizes(self, sizes) -> None:
        pass

    def setoutputsize(self, size, column=None) -> None:
        pass

    def close(self) -> None:
        self._rows = None

    def __enter__(self) -> "Cursor":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __iter__(self):
        while True:
            row = self.fetchone()
            if row is None:
                return
            yield row

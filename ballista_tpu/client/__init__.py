from ballista_tpu.client.context import BallistaContext, BallistaDataFrame  # noqa: F401

"""Logical optimizer.

The reference delegates optimization to DataFusion (invoked at
rust/scheduler/src/lib.rs:317). Implemented natively here. The headline rule
is projection pushdown: scans read only required columns — essential for
Parquet/TPC-H (lineitem has 16 columns, q6 needs 4) and for keeping
host->device transfer minimal on the TPU path.
"""

from __future__ import annotations

from typing import List, Optional, Set

from ballista_tpu.logical import expr as lx
from ballista_tpu.logical import plan as lp


def optimize_plan(plan: lp.LogicalPlan) -> lp.LogicalPlan:
    plan = push_down_projection(plan, None)
    return plan


# ---------------------------------------------------------------------------
# Projection pushdown
# ---------------------------------------------------------------------------


def _expr_columns(e: lx.Expr, out: Set[str]) -> bool:
    """Collect flat column names; returns False if the expr is opaque
    (contains a subquery), which disables pushdown above it."""
    if isinstance(e, (lx.ScalarSubquery, lx.InSubquery, lx.Exists)):
        return False
    if isinstance(e, lx.Column):
        out.add(e.flat_name())
    ok = True
    for c in e.children():
        ok = _expr_columns(c, out) and ok
    return ok


def _exprs_columns(exprs, out: Set[str]) -> bool:
    ok = True
    for e in exprs:
        ok = _expr_columns(e, out) and ok
    return ok


def _resolve(names: Set[str], schema) -> Set[str]:
    """Normalize required names to the actual field names in a schema
    (an unqualified name may match a qualified field and vice versa)."""
    fields = list(schema.names)
    out: Set[str] = set()
    for n in names:
        if n in fields:
            out.add(n)
            continue
        # unqualified ref matching one qualified field
        matches = [f for f in fields if f.endswith("." + n)]
        if len(matches) == 1:
            out.add(matches[0])
            continue
        bare = n.split(".")[-1]
        if bare in fields:
            out.add(bare)
    return out


def push_down_projection(
    plan: lp.LogicalPlan, required: Optional[Set[str]]
) -> lp.LogicalPlan:
    """required = flat column names needed above this node; None = all."""

    if isinstance(plan, lp.TableScan):
        if required is None:
            return plan
        schema = plan.source.schema()
        req = _resolve(required, schema)
        indices = [i for i, n in enumerate(schema.names) if n in req]
        if not indices:
            indices = [0]  # keep at least one column (e.g. COUNT(*) scans)
        if len(indices) == len(schema.names):
            return plan
        return lp.TableScan(plan.table_name, plan.source, indices, plan.filters)

    if isinstance(plan, lp.Projection):
        used: Set[str] = set()
        ok = _exprs_columns(plan.exprs, used)
        child = push_down_projection(plan.input, used if ok else None)
        return lp.Projection(child, plan.exprs)

    if isinstance(plan, lp.Filter):
        used = set(required) if required is not None else None
        ok = True
        if used is not None:
            ok = _expr_columns(plan.predicate, used)
        child = push_down_projection(plan.input, used if ok else None)
        return lp.Filter(child, plan.predicate)

    if isinstance(plan, lp.Aggregate):
        used = set()
        ok = _exprs_columns(plan.group_exprs, used)
        ok = _exprs_columns(plan.aggr_exprs, used) and ok
        child = push_down_projection(plan.input, used if ok else None)
        return lp.Aggregate(
            child, plan.group_exprs, plan.aggr_exprs,
            exact_floats=getattr(plan, "exact_floats", False),
        )

    if isinstance(plan, lp.Sort):
        used = set(required) if required is not None else None
        ok = True
        if used is not None:
            ok = _exprs_columns(plan.sort_exprs, used)
        child = push_down_projection(plan.input, used if ok else None)
        return lp.Sort(child, plan.sort_exprs)

    if isinstance(plan, lp.Limit):
        child = push_down_projection(plan.input, required)
        return lp.Limit(child, plan.n, plan.skip)

    if isinstance(plan, lp.Repartition):
        used = set(required) if required is not None else None
        ok = True
        if used is not None and plan.scheme == lp.PartitionScheme.HASH:
            ok = _exprs_columns(plan.hash_exprs, used)
        child = push_down_projection(plan.input, used if ok else None)
        return lp.Repartition(child, plan.scheme, plan.n, plan.hash_exprs)

    if isinstance(plan, lp.SubqueryAlias):
        if required is None:
            child = push_down_projection(plan.input, None)
            return lp.SubqueryAlias(child, plan.alias)
        # map required output names -> input names positionally
        out_schema = plan.schema()
        in_schema = plan.input.schema()
        req = _resolve(required, out_schema)
        child_req = {
            in_schema.names[i]
            for i, n in enumerate(out_schema.names)
            if n in req
        }
        if not child_req:
            child_req = {in_schema.names[0]}
        child = push_down_projection(plan.input, child_req)
        # rebuild alias over (possibly narrowed) child — schema recomputed
        return lp.SubqueryAlias(child, plan.alias)

    if isinstance(plan, lp.Join):
        lschema = plan.left.schema()
        rschema = plan.right.schema()
        lnames = set(lschema.names)
        used = set(required) if required is not None else None
        ok = True
        if used is not None:
            for l, r in plan.on:
                used.add(l.flat_name())
                used.add(r.flat_name())
            if plan.filter is not None:
                ok = _expr_columns(plan.filter, used)
        if used is None or not ok:
            lreq = rreq = None
        else:
            resolved_l = _resolve(used, lschema)
            resolved_r = _resolve(used, rschema)
            lreq, rreq = resolved_l, resolved_r
        left = push_down_projection(plan.left, lreq)
        right = push_down_projection(plan.right, rreq)
        # a narrowed child may have dropped columns entirely absent from
        # requirements; Join schema recomputes from children
        return lp.Join(left, right, plan.on, plan.join_type, plan.filter)

    if isinstance(plan, lp.CrossJoin):
        if required is None:
            lreq = rreq = None
        else:
            lreq = _resolve(required, plan.left.schema())
            rreq = _resolve(required, plan.right.schema())
            if not lreq:
                lreq = {plan.left.schema().names[0]}
            if not rreq:
                rreq = {plan.right.schema().names[0]}
        left = push_down_projection(plan.left, lreq)
        right = push_down_projection(plan.right, rreq)
        return lp.CrossJoin(left, right)

    if isinstance(plan, (lp.Distinct, lp.Union, lp.Window, lp.Explain)):
        # these need all input columns (or handled elsewhere)
        children = [push_down_projection(c, None) for c in plan.children()]
        return plan.with_children(children)

    # unknown node: conservative recurse requiring everything
    children = [push_down_projection(c, None) for c in plan.children()]
    if children:
        return plan.with_children(children)
    return plan

"""Incremental execution: result-cache advancement helpers (ISSUE 19).

A repeated aggregate query over a GROWN scan-file set (``files ∪ {new}``)
misses the result cache — the ``result_key`` covers every file's (path,
mtime, size) — even though the cached result already embodies all the old
files' work. When the plan's aggregate state is RESUMABLE, the scheduler
advances instead of recomputing: it runs a delta job over only the new
files through the ordinary planning/ledger machinery, folds the delta's
output into the cached result, and publishes the advanced entry under the
new key. The contract is bit-identity — the advanced result must equal a
cold full run byte for byte — so eligibility is conservative:

- the plan is Sort > [Projection] > Aggregate > (Filter|SubqueryAlias)* >
  file-backed TableScan, the projection a pure rename layer;
- every aggregate member folds by an ORDER-INSENSITIVE merge: count and
  integer sum fold by addition, min/max by themselves. Float sums (f32
  device accumulation is not associative), avg, and DISTINCT aggregates
  decline to a full recompute — recorded (``advance_declined``), never
  silent;
- the output carries a total row order: the Sort keys must cover every
  group column (group keys are unique per row, so re-sorting the folded
  table reproduces the cold run's order), or the aggregate has no group
  columns at all (one row).

The fold itself is plain Arrow host compute over exact types — int64
sums/counts and min/max merge without any floating-point reassociation,
which is what makes the bit-identity contract holdable.
"""

from __future__ import annotations

import logging
import os
from typing import List, Optional, Tuple

import pyarrow as pa

from ballista_tpu.logical import plan as lp
from ballista_tpu.logical.expr import (
    AggregateExpr,
    Alias,
    Column,
)

log = logging.getLogger(__name__)


class FoldSpec:
    """How to merge a cached aggregate result with a delta result.

    keys:      output column names that are group keys (row identity)
    merges:    (output column name, arrow aggregate op) for every member —
               "sum" (covers count), "min", or "max"
    sort_keys: (output column name, ascending) restoring the cold run's
               total row order after the fold
    nulls_first: the (uniform) null placement of the sort
    """

    def __init__(self, keys, merges, sort_keys, nulls_first):
        self.keys: List[str] = keys
        self.merges: List[Tuple[str, str]] = merges
        self.sort_keys: List[Tuple[str, bool]] = sort_keys
        self.nulls_first: bool = nulls_first


def _decline(reason: str) -> None:
    log.info("advancement ineligible: %s", reason)


def fold_spec(plan: lp.LogicalPlan) -> Optional[FoldSpec]:
    """FoldSpec when `plan`'s result is resumable aggregate state, else
    None (with the reason logged). See the module docstring for the
    eligibility contract."""
    p = plan
    sort = None
    if isinstance(p, lp.Sort):
        sort = p
        p = p.input
    if isinstance(p, lp.Limit):
        _decline("LIMIT truncates fold inputs")
        return None
    proj = None
    if isinstance(p, lp.Projection):
        proj = p
        p = p.input
    if not isinstance(p, lp.Aggregate):
        _decline("plan root is not an aggregate")
        return None
    agg = p
    q = agg.input
    while isinstance(q, (lp.Filter, lp.SubqueryAlias)):
        q = q.input
    if not isinstance(q, lp.TableScan):
        _decline(f"aggregate input is {type(q).__name__}, not a plain scan")
        return None
    if not getattr(q.source, "files", None):
        _decline("scan is not file-backed")
        return None

    # role of every aggregate-schema field: group key, or a merge op
    in_schema = agg.input.schema()
    roles = {}
    for ge in agg.group_exprs:
        if not isinstance(ge, Column):
            _decline(f"group key {ge} is not a plain column")
            return None
        roles[ge.output_name()] = "key"
    for ae in agg.aggr_exprs:
        inner = ae.expr if isinstance(ae, Alias) else ae
        if not isinstance(inner, AggregateExpr):
            _decline(f"aggregate member {ae} is not an aggregate function")
            return None
        if inner.distinct:
            _decline(f"{inner} requires the full input (DISTINCT)")
            return None
        if inner.fn == "count":
            role = "sum"  # counts fold by addition
        elif inner.fn in ("min", "max"):
            role = inner.fn
        elif inner.fn == "sum":
            if not pa.types.is_integer(inner.data_type(in_schema)):
                _decline(f"{inner} accumulates floats (not associative on "
                         "the device's f32 lanes)")
                return None
            role = "sum"
        else:
            _decline(f"{inner} has no order-insensitive fold")
            return None
        roles[ae.output_name()] = role

    # the projection must be a pure rename layer over the aggregate output
    out_cols: List[Tuple[str, str]] = []  # (output name, aggregate field)
    if proj is None:
        out_cols = [(n, n) for n in roles]
    else:
        for e in proj.exprs:
            inner = e.expr if isinstance(e, Alias) else e
            if not isinstance(inner, Column) or inner.name not in roles:
                _decline(f"projection expr {e} computes, not renames")
                return None
            out_cols.append((e.output_name(), inner.name))
    names = [n for n, _ in out_cols]
    if len(set(names)) != len(names):
        _decline("duplicate output column names")
        return None
    covered_groups = {src for _, src in out_cols if roles[src] == "key"}
    all_groups = {n for n, r in roles.items() if r == "key"}
    if covered_groups != all_groups:
        _decline("projection drops a group key (fold would merge rows the "
                 "cold run keeps distinct)")
        return None
    keys = [n for n, src in out_cols if roles[src] == "key"]
    merges = [(n, roles[src]) for n, src in out_cols if roles[src] != "key"]

    # total row order: sort keys covering every group key (group rows are
    # unique per key set), or a single global-aggregate row
    sort_keys: List[Tuple[str, bool]] = []
    nulls_first = False
    if keys:
        if sort is None:
            _decline("no ORDER BY: cold-run row order is partition-"
                     "dependent, the fold cannot reproduce it")
            return None
        nf_flags = set()
        for se in sort.sort_exprs:
            inner = se.expr
            if not isinstance(inner, Column) or inner.name not in names:
                _decline(f"sort key {se} is not an output column")
                return None
            sort_keys.append((inner.name, se.ascending))
            nf_flags.add(se.nulls_first)
        if len(nf_flags) > 1:
            _decline("mixed NULLS FIRST/LAST across sort keys")
            return None
        nulls_first = nf_flags.pop()
        if not set(keys) <= {n for n, _ in sort_keys}:
            _decline("ORDER BY does not cover every group key (row order "
                     "among ties is partition-dependent)")
            return None
    return FoldSpec(keys, merges, sort_keys, nulls_first)


# -- delta plan -------------------------------------------------------------

def new_scan_files(facts, base_facts) -> Optional[List[str]]:
    """The file paths a submission's fact set grew over a cached base, or
    None when the delta is not purely additive (a BASE file's identity
    moved — its old fact would be folded in as if still true)."""
    base = set(base_facts)
    cur = set(facts)
    if not base < cur:
        return None
    base_paths = {f.rsplit("|", 2)[0] for f in base}
    new = sorted(cur - base)
    paths = [f.rsplit("|", 2)[0] for f in new]
    if any(p in base_paths for p in paths):
        return None  # moved identity, not an append
    return paths


def build_delta_plan(plan: lp.LogicalPlan, new_file: str) -> lp.LogicalPlan:
    """The same logical plan over ONE new file. Single-file sources are
    serde-clean: ParquetTableSource(file).files == [file], and the proto
    round-trip re-discovers exactly that list, so the delta job's tasks
    recover/requeue like any other job's."""
    from ballista_tpu.datasource import ParquetTableSource

    def rebuild(p: lp.LogicalPlan) -> lp.LogicalPlan:
        if isinstance(p, lp.TableScan):
            return lp.TableScan(
                p.table_name, ParquetTableSource(new_file),
                p.projection, list(p.filters),
            )
        return p.with_children([rebuild(c) for c in p.children()])

    return rebuild(plan)


# -- the fold ---------------------------------------------------------------

def table_to_ipc(table: pa.Table) -> bytes:
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, table.schema) as w:
        w.write_table(table)
    return sink.getvalue().to_pybytes()


def ipc_to_table(raw: bytes) -> pa.Table:
    with pa.ipc.open_stream(pa.BufferReader(raw)) as r:
        return r.read_all()


def fold_tables(
    tables: List[pa.Table], spec: FoldSpec, schema: pa.Schema
) -> pa.Table:
    """Merge the cached result with the delta results into the table a
    cold full run would produce: concatenate, re-group on the key columns
    with each member's fold op, restore the total sort order. All host
    Arrow compute over exact types (int64 sums/counts, min/max) — no
    floating-point reassociation, so bit-identity holds."""
    import pyarrow.compute as pc

    combined = pa.concat_tables(
        [t.select(schema.names).cast(schema) for t in tables]
    )
    if spec.keys:
        folded = combined.group_by(spec.keys, use_threads=False).aggregate(
            list(spec.merges)
        )
        rename = {f"{n}_{op}": n for n, op in spec.merges}
        folded = folded.rename_columns(
            [rename.get(c, c) for c in folded.column_names]
        ).select(schema.names)
    else:
        # global aggregate: one row per input, one row out
        cols = {}
        for n, op in spec.merges:
            fn = {"sum": pc.sum, "min": pc.min, "max": pc.max}[op]
            cols[n] = pa.array(
                [fn(combined.column(n)).as_py()], type=schema.field(n).type
            )
        folded = pa.table(
            {n: cols[n] for n in schema.names}, schema=schema
        )
    folded = folded.cast(schema)
    if spec.sort_keys:
        idx = pc.sort_indices(
            folded,
            sort_keys=[
                (n, "ascending" if asc else "descending")
                for n, asc in spec.sort_keys
            ],
            null_placement="at_start" if spec.nulls_first else "at_end",
        )
        folded = folded.take(idx)
    return folded.combine_chunks()


# -- result fetch (the scheduler acting as a client) ------------------------

def _storage_read(loc, config) -> Optional[pa.Table]:
    """Shared-storage read of a storage-homed partition, confined to the
    scheduler's own configured shuffle dir (mirrors the client's read)."""
    if not loc.storage_uri:
        return None
    root = config.shuffle_dir()
    if not root:
        return None
    from ballista_tpu.executor.confine import resolve_contained

    resolved = resolve_contained(os.path.join(loc.path, "0.arrow"), root)
    if resolved is None or not os.path.exists(resolved):
        return None
    try:
        with pa.ipc.open_file(resolved) as r:
            return r.read_all()
    except Exception:
        return None


def fetch_completed_table(locations, config, schema: pa.Schema) -> pa.Table:
    """All result partitions of a completed job (or cached entry) as one
    table, in partition order — storage first, Flight fallback. Any fetch
    failure raises; the caller declines the advancement and falls back to
    a full recompute (the ordinary lost-partition machinery still guards
    the non-advancement paths)."""
    from ballista_tpu.client.flight import BallistaClient

    tables = []
    for loc in sorted(locations, key=lambda l: l.partition_id.partition_id):
        t = _storage_read(loc, config)
        if t is None:
            client = BallistaClient(
                loc.executor_meta.host,
                loc.executor_meta.port,
                retries=config.rpc_retries(),
                backoff_s=config.rpc_backoff_s(),
            )
            try:
                t = client.fetch_partition(os.path.join(loc.path, "0.arrow"))
            finally:
                client.close()
        tables.append(t)
    if not tables:
        return schema.empty_table()
    return pa.concat_tables(
        [t.cast(schema) for t in tables]
    ).combine_chunks()

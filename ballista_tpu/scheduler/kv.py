"""Cluster-state KV backends.

The reference abstracts all scheduler state behind a KV trait with two
implementations — etcd (distributed) and sled (embedded) — plus a global
lock (reference rust/scheduler/src/state/mod.rs:46-59, etcd.rs, standalone.rs).
Here:

- MemoryBackend: in-process dict (tests, --local mode)
- SqliteBackend: embedded durable store (the sled role; sqlite3 is the
  native embedded engine shipped with CPython)
- EtcdBackend: stub that activates only if a python etcd client is present
  (none is baked into this image; the trait boundary is what matters)

Leases: keys may carry an expiry; expired keys are invisible to get/scan
(the reference gives executor registrations a 60s lease, state/mod.rs:42).
"""

from __future__ import annotations

import contextlib
import os
import sqlite3
import time

from ballista_tpu.utils.locks import make_rlock
from typing import Dict, Iterator, List, Optional, Tuple


class KvBackend:
    def get(self, key: str) -> Optional[bytes]:
        raise NotImplementedError

    def get_prefix(self, prefix: str) -> List[Tuple[str, bytes]]:
        raise NotImplementedError

    def put(self, key: str, value: bytes, lease_seconds: Optional[float] = None) -> None:
        raise NotImplementedError

    def put_all(
        self,
        items: List[Tuple[str, bytes]],
        *,
        compare: Optional[Tuple[str, Optional[bytes]]] = None,
        leases: Optional[List[Tuple[str, bytes, float]]] = None,
    ) -> bool:
        """Atomic multi-put: either every (key, value) lands or none does —
        the crash-safe publish seam for multi-key writes (a job's planning
        output must never be half-visible, ISSUE 6). Backends without real
        transactions must still make the batch all-or-nothing under the
        global lock.

        ISSUE 20 extensions for the replicated control plane:
        - `compare=(key, expected)` turns the batch into a fenced
          compare-and-swap: the batch lands only while `key`'s live value
          equals `expected` (`expected=None` means the key must be ABSENT);
          on mismatch nothing is written and the call returns False. This
          is the fencing rule — a deposed job owner's remembered lease
          value no longer matches, so its stale writes are rejected whole.
        - `leases=[(key, value, ttl_seconds)]` rides TTL-carrying writes in
          the same atomic batch (a job's ownership lease is minted with the
          planning commit, never beside it).

        Returns True when the batch landed."""
        raise NotImplementedError

    # -- lease primitives (ISSUE 20) ------------------------------------
    def lease_grant(self, key: str, value: bytes, ttl_seconds: float) -> None:
        """Write `key` with a TTL: invisible to get/scan after expiry
        unless renewed. Equivalent to put(..., lease_seconds=ttl) on
        embedded backends; etcd mints a real lease handle."""
        self.put(key, value, lease_seconds=ttl_seconds)

    def lease_renew(self, key: str, ttl_seconds: float) -> bool:
        """Extend a live leased key's expiry, preserving its value. Returns
        False when the key is missing or already expired — the caller has
        been deposed and must NOT write as if it still held the lease."""
        raise NotImplementedError

    def delete(self, key: str) -> None:
        """Delete exactly `key`. NOT delete_prefix(key): ledger keys like
        assignments/j/1/2 are string prefixes of assignments/j/1/20."""
        raise NotImplementedError

    def delete_prefix(self, prefix: str) -> None:
        raise NotImplementedError

    @contextlib.contextmanager
    def lock(self) -> Iterator[None]:
        """Global scheduler lock (ref /ballista_global_lock)."""
        raise NotImplementedError


class MemoryBackend(KvBackend):
    def __init__(self) -> None:
        self._data: Dict[str, Tuple[bytes, Optional[float]]] = {}  # guarded-by: self._mu
        self._mu = make_rlock("scheduler.kv.lock")

    # holds-lock: self._mu
    def _live(self, key: str) -> Optional[bytes]:
        item = self._data.get(key)
        if item is None:
            return None
        value, expires = item
        if expires is not None and time.time() > expires:
            del self._data[key]
            return None
        return value

    def get(self, key: str) -> Optional[bytes]:
        with self._mu:
            return self._live(key)

    def get_prefix(self, prefix: str) -> List[Tuple[str, bytes]]:
        with self._mu:
            out = []
            for k in sorted(self._data):
                if k.startswith(prefix):
                    v = self._live(k)
                    if v is not None:
                        out.append((k, v))
            return out

    def put(self, key: str, value: bytes, lease_seconds: Optional[float] = None) -> None:
        with self._mu:
            expires = time.time() + lease_seconds if lease_seconds else None
            self._data[key] = (value, expires)

    def put_all(
        self,
        items: List[Tuple[str, bytes]],
        *,
        compare: Optional[Tuple[str, Optional[bytes]]] = None,
        leases: Optional[List[Tuple[str, bytes, float]]] = None,
    ) -> bool:
        # validate the whole batch before touching the dict so a bad item
        # cannot leave a partial write behind
        staged = [(k, (v, None)) for k, v in items]
        for k, v, ttl in leases or ():
            float(ttl)
        with self._mu:
            if compare is not None and self._live(compare[0]) != compare[1]:
                return False
            now = time.time()
            self._data.update(staged)
            self._data.update(
                (k, (v, now + ttl)) for k, v, ttl in leases or ()
            )
            return True

    def lease_renew(self, key: str, ttl_seconds: float) -> bool:
        with self._mu:
            value = self._live(key)
            if value is None:
                return False
            self._data[key] = (value, time.time() + ttl_seconds)
            return True

    def delete(self, key: str) -> None:
        with self._mu:
            self._data.pop(key, None)

    def delete_prefix(self, prefix: str) -> None:
        with self._mu:
            for k in [k for k in self._data if k.startswith(prefix)]:
                del self._data[k]

    @contextlib.contextmanager
    def lock(self) -> Iterator[None]:
        with self._mu:
            yield


class SqliteBackend(KvBackend):
    """Durable embedded store (the reference's sled role). A restarted
    scheduler process resumes from the same DB file."""

    def __init__(self, path: str) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._path = path
        self._mu = make_rlock("scheduler.kv.lock")
        # one shared connection, serialized by self._mu (sqlite3 objects are
        # not thread-safe under check_same_thread=False without it)
        self._conn = sqlite3.connect(path, check_same_thread=False)  # guarded-by: self._mu
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS kv ("
            "key TEXT PRIMARY KEY, value BLOB NOT NULL, expires REAL)"
        )
        self._conn.commit()

    @classmethod
    def temporary(cls) -> "SqliteBackend":
        """In-memory sqlite for tests (ref StandaloneClient::try_new_temporary)."""
        obj = cls.__new__(cls)
        obj._path = ":memory:"
        obj._mu = make_rlock("scheduler.kv.lock")
        obj._conn = sqlite3.connect(":memory:", check_same_thread=False)
        obj._conn.execute(
            "CREATE TABLE IF NOT EXISTS kv ("
            "key TEXT PRIMARY KEY, value BLOB NOT NULL, expires REAL)"
        )
        obj._conn.commit()
        return obj

    def get(self, key: str) -> Optional[bytes]:
        with self._mu:
            row = self._conn.execute(
                "SELECT value, expires FROM kv WHERE key = ?", (key,)
            ).fetchone()
            if row is None:
                return None
            value, expires = row
            if expires is not None and time.time() > expires:
                self._conn.execute("DELETE FROM kv WHERE key = ?", (key,))
                self._conn.commit()
                return None
            return value

    def get_prefix(self, prefix: str) -> List[Tuple[str, bytes]]:
        with self._mu:
            rows = self._conn.execute(
                "SELECT key, value, expires FROM kv WHERE key >= ? AND key < ? ORDER BY key",
                (prefix, prefix + "￿"),
            ).fetchall()
            now = time.time()
            out = []
            for k, v, exp in rows:
                if exp is not None and now > exp:
                    continue
                out.append((k, v))
            return out

    def put(self, key: str, value: bytes, lease_seconds: Optional[float] = None) -> None:
        with self._mu:
            expires = time.time() + lease_seconds if lease_seconds else None
            self._conn.execute(
                "INSERT OR REPLACE INTO kv (key, value, expires) VALUES (?, ?, ?)",
                (key, value, expires),
            )
            self._conn.commit()

    def put_all(
        self,
        items: List[Tuple[str, bytes]],
        *,
        compare: Optional[Tuple[str, Optional[bytes]]] = None,
        leases: Optional[List[Tuple[str, bytes, float]]] = None,
    ) -> bool:
        # one sqlite transaction: a crash (or a bad item) mid-batch rolls
        # the whole publish back — this is the backend-transaction form of
        # the ISSUE 6 all-or-nothing planning write. The fenced compare
        # reads under the same lock+transaction, so the check-then-write
        # is atomic against every other writer of this store.
        with self._mu:
            try:
                if compare is not None:
                    ckey, expected = compare
                    row = self._conn.execute(
                        "SELECT value, expires FROM kv WHERE key = ?", (ckey,)
                    ).fetchone()
                    current = None
                    if row is not None:
                        value, exp = row
                        if exp is None or time.time() <= exp:
                            current = bytes(value)
                    if current != expected:
                        self._conn.rollback()
                        return False
                self._conn.executemany(
                    "INSERT OR REPLACE INTO kv (key, value, expires) "
                    "VALUES (?, ?, NULL)",
                    items,
                )
                if leases:
                    now = time.time()
                    self._conn.executemany(
                        "INSERT OR REPLACE INTO kv (key, value, expires) "
                        "VALUES (?, ?, ?)",
                        [(k, v, now + ttl) for k, v, ttl in leases],
                    )
                self._conn.commit()
                return True
            except BaseException:
                self._conn.rollback()
                raise

    def lease_renew(self, key: str, ttl_seconds: float) -> bool:
        with self._mu:
            now = time.time()
            cur = self._conn.execute(
                "UPDATE kv SET expires = ? WHERE key = ? "
                "AND (expires IS NULL OR expires > ?)",
                (now + ttl_seconds, key, now),
            )
            self._conn.commit()
            return cur.rowcount > 0

    def delete(self, key: str) -> None:
        with self._mu:
            self._conn.execute("DELETE FROM kv WHERE key = ?", (key,))
            self._conn.commit()

    def delete_prefix(self, prefix: str) -> None:
        with self._mu:
            self._conn.execute(
                "DELETE FROM kv WHERE key >= ? AND key < ?",
                (prefix, prefix + "￿"),
            )
            self._conn.commit()

    @contextlib.contextmanager
    def lock(self) -> Iterator[None]:
        with self._mu:
            yield


class EtcdBackend(KvBackend):
    """Distributed backend over etcd's v3 API. Activates only when a python
    etcd3 client library is importable; the image ships none, so multi-
    scheduler HA deployments bring their own (the trait is the contract)."""

    def __init__(self, endpoints: str) -> None:
        try:
            import etcd3  # type: ignore
        except ImportError as e:
            raise RuntimeError(
                "etcd backend requires the 'etcd3' package; "
                "use MemoryBackend or SqliteBackend instead"
            ) from e
        host, _, port = endpoints.partition(":")
        self._client = etcd3.client(host=host, port=int(port or 2379))
        self._lock_name = "/ballista_global_lock"
        # lease handles this client granted, keyed by the key they guard:
        # etcd renews through the handle (keepalive), not through the key
        self._leases: Dict[str, object] = {}

    def get(self, key: str) -> Optional[bytes]:
        value, _ = self._client.get(key)
        return value

    def get_prefix(self, prefix: str) -> List[Tuple[str, bytes]]:
        return [
            (meta.key.decode(), value)
            for value, meta in self._client.get_prefix(prefix, sort_order="ascend")
        ]

    def put(self, key: str, value: bytes, lease_seconds: Optional[float] = None) -> None:
        # etcd leases are whole seconds with a 1s minimum; round up so a
        # sub-second lease never truncates to "no expiry"
        import math

        lease = (
            self._client.lease(max(1, math.ceil(lease_seconds)))
            if lease_seconds
            else None
        )
        self._client.put(key, value, lease=lease)

    # etcd rejects transactions above --max-txn-ops (default 128); a plan
    # batch beyond it cannot be published atomically on a default server
    MAX_TXN_OPS = 128

    def put_all(
        self,
        items: List[Tuple[str, bytes]],
        *,
        compare: Optional[Tuple[str, Optional[bytes]]] = None,
        leases: Optional[List[Tuple[str, bytes, float]]] = None,
    ) -> bool:
        # etcd v3 transaction; the fenced form compares the guard key's
        # live VALUE (version==0 for expect-absent) in the same txn, which
        # is exactly etcd's native compare-and-swap
        import math

        n = len(items) + len(leases or ())
        if n > self.MAX_TXN_OPS:
            # fail LOUDLY instead of letting the server reject with an
            # opaque error (or silently splitting and losing atomicity):
            # the deployment must raise --max-txn-ops to plan jobs with
            # this many stages x partitions
            raise RuntimeError(
                f"atomic batch of {n} keys exceeds etcd's default "
                f"max-txn-ops ({self.MAX_TXN_OPS}); raise --max-txn-ops on "
                "the etcd server (and MAX_TXN_OPS here) or reduce "
                "ballista.shuffle.partitions"
            )
        compares = []
        if compare is not None:
            ckey, expected = compare
            if expected is None:
                compares = [self._client.transactions.version(ckey) == 0]
            else:
                compares = [self._client.transactions.value(ckey) == expected]
        ops = [self._client.transactions.put(k, v) for k, v in items]
        for k, v, ttl in leases or ():
            handle = self._client.lease(max(1, math.ceil(ttl)))
            self._leases[k] = handle
            ops.append(self._client.transactions.put(k, v, lease=handle))
        ok, _responses = self._client.transaction(
            compare=compares, success=ops, failure=[]
        )
        return bool(ok)

    def lease_grant(self, key: str, value: bytes, ttl_seconds: float) -> None:
        import math

        handle = self._client.lease(max(1, math.ceil(ttl_seconds)))
        self._leases[key] = handle
        self._client.put(key, value, lease=handle)

    def lease_renew(self, key: str, ttl_seconds: float) -> bool:
        current = self.get(key)
        if current is None:
            # expired (or never ours): the handle, if any, is dead weight
            self._leases.pop(key, None)
            return False
        handle = self._leases.get(key)
        if handle is not None:
            handle.refresh()
            return True
        # live key granted by another client (e.g. adopted after a peer
        # died mid-TTL): re-grant under a fresh lease, preserving the value
        self.lease_grant(key, current, ttl_seconds)
        return True

    def delete(self, key: str) -> None:
        self._client.delete(key)

    def delete_prefix(self, prefix: str) -> None:
        self._client.delete_prefix(prefix)

    @contextlib.contextmanager
    def lock(self) -> Iterator[None]:
        with self._client.lock(self._lock_name):
            yield

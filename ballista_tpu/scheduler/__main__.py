"""Scheduler daemon: python -m ballista_tpu.scheduler [--port 50050 ...]

(ref rust/scheduler/src/main.rs: parse config, pick state backend, serve.)
"""

from __future__ import annotations

import logging
import time

from ballista_tpu.daemon_config import SCHEDULER_SPEC, load_config
from ballista_tpu.scheduler.kv import EtcdBackend, MemoryBackend, SqliteBackend
from ballista_tpu.scheduler.server import SchedulerServer, serve


def main() -> None:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s %(message)s",
    )
    cfg = load_config(
        SCHEDULER_SPEC,
        "BALLISTA_SCHEDULER_",
        "/etc/ballista/scheduler.toml",
        prog="ballista-scheduler",
    )
    backend = cfg["config_backend"].lower()
    if backend == "etcd":
        kv = EtcdBackend(cfg["etcd_urls"])
    elif backend == "sqlite":
        kv = SqliteBackend(cfg["sqlite_path"])
    else:
        kv = MemoryBackend()
    from ballista_tpu.config import BallistaConfig

    impl = SchedulerServer(
        kv,
        namespace=cfg["namespace"],
        config=BallistaConfig(
            {"ballista.executor.data_roots": cfg["data_roots"]}
        ),
    )
    server = serve(impl, cfg["bind_host"], cfg["port"])
    logging.getLogger("ballista.scheduler").info(
        "Ballista-TPU scheduler up (backend=%s, namespace=%s, port=%s)",
        backend, cfg["namespace"], cfg["port"],
    )
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.stop(grace=2)


if __name__ == "__main__":
    main()

"""gRPC plumbing for the SchedulerGrpc service.

The service contract lives in ballista.proto (ref proto:594-605). The grpc
codegen plugin isn't in this toolchain, so the server registration and the
client stub are written over grpcio's generic API — same wire behavior as
generated stubs (method paths /ballista.SchedulerGrpc/<Method>).
"""

from __future__ import annotations

from typing import Optional

import grpc

from ballista_tpu.proto import ballista_pb2 as pb

SERVICE_NAME = "ballista.SchedulerGrpc"

# serialized logical plans embed in-memory table data; gRPC's 4MB default
# rejects them for anything but toy tables. 256MB matches the data sizes the
# memory-scan path is meant for — file-backed scans ship only paths.
_MAX_MSG = 256 * 1024 * 1024
GRPC_MESSAGE_OPTIONS = [
    ("grpc.max_send_message_length", _MAX_MSG),
    ("grpc.max_receive_message_length", _MAX_MSG),
]

_METHODS = {
    "ExecuteQuery": (pb.ExecuteQueryParams, pb.ExecuteQueryResult),
    "PollWork": (pb.PollWorkParams, pb.PollWorkResult),
    "GetJobStatus": (pb.GetJobStatusParams, pb.GetJobStatusResult),
    "GetExecutorsMetadata": (pb.GetExecutorMetadataParams, pb.GetExecutorMetadataResult),
    "GetFileMetadata": (pb.GetFileMetadataParams, pb.GetFileMetadataResult),
}


def add_scheduler_service(server: grpc.Server, servicer) -> None:
    handlers = {}
    for name, (req_cls, resp_cls) in _METHODS.items():
        method = getattr(servicer, name)

        def make(method):
            def handle(request, context):
                return method(request, context)

            return handle

        handlers[name] = grpc.unary_unary_rpc_method_handler(
            make(method),
            request_deserializer=req_cls.FromString,
            response_serializer=lambda m: m.SerializeToString(),
        )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE_NAME, handlers),)
    )


class SchedulerGrpcClient:
    """Client stub (plays the role of tonic's generated SchedulerGrpcClient)."""

    def __init__(self, host: str, port: int, channel: Optional[grpc.Channel] = None) -> None:
        self.channel = channel or grpc.insecure_channel(
            f"{host}:{port}", options=GRPC_MESSAGE_OPTIONS
        )
        self._stubs = {}
        for name, (req_cls, resp_cls) in _METHODS.items():
            self._stubs[name] = self.channel.unary_unary(
                f"/{SERVICE_NAME}/{name}",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=resp_cls.FromString,
            )

    def _call(self, name: str, params):
        from ballista_tpu.errors import RpcError

        try:
            return self._stubs[name](params)
        except grpc.RpcError as e:
            detail = e.details() if hasattr(e, "details") else str(e)
            raise RpcError(f"{name} failed: {detail}") from e

    def execute_query(self, params: pb.ExecuteQueryParams) -> pb.ExecuteQueryResult:
        return self._call("ExecuteQuery", params)

    def poll_work(self, params: pb.PollWorkParams) -> pb.PollWorkResult:
        return self._call("PollWork", params)

    def get_job_status(self, params: pb.GetJobStatusParams) -> pb.GetJobStatusResult:
        return self._call("GetJobStatus", params)

    def get_executors_metadata(self) -> pb.GetExecutorMetadataResult:
        return self._call("GetExecutorsMetadata", pb.GetExecutorMetadataParams())

    def get_file_metadata(self, params: pb.GetFileMetadataParams) -> pb.GetFileMetadataResult:
        return self._call("GetFileMetadata", params)

    def close(self) -> None:
        self.channel.close()

"""gRPC plumbing for the SchedulerGrpc service.

The service contract lives in ballista.proto (ref proto:594-605). The grpc
codegen plugin isn't in this toolchain, so the server registration and the
client stub are written over grpcio's generic API — same wire behavior as
generated stubs (method paths /ballista.SchedulerGrpc/<Method>).
"""

from __future__ import annotations

import random
import threading
import time
from typing import Optional

import grpc

from ballista_tpu.proto import ballista_pb2 as pb
from ballista_tpu.utils.locks import make_lock

SERVICE_NAME = "ballista.SchedulerGrpc"

# serialized logical plans embed in-memory table data; gRPC's 4MB default
# rejects them for anything but toy tables. 256MB matches the data sizes the
# memory-scan path is meant for — file-backed scans ship only paths.
_MAX_MSG = 256 * 1024 * 1024
GRPC_MESSAGE_OPTIONS = [
    ("grpc.max_send_message_length", _MAX_MSG),
    ("grpc.max_receive_message_length", _MAX_MSG),
    # a scheduler restart closes every channel (GOAWAY); grpc's default
    # ~1s initial TCP reconnect backoff would outlast the app-level retry
    # budget (ballista.rpc.retries x backoff_ms), so a client whose
    # reconnect attempt lands in the tiny rebind gap reported "connection
    # refused" for a full second. Restarts are routine here (ISSUE 6
    # crash tolerance, rolling deploys): reconnect fast, cap at 1s.
    ("grpc.initial_reconnect_backoff_ms", 50),
    ("grpc.min_reconnect_backoff_ms", 50),
    ("grpc.max_reconnect_backoff_ms", 1000),
]

_METHODS = {
    "ExecuteQuery": (pb.ExecuteQueryParams, pb.ExecuteQueryResult),
    "PollWork": (pb.PollWorkParams, pb.PollWorkResult),
    "GetJobStatus": (pb.GetJobStatusParams, pb.GetJobStatusResult),
    "GetExecutorsMetadata": (pb.GetExecutorMetadataParams, pb.GetExecutorMetadataResult),
    "GetFileMetadata": (pb.GetFileMetadataParams, pb.GetFileMetadataResult),
    "ReportLostPartition": (
        pb.ReportLostPartitionParams,
        pb.ReportLostPartitionResult,
    ),
}

# server-streaming methods (ISSUE 8/11): the response type streams. Kept in
# a separate table because the handler/stub constructors differ.
_STREAM_METHODS = {
    "SubscribeWork": (pb.SubscribeWorkParams, pb.TaskDefinition),
    "SubscribeJobStatus": (pb.GetJobStatusParams, pb.GetJobStatusResult),
}


def add_scheduler_service(server: grpc.Server, servicer) -> None:
    handlers = {}
    for name, (req_cls, resp_cls) in _METHODS.items():
        method = getattr(servicer, name)

        def make(method):
            def handle(request, context):
                return method(request, context)

            return handle

        handlers[name] = grpc.unary_unary_rpc_method_handler(
            make(method),
            request_deserializer=req_cls.FromString,
            response_serializer=lambda m: m.SerializeToString(),
        )
    for name, (req_cls, resp_cls) in _STREAM_METHODS.items():
        method = getattr(servicer, name, None)
        if method is None:
            continue  # wire compat: pre-ISSUE-8 servicers have no stream

        def make_stream(method):
            def handle(request, context):
                return method(request, context)

            return handle

        handlers[name] = grpc.unary_stream_rpc_method_handler(
            make_stream(method),
            request_deserializer=req_cls.FromString,
            response_serializer=lambda m: m.SerializeToString(),
        )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE_NAME, handlers),)
    )


def backoff_delay(attempt: int, base: float, cap: float = 2.0) -> float:
    """Jittered exponential backoff: base * 2^attempt scaled by a uniform
    [0.5, 1.5) jitter so a fleet of retrying clients decorrelates, then
    capped — the cap is a hard ceiling (an executor sleeping past it eats
    into its heartbeat/lease budget). The jitter draws from the module rng —
    it shapes TIMING only, never results, so it stays outside the
    deterministic chaos machinery."""
    if base <= 0.0:
        return 0.0
    return min(cap, base * (2.0 ** attempt) * random.uniform(0.5, 1.5))


class SchedulerGrpcClient:
    """Client stub (plays the role of tonic's generated SchedulerGrpcClient).

    Transient failures (UNAVAILABLE / connect errors — a scheduler restart,
    a network blip) are retried `retries` times with jittered exponential
    backoff; execution errors surface immediately. An armed chaos injector
    (utils/chaos.py "rpc.call" site) exercises exactly this loop.

    Replicated control plane (ISSUE 20): the client may hold a LIST of
    scheduler endpoints. Calls go to the active endpoint; every transient
    failure rotates to the next before retrying, so a dead replica (or an
    ownership redirect, which the replicas answer as UNAVAILABLE naming
    the owner) re-homes the caller within one retry loop. Channels are
    built lazily per endpoint and all share one options/backoff config."""

    def __init__(
        self,
        host: str,
        port: int,
        channel: Optional[grpc.Channel] = None,
        retries: int = 3,
        backoff_s: float = 0.05,
        chaos=None,
        endpoints=None,
    ) -> None:
        # (host, port) stays endpoint 0 for wire compat; `endpoints` adds
        # failover peers in preference order (duplicates of endpoint 0 drop)
        self.endpoints = [(host, int(port))]
        for ep in endpoints or ():
            ep = (ep[0], int(ep[1]))
            if ep not in self.endpoints:
                self.endpoints.append(ep)
        self._channels: dict = {}
        if channel is not None:
            self._channels[0] = channel
        self._active = 0
        self._stub_cache: dict = {}
        self.retries = max(0, retries)
        self.backoff_s = backoff_s
        self.chaos = chaos
        self._chaos_mu = make_lock("scheduler.rpc._chaos_mu")
        # method -> call count
        # guarded-by: self._chaos_mu
        self._chaos_calls: dict = {}

    @property
    def channel(self) -> grpc.Channel:
        """The ACTIVE endpoint's channel (wire compat with single-endpoint
        callers that reach in for it)."""
        return self._channel(self._active)

    def _channel(self, idx: int) -> grpc.Channel:
        ch = self._channels.get(idx)
        if ch is None:
            h, p = self.endpoints[idx]
            ch = grpc.insecure_channel(
                f"{h}:{p}", options=GRPC_MESSAGE_OPTIONS
            )
            self._channels[idx] = ch
        return ch

    def _stub(self, name: str, stream: bool = False):
        idx = self._active
        key = (idx, name)
        stub = self._stub_cache.get(key)
        if stub is None:
            factory = (
                self._channel(idx).unary_stream
                if stream
                else self._channel(idx).unary_unary
            )
            resp_cls = (_STREAM_METHODS if stream else _METHODS)[name][1]
            stub = factory(
                f"/{SERVICE_NAME}/{name}",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=resp_cls.FromString,
            )
            self._stub_cache[key] = stub
        return stub

    def active_endpoint(self):
        return self.endpoints[self._active]

    def rotate_endpoint(self) -> None:
        """Advance to the next endpoint (no-op with one). Benign under
        concurrent callers: _active is a plain index and every value of it
        names a valid endpoint."""
        if len(self.endpoints) > 1:
            self._active = (self._active + 1) % len(self.endpoints)

    def prefer_endpoint(self, addr: str) -> bool:
        """Jump to the endpoint named by a `host:port` ownership hint
        (GetJobStatusResult.owner_addr). True iff this SWITCHED the active
        endpoint; unknown addresses are ignored — the hint optimizes
        rotation, it never widens the configured endpoint set."""
        host, _, port = addr.rpartition(":")
        try:
            ep = (host, int(port))
        except ValueError:
            return False
        if ep not in self.endpoints or ep == self.endpoints[self._active]:
            return False
        self._active = self.endpoints.index(ep)
        return True

    def _prefer_from_detail(self, detail: str) -> bool:
        """Parse a replica's ownership-redirect detail (`... owned by peer
        replica '<id>' at <host:port>; ...`) and jump to the named owner.
        False when the detail carries no usable hint."""
        if "owned by peer replica" not in detail:
            return False
        _, _, rest = detail.partition(" at ")
        addr = rest.split(";", 1)[0].strip()
        return bool(addr) and self.prefer_endpoint(addr)

    def _chaos_key(self, name: str) -> str:
        # per-method call index: a RETRY of a failed call draws a fresh
        # deterministic verdict instead of failing forever
        with self._chaos_mu:
            n = self._chaos_calls.get(name, 0) + 1
            self._chaos_calls[name] = n
        return f"{name}/{n}"

    def _call(self, name: str, params, also_transient=None):
        """One RPC with the transient-retry loop. `also_transient` is an
        optional predicate over the error detail string for responses a
        specific method knows to be retryable (e.g. the GetFileMetadata
        throttle hint) even though their status code says otherwise."""
        from ballista_tpu.errors import RpcError
        from ballista_tpu.ops.runtime import record_recovery
        from ballista_tpu.utils.chaos import ChaosInjected

        attempts = self.retries + 1
        for i in range(attempts):
            try:
                if self.chaos is not None:
                    self.chaos.maybe_fail("rpc.call", self._chaos_key(name))
                return self._stub(name)(params)
            except ChaosInjected as e:
                transient, detail, err = True, str(e), e
            except grpc.RpcError as e:
                code = e.code() if hasattr(e, "code") else None
                detail = e.details() if hasattr(e, "details") else str(e)
                # UNAVAILABLE covers both "server not up yet" (connect
                # refused) and "went away mid-call". CANCELLED is the other
                # went-away shape (ISSUE 11): a scheduler crash/restart
                # stops its gRPC server, which GOAWAYs in-flight unary
                # calls as CANCELLED — for a crash-tolerant client that is
                # the same transient as UNAVAILABLE (this client never
                # cancels its own unary calls). Anything else is the
                # server actually answering — surface it immediately.
                transient = code in (
                    grpc.StatusCode.UNAVAILABLE, grpc.StatusCode.CANCELLED
                ) or (
                    also_transient is not None and also_transient(detail)
                )
                err = e
            if not transient or i + 1 >= attempts:
                raise RpcError(f"{name} failed: {detail}") from err
            record_recovery("rpc_retry")
            # replica failover (ISSUE 20): try another endpoint before
            # sleeping — a dead or redirecting replica should cost one
            # backoff step, not the whole retry budget. An ownership
            # redirect names the owner in its detail; jump straight there
            # when it is a configured endpoint, else rotate blind.
            if not self._prefer_from_detail(detail):
                self.rotate_endpoint()
            time.sleep(backoff_delay(i, self.backoff_s))
        raise AssertionError("unreachable")  # loop always returns or raises

    def execute_query(self, params: pb.ExecuteQueryParams) -> pb.ExecuteQueryResult:
        return self._call("ExecuteQuery", params)

    def poll_work(self, params: pb.PollWorkParams) -> pb.PollWorkResult:
        return self._call("PollWork", params)

    def subscribe_work(self, params: pb.SubscribeWorkParams):
        """Open the push-dispatch stream (ISSUE 8). Returns the live gRPC
        call object — an iterator of TaskDefinition that also supports
        .cancel(). NO retry wrapper here: stream life-cycle (reconnect with
        backoff, fallback to polling while down) belongs to the subscribe
        loop in executor/execution_loop.py, which must observe every drop.
        Opens against the ACTIVE endpoint — after a failover rotated the
        client, a re-subscribe lands on the adopting replica."""
        return self._stub("SubscribeWork", stream=True)(params)

    def get_job_status(self, params: pb.GetJobStatusParams) -> pb.GetJobStatusResult:
        return self._call("GetJobStatus", params)

    def subscribe_job_status(self, params: pb.GetJobStatusParams):
        """Open the push job-status stream (ISSUE 11). Returns the live
        gRPC call object — an iterator of GetJobStatusResult that also
        supports .cancel(). NO retry wrapper, like subscribe_work: the
        client's status-watch helper owns fallback-to-polling on any drop.
        Opens against the ACTIVE endpoint (re-homed by owner_addr hints)."""
        return self._stub("SubscribeJobStatus", stream=True)(params)

    def get_executors_metadata(self) -> pb.GetExecutorMetadataResult:
        return self._call("GetExecutorsMetadata", pb.GetExecutorMetadataParams())

    def report_lost_partition(
        self, params: pb.ReportLostPartitionParams
    ) -> pb.ReportLostPartitionResult:
        return self._call("ReportLostPartition", params)

    def get_file_metadata(self, params: pb.GetFileMetadataParams) -> pb.GetFileMetadataResult:
        """GetFileMetadata with throttle handling: the server sheds load
        with a fail-fast 'too many concurrent metadata requests; retry'
        error (scheduler/server.py caps its slots); honor the hint with the
        shared backoff loop instead of surfacing it to the caller."""
        return self._call(
            "GetFileMetadata",
            params,
            also_transient=lambda detail: (
                "too many concurrent metadata requests" in detail
            ),
        )

    def close(self) -> None:
        for ch in self._channels.values():
            ch.close()

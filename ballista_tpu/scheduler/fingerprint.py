"""Normalized logical-plan fingerprints for the result / plan caches.

The persisted layout cache (ops/kernels.py stage keys -> ops/layout_cache.py)
established the "fully file-backed identity" rule: an artifact may only be
reused across processes when its key covers EVERY input's identity — file
paths + mtimes for file-backed scans, embedded content for memory tables —
so a rewritten input misses cleanly instead of silently serving stale data.
This module applies the same rule one level up, to whole queries:

- ``content_key``  hashes the serialized logical plan proto (memory-table
  data rides inside it as Arrow IPC bytes, so it is content-addressed by
  construction) plus every result-affecting setting. This is the CROSS-JOB
  identity of "the same query over the same sources": the scheduler's
  physical-plan cache keys on it, so N tenants submitting the same
  dashboard query pay optimize+planning once.
- ``result_key``   extends the content key with each scan file's (path,
  mtime, size) triple. This keys the RESULT cache: touching an input
  file's mtime changes the key, so the stale entry is simply never found
  again (invalidation by construction, exactly like the layout cache).

Tenancy settings (``ballista.tenant.*``) are EXCLUDED from both keys: the
whole point of the artifact economy is that tenants share; admission
control isolates their execution, not their cache lines. A plan with any
non-file, non-memory source (or a missing file), or containing a VOLATILE
scalar function (now() — its value depends on when the query runs, not on
its inputs), is not fingerprintable and returns None — an un-keyable
query must never produce a cache entry.
"""

from __future__ import annotations

import hashlib
import os
from typing import Dict, Optional, Tuple

from ballista_tpu.logical import plan as lp


def _walk_sources(plan: lp.LogicalPlan):
    if isinstance(plan, lp.TableScan):
        yield plan.source
    for c in plan.children():
        yield from _walk_sources(c)


# scalar functions whose value depends on WHEN the query runs, not on its
# inputs (physical/expr.py evaluates now() at execution time): a plan
# containing one has no stable result identity and must never be cached
_VOLATILE_FNS = frozenset({"now"})


def _has_volatile_fn(msg) -> bool:
    """Recursively scan a plan proto for ScalarFunctionNode.fn in the
    volatile set — proto-level, so every expression position (filters,
    projections, join filters, subquery rewrites) is covered without
    tracking the logical expr shapes."""
    from google.protobuf.message import Message

    if type(msg).__name__ == "ScalarFunctionNode" and msg.fn in _VOLATILE_FNS:
        return True
    for fd, value in msg.ListFields():
        if fd.type != fd.TYPE_MESSAGE:
            continue
        # a repeated message field lists as a container, a singular one as
        # the Message itself (ducks around the deprecated fd.label API)
        children = (value,) if isinstance(value, Message) else value
        if any(_has_volatile_fn(v) for v in children):
            return True
    return False


def _settings_component(settings: Dict[str, str]) -> str:
    """Result-affecting settings, canonically ordered. Tenancy keys are
    excluded (tenants share cache lines); so is ballista.cache.advance —
    advancement is bit-identical to a cold run by contract (ISSUE 19), so
    advance-on and advance-off clients must share content keys. Everything
    else a client set participates — backend choice, batch size, chaos
    arming etc. can all change result bytes or execution shape, and a
    false cache hit across them would be silent corruption."""
    items = sorted(
        (k, v) for k, v in settings.items()
        if not k.startswith("ballista.tenant.")
        and k != "ballista.cache.advance"
    )
    return ";".join(f"{k}={v}" for k, v in items)


def plan_file_facts(plan: lp.LogicalPlan) -> Optional[list]:
    """Every scan file's ``path|mtime|size`` fact, or None when any source
    is neither file-backed nor content-embedded (or a file is unstattable).
    The facts are the per-file half of ``result_key`` — and the unit of the
    advancement probe (ISSUE 19): a cached entry whose fact set is a strict
    subset of a new submission's facts covers a prefix of its inputs."""
    file_facts = []
    for src in _walk_sources(plan):
        files = getattr(src, "files", None)
        if files:
            for f in files:
                try:
                    st = os.stat(f)
                except OSError:
                    return None  # identity does not cover this leaf
                file_facts.append(f"{f}|{st.st_mtime}|{st.st_size}")
        elif getattr(src, "partitions", None) is not None:
            # memory table: its data serializes INTO the plan proto as
            # Arrow IPC partitions, so the content hash already covers it
            continue
        else:
            return None  # neither file-backed nor content-embedded
    return file_facts


def plan_fingerprint(
    plan: lp.LogicalPlan, settings: Dict[str, str], file_facts=None
) -> Optional[Tuple[str, str]]:
    """(content_key, result_key) for a fully identifiable plan, else None.

    content_key: sha256 over (plan proto bytes, result-affecting settings).
    result_key:  sha256 over (content_key, sorted (path, mtime, size) of
    every scan file) — the result-cache identity with mtime invalidation
    built into the key.

    Pass `file_facts` (from plan_file_facts) when the caller already holds
    them, so the key and the caller's fact set are built from ONE stat per
    file — a file rewritten between two stats must not leave a cache entry
    whose scan_fact disagrees with the result_key it sits under.
    """
    from ballista_tpu.proto import ballista_pb2 as pb  # noqa: F401
    from ballista_tpu.serde.logical import plan_to_proto

    if file_facts is None:
        file_facts = plan_file_facts(plan)
    if file_facts is None:
        return None
    try:
        proto = plan_to_proto(plan)
    except Exception:
        return None  # unserializable plans carry no stable identity
    if _has_volatile_fn(proto):
        return None  # now() etc.: results depend on execution time
    proto_bytes = proto.SerializeToString()
    h = hashlib.sha256()
    h.update(proto_bytes)
    h.update(b"\x00")
    h.update(_settings_component(settings).encode())
    content_key = h.hexdigest()
    h2 = hashlib.sha256()
    h2.update(content_key.encode())
    for fact in sorted(file_facts):
        h2.update(b"\x00")
        h2.update(fact.encode())
    return content_key, h2.hexdigest()

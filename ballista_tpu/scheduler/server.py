"""SchedulerServer: the control plane's 5 RPCs.

Mirrors the reference's SchedulerServer (rust/scheduler/src/lib.rs:82-428):

- ExecuteQuery: decode logical plan proto (or parse SQL), mint a 7-char
  alphanumeric job id (ref lib.rs:262-269), persist Queued, then plan
  asynchronously: optimize -> physical plan -> distributed stages -> persist
  each stage plan + one pending TaskStatus per (stage, partition)
  (ref lib.rs:288-401).
- PollWork: executor heartbeat + piggy-backed task statuses + work pull,
  the whole body under the global state lock (ref lib.rs:105-182).
- GetJobStatus / GetExecutorsMetadata / GetFileMetadata (parquet-only
  schema discovery, ref lib.rs:184-222).
"""

from __future__ import annotations

import logging
import queue
import random
import string
import threading
import time
from concurrent import futures
from typing import Dict, Optional

import grpc

from ballista_tpu.config import BallistaConfig
from ballista_tpu.distributed.planner import DistributedPlanner
from ballista_tpu.engine.context import ExecutionContext
from ballista_tpu.proto import ballista_pb2 as pb
from ballista_tpu.scheduler.kv import KvBackend, MemoryBackend
from ballista_tpu.scheduler.rpc import add_scheduler_service
from ballista_tpu.scheduler.state import SchedulerState
from ballista_tpu.serde.arrow import schema_to_ipc
from ballista_tpu.serde.logical import plan_from_proto
from ballista_tpu.utils.locks import make_lock

log = logging.getLogger("ballista.scheduler")


def _job_id() -> str:
    # 7 alphanumeric chars, first char alphabetic (ref lib.rs:262-269)
    first = random.choice(string.ascii_lowercase)
    rest = "".join(random.choices(string.ascii_lowercase + string.digits, k=6))
    return first + rest


class _PushSubscriber:
    """One executor's open SubscribeWork stream (ISSUE 8).

    `outstanding` is the scheduler-side credit ledger: plan coordinates of
    tasks pushed over this stream whose terminal status has not arrived yet
    — at most `slots` may be outstanding, so a slow executor is never
    buried under pushed work its semaphore cannot absorb. Entries resolve
    from the executor's own heartbeat statuses and are re-verified against
    the KV on every pump (a requeued orphan must free its credit). All
    fields are touched only under the scheduler's global KV lock (pump,
    PollWork) except `queue`/`closed`, which are internally thread-safe and
    shared with the stream generator thread."""

    def __init__(self, executor_id: str, slots: int) -> None:
        self.executor_id = executor_id  # durability: ephemeral(stream identity, dies with the stream)
        self.slots = max(1, slots)  # durability: ephemeral(credit capacity of this live stream)
        self.queue: "queue.Queue[pb.TaskDefinition]" = queue.Queue()  # durability: ephemeral(live stream plumbing)
        self.closed = threading.Event()  # durability: ephemeral(live stream plumbing)
        # (job, stage, part, attempt)
        # durability: ephemeral(credit ledger, re-verified against the KV on every pump)
        self.outstanding: set = set()

    def close(self) -> None:
        """Close + UNBLOCK: the None sentinel wakes a stream generator
        parked in queue.get immediately, so scheduler shutdown/restart
        never waits out the 0.25s tick (a restarted scheduler must rebind
        its port before retrying clients exhaust their backoff budget)."""
        self.closed.set()
        self.queue.put(None)


class SchedulerServer:
    def __init__(
        self,
        kv: Optional[KvBackend] = None,
        namespace: str = "default",
        config: Optional[BallistaConfig] = None,
        synchronous_planning: bool = False,
        replica_id: str = "",
        advertise_addr: str = "",
    ) -> None:
        self.config = config or BallistaConfig()  # durability: ephemeral(construction parameter)
        # ISSUE 14: one config flag arms the dynamic lock-order witness for
        # the whole process (scheduler threads, stream generators, pumps)
        from ballista_tpu.utils import locks as _locks

        _locks.maybe_enable_from_config(self.config)
        self.state = SchedulerState(kv or MemoryBackend(), namespace, config=self.config)  # durability: ephemeral(the owned SchedulerState, classified field by field)
        # replica identity (ISSUE 20) lands BEFORE recovery: recover()'s
        # lease reclaim compares replica ids, and a replica restarting
        # under its own name must reclaim its predecessor's surviving
        # leases instead of treating them as a live peer's
        self.state.replica_id = replica_id
        self.state.replica_addr = advertise_addr
        # restart recovery BEFORE serving: discard torn (uncommitted) jobs,
        # reload the durable assignment ledger with a fresh grace window
        # (no-op with zero counters on a fresh store)
        self.recovery_stats = self.state.recover()  # durability: ephemeral(snapshot of this life's recovery counters)
        # catalog for SQL queries arriving as text (CREATE EXTERNAL TABLE
        # statements executed through the scheduler register here)
        self.catalog = ExecutionContext(self.config)  # durability: ephemeral(clients re-register external tables per session)
        self.synchronous_planning = synchronous_planning  # durability: ephemeral(construction parameter)
        # dead-executor sweep clock, touched only inside PollWork's global
        # lock (the `self._lock = threading.Lock()` that used to sit here
        # guarded nothing — the ISSUE 14 coverage sweep retired it)
        self._last_lost_check = 0.0  # durability: ephemeral(sweep clock, a fresh replica sweeps promptly)  # guarded-by: self.state.kv.lock()
        # deterministic scheduler-death injection (utils/chaos.py
        # "scheduler.crash"): keyed on the ACCEPTED-STATUS sequence so the
        # seeded crash lands mid-job (statuses only exist after planning
        # committed), regardless of poll interleaving. Once crashed, every
        # RPC answers UNAVAILABLE — exactly what a dead process looks like
        # to retrying clients — until the harness restarts the scheduler on
        # the same KV store (StandaloneCluster.restart_scheduler).
        self._chaos = self.state._chaos  # durability: ephemeral(deterministic fault-injection config, per process by design)
        self._accepted_statuses = 0  # under the kv lock (PollWork body)  # durability: ephemeral(per-process chaos sequence)
        self.crashed = False  # durability: ephemeral(crash-simulation flag for this process only)
        self.on_crash = None  # durability: ephemeral(harness callback)
        # tasks running on executors whose lease lapsed are rescheduled this
        # often (the reference loses such work permanently)
        self.lost_task_check_interval = 5.0  # durability: ephemeral(tuning knob)
        # GetFileMetadata walks globs and reads parquet footers; cap how many
        # RPC worker threads it may hold at once so a burst of large metadata
        # requests can never starve PollWork heartbeats of workers
        self._file_meta_slots = threading.BoundedSemaphore(4)  # durability: ephemeral(RPC worker throttle, process-local by nature)
        # cross-job physical-plan cache (ISSUE 7): optimize + physical
        # planning output serialized per CONTENT key (plan proto + settings,
        # no mtimes — planning depends on the file LIST, not file contents),
        # so N tenants submitting the same dashboard query plan once. The
        # cached value is the serialized proto, deserialized fresh per job:
        # plan trees are mutable (stage split, operator state) and must
        # never be shared across planner invocations.
        self._plan_cache_mu = make_lock("scheduler.server._plan_cache_mu")  # durability: ephemeral(a lock guards state, it is not state)
        self._plan_cache: "dict[str, bytes]" = {}  # durability: ephemeral(content-keyed memo, a fresh replica misses once per plan)  # guarded-by: self._plan_cache_mu
        self._plan_cache_cap = 128  # durability: ephemeral(tuning knob)
        # push-based task dispatch (ISSUE 8): executor id -> open stream.
        # The registry lock only guards the dict itself; subscriber credit
        # state is touched under the global KV lock (see _PushSubscriber).
        # Ordering: kv.lock() may be held when _push_mu is taken (pump),
        # NEVER the reverse.
        self.push_enabled = self.config.push_dispatch()  # durability: ephemeral(config snapshot)
        self._push_mu = make_lock("scheduler.server._push_mu")  # durability: ephemeral(a lock guards state, it is not state)
        self._subscribers: Dict[str, _PushSubscriber] = {}  # durability: ephemeral(live stream registry, streams die with the process)  # guarded-by: self._push_mu
        self._push_seq = 0  # scheduler.push chaos rotation; under the kv lock  # durability: ephemeral(per-process chaos sequence)
        # push job-status notifications (ISSUE 11): job id -> queues of
        # open SubscribeJobStatus streams. The state hook fans every
        # job-status write out to them; each stream terminates itself after
        # a terminal status (or client disconnect), so entries are
        # short-lived. Queue puts are internally thread-safe; the dict is
        # guarded by its own lock (never taken with the KV lock held by
        # anything that blocks).
        self._status_mu = make_lock("scheduler.server._status_mu")  # durability: ephemeral(a lock guards state, it is not state)
        self._status_subs: Dict[str, list] = {}  # durability: ephemeral(live stream registry, streams die with the process)  # guarded-by: self._status_mu
        # job -> last pushed serialized status: synchronize_job_status
        # re-writes a byte-identical running status on every non-final
        # task completion; one push per TRANSITION means suppressing those
        self._status_last: Dict[str, bytes] = {}  # durability: ephemeral(push dedup memo, a reconnected stream gets a fresh snapshot)  # guarded-by: self._status_mu
        self.state.on_job_status = self._notify_job_status
        # replicated-control-plane housekeeping (ISSUE 20): the daemon that
        # renews this replica's job leases, adopts dead peers' expired jobs,
        # and fails queued jobs whose planning replica died mid-plan. Started
        # from serve() ONLY — in-process test servers must not leak threads.
        self._hk_stop = threading.Event()  # durability: ephemeral(live thread plumbing)
        self._hk_thread: Optional[threading.Thread] = None  # durability: ephemeral(live thread handle, dies with the process)
        # job ids THIS replica is still planning/advancing: the queued-grace
        # sweep must never fail a job whose planner is alive in this very
        # process (set add/discard are atomic under the GIL)
        self._planning: set = set()  # durability: ephemeral(in-flight planning threads die with the process; peers judge them by the replica heartbeat instead)
        # scheduler-side shared-shuffle TTL sweep (ISSUE 20 satellite,
        # ROADMAP residue): same 1h TTL as the executor-side sweep
        self.shuffle_ttl_seconds = 3600.0  # durability: ephemeral(tuning knob)

    # -- crash simulation ---------------------------------------------------
    def _refuse_if_crashed(self, context) -> None:
        """A chaos-crashed scheduler is a dead process: every RPC fails
        UNAVAILABLE (transient to retrying clients) until the restart."""
        if not self.crashed:
            return
        if context is not None:
            context.abort(
                grpc.StatusCode.UNAVAILABLE, "scheduler crashed (chaos)"
            )
        raise RuntimeError("scheduler crashed (chaos)")

    def _crash(self, context) -> None:
        from ballista_tpu.ops.runtime import record_recovery

        record_recovery("chaos_injected")
        record_recovery("chaos_scheduler_crash")
        log.warning(
            "chaos[scheduler.crash]: scheduler dying after accepting "
            "status #%d", self._accepted_statuses,
        )
        self.crashed = True
        # a dead process's housekeeping and streams die with it
        self._hk_stop.set()
        self.close_push_streams()
        if self.on_crash is not None:
            try:
                self.on_crash()
            except Exception as e:
                log.warning("on_crash hook failed: %s", e)
        self._refuse_if_crashed(context)

    # -- replicated-control-plane housekeeping (ISSUE 20) -------------------
    def start_housekeeping(self) -> None:
        """Start the replica housekeeping daemon: lease renewal (every
        ~TTL/3), the replica liveness heartbeat, adoption of dead peers'
        expired running jobs, the queued-grace sweep, and the scheduler-
        side shared-shuffle TTL sweep. Called from serve() — never from
        __init__, so the hundreds of in-process test servers stay
        thread-free."""
        if self._hk_thread is not None or self.crashed:
            return
        self._hk_stop.clear()
        self._hk_thread = threading.Thread(
            target=self._housekeeping_loop, daemon=True,
            name=f"scheduler-housekeeping-{self.state.replica_id or 'solo'}",
        )
        self._hk_thread.start()

    def stop_housekeeping(self) -> None:
        self._hk_stop.set()
        t = self._hk_thread
        if t is not None:
            t.join(timeout=5)
            self._hk_thread = None

    def _housekeeping_loop(self) -> None:
        from ballista_tpu.utils.chaos import ChaosInjected

        state = self.state
        # renew at a third of the TTL: two consecutive torn/missed rounds
        # still leave the lease alive, three depose us truthfully
        tick = max(0.05, state._lease_ttl / 3.0)
        renew_seq = 0
        queued_seen: Dict[str, float] = {}  # job -> first seen queued, grace clock
        last_shuffle_sweep = time.time()
        while not self._hk_stop.wait(tick):
            if self.crashed:
                return
            try:
                renew_seq += 1
                # scheduler.lease chaos: one torn RENEWAL round — the
                # heartbeat and every owned lease burn a round of TTL
                # budget; enough consecutive verdicts and peers adopt this
                # replica's jobs, which is exactly the failure under test
                if self._chaos is not None:
                    self._chaos.maybe_fail(
                        "scheduler.lease",
                        f"g{state.generation}/renew{renew_seq}",
                    )
                with state.kv.lock():
                    state.replica_heartbeat()
                    state.renew_owned_leases()
            except ChaosInjected:
                log.warning(
                    "chaos[scheduler.lease]: renewal round %d skipped",
                    renew_seq,
                )
            except Exception:
                log.warning("lease renewal round failed", exc_info=True)
            try:
                with state.kv.lock():
                    if self._adopt_orphaned_jobs_locked():
                        self._pump_pushes()
                    self._sweep_queued_grace_locked(queued_seen)
            except ChaosInjected:
                pass  # kv.lease tore an adoption claim; next round retries
            except Exception:
                log.warning("failover scan failed", exc_info=True)
            now = time.time()
            if now - last_shuffle_sweep >= 60.0:
                last_shuffle_sweep = now
                try:
                    self.sweep_shuffle_dir()
                except Exception:
                    log.warning("shuffle-dir sweep failed", exc_info=True)

    def _adopt_orphaned_jobs_locked(self) -> int:
        """Adopt every running job whose owner's lease expired (caller
        holds the global KV lock). The leasegen/ scan finds exactly the
        jobs that HAVE had owners; a live lease means the owner still
        heartbeats and the job is not ours to touch. adopt_job runs
        recovery scoped to the job — assignment/speculation ledgers
        reload, orphan grace restarts — so failover is the restart story
        executed by a peer."""
        state = self.state
        adopted = 0
        for key, _gen in state.kv.get_prefix(state._key("leasegen", "")):
            job_id = key.rsplit("/", 1)[1]
            if state.owns_job(job_id):
                continue
            if state.kv.get(state._lease_key(job_id)) is not None:
                continue  # owner alive (or a peer just adopted)
            st = state.get_job_metadata(job_id)
            if st is None or st.WhichOneof("status") != "running":
                continue
            if state.adopt_job(job_id):
                adopted += 1
                log.warning(
                    "replica %s adopted job %s from its expired owner",
                    state.replica_id or "<solo>", job_id,
                )
        return adopted

    def _sweep_queued_grace_locked(self, queued_seen: Dict[str, float]) -> int:
        """Fail queued jobs whose submitting replica died before the
        planning commit (caller holds the global KV lock). Scoped hard:
        only jobs carrying a planner/ provenance stamp whose replica
        heartbeat lapsed, never this replica's own in-flight planning,
        and only after a 2xTTL grace. The failure is a CAS against the
        exact queued bytes — racing the (resurrected) planner's atomic
        commit, exactly one of the two writes lands."""
        from ballista_tpu.ops.runtime import record_recovery

        state = self.state
        now = time.time()
        failed_n = 0
        live = set()
        for key, raw in state.kv.get_prefix(state._key("jobs", "")):
            job_id = key.rsplit("/", 1)[1]
            st = pb.JobStatus()
            try:
                st.ParseFromString(raw)
            except Exception:
                continue
            if st.WhichOneof("status") != "queued":
                continue
            live.add(job_id)
            if job_id in self._planning:
                continue
            planner = state.job_planner(job_id)
            if planner is None:
                continue  # anonymous submission: restart recovery owns it
            if planner != state.replica_id and state.replica_alive(planner):
                queued_seen.pop(job_id, None)  # planner heartbeating: reset
                continue
            # our own stamp but not in self._planning: the planner thread
            # died with a predecessor process (restart under the same
            # replica id, with live peers suppressing the full-recovery
            # torn-job sweep) — grace applies to us like any dead peer
            first = queued_seen.setdefault(job_id, now)
            if now - first < 2.0 * state._lease_ttl:
                continue
            failed = pb.JobStatus()
            failed.failed.error = (
                f"planning replica {planner!r} died before committing "
                "the job's plan"
            )
            if state.kv.put_all(
                [(key, failed.SerializeToString())], compare=(key, raw)
            ):
                failed_n += 1
                record_recovery("queued_grace_failed")
                log.warning(
                    "queued job %s failed: planner replica %r lapsed "
                    "without committing", job_id, planner,
                )
            queued_seen.pop(job_id, None)
        # drop grace clocks for jobs that left queued (committed/failed)
        for job_id in [j for j in queued_seen if j not in live]:
            queued_seen.pop(job_id, None)
        return failed_n

    def _peer_with_pending_work_locked(self):
        """A live peer's (job_id, JobLease) whose job still has PENDING
        tasks (caller holds the global KV lock) — the re-home target for an
        idle executor this replica has nothing to dispatch to. None when
        every leased job is ours, drained, or address-less. Runs only on
        fully idle polls, whose frequency decays toward the idle ceiling."""
        state = self.state
        for key, raw in state.kv.get_prefix(state._key("leases", "")):
            job_id = key.rsplit("/", 1)[1]
            if state.owns_job(job_id):
                continue
            jl = pb.JobLease()
            try:
                jl.ParseFromString(raw)
            except Exception:
                continue
            if not jl.addr or jl.addr == state.replica_addr:
                continue
            for _k, v in state.kv.get_prefix(state._key("tasks", job_id) + "/"):
                ts = pb.TaskStatus()
                try:
                    ts.ParseFromString(v)
                except Exception:
                    continue
                if ts.WhichOneof("status") is None:
                    return job_id, jl
        return None

    def sweep_shuffle_dir(self) -> int:
        """Scheduler-side TTL sweep of the shared shuffle root (ISSUE 20
        satellite, ROADMAP residue): executors sweep the mount too, but a
        fleet scaled to zero — or torn down uncleanly — leaves nobody else
        to reclaim expired job dirs, and the mount would grow without
        bound. Same TTL and racing-rmtree tolerance as the executor
        sweep (executor/execution_loop.py::gc_work_dir)."""
        import os
        import shutil

        root = self.config.shuffle_dir()
        if not root or not os.path.isdir(root):
            return 0
        removed = 0
        cutoff = time.time() - self.shuffle_ttl_seconds
        for job_dir in os.listdir(root):
            path = os.path.join(root, job_dir)
            try:
                if os.path.isdir(path) and os.path.getmtime(path) < cutoff:
                    shutil.rmtree(path, ignore_errors=True)
                    removed += 1
            except OSError:
                continue
        if removed:
            log.info(
                "scheduler shuffle sweep: removed %d expired job dirs",
                removed,
            )
        return removed

    # -- RPC implementations ------------------------------------------------
    def ExecuteQuery(self, request: pb.ExecuteQueryParams, context=None) -> pb.ExecuteQueryResult:
        self._refuse_if_crashed(context)
        from ballista_tpu.executor.confine import (
            check_proto_scan_roots,
            check_scan_files,
            check_scan_roots,
            check_scan_roots_path,
        )

        which = request.WhichOneof("query")
        settings = {kv.key: kv.value for kv in request.settings}
        config = BallistaConfig({**self.config.to_dict(), **settings})
        # data-root allowlist from the SCHEDULER's own config (client
        # settings must not widen it). Two layers, like the executor entry
        # points: the raw proto before any table source construction touches
        # disk, then the constructed plan's RESOLVED file lists (discovery
        # follows directory symlinks).
        roots = self.config.data_roots()
        if which == "logical_plan":
            check_proto_scan_roots(request.logical_plan, roots)
            plan = plan_from_proto(request.logical_plan)
            check_scan_roots(plan, roots)
        elif which == "sql":
            from ballista_tpu.logical import plan as lp
            from ballista_tpu.sql.planner import plan_sql

            plan = plan_sql(request.sql, self.catalog)
            if isinstance(plan, lp.CreateExternalTable):
                check_scan_roots_path(plan.location, roots)
                key = plan.name.lower()
                prior = self.catalog.tables.get(key)
                self.catalog._create_external_table(plan)
                src = self.catalog.tables.get(key)
                try:
                    check_scan_files(getattr(src, "files", []) or [], roots)
                except Exception:
                    # restore the pre-existing registration (a failing CET
                    # must not unregister someone else's table)
                    if prior is None:
                        self.catalog.tables.pop(key, None)
                    else:
                        self.catalog.tables[key] = prior
                    raise
                return pb.ExecuteQueryResult(job_id="")
        else:
            raise ValueError("ExecuteQueryParams requires a plan or sql")

        from ballista_tpu.config import BALLISTA_TENANT, BALLISTA_TENANT_PRIORITY
        from ballista_tpu.ops.runtime import record_tenancy
        from ballista_tpu.scheduler.fingerprint import (
            plan_file_facts,
            plan_fingerprint,
        )

        # tenancy (ISSUE 7): the proto field is authoritative; settings keep
        # wire compat with clients that only flow the config map
        tenant = request.tenant or settings.get(BALLISTA_TENANT, "").strip()
        try:
            # clamp: pb.JobTenant.priority is uint32 — a negative settings
            # value must degrade to 0, not kill the submission
            priority = request.priority or max(0, int(
                settings.get(BALLISTA_TENANT_PRIORITY, "0") or 0
            ))
        except ValueError:
            priority = 0

        # plan-fingerprint identity (None when any source is neither
        # file-backed nor content-embedded — such plans never cache). The
        # facts are statted ONCE and shared with the key derivation, so
        # the stored scan_fact set always agrees with the result_key.
        fp = None
        facts = None
        if config.result_cache() or config.plan_cache():
            facts = plan_file_facts(plan)
            fp = plan_fingerprint(plan, settings, file_facts=facts)
        if fp is None and config.result_cache():
            record_tenancy("cache_unkeyable")

        job_id = _job_id()
        if fp is not None and config.result_cache():
            # result-cache lookup + job publish under the global lock so a
            # concurrent completion's cache put cannot interleave
            with self.state.kv.lock():
                hit = self.state.result_cache_lookup(fp[1])
                if hit is not None:
                    completed = pb.JobStatus()
                    completed.completed.CopyFrom(hit)
                    self.state.save_job_metadata(job_id, completed)
                    self.state.save_job_tenant(job_id, tenant, priority)
                    # link job -> entry so a lost cached result partition
                    # (ReportLostPartition) invalidates the right entry
                    self.state.save_job_fingerprint(job_id, fp[1])
                    # cache-served jobs complete HERE, never through
                    # synchronize_job_status — their SLO outcome (ISSUE
                    # 11) counts all the same, or per-tenant attainment
                    # would exclude exactly the fastest workloads
                    self.state._note_job_slo(job_id)
                    log.info(
                        "job %s served from result cache (tenant=%s, fp=%s...)",
                        job_id, tenant or "<default>", fp[1][:16],
                    )
                    return pb.ExecuteQueryResult(job_id=job_id)
                # miss: result-cache advancement (ISSUE 19) — a live
                # same-content entry over a strict SUBSET of this
                # submission's scan files can be folded forward with a
                # delta job over only the new files, instead of paying a
                # full recompute. Probed under the same lock, so the job
                # publish cannot interleave with a concurrent put.
                if config.cache_advance() and facts is not None:
                    if self._try_advance(job_id, plan, config, settings,
                                         tenant, priority, fp, facts):
                        return pb.ExecuteQueryResult(job_id=job_id)

        queued = pb.JobStatus()
        queued.queued.SetInParent()
        self.state.save_job_metadata(job_id, queued)
        # queued-grace provenance (ISSUE 20): peers may fail this job if
        # this replica dies before the planning commit
        self.state.mark_job_planner(job_id)
        # per-job client settings ride TaskDefinition to executors (the
        # reference drops its settings map, serde/scheduler/to_proto.rs:29-35)
        self.state.save_job_settings(job_id, settings)
        self.state.save_job_tenant(job_id, tenant, priority)
        if fp is not None and config.result_cache():
            self.state.save_job_fingerprint(job_id, fp[1])
            if facts is not None:
                # advancement identity (ISSUE 19): the completion-time
                # cache put stamps these onto the entry, making it a
                # candidate fold base for later grown-file-set submissions
                self.state.save_job_facts(job_id, fp[0], facts)

        content_key = fp[0] if (fp is not None and config.plan_cache()) else None
        if self.synchronous_planning:
            self._planning.add(job_id)
            try:
                self._plan_job(job_id, plan, config, content_key=content_key)
            finally:
                self._planning.discard(job_id)
        else:
            threading.Thread(
                target=self._plan_job_safe,
                args=(job_id, plan, config, content_key),
                daemon=True,
            ).start()
        return pb.ExecuteQueryResult(job_id=job_id)

    def _plan_job_safe(self, job_id: str, plan, config, content_key=None) -> None:
        self._planning.add(job_id)
        try:
            self._plan_job_guarded(job_id, plan, config, content_key)
        finally:
            # only now may a peer's queued-grace sweep judge the job: past
            # this point either the commit landed (running) or a terminal
            # failed status did — a still-queued job is truly abandoned
            self._planning.discard(job_id)

    def _plan_job_guarded(self, job_id: str, plan, config, content_key=None) -> None:
        from ballista_tpu.ops.runtime import record_recovery
        from ballista_tpu.utils.chaos import ChaosInjected

        limit = self.state.retry_limit(job_id)
        attempt = 0
        while True:
            if self.crashed:
                # fence: this planning thread belongs to a crashed (or
                # restarted-over) scheduler instance. Committing now would
                # resurrect a job the successor's recover() already failed
                # as torn — abandon without writing anything
                log.warning("abandoning planning of job %s: scheduler "
                            "instance crashed", job_id)
                return
            try:
                self._plan_job(job_id, plan, config, attempt=attempt,
                               content_key=content_key)
                return
            except ChaosInjected as e:
                # the staged batch died before commit, so NOTHING was
                # published (atomic publish) — planning retries whole, like
                # a task attempt, with the chaos key rotated so the seeded
                # retry draws fresh verdicts
                attempt += 1
                if attempt > limit:
                    log.error("planning job %s failed after %d chaos-torn "
                              "attempts", job_id, attempt)
                    failed = pb.JobStatus()
                    failed.failed.error = (
                        f"planning failed after {attempt} attempts: {e}"
                    )
                    self.state.save_job_metadata(job_id, failed)
                    return
                record_recovery("plan_retry")
                log.warning("planning job %s torn by chaos; retrying "
                            "(attempt %d)", job_id, attempt)
            except Exception as e:  # surface planning failure as job failure
                log.exception("planning job %s failed", job_id)
                if self.crashed:
                    return  # successor owns the job's fate now
                failed = pb.JobStatus()
                failed.failed.error = f"planning failed: {e}"
                self.state.save_job_metadata(job_id, failed)
                return

    # -- result-cache advancement (ISSUE 19) --------------------------------
    def _try_advance(
        self, job_id, plan, config, settings, tenant, priority, fp, facts
    ) -> bool:
        """Called UNDER the global KV lock on a result-cache miss: when a
        fold base exists and the plan's aggregate state is resumable,
        publish the user job (queued) and hand it to the advancement
        worker. Returns False to fall through to ordinary planning. A base
        that exists but cannot fold (float sums, DISTINCT, no total
        order…) is a recorded decline — never a silent one."""
        from ballista_tpu.ops.runtime import record_delta
        from ballista_tpu.scheduler import delta as delta_mod

        base = self.state.result_cache_probe_advance(fp[0], facts)
        if base is None:
            return False
        spec = delta_mod.fold_spec(plan)
        if spec is None:
            record_delta("advance_declined")
            return False
        new_files = delta_mod.new_scan_files(facts, list(base.scan_fact))
        if not new_files:
            return False
        queued = pb.JobStatus()
        queued.queued.SetInParent()
        self.state.save_job_metadata(job_id, queued)
        self.state.mark_job_planner(job_id)
        self.state.save_job_settings(job_id, settings)
        self.state.save_job_tenant(job_id, tenant, priority)
        self.state.save_job_fingerprint(job_id, fp[1])
        self.state.save_job_facts(job_id, fp[0], facts)
        log.info(
            "job %s advancing cached result (epoch %d, +%d file(s), fp=%s...)",
            job_id, base.advance_epoch, len(new_files), fp[1][:16],
        )
        # the user job stays QUEUED for the whole advancement (possibly
        # minutes of delta-job execution): shield it from peers' queued-
        # grace sweeps for as long as this worker lives
        self._planning.add(job_id)
        threading.Thread(
            target=self._advance_job_safe,
            args=(job_id, plan, config, settings, tenant, priority, fp,
                  facts, base, new_files, spec),
            daemon=True,
        ).start()
        return True

    def _advance_job_safe(
        self, job_id, plan, config, settings, tenant, priority, fp, facts,
        base, new_files, spec,
    ) -> None:
        """Advancement worker: run one delta job per new file through the
        ORDINARY planning machinery (ledger, retries, speculation and
        recovery all apply to its tasks), fold the delta outputs into the
        cached base, publish the advanced entry under the grown set's
        result_key, and complete the user job with the folded result
        inline. ANY failure — a failed delta job, an unfetchable base, a
        chaos-torn publish — declines: recorded, logged, and the user job
        replans as a full recompute, so the fold is only ever an
        accelerator on a path whose fallback is the bit-identical truth."""
        try:
            self._advance_job(job_id, plan, config, settings, tenant,
                              priority, fp, facts, base, new_files, spec)
        finally:
            self._planning.discard(job_id)

    def _advance_job(
        self, job_id, plan, config, settings, tenant, priority, fp, facts,
        base, new_files, spec,
    ) -> None:
        import time as _time

        from ballista_tpu.config import BALLISTA_DELTA_FOR
        from ballista_tpu.ops.runtime import record_delta
        from ballista_tpu.scheduler import delta as delta_mod

        content_key = fp[0] if config.plan_cache() else None

        def fall_back(reason: str) -> None:
            record_delta("advance_declined")
            log.warning("advancement of job %s declined (%s); planning a "
                        "full recompute", job_id, reason)
            self._plan_job_safe(job_id, plan, config, content_key)

        try:
            schema = plan.schema()
            delta_jobs = []
            for f in new_files:
                dj = _job_id()
                dsettings = dict(settings)
                dsettings[BALLISTA_DELTA_FOR] = job_id
                queued = pb.JobStatus()
                queued.queued.SetInParent()
                with self.state.kv.lock():
                    # no jobfp/jobfacts: a delta job's partial result must
                    # never enter the result cache under any key
                    self.state.save_job_metadata(dj, queued)
                    self.state.mark_job_planner(dj)
                    self.state.save_job_settings(dj, dsettings)
                    self.state.save_job_tenant(dj, tenant, priority)
                threading.Thread(
                    target=self._plan_job_safe,
                    args=(dj, delta_mod.build_delta_plan(plan, f), config,
                          None),
                    daemon=True,
                ).start()
                delta_jobs.append(dj)
            deadline = _time.time() + 600.0
            delta_tables = []
            for dj in delta_jobs:
                while True:
                    if self.crashed:
                        return  # the successor owns the job's fate now
                    st = self.state.get_job_metadata(dj)
                    which = st.WhichOneof("status") if st else None
                    if which == "completed":
                        break
                    if which == "failed":
                        return fall_back(
                            f"delta job {dj} failed: {st.failed.error}"
                        )
                    if _time.time() > deadline:
                        return fall_back(f"delta job {dj} timed out")
                    _time.sleep(0.005)
                delta_tables.append(delta_mod.fetch_completed_table(
                    st.completed.partition_location, config, schema
                ))
            if base.state_ipc:
                base_table = delta_mod.ipc_to_table(base.state_ipc)
            else:
                base_table = delta_mod.fetch_completed_table(
                    base.partition_location, config, schema
                )
            folded = delta_mod.fold_tables(
                [base_table] + delta_tables, spec, schema
            )
            ipc = delta_mod.table_to_ipc(folded)
            with self.state.kv.lock():
                if self.crashed:
                    return
                published = self.state.result_cache_put_advanced(
                    fp[1], fp[0], facts, ipc, base.advance_epoch
                )
                if published:
                    record_delta("advance_hits")
                    completed = pb.JobStatus()
                    completed.completed.cached = True
                    completed.completed.inline_result = ipc
                    self.state.save_job_metadata(job_id, completed)
                    self.state._note_job_slo(job_id)
            if not published:
                # outside the KV lock: the fallback replans through the
                # plan cache, whose mutex must never nest under the store
                return fall_back("publish torn by chaos")
            log.info(
                "job %s advanced from cached base (epoch %d -> %d, %d delta "
                "file(s), fp=%s...)",
                job_id, base.advance_epoch, base.advance_epoch + 1,
                len(new_files), fp[1][:16],
            )
        except Exception as e:
            if self.crashed:
                return
            log.exception("advancement of job %s failed", job_id)
            fall_back(str(e))

    def _physical_plan(self, plan, config, content_key=None):
        """Optimize + physical-plan, through the cross-job plan cache when a
        content key is available: a cache hit deserializes the stored proto
        (fresh tree per job — plan nodes are mutable) instead of re-running
        the optimizer, so N tenants submitting the same query plan once."""
        from ballista_tpu.config import BALLISTA_TPU_COALESCE_AGG
        from ballista_tpu.ops.runtime import record_tenancy
        from ballista_tpu.serde.physical import (
            phys_plan_from_proto,
            phys_plan_to_proto,
        )

        if content_key is not None:
            with self._plan_cache_mu:
                blob = self._plan_cache.get(content_key)
            kv_hit = False
            if blob is None:
                # KV read-through tier (ISSUE 20): a peer replica's
                # planning output serves this replica's first miss — N
                # replicas sharing an admission load plan each dashboard
                # query ONCE cluster-wide, not once per replica
                blob = self.state.kv.get(
                    self.state._key("plancache", content_key)
                )
                kv_hit = blob is not None
            if blob is not None:
                # a cached blob that stops deserializing (e.g. after a code
                # change mid-process) must evict and fall through to fresh
                # planning, never fail the job
                try:
                    node = pb.PhysicalPlanNode()
                    node.ParseFromString(blob)
                    plan_tree = phys_plan_from_proto(node)
                except Exception:
                    with self._plan_cache_mu:
                        self._plan_cache.pop(content_key, None)
                    self.state.kv.delete(
                        self.state._key("plancache", content_key)
                    )
                else:
                    record_tenancy("plan_cache_hit")
                    if kv_hit:
                        self._plan_cache_insert(content_key, blob)
                    return plan_tree
        # distributed jobs keep the Partial/exchange/Final shape: the stage
        # split parallelizes across executors, and the SPMD fuse needs it
        ctx = ExecutionContext(config.with_setting(BALLISTA_TPU_COALESCE_AGG, "false"))
        physical = ctx.create_physical_plan(plan)
        if content_key is not None:
            # validate the blob round-trips BEFORE inserting (and hand out
            # the fresh tree): a plan that serializes but cannot
            # deserialize must never enter the cache — inserting first
            # would open a window where a concurrent submission hits the
            # poisoned entry
            try:
                blob = phys_plan_to_proto(physical).SerializeToString()
                node = pb.PhysicalPlanNode()
                node.ParseFromString(blob)
                fresh = phys_plan_from_proto(node)
            except Exception:
                return physical  # unserializable plans just don't cache
            self._plan_cache_insert(content_key, blob)
            # the KV tier is namespace-lifetime (no cap): plancache rows
            # are keyed by plan content and die with the store, like
            # resultcache entries
            self.state.kv.put(
                self.state._key("plancache", content_key), blob
            )
            return fresh
        return physical

    def _plan_cache_insert(self, content_key: str, blob: bytes) -> None:
        with self._plan_cache_mu:
            if len(self._plan_cache) >= self._plan_cache_cap:
                # drop the oldest insertion (dict preserves order) —
                # a simple bound, not an LRU; the cap is generous
                self._plan_cache.pop(next(iter(self._plan_cache)))
            self._plan_cache[content_key] = blob

    def _plan_job(
        self, job_id: str, plan, config, attempt: int = 0, content_key=None
    ) -> None:
        physical = self._physical_plan(plan, config, content_key)
        stages = DistributedPlanner(config).plan_query_stages(job_id, physical)
        # all-or-nothing publish: stage plans, pending tasks, and the
        # queued->running flip land in ONE KV batch, so a crash mid-plan
        # leaves no torn job (the job stays queued with no planning keys
        # and recover() fails it cleanly on restart)
        batch = self.state.stage_job_plan(job_id, attempt)
        for stage in stages:
            batch.add_stage_plan(stage.stage_id, stage)
            n = stage.output_partitioning().partition_count()
            for p in range(n):
                batch.add_pending_task(stage.stage_id, p)
        if self.crashed:
            # last fence before the publish (narrow in-process race left:
            # real restarts are separate processes where the dead
            # scheduler's threads cannot write at all)
            raise RuntimeError("scheduler crashed during planning")
        batch.commit()
        log.info("job %s planned into %d stages", job_id, len(stages))
        # the whole point of push dispatch: the job's first tasks leave for
        # subscribed executors the moment planning commits, not after the
        # next PollWork round-trip
        with self.state.kv.lock():
            self._pump_pushes()

    # -- push dispatch (ISSUE 8) --------------------------------------------
    def _task_definition(self, status: pb.TaskStatus, plan) -> pb.TaskDefinition:
        """Serialize one assignment into the wire TaskDefinition — the ONE
        shape both dispatch paths (PollWork reply, SubscribeWork push) send,
        so the executor cannot tell them apart."""
        from ballista_tpu.serde.physical import phys_plan_to_proto

        from ballista_tpu.config import BALLISTA_DELTA_FOR

        td = pb.TaskDefinition()
        td.task_id.CopyFrom(status.partition_id)
        td.attempt = status.attempt
        td.plan.CopyFrom(phys_plan_to_proto(plan))
        for k, v in self.state.get_job_settings(
            status.partition_id.job_id
        ).items():
            td.settings.add(key=k, value=v)
            if k == BALLISTA_DELTA_FOR:
                # delta provenance (ISSUE 19) rides first-class too
                td.delta_for = v
        return td

    def _close_subscriber(self, sub: _PushSubscriber) -> None:
        sub.close()
        with self._push_mu:
            if self._subscribers.get(sub.executor_id) is sub:
                del self._subscribers[sub.executor_id]

    def close_push_streams(self) -> None:
        """Close every server-push stream NOW (shutdown/restart/crash) —
        work-dispatch subscribers AND job-status subscribers: the
        generators return on their sentinel instead of finishing a 0.25s
        tick, so the gRPC server's stop().wait() drains promptly (clients
        fall back to status polling until they re-subscribe)."""
        with self._push_mu:
            subs = list(self._subscribers.values())
            self._subscribers.clear()
        for sub in subs:
            sub.close()
        with self._status_mu:
            status_qs = [q for qs in self._status_subs.values() for q in qs]
            self._status_subs.clear()
            self._status_last.clear()
        for q in status_qs:
            q.put(None)

    # -- push job-status notifications (ISSUE 11) ---------------------------
    def _notify_job_status(self, job_id: str, status: pb.JobStatus) -> None:
        """State hook: fan one job-status write out to this job's open
        SubscribeJobStatus streams — one push per TRANSITION: a re-write
        byte-identical to the last pushed status is suppressed. Each
        subscriber gets its own copy (the caller may keep mutating the
        message)."""
        with self._status_mu:
            qs = list(self._status_subs.get(job_id, ()))
            if not qs:
                # no listeners: skip the serialization too — this hook
                # rides the scheduler's hottest write path
                self._status_last.pop(job_id, None)
                return
            data = status.SerializeToString()
            if self._status_last.get(job_id) == data:
                return
            self._status_last[job_id] = data
        for q in qs:
            snap = pb.JobStatus()
            snap.CopyFrom(status)
            q.put(snap)

    def SubscribeJobStatus(self, request: pb.GetJobStatusParams, context=None):
        """Server-streaming job-status push (ISSUE 11): one
        GetJobStatusResult per status transition, seeded with the current
        status (subscribing after completion still answers immediately),
        terminating after a terminal status. Mirrors SubscribeWork's
        lifecycle: the client's status POLL stays as the automatic fallback
        whenever this stream is down, refused, or racing a restart."""
        self._refuse_if_crashed(context)
        job_id = request.job_id
        # push is replica-LOCAL by design (ISSUE 20): status transitions
        # fan out from the replica that writes them — subscribing here for
        # a live peer's job would hold a silent stream. Refuse with the
        # owner's address; the client re-homes (or its poll fallback,
        # which reads shared KV truth, carries it to completion).
        lease = self.state.job_lease(job_id)
        if lease is not None and not self.state.owns_job(job_id):
            detail = (
                f"job {job_id} owned by peer replica {lease.replica_id!r}"
                f" at {lease.addr}; subscribe there"
            )
            if context is not None:
                context.abort(grpc.StatusCode.UNAVAILABLE, detail)
            raise RuntimeError(detail)
        q: "queue.Queue" = queue.Queue()
        with self._status_mu:
            self._status_subs.setdefault(job_id, []).append(q)
        cur = self.state.get_job_metadata(job_id)
        if cur is not None:
            # the seed is this subscriber's baseline — record it for the
            # transition dedup too (only when no push set it already: a
            # racing notify may have just advanced it past this snapshot)
            with self._status_mu:
                self._status_last.setdefault(job_id, cur.SerializeToString())
            q.put(cur)

        def stream():
            try:
                while not self.crashed:
                    if context is not None and not context.is_active():
                        return
                    try:
                        st = q.get(timeout=0.25)
                    except queue.Empty:
                        continue
                    if st is None:  # close sentinel (shutdown/restart)
                        return
                    res = pb.GetJobStatusResult()
                    res.status.CopyFrom(st)
                    yield res
                    if st.WhichOneof("status") in ("completed", "failed"):
                        return
            finally:
                with self._status_mu:
                    qs = self._status_subs.get(job_id)
                    if qs is not None:
                        try:
                            qs.remove(q)
                        except ValueError:
                            pass
                        if not qs:
                            del self._status_subs[job_id]
                            self._status_last.pop(job_id, None)

        return stream()

    def _pump_pushes(self) -> int:
        """Assign + push runnable tasks to every subscribed executor with
        free credit. Caller MUST hold the global KV lock — assignment, the
        credit ledger, and the chaos sequence all live under it, exactly
        like the PollWork dispatch path. Returns the number pushed.

        The `scheduler.push` chaos site tears the DELIVERY, after the
        Running flip: the assignment stands, the subscriber's stream is
        killed with the verdict, and recovery is exactly the lost-PollWork-
        response story — the executor's polls never echo the task, the
        orphaned-assignment grace reconciliation requeues it, and the
        executor re-subscribes. Keyed on a generation-rotated per-process
        sequence (like scheduler.admit) so a restarted scheduler draws
        fresh verdicts."""
        from ballista_tpu.ops.runtime import record_recovery, record_serving
        from ballista_tpu.utils.chaos import ChaosInjected

        if not self.push_enabled or self.crashed:
            return 0
        with self._push_mu:
            subs = list(self._subscribers.values())
        pushed = 0
        for sub in subs:
            pushed += self._pump_one_locked(sub)
        return pushed

    def _pump_one_locked(self, sub: _PushSubscriber) -> int:
        """Pump ONE subscriber (caller holds the global KV lock). The
        per-subscriber stream tick calls this for its own stream only —
        pumping every subscriber from every tick would be O(N^2) idle KV
        traffic at 4Hz on the scheduler's one lock."""
        from ballista_tpu.ops.runtime import record_recovery, record_serving
        from ballista_tpu.utils.chaos import ChaosInjected

        if not self.push_enabled or self.crashed or sub.closed.is_set():
            return 0
        # re-verify outstanding credits against the KV: a task requeued
        # behind our back (orphan reconciliation, lost-task reset) must
        # free its credit even though no terminal status ever arrives.
        # Bounded by `slots` reads, and only when credit is actually held.
        # A SPECULATIVE duplicate (ISSUE 11) has no tasks/ status of its
        # own — its credit stands while its speculation-ledger entry lives.
        for key in list(sub.outstanding):
            if self.state.speculation_active(
                (key[0], key[1], key[2]), sub.executor_id, key[3]
            ):
                continue
            cur = self.state.get_task_status(key[0], key[1], key[2])
            if (
                cur is None
                or cur.WhichOneof("status") != "running"
                or cur.attempt != key[3]
                or cur.running.executor_id != sub.executor_id
            ):
                sub.outstanding.discard(key)
        pushed = 0
        while len(sub.outstanding) < sub.slots and not sub.closed.is_set():
            speculative = False
            try:
                assigned = self.state.assign_next_schedulable_task(
                    sub.executor_id
                )
            except ChaosInjected:
                # scheduler.admit chaos: nothing was written (the abort
                # fires before the Running flip); the next pump retries
                # with a rotated admission key — same recovery story as
                # the aborted-PollWork form of this site
                break
            if assigned is None:
                # no fresh work for this executor: offer the slot to the
                # straggler monitor — push dispatch is exactly what makes
                # a speculative duplicate land instantly (ISSUE 11)
                assigned = self.state.maybe_speculate(sub.executor_id)
                speculative = assigned is not None
            if assigned is None:
                break
            status, plan = assigned
            pid = status.partition_id
            self._push_seq += 1
            if self._chaos is not None and self._chaos.should_inject(
                "scheduler.push",
                f"g{self.state.generation}/push{self._push_seq}",
            ):
                record_recovery("chaos_injected")
                record_recovery("chaos_push_torn")
                log.warning(
                    "chaos[scheduler.push]: tearing delivery of "
                    "%s/%s/%s to %s (stream killed)",
                    pid.job_id, pid.stage_id, pid.partition_id,
                    sub.executor_id,
                )
                self._close_subscriber(sub)
                break
            td = self._task_definition(status, plan)
            td.speculative = speculative
            sub.outstanding.add(
                (pid.job_id, pid.stage_id, pid.partition_id, status.attempt)
            )
            if not speculative:
                # scan-sharing pass (ISSUE 13): ride co-pending compatible
                # stages of OTHER jobs on this dispatch as batch siblings —
                # each holds its own push credit, resolved by its own
                # terminal status like any pushed task
                for st2, plan2 in self.state.form_shared_batch(
                    status, plan, sub.executor_id
                ):
                    td.siblings.add().CopyFrom(
                        self._task_definition(st2, plan2)
                    )
                    p2 = st2.partition_id
                    sub.outstanding.add(
                        (p2.job_id, p2.stage_id, p2.partition_id, st2.attempt)
                    )
            sub.queue.put(td)
            record_serving("dispatch_push")
            pushed += 1
        return pushed

    def SubscribeWork(self, request: pb.SubscribeWorkParams, context=None):
        """Server-streaming push dispatch (ISSUE 8): register the executor,
        then stream TaskDefinitions as the pump assigns them. One stream per
        executor — a new subscription supersedes (and closes) the old one,
        so a reconnect after a network blip cannot leave a zombie stream
        holding credit."""
        self._refuse_if_crashed(context)
        if not self.push_enabled:
            if context is not None:
                context.abort(
                    grpc.StatusCode.UNIMPLEMENTED,
                    "push dispatch disabled on this scheduler",
                )
            raise RuntimeError("push dispatch disabled")
        sub = _PushSubscriber(request.metadata.id, request.slots or 4)
        with self._push_mu:
            prior = self._subscribers.get(sub.executor_id)
            if prior is not None:
                prior.close()
            self._subscribers[sub.executor_id] = sub
        log.info("executor %s subscribed for push dispatch (slots=%d)",
                 sub.executor_id, sub.slots)
        with self.state.kv.lock():
            # register the executor before its first poll so assignment's
            # liveness/blacklist checks see it, then hand it whatever is
            # already runnable
            self.state.save_executor_metadata(request.metadata)
            self._pump_pushes()

        def stream():
            try:
                while not sub.closed.is_set() and not self.crashed:
                    if context is not None and not context.is_active():
                        return
                    try:
                        td = sub.queue.get(timeout=0.25)
                        if td is None:  # close() sentinel
                            return
                    except queue.Empty:
                        # periodic self-heal pump — THIS subscriber only:
                        # requeues with no event hook (restart recovery,
                        # lease-expiry resets) still dispatch within one
                        # tick, at O(subscribers) total idle cost
                        try:
                            with self.state.kv.lock():
                                self._pump_one_locked(sub)
                        except Exception:
                            pass
                        continue
                    yield td
            finally:
                self._close_subscriber(sub)

        return stream()

    def PollWork(self, request: pb.PollWorkParams, context=None) -> pb.PollWorkResult:
        import time as _time

        self._refuse_if_crashed(context)
        with self.state.kv.lock():
            self.state.save_executor_metadata(request.metadata)
            now = _time.time()
            if now - self._last_lost_check > self.lost_task_check_interval:
                self._last_lost_check = now
                n = self.state.reset_lost_tasks()
                if n:
                    log.warning("re-scheduled %d tasks from dead executors", n)
            # ownership gate (ISSUE 20): fold statuses only for jobs this
            # replica owns — adopting expired-lease jobs on the spot (the
            # thread-free half of failover). Statuses for a live PEER's
            # jobs are left on the executor's queue: the poll still folds
            # everything writable, then ends in a redirecting UNAVAILABLE
            # so the executor's retry loop re-homes to the owner and
            # re-delivers (accept_task_status is idempotent).
            foreign: Dict[str, pb.JobLease] = {}
            for job_id in sorted(
                {ts.partition_id.job_id for ts in request.task_status}
            ):
                holder = self.state.ensure_job_writable(job_id)
                if holder is not None:
                    foreign[job_id] = holder
            jobs = set()
            for ts in request.task_status:
                if ts.partition_id.job_id in foreign:
                    continue
                # stale reports from already-reset attempts are dropped;
                # accepted ones keep the KV-side attempt history
                if self.state.accept_task_status(ts):
                    jobs.add(ts.partition_id.job_id)
                    self._accepted_statuses += 1
                    # generation-rotated key: a restarted scheduler must
                    # draw fresh verdicts, not re-crash at the same status
                    if self._chaos is not None and self._chaos.should_inject(
                        "scheduler.crash",
                        f"g{self.state.generation}"
                        f"/status{self._accepted_statuses}",
                    ):
                        # accepted writes up to HERE are durable; the rest
                        # of this poll's statuses are requeued by the
                        # executor and re-delivered to the restarted
                        # scheduler (accept_task_status is idempotent)
                        self._crash(context)
            # after statuses (a completed report must clear its assignment
            # first): requeue assignments this executor never received.
            # Prefer the attempt-enriched echo; fall back to the bare
            # PartitionId form for pre-ISSUE-6 executors
            echo = (
                request.running_echo
                if len(request.running_echo)
                else request.running_tasks
            )
            n = self.state.reconcile_running_tasks(request.metadata.id, echo)
            if n:
                log.warning(
                    "requeued %d orphaned assignment(s) for executor %s",
                    n, request.metadata.id,
                )
            # push-credit resolution (ISSUE 8): a terminal status from this
            # executor frees the pushed-task credit it held
            with self._push_mu:
                sub = self._subscribers.get(request.metadata.id)
            if sub is not None:
                for ts in request.task_status:
                    if ts.WhichOneof("status") in (
                        "completed", "failed", "fetch_failed"
                    ):
                        pid = ts.partition_id
                        sub.outstanding.discard(
                            (pid.job_id, pid.stage_id, pid.partition_id,
                             ts.attempt)
                        )
            result = pb.PollWorkResult()
            # no dispatch on a poll that is about to redirect: an assigned
            # task would flip Running durably and then die with the abort,
            # riding the 3s orphan grace for nothing
            if request.can_accept_task and not foreign:
                speculative = False
                assigned = self.state.assign_next_schedulable_task(request.metadata.id)
                if assigned is None:
                    # idle capacity + no fresh work: offer the slot to the
                    # straggler monitor (ISSUE 11) — on poll-mode clusters
                    # this is how a speculative duplicate dispatches
                    assigned = self.state.maybe_speculate(request.metadata.id)
                    speculative = assigned is not None
                if assigned is not None:
                    from ballista_tpu.ops.runtime import record_serving

                    status, plan = assigned
                    result.task.CopyFrom(self._task_definition(status, plan))
                    result.task.speculative = speculative
                    if not speculative:
                        # scan-sharing pass (ISSUE 13): batch co-pending
                        # compatible stages of other jobs onto this reply
                        for st2, plan2 in self.state.form_shared_batch(
                            status, plan, request.metadata.id
                        ):
                            result.task.siblings.add().CopyFrom(
                                self._task_definition(st2, plan2)
                            )
                    record_serving("dispatch_poll")
            for job_id in jobs:
                self.state.synchronize_job_status(job_id)
            # accepted statuses may have completed upstream stages (or the
            # credit resolution above freed slots): dispatch the newly
            # runnable work NOW instead of waiting for a subscriber tick
            self._pump_pushes()
            if foreign:
                from ballista_tpu.ops.runtime import record_recovery

                job_id, holder = sorted(foreign.items())[0]
                record_recovery("ownership_redirected")
                detail = (
                    f"job {job_id} owned by peer replica "
                    f"{holder.replica_id!r} at {holder.addr}; re-home"
                )
                log.info("PollWork(%s) redirected: %s",
                         request.metadata.id, detail)
                if context is not None:
                    context.abort(grpc.StatusCode.UNAVAILABLE, detail)
                raise RuntimeError(detail)
            # idle-capacity re-home (ISSUE 20): a fully idle executor (no
            # statuses, no echoes, nothing assigned this poll) polled a
            # replica with nothing to dispatch while a live peer owns a job
            # that still has PENDING tasks. Without this, an executor homed
            # to a workless replica never learns a failover moved its work:
            # the non-owner answers empty polls forever. Bounce it to the
            # owner — the client's retry loop jumps endpoints on the named
            # address, and closing the local push stream (idle by the same
            # check) makes the re-subscribe follow.
            if (
                not result.HasField("task")
                and not request.task_status
                and not len(request.running_echo)
                and not len(request.running_tasks)
            ):
                hint = self._peer_with_pending_work_locked()
                if hint is not None:
                    job_id, holder = hint
                    with self._push_mu:
                        sub = self._subscribers.get(request.metadata.id)
                        if sub is not None and sub.outstanding:
                            # pushed work in flight: not idle after all
                            return result
                        self._subscribers.pop(request.metadata.id, None)
                    if sub is not None:
                        sub.close()
                    from ballista_tpu.ops.runtime import record_recovery

                    record_recovery("idle_rehomed")
                    detail = (
                        f"job {job_id} owned by peer replica "
                        f"{holder.replica_id!r} at {holder.addr}; re-home"
                    )
                    log.info("PollWork(%s) idle re-home: %s",
                             request.metadata.id, detail)
                    if context is not None:
                        context.abort(grpc.StatusCode.UNAVAILABLE, detail)
                    raise RuntimeError(detail)
            return result

    def GetJobStatus(self, request: pb.GetJobStatusParams, context=None) -> pb.GetJobStatusResult:
        self._refuse_if_crashed(context)
        status = self.state.get_job_metadata(request.job_id)
        result = pb.GetJobStatusResult()
        if status is not None:
            result.status.CopyFrom(status)
            # ownership hint (ISSUE 20): the status itself is KV truth and
            # answers from ANY replica, but push subscriptions and lost-
            # partition reports belong on the owner — hand clients its
            # address when that is a live peer
            lease = self.state.job_lease(request.job_id)
            if (
                lease is not None
                and lease.addr
                and not self.state.owns_job(request.job_id)
            ):
                result.owner_addr = lease.addr
        return result

    def ReportLostPartition(
        self, request: pb.ReportLostPartitionParams, context=None
    ) -> pb.ReportLostPartitionResult:
        """A client's result fetch failed: restart the final-stage tasks
        that died with the named executor through the lineage/retry
        machinery (scheduler/state.py::restart_completed_job). Covers both
        a COMPLETED job (the PR 5/6 buffered-fetch case; the status flips
        back to running) and a still-RUNNING job whose published
        partial_location died under a streaming client (ISSUE 8). Declined
        (restarted=False) when the job is terminal-failed/queued or nothing
        completed on that executor — the client re-raises its fetch error."""
        self._refuse_if_crashed(context)
        with self.state.kv.lock():
            # restart surgery belongs on the owner (ISSUE 20): it rewrites
            # task statuses and the assignment ledger. Adopt expired-lease
            # jobs on the spot; redirect for a live peer's.
            holder = self.state.ensure_job_writable(request.job_id)
            if holder is not None:
                detail = (
                    f"job {request.job_id} owned by peer replica "
                    f"{holder.replica_id!r} at {holder.addr}; report there"
                )
                if context is not None:
                    context.abort(grpc.StatusCode.UNAVAILABLE, detail)
                raise RuntimeError(detail)
            n = self.state.restart_completed_job(
                request.job_id, request.executor_id
            )
            restarted = n > 0
            if n == 0:
                # concurrent-reporter race: another client's report already
                # flipped the job back to running. Tell this client to keep
                # polling (restarted=True) instead of re-raising its fetch
                # error while recovery is in flight.
                js = self.state.get_job_metadata(request.job_id)
                restarted = (
                    js is not None and js.WhichOneof("status") == "running"
                )
                if (
                    js is not None
                    and js.WhichOneof("status") == "completed"
                    and js.completed.cached
                ):
                    # a CACHE-SERVED job has no tasks to restart: the data
                    # died (or was GC'd) under a still-live lease. Eagerly
                    # invalidate the entry and fail the job — the client
                    # resubmits and the fresh submission misses the cache
                    # and executes for real (client/context.py retries the
                    # resubmission itself on collect()).
                    fp = self.state.get_job_fingerprint(request.job_id)
                    if fp is not None:
                        self.state.result_cache_invalidate(fp)
                    failed = pb.JobStatus()
                    failed.failed.error = (
                        "cached result partitions lost with executor "
                        f"{request.executor_id}; the cache entry was "
                        "invalidated — resubmit the query"
                    )
                    self.state.save_job_metadata(request.job_id, failed)
                    restarted = False
            if n:
                # the requeued final-stage tasks are runnable immediately
                self._pump_pushes()
        log.warning(
            "ReportLostPartition(job=%s, executor=%s, %s/%s): restarted %d",
            request.job_id, request.executor_id,
            request.stage_id, request.partition_id, n,
        )
        return pb.ReportLostPartitionResult(restarted=restarted, tasks_restarted=n)

    def GetExecutorsMetadata(self, request, context=None) -> pb.GetExecutorMetadataResult:
        self._refuse_if_crashed(context)
        result = pb.GetExecutorMetadataResult()
        for m in self.state.get_executors_metadata():
            result.metadata.add().CopyFrom(m)
        return result

    def GetFileMetadata(self, request: pb.GetFileMetadataParams, context=None) -> pb.GetFileMetadataResult:
        self._refuse_if_crashed(context)
        # parquet only, like the reference (lib.rs:184-222)
        if request.file_type.lower() != "parquet":
            raise ValueError("GetFileMetadata supports parquet only")
        # fail fast: a blocked waiter would itself occupy an RPC worker
        # thread, defeating the purpose of the cap
        if not self._file_meta_slots.acquire(blocking=False):
            raise RuntimeError(
                "GetFileMetadata: too many concurrent metadata requests; retry"
            )
        try:
            from ballista_tpu.datasource import ParquetTableSource
            from ballista_tpu.executor.confine import (
                check_scan_files,
                check_scan_roots_path,
            )

            # same allowlist as ExecuteQuery: this RPC reads parquet footers of
            # client-named host paths
            check_scan_roots_path(request.path, self.config.data_roots())
            src = ParquetTableSource(request.path)
            check_scan_files(src.files, self.config.data_roots())
            return pb.GetFileMetadataResult(
                schema_ipc=schema_to_ipc(src.schema()),
                num_partitions=src.num_partitions(),
            )
        finally:
            self._file_meta_slots.release()


def serve(
    server_impl: SchedulerServer,
    bind_host: str = "0.0.0.0",
    port: int = 50050,
    max_workers: int = 32,
) -> grpc.Server:
    from ballista_tpu.scheduler.rpc import GRPC_MESSAGE_OPTIONS

    # each subscribed executor's SubscribeWork stream pins one worker thread
    # for its lifetime (ISSUE 8): deployments MUST size max_workers to
    # executor_count + heartbeat headroom (default fits ~16 push executors;
    # past that, raise it or disable ballista.executor.push_dispatch), or a
    # full pool would starve PollWork heartbeats and lapse healthy leases
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=GRPC_MESSAGE_OPTIONS,
    )
    add_scheduler_service(server, server_impl)
    bound = server.add_insecure_port(f"{bind_host}:{port}")
    if bound == 0:
        raise RuntimeError(f"cannot bind scheduler to {bind_host}:{port}")
    server.start()
    # a SERVING scheduler runs replica housekeeping (ISSUE 20): lease
    # renewal, dead-peer adoption, queued-grace, shuffle-dir TTL sweep.
    # In-process test servers that never serve() stay thread-free.
    server_impl.start_housekeeping()
    # SubscribeWork streams (ISSUE 8) hold their worker thread inside the
    # response generator until cancelled; a process exiting WITHOUT a clean
    # cluster shutdown would then hang in ThreadPoolExecutor's atexit join
    # forever. Regular atexit callbacks run BEFORE threading's — stopping
    # the server here cancels every live stream so the join drains.
    # Idempotent: a second stop() on an already-stopped server is a no-op.
    import atexit

    atexit.register(server.stop, None)
    log.info("scheduler listening on %s:%s", bind_host, bound)
    server._ballista_port = bound  # actual port when port=0
    return server

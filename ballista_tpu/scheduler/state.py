"""Scheduler cluster state over a KV backend.

Mirrors the reference's SchedulerState (rust/scheduler/src/state/mod.rs):
every piece of cluster state is a protobuf value under
/ballista/{namespace}/... keys, so a restarted scheduler on a durable
backend resumes mid-job. Key layout (ref state/mod.rs:387-434):

    executors/{id}                  ExecutorMetadata (60s lease)
    jobs/{job_id}                   JobStatus
    settings/{job_id}               JobSettings (client per-job settings)
    stages/{job_id}/{stage_id}      PhysicalPlanNode (the stage plan)
    tasks/{job_id}/{stage_id}/{p}   TaskStatus (empty oneof = pending)
    assignments/{job_id}/{stage}/{p} Assignment (durable in-flight ledger)
    tenants/{job_id}                JobTenant (tenant + priority, ISSUE 7)
    jobfp/{job_id}                  result-cache fingerprint of the job
    resultcache/{fingerprint}       ResultCacheEntry (completed locations)
    meta/restart_generation         int (bumped by each restart recovery)
    leases/{job_id}                 JobLease, TTL-leased (ISSUE 20: which
                                    replica owns the job + fencing gen)
    leasegen/{job_id}               int (monotonic fencing-generation
                                    counter; outlives each lease)
    meta/plan_epoch                 int (bumped on task-set mutations so
                                    peer task indexes re-seed on change)
    meta/rc_epoch                   int (bumped on result-cache count
                                    changes; peers re-derive the count)
    replicas/{replica_id}           replica liveness heartbeat, TTL-leased
                                    (renewed by the housekeeping thread)
    planner/{job_id}                which replica accepted the submission
                                    (queued-grace provenance, ISSUE 20)
    plancache/{content_key}         serialized PhysicalPlanNode — the KV
                                    tier of the cross-job plan cache

Crash tolerance (ISSUE 6): planning writes publish atomically through
KvBackend.put_all (the `running` job status is the commit marker — a job
still `queued` after a scheduler crash was never committed), the
assignment ledger is written through to the KV so a restarted scheduler
reloads it, and `recover()` folds the reloaded ledger against executors'
PollWork `running_echo` — tasks the owner still runs are re-adopted,
tasks nobody vouches for within the grace window requeue through the
normal retry/lineage path.

Replicated control plane (ISSUE 20): N scheduler replicas share one KV
store, and job ownership shards by lease — `leases/{job}` is minted
atomically WITH the planning commit (same put_all) and renewed by the
owner; replica death is lease expiry, and an idle peer adopts the dead
replica's jobs by running recover() scoped to them (failover = restart
recovery run by a peer). Every job-scoped durable write by an owner is a
compare-and-swap against its remembered lease value (the FENCING rule):
a deposed-but-alive owner's stale writes are rejected whole, and the
rejection drops its local ownership. The fencing generation is minted
from the durable `leasegen/{job}` counter in the same atomic batch, so
generations never repeat across adoptions.
"""

from __future__ import annotations

import logging
import os
import shutil
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

from ballista_tpu.config import BALLISTA_MAX_TASK_RETRIES, BallistaConfig
from ballista_tpu.distributed.planner import (
    find_unresolved_shuffles,
    remove_unresolved_shuffles,
)
from ballista_tpu.distributed.stages import (
    ShuffleLocation,
    ShuffleReaderExec,
    ShuffleWriterExec,
)
from ballista_tpu.proto import ballista_pb2 as pb
from ballista_tpu.scheduler.kv import KvBackend
from ballista_tpu.serde.physical import phys_plan_from_proto, phys_plan_to_proto
from ballista_tpu.utils.locks import make_lock

log = logging.getLogger("ballista.scheduler")

EXECUTOR_LEASE_SECS = 60.0  # ref state/mod.rs:42

# how long after assignment an executor's polls may omit a task from its
# running_tasks echo before the scheduler treats the assignment as lost in
# transit (PollWork response never arrived) and requeues it. Must exceed a
# couple of executor poll intervals (0.25s) plus scheduling slack.
ORPHANED_ASSIGNMENT_GRACE_SECS = 3.0

# cold prior one never-observed pending task contributes to the predicted
# autoscaling backlog (ISSUE 15): small enough that priors alone never
# grow the fleet, nonzero so a deep cold queue still registers
BACKLOG_COLD_TASK_SECONDS = 0.02


def _record_recovery(event: str, n: int = 1) -> None:
    # lazy: scheduler state must stay importable before the ops runtime
    from ballista_tpu.ops.runtime import record_recovery

    record_recovery(event, n)


def _record_tenancy(event: str, n: int = 1) -> None:
    from ballista_tpu.ops.runtime import record_tenancy

    record_tenancy(event, n)


def _record_speculation(event: str, n: float = 1) -> None:
    from ballista_tpu.ops.runtime import record_speculation

    record_speculation(event, n)


def _record_shared_scan(event: str, n: int = 1) -> None:
    from ballista_tpu.ops.runtime import record_shared_scan

    record_shared_scan(event, n)


def _record_routing(engine: str, op: str = "", predicted_s=None,
                    observed_s=None) -> None:
    from ballista_tpu.ops.runtime import record_routing

    record_routing(engine, op, predicted_s, observed_s)


def _record_delta(event: str, n: int = 1) -> None:
    from ballista_tpu.ops.runtime import record_delta

    record_delta(event, n)


def _record_shuffle_tier(event: str, n: int = 1) -> None:
    from ballista_tpu.ops.runtime import record_shuffle_tier

    record_shuffle_tier(event, n)


def _attempts_error(t: pb.TaskStatus) -> str:
    """Human-readable failure naming EVERY attempt of the task — the error
    a job fails with once retries are exhausted."""
    lines = [
        f"attempt {h.attempt} on {h.executor_id or '?'}: {h.error}"
        for h in t.history
    ]
    w = t.WhichOneof("status")
    if w == "failed":
        lines.append(
            f"attempt {t.attempt} on {t.failed.executor_id or '?'}: {t.failed.error}"
        )
    elif w == "fetch_failed":
        ff = t.fetch_failed
        lines.append(
            f"attempt {t.attempt} on {ff.executor_id or '?'}: fetch of lost "
            f"shuffle output {ff.map_executor_id}:{ff.path} "
            f"(map {ff.map_stage_id}/{ff.map_partition_id}) failed: {ff.error}"
        )
    pid = t.partition_id
    return (
        f"task {pid.job_id}/{pid.stage_id}/{pid.partition_id} failed after "
        f"{len(lines)} attempt(s): " + "; ".join(lines)
    )


class _TaskIndex:
    """Per-stage pending/incomplete index over task statuses.

    assign_next_schedulable_task previously re-scanned (and re-parsed) EVERY
    task protobuf in the KV under the global scheduler lock on every poll —
    O(total tasks) per idle poll. The index keeps, per (job_id, stage_id):
    the pending partitions (status oneof unset), the not-yet-completed
    partitions (answers "is this upstream stage fully done" in O(1)), and
    the total task count (a stage with NO tasks is never a satisfied
    dependency). It is seeded lazily from one full scan — a restarted
    scheduler on a durable backend resumes correctly — and then maintained
    on every save_task_status transition, which is the single write path
    for task state (planning, poll updates, lost-task resets)."""

    def __init__(self) -> None:
        self.pending: Dict[Tuple[str, int], set] = {}
        self.incomplete: Dict[Tuple[str, int], set] = {}
        self.total: Dict[Tuple[str, int], set] = {}
        # in-flight partitions per stage (status oneof == running): the
        # per-tenant in-flight totals behind admission quotas and weighted
        # fair share (ISSUE 7) sum these through the job->tenant map
        self.running: Dict[Tuple[str, int], set] = {}

    def observe(self, status: pb.TaskStatus) -> None:
        pid = status.partition_id
        key = (pid.job_id, pid.stage_id)
        part = pid.partition_id
        self.total.setdefault(key, set()).add(part)
        w = status.WhichOneof("status")
        if w is None:
            self.pending.setdefault(key, set()).add(part)
        else:
            self._drop(self.pending, key, part)
        if w == "running":
            self.running.setdefault(key, set()).add(part)
        else:
            self._drop(self.running, key, part)
        if w == "completed":
            self._drop(self.incomplete, key, part)
        else:
            self.incomplete.setdefault(key, set()).add(part)

    @staticmethod
    def _drop(index: Dict[Tuple[str, int], set], key, part) -> None:
        """Remove part from index[key], deleting drained entries — a
        long-lived scheduler must not re-sort every stage it ever saw on
        each poll."""
        s = index.get(key)
        if s is None:
            return
        s.discard(part)
        if not s:
            del index[key]

    def stage_done(self, job_id: str, stage_id: int) -> bool:
        key = (job_id, stage_id)
        return bool(self.total.get(key)) and not self.incomplete.get(key)


# a peer scheduler sharing the namespace writes tasks this instance's index
# never observes; re-seed from a full scan at most this often so peer-
# submitted jobs are discovered within a bounded delay (single-scheduler
# deployments see every write through save_task_status and never need it,
# but still pay at most one scan per interval instead of one per poll)
TASK_INDEX_RESEED_SECS = 5.0


class JobPlanBatch:
    """One job's planning output, published all-or-nothing (ISSUE 6).

    Job submission used to write job metadata, per-stage plans, and task
    statuses as independent puts — a scheduler crash mid-plan left a torn
    job (some stages visible, some tasks missing, status forever queued).
    The batch stages every planning write in memory and commits them in a
    single KvBackend.put_all TOGETHER WITH the `running` job-status flip,
    which is therefore the commit marker: a job still `queued` after a
    crash provably has no planning keys (transactional backends roll the
    batch back; recover() discards leakage from non-transactional ones).

    Every staged write passes the `scheduler.plan_write` chaos site, keyed
    on PLAN coordinates + the planning attempt (never the random job id),
    so a seeded chaos run tears planning at the same point every run and a
    planning retry draws fresh verdicts."""

    def __init__(self, state: "SchedulerState", job_id: str, attempt: int = 0) -> None:
        self._state = state
        self.job_id = job_id
        self.attempt = attempt
        self._items: List[Tuple[str, bytes]] = []
        self._tasks: List[pb.TaskStatus] = []

    def _chaos(self, key: str) -> None:
        if self._state._chaos is not None:
            self._state._chaos.maybe_fail(
                "scheduler.plan_write", f"{key}@a{self.attempt}"
            )

    def add_stage_plan(self, stage_id: int, plan) -> None:
        self._chaos(f"stage{stage_id}")
        msg = phys_plan_to_proto(plan)
        self._items.append((
            self._state._key("stages", self.job_id, str(stage_id)),
            msg.SerializeToString(),
        ))

    def add_pending_task(self, stage_id: int, partition: int) -> None:
        self._chaos(f"{stage_id}/{partition}")
        pending = pb.TaskStatus()
        pending.partition_id.job_id = self.job_id
        pending.partition_id.stage_id = stage_id
        pending.partition_id.partition_id = partition
        self._items.append((
            self._state._key(
                "tasks", self.job_id, str(stage_id), str(partition)
            ),
            pending.SerializeToString(),
        ))
        self._tasks.append(pending)

    def commit(self) -> None:
        """Publish the whole plan + the queued->running flip atomically,
        minting the job's ownership lease in the same batch (ISSUE 20)."""
        self._chaos("commit")
        running = pb.JobStatus()
        running.running.SetInParent()
        items = self._items + [(
            self._state._key("jobs", self.job_id),
            running.SerializeToString(),
        )]
        self._state.commit_plan_batch(self.job_id, items)
        # index only AFTER the publish succeeded: an aborted batch must
        # leave no trace, in the index included
        if self._state._task_index is not None:
            for t in self._tasks:
                self._state._task_index.observe(t)
        # the running flip bypasses save_job_metadata (it rides the atomic
        # batch), so push-status subscribers (ISSUE 11) are notified here
        self._state._notify_job_status(self.job_id, running)


class SchedulerState:
    def __init__(
        self,
        kv: KvBackend,
        namespace: str = "default",
        config: Optional[BallistaConfig] = None,
    ) -> None:
        self.kv = kv  # durability: ephemeral(the backend handle itself, not state)
        self.namespace = namespace  # durability: ephemeral(construction parameter)
        self.config = config or BallistaConfig()  # durability: ephemeral(construction parameter)
        self._task_index: Optional[_TaskIndex] = None  # durability: derived(_ensure_task_index)
        self._task_index_seeded_at = 0.0  # durability: derived(_ensure_task_index)
        # deterministic fault injection for the KV write seam (utils/chaos.py)
        from ballista_tpu.utils.chaos import chaos_from_config

        # durability: ephemeral(deterministic fault-injection config, per process by design)
        self._chaos = chaos_from_config(self.config)
        # kv.put key rotation; under the kv lock
        # durability: ephemeral(per-process chaos sequence, fresh verdicts after restart by design)
        self._chaos_puts = 0
        # assignment ledger: (job, stage, part) -> (executor, attempt,
        # monotonic time, restored-by-restart). PollWork is retried on
        # UNAVAILABLE and is NOT idempotent: if the response carrying an
        # assignment is lost, the task sits Running on a live-lease executor
        # that never heard of it. Executors echo their in-flight tasks each
        # poll; reconcile_running_tasks requeues ledger entries the owner
        # stopped vouching for. Every mutation is WRITTEN THROUGH to the KV
        # under assignments/{job}/{stage}/{part} (pb.Assignment, keyed by
        # plan coordinates so replays are idempotent) — recover() reloads it
        # after a scheduler restart with a fresh grace window, so restart
        # reconciliation re-adopts tasks executors still run instead of
        # waiting for the lease machinery. The in-memory map carries the
        # monotonic timestamp (wall clock is not restart-comparable). All
        # access happens under the scheduler's global KV lock held by
        # PollWork.
        self._assigned: Dict[  # durability: durable(assignments)
            Tuple[str, int, int], Tuple[str, int, float, bool]
        ] = {}
        # how many restart recoveries this store has seen (0 = first life).
        # Chaos keys that are per-process sequences (scheduler.crash) fold
        # the generation in, so a restarted scheduler draws FRESH verdicts
        # instead of deterministically re-crashing at the same point.
        self.generation = 0  # durability: durable(meta)
        # -- multi-tenant bookkeeping (ISSUE 7) -----------------------------
        # read-through cache of the durable tenants/{job} records (a job's
        # tenant is immutable, so cached entries never go stale) and the
        # per-tenant assignment totals behind bench's fairness report.
        # Both are touched from PollWork (under the global KV lock) AND from
        # ExecuteQuery / test probes, so they carry their own lock.
        self._tenant_mu = make_lock("scheduler.state._tenant_mu")  # durability: ephemeral(a lock guards state, it is not state)
        # job -> (tenant, priority, created_at); guarded-by: self._tenant_mu
        self._tenant_cache: Dict[str, Tuple[str, int, float]] = {}  # durability: derived(_job_tenant_full)
        self.tenant_assigned: Dict[str, int] = {}  # durability: ephemeral(fairness telemetry, re-accumulates from live flow)  # guarded-by: self._tenant_mu
        # scheduler.admit chaos rotation: like _chaos_puts, a per-process
        # admission sequence so a faulted admission's retry (the executor's
        # next poll) draws a fresh deterministic verdict
        self._admit_seq = 0  # under the kv lock (PollWork body)  # durability: ephemeral(per-process chaos sequence)
        # parse the tenancy config ONCE, here: a malformed weights string
        # (or quota) must fail scheduler construction with a clear error,
        # not raise inside every assignment scan and wedge all scheduling
        self._tenant_weights = self.config.tenant_weights()  # durability: ephemeral(parsed once from config at construction)
        self._tenant_quota = self.config.tenant_max_inflight()  # durability: ephemeral(parsed once from config at construction)
        self._tenant_slos = self.config.tenant_slos()  # durability: ephemeral(parsed once from config at construction)
        # -- speculative execution (ISSUE 11) ------------------------------
        # the scheduler is also a cost-model CLIENT now: completed task
        # durations are observed under job-independent task.run ops and the
        # straggler monitor predicts from them, so configure the store from
        # this config (idempotent beside the executor-side configures — a
        # standalone cluster shares one process-global store)
        from ballista_tpu.ops import costmodel

        costmodel.configure(self.config)
        self._spec_enabled = self.config.speculation()  # durability: ephemeral(config snapshot)
        self._spec_multiplier = self.config.speculation_multiplier()  # durability: ephemeral(config snapshot)
        self._spec_floor_s = self.config.speculation_min_runtime_s()  # durability: ephemeral(config snapshot)
        # re-speculation bound (ISSUE 15 satellite, PR 11 residue): a
        # duplicate that itself straggles past the same threshold may be
        # superseded by a fresh duplicate, up to this many launches per
        # task. _spec_launches counts them; _spec_superseded remembers the
        # ABANDONED duplicates' attempt numbers so their late reports are
        # retired without touching the task (a superseded completion still
        # wins — first completion wins, whoever crosses the line). Both
        # in-memory, under the global KV lock like the ledger map; a
        # restarted scheduler rebuilds the launch count from the ledger
        # record (attempt arithmetic) and forgets the superseded set — the
        # attempt-numbering floor in requeue_task keeps late reports from
        # ever impersonating a fresh attempt regardless.
        self._spec_max = self.config.speculation_max_attempts()  # durability: ephemeral(config snapshot)
        self._spec_launches: Dict[Tuple[str, int, int], int] = {}  # durability: derived(recover)
        self._spec_superseded: Dict[Tuple[str, int, int], set] = {}  # durability: ephemeral(superseded-attempt memory, the attempt floor retires late reports regardless)
        # running-task watch: (job, stage, part) -> (executor, attempt,
        # monotonic start). Maintained by save_task_status (the single task
        # write path), consumed by the straggler monitor and by the
        # completion-duration observation. In-memory only — a restarted
        # scheduler re-learns durations from fresh completions.
        self._running_since: Dict[  # durability: ephemeral(monotonic watch, re-learned from live polls)
            Tuple[str, int, int], Tuple[str, int, float]
        ] = {}
        # active speculative duplicates: (job, stage, part) -> (executor,
        # attempt, monotonic launch, vouched, restored). Write-through to
        # speculation/{job}/{stage}/{part} (pb.Assignment) so a scheduler
        # restart recovers BOTH attempts of an in-flight pair — the primary
        # from its tasks/ running status, the duplicate from here.
        self._speculative: Dict[  # durability: durable(speculation)
            Tuple[str, int, int], Tuple[str, int, float, bool, bool]
        ] = {}
        # elapsed-ordered straggler heap (ISSUE 13 satellite, PR 11
        # residue): (monotonic start, key3) entries mirroring
        # _running_since, so the straggler monitor scans ONLY tasks past
        # the speculation floor instead of every running task under the
        # global KV lock on each idle slot. Lazily invalidated — an entry
        # whose start time no longer matches the watch map is a superseded
        # attempt and drops on sight. Access under the global KV lock like
        # _running_since.
        self._running_heap: List[Tuple[float, Tuple[str, int, int]]] = []  # durability: ephemeral(scan accelerator mirroring _running_since, lazily invalidated)
        # -- shared-scan batching (ISSUE 13) --------------------------------
        self._shared_scan = self.config.shared_scan()  # durability: ephemeral(config snapshot)
        self._shared_max_batch = self.config.shared_scan_max_batch()  # durability: ephemeral(config snapshot)
        # scheduler.batch chaos rotation (like _admit_seq): a torn batch
        # formation degrades THAT dispatch to solo; the next formation
        # draws a fresh deterministic verdict
        self._batch_seq = 0  # under the kv lock (dispatch paths)  # durability: ephemeral(per-process chaos sequence)
        # batched-task accounting: member key3 -> batch id, and batch id ->
        # {k, t0, remaining, predicted, dirty}. In-memory only (pure
        # cost-model learning; a restarted scheduler just re-learns), all
        # access under the global KV lock.
        self._batch_members: Dict[Tuple[str, int, int], int] = {}  # durability: ephemeral(cost-model learning, a restarted scheduler re-learns)
        self._batches: Dict[int, dict] = {}  # durability: ephemeral(cost-model learning, a restarted scheduler re-learns)
        self._batch_next_id = 0  # durability: ephemeral(batch ids are process-local handles)
        # (job, stage) -> scan-sharing signature (or None): stage plans are
        # immutable once planned, so the signature is computed once — the
        # candidate scan must not re-deserialize every co-pending stage
        # plan on every dispatch. Bounded like _task_op_cache.
        self._shared_sig_cache: Dict[Tuple[str, int], Optional[tuple]] = {}  # durability: ephemeral(content-keyed memo over immutable stage plans, misses recompute)
        # per-(job, stage) cache of the job-independent task.run cost op
        self._task_op_cache: Dict[Tuple[str, int], str] = {}  # durability: ephemeral(content-keyed memo, misses recompute)
        # scheduler-owned task.run rates (op -> (total seconds, n)): the
        # process-global cost store is cleared by ANY job whose merged
        # per-job settings carry a different cost_model_dir (configure()
        # drops the store on a dir change) — the straggler monitor must
        # not lose its rates to a client config quirk. Observations mirror
        # into the store too (observability + cross-restart persistence
        # when a dir is configured); predictions consult this first.
        self._task_rates: Dict[str, Tuple[float, int]] = {}  # durability: ephemeral(duration learning, re-learned from completions and mirrored to the cost store)
        # tenant -> last wall time its oldest pending job was seen overdue:
        # the admit_slo_boosted counter counts boost EPISODES (enter
        # overdue), not admission scans — the scan runs on every poll/pump
        # tick, and a momentary pending-set drain at a stage boundary must
        # not end (and re-count) a continuous episode
        self._slo_boosted: Dict[str, float] = {}  # durability: ephemeral(episode edge detector, restart starts a new episode)
        # jobs whose SLO outcome was already counted: restart_completed_job
        # can re-fold a job to completed; one job is one outcome
        self._slo_noted: set = set()  # durability: ephemeral(one-outcome-per-job memo, re-folds idempotently)
        # push job-status notifications (ISSUE 11): the server installs a
        # callback invoked on every job-status write; must never raise into
        # the write path
        self.on_job_status = None  # durability: ephemeral(callback installed by the owning server at construction)
        # best-effort live result-cache entry count (ISSUE 8): lets the
        # under-cap common case of result_cache_put skip the full prefix
        # scan (a 1024-key range read per job completion, under the global
        # lock, just to learn nothing needs evicting). Lazily seeded from
        # one scan; the at-cap eviction path re-derives it from the
        # authoritative scan, so drift (e.g. a peer scheduler's writes)
        # self-corrects exactly when it would matter. All mutation happens
        # under the global KV lock the cache paths already hold.
        self._rc_count: Optional[int] = None  # durability: derived(_ensure_rc_count)
        # -- replicated control plane (ISSUE 20) ----------------------------
        # this replica's identity. "" is the single-scheduler default: a
        # restarted singleton sees its predecessor's leases carry the same
        # (empty) replica id and reclaims them, so every pre-replication
        # restart test keeps its exact semantics.
        self.replica_id = ""  # durability: ephemeral(replica identity, assigned by the owning server)
        self.replica_addr = ""  # durability: ephemeral(advertised host:port, assigned by the owning server)
        # job -> the exact serialized JobLease WE minted (the fencing token).
        # Every job-scoped durable write CASes against this value; a mismatch
        # means a peer adopted the job and this entry drops (_deposed). The
        # durable truth is leases/{job} itself — minted atomically with the
        # planning commit, recovered by re-minting in recover()/adopt_job.
        self._owned: Dict[str, bytes] = {}  # durability: durable(leases)
        self._lease_ttl = float(self.config.scheduler_lease_ttl_s())  # durability: ephemeral(config snapshot)
        # kv.lease chaos rotation (like _chaos_puts): generation-folded so a
        # restarted scheduler draws fresh verdicts; under the kv lock
        self._lease_seq = 0  # durability: ephemeral(per-process chaos sequence)
        # fencing telemetry: stale writes rejected because a peer holds the
        # lease now. Counts REJECTIONS observed by this (deposed) replica.
        self.fence_rejected = 0  # durability: ephemeral(telemetry counter, meaningful per life)
        # jobs this replica was deposed FROM: they must not degrade to the
        # unfenced never-leased write path — every later write stays
        # rejected until adopt_job re-claims the lease for real. The
        # durable truth is leases/{job}; this only pins the local verdict.
        self._deposed_jobs: set = set()  # durability: ephemeral(local deposition memory; the lease row is the durable truth)
        # generation-stamped read-through views (ISSUE 20): the derived
        # task-index / rc-count caches were single-scheduler-fresh by
        # construction; with peers mutating the same KV they re-derive when
        # the durable epoch moves. None = never read the epoch yet.
        self._plan_epoch_seen: Optional[int] = None  # durability: derived(_ensure_task_index)
        self._rc_epoch_seen: Optional[int] = None  # durability: derived(_ensure_rc_count)

    def _key(self, *parts: str) -> str:
        return "/".join(("/ballista", self.namespace) + parts)

    # -- durable assignment ledger ------------------------------------------
    def _ledger_key(self, key: Tuple[str, int, int]) -> str:
        job_id, stage_id, partition = key
        return self._key("assignments", job_id, str(stage_id), str(partition))

    def _ledger_put(
        self, key: Tuple[str, int, int], executor_id: str, attempt: int
    ) -> None:
        """Record an in-flight assignment, write-through: memory carries the
        monotonic grace-window clock, the KV carries the restart truth."""
        self._assigned[key] = (executor_id, attempt, time.monotonic(), False)
        msg = pb.Assignment(executor_id=executor_id, attempt=attempt)
        # fenced (ISSUE 20): a rejected write means a peer adopted the job —
        # _fenced_put's deposition purge drops the entry just added above
        self._fenced_put(key[0], self._ledger_key(key), msg.SerializeToString())

    def _ledger_del(self, key: Tuple[str, int, int]) -> None:
        self._assigned.pop(key, None)
        self.kv.delete(self._ledger_key(key))

    # -- speculative-attempt ledger (ISSUE 11) ------------------------------
    def _spec_key(self, key: Tuple[str, int, int]) -> str:
        job_id, stage_id, partition = key
        return self._key("speculation", job_id, str(stage_id), str(partition))

    def _spec_put(
        self, key: Tuple[str, int, int], executor_id: str, attempt: int
    ) -> None:
        """Record an in-flight speculative duplicate, write-through like the
        assignment ledger: the KV carries the restart truth, memory the
        grace/accounting clocks."""
        self._speculative[key] = (
            executor_id, attempt, time.monotonic(), False, False,
        )
        msg = pb.Assignment(executor_id=executor_id, attempt=attempt)
        # fenced like _ledger_put: rejection purges the entry via _deposed
        self._fenced_put(key[0], self._spec_key(key), msg.SerializeToString())

    def _spec_del(self, key: Tuple[str, int, int]) -> None:
        if self._speculative.pop(key, None) is not None:
            self.kv.delete(self._spec_key(key))
        # the episode's launch budget resets with the ledger entry (a fresh
        # straggler signal may speculate again, as before ISSUE 15) — but
        # the SUPERSEDED set must outlive it: abandoned duplicates may
        # still be running, and their late reports are retired against it
        # until the task itself resolves (_spec_resolve).
        self._spec_launches.pop(key, None)

    def _spec_resolve(self, key: Tuple[str, int, int]) -> None:
        """The TASK resolved (completion accepted, requeue, or job done):
        close the whole speculation episode, superseded bookkeeping
        included. Requeues number past every minted speculative attempt
        (_spec_attempt_floor), so nothing retired here can impersonate a
        fresh attempt later."""
        self._spec_del(key)
        self._spec_superseded.pop(key, None)

    def _spec_attempt_floor(self, key: Tuple[str, int, int]) -> int:
        """Highest speculative attempt ever minted for the task (the live
        ledger entry and every superseded one): a requeue must number PAST
        it, or a late report from an abandoned duplicate could impersonate
        the fresh attempt and clobber its state."""
        spec = self._speculative.get(key)
        top = spec[1] if spec is not None else 0
        sup = self._spec_superseded.get(key)
        if sup:
            top = max(top, max(sup))
        return top

    def speculation_active(
        self, key: Tuple[str, int, int], executor_id: str, attempt: int
    ) -> bool:
        """True while (executor, attempt) is the live speculative duplicate
        of the task — the push-credit re-verification consults this (the
        duplicate has no tasks/ status of its own to vouch for it)."""
        s = self._speculative.get(key)
        return s is not None and s[0] == executor_id and s[1] == attempt

    def _notify_job_status(self, job_id: str, status: pb.JobStatus) -> None:
        """Invoke the push-status hook (ISSUE 11); a subscriber bug must
        never fail the status write it observes."""
        cb = self.on_job_status
        if cb is not None:
            try:
                cb(job_id, status)
            except Exception:
                log.debug("job-status notification failed", exc_info=True)

    # -- job-ownership leases + write fencing (ISSUE 20) --------------------
    def _lease_key(self, job_id: str) -> str:
        return self._key("leases", job_id)

    def _leasegen_key(self, job_id: str) -> str:
        return self._key("leasegen", job_id)

    def _lease_chaos(self) -> None:
        """kv.lease injection seam: the lease mint/claim op fails as if the
        store dropped the request. Keyed like kv.put on a generation-rotated
        per-process sequence (under the kv lock) so a retried mint draws a
        fresh deterministic verdict."""
        if self._chaos is not None:
            self._lease_seq += 1
            self._chaos.maybe_fail(
                "kv.lease", f"g{self.generation}/lease{self._lease_seq}"
            )

    def _mint_lease_items(self, job_id: str) -> Tuple[bytes, Tuple[str, bytes]]:
        """Next fencing generation for the job: read the durable
        `leasegen/{job}` counter and build (serialized JobLease to grant,
        the counter write that must ride the SAME atomic batch). The
        counter outlives each lease on purpose — fencing generations stay
        monotonic across any number of expiries and adoptions."""
        prior = self.kv.get(self._leasegen_key(job_id))
        fence = (int(prior) if prior else 0) + 1
        lease = pb.JobLease(
            replica_id=self.replica_id, fence=fence, addr=self.replica_addr
        )
        return (
            lease.SerializeToString(),
            (self._leasegen_key(job_id), str(fence).encode()),
        )

    def job_lease(self, job_id: str) -> Optional[pb.JobLease]:
        """The live ownership lease, or None (expired / never leased)."""
        raw = self.kv.get(self._lease_key(job_id))
        if raw is None:
            return None
        jl = pb.JobLease()
        jl.ParseFromString(raw)
        return jl

    def owns_job(self, job_id: str) -> bool:
        return job_id in self._owned

    def owned_jobs(self) -> List[str]:
        return list(self._owned)

    def renew_owned_leases(self) -> int:
        """Heartbeat: extend every owned job lease by one TTL. A renewal
        that finds the lease gone (expired, or a peer already claimed it)
        just drops — the next fenced write settles ownership truthfully.
        Returns how many leases were renewed."""
        n = 0
        for job_id in list(self._owned):
            if self.kv.lease_renew(self._lease_key(job_id), self._lease_ttl):
                n += 1
        return n

    def commit_plan_batch(self, job_id: str, items) -> None:
        """Publish a planned job's stages/tasks/running-flip atomically AND
        mint its ownership lease in the same batch (ISSUE 20): the lease is
        born with the commit marker, so there is no committed job without
        an owner and no owned job without a commit. The expect-absent CAS
        on the lease key makes two replicas racing the same job id lose
        cleanly (nothing from the loser's batch lands)."""
        lk = self._lease_key(job_id)
        self._lease_chaos()
        value, gen_item = self._mint_lease_items(job_id)
        ok = self.kv.put_all(
            list(items) + [gen_item],
            compare=(lk, None),
            leases=[(lk, value, self._lease_ttl)],
        )
        if not ok:
            raise RuntimeError(
                f"job {job_id}: planning commit lost the lease race — "
                "another replica already owns the job"
            )
        self._owned[job_id] = value
        self._bump_plan_epoch()

    def _fenced_put(self, job_id: str, key: str, value: bytes) -> bool:
        """The single job-scoped durable write seam (ISSUE 20). Owned jobs
        compare-and-swap against the remembered lease value: a mismatch
        means a peer adopted the job — this replica is DEPOSED, drops its
        ownership, and the write is REJECTED whole. An expired-but-
        unclaimed lease is lazily re-minted (fresh fencing generation) in
        the same batch: single-replica servers run no heartbeat thread, so
        their leases routinely expire mid-job and must self-heal. Jobs this
        replica never leased (hand-built test states, pre-ISSUE-20 rows)
        write straight through, exactly as before replication."""
        expected = self._owned.get(job_id)
        if expected is None:
            if job_id in self._deposed_jobs:
                return False  # deposed: never degrade to unfenced writes
            self.kv.put(key, value)
            return True
        lk = self._lease_key(job_id)
        if self.kv.put_all([(key, value)], compare=(lk, expected)):
            return True
        if self.kv.get(lk) is None:
            minted, gen_item = self._mint_lease_items(job_id)
            if self.kv.put_all(
                [(key, value), gen_item],
                compare=(lk, None),
                leases=[(lk, minted, self._lease_ttl)],
            ):
                self._owned[job_id] = minted
                _record_recovery("lease_reminted")
                return True
        self._deposed(job_id)
        return False

    def _deposed(self, job_id: str) -> None:
        """A peer's lease fenced out our write: drop ownership and every
        in-memory claim on the job. The DURABLE rows (assignment and
        speculation ledgers, statuses) now belong to the adopter — they are
        read here only to size the handoff, never deleted: the adopter's
        scoped recovery already reloaded them."""
        self._owned.pop(job_id, None)
        self._deposed_jobs.add(job_id)
        self.fence_rejected += 1
        _record_recovery("fence_rejected")
        holder = self.job_lease(job_id)
        handed_over = len(
            self.kv.get_prefix(self._key("assignments", job_id) + "/")
        ) + len(self.kv.get_prefix(self._key("speculation", job_id) + "/"))
        for key in [k for k in self._assigned if k[0] == job_id]:
            self._assigned.pop(key, None)
        for key in [k for k in self._speculative if k[0] == job_id]:
            self._speculative.pop(key, None)
            self._spec_launches.pop(key, None)
            self._spec_superseded.pop(key, None)
        for key in [k for k in self._running_since if k[0] == job_id]:
            self._running_since.pop(key, None)
        log.warning(
            "job %s: write fenced out — adopted by replica %r at %r "
            "(%d durable ledger entries handed over)",
            job_id,
            holder.replica_id if holder is not None else "?",
            holder.addr if holder is not None else "?",
            handed_over,
        )

    def adopt_job(self, job_id: str) -> bool:
        """Claim an expired job lease and run failover recovery scoped to
        the job (ISSUE 20): failover IS restart recovery run by a peer —
        the assignment/speculation ledgers reload with a fresh grace
        window, executors' running echoes re-adopt what still runs, and
        `restart_generation` stays untouched (no process died). Returns
        False when a peer won the claim race."""
        if job_id in self._owned:
            return True
        lk = self._lease_key(job_id)
        self._lease_chaos()
        minted, gen_item = self._mint_lease_items(job_id)
        if not self.kv.put_all(
            [gen_item], compare=(lk, None),
            leases=[(lk, minted, self._lease_ttl)],
        ):
            return False
        self._owned[job_id] = minted
        self._deposed_jobs.discard(job_id)
        _record_recovery("lease_adopted")
        self.recover(jobs={job_id})
        return True

    def _may_schedule(self, job_id: str) -> bool:
        """Ownership gate for the dispatch path: this replica schedules a
        job iff it holds (or can claim) the job's lease. Adopt-on-demand is
        the thread-free half of failover: any replica asked for work on a
        job whose owner's lease expired picks the job up on the spot."""
        if job_id in self._owned:
            return True
        if self.kv.get(self._lease_key(job_id)) is not None:
            return False  # a live peer owns it
        if self.kv.get(self._leasegen_key(job_id)) is None:
            return True  # never leased: legacy/hand-built state
        return self.adopt_job(job_id)

    def ensure_job_writable(self, job_id: str) -> Optional[pb.JobLease]:
        """Server admission gate: None when this replica may host work for
        the job (owned, adopted on the spot, or never leased), else the
        live FOREIGN lease carrying the owner's address to redirect to.
        Bounded retry: a foreign lease expiring between the two reads
        makes the job adoptable — loop back instead of returning a stale
        verdict either way."""
        for _ in range(3):
            if self._may_schedule(job_id):
                return None
            lease = self.job_lease(job_id)
            if lease is not None:
                return lease
        return None  # repeated expiry races: treat as writable (legacy path)

    def replica_heartbeat(self) -> None:
        """Renew (or re-grant) this replica's liveness key. The queued-
        grace sweep on PEERS reads it: a queued job whose submitting
        replica's heartbeat lapsed has no planner left to commit it."""
        if not self.replica_id:
            return
        k = self._key("replicas", self.replica_id)
        if not self.kv.lease_renew(k, self._lease_ttl):
            self.kv.lease_grant(k, self.replica_id.encode(), self._lease_ttl)

    def replica_alive(self, replica_id: str) -> bool:
        return self.kv.get(self._key("replicas", replica_id)) is not None

    def mark_job_planner(self, job_id: str) -> None:
        """Stamp queued-grace provenance on a freshly accepted submission:
        which replica owes this job its planning commit. Anonymous
        (single-replica) servers skip it — their restart recovery already
        sweeps torn queued jobs."""
        if self.replica_id:
            self.kv.put(
                self._key("planner", job_id), self.replica_id.encode()
            )

    def job_planner(self, job_id: str) -> Optional[str]:
        raw = self.kv.get(self._key("planner", job_id))
        return raw.decode() if raw is not None else None

    def _bump_plan_epoch(self) -> None:
        """Advance the durable task-set epoch (ISSUE 20): the derived task
        index used to be fresh by construction (single scheduler observes
        its own writes); with peers mutating the same namespace,
        _ensure_task_index re-seeds when the epoch it last saw moved. The
        wall-clock reseed stays as the backstop for non-epoch drift."""
        k = self._key("meta", "plan_epoch")
        prior = self.kv.get(k)
        nxt = (int(prior) if prior else 0) + 1
        self.kv.put(k, str(nxt).encode())
        self._plan_epoch_seen = nxt

    def _bump_rc_epoch(self) -> None:
        """Advance the durable result-cache epoch: peers re-derive their
        entry count (a capacity input, not truth) after any delete."""
        k = self._key("meta", "rc_epoch")
        prior = self.kv.get(k)
        nxt = (int(prior) if prior else 0) + 1
        self.kv.put(k, str(nxt).encode())
        self._rc_epoch_seen = nxt

    def _reclaim_lease(self, job_id: str, raw) -> bool:
        """Restart path: re-mint the lease a predecessor with OUR replica
        id held — CAS against its exact surviving value, or expect-absent
        when it already expired. Jobs never leased at all (pre-ISSUE-20
        rows, hand-built test states) are reclaimed as unleased legacy
        jobs. False = a peer claimed the job meanwhile."""
        lk = self._lease_key(job_id)
        if raw is None and self.kv.get(self._leasegen_key(job_id)) is None:
            return True
        minted, gen_item = self._mint_lease_items(job_id)
        if self.kv.put_all(
            [gen_item],
            compare=(lk, raw),
            leases=[(lk, minted, self._lease_ttl)],
        ):
            self._owned[job_id] = minted
            return True
        return False

    def _restore_ledger_rows(self, rows, now: float, bump) -> None:
        """Reload surviving assignment-ledger rows with a FRESH grace
        window (restart and failover share this): entries whose KV task
        status no longer matches (resolved or superseded before the owner
        died) are dropped; the rest wait for their owner's running_echo."""
        for k, v in rows:
            tail = k.rsplit("/", 3)
            key = (tail[1], int(tail[2]), int(tail[3]))
            a = pb.Assignment()
            a.ParseFromString(v)
            cur = self.get_task_status(*key)
            if (
                cur is None
                or cur.WhichOneof("status") != "running"
                or cur.attempt != a.attempt
                or cur.running.executor_id != a.executor_id
            ):
                # resolved or superseded before the crash; drop the entry
                self.kv.delete(self._ledger_key(key))
                continue
            self._assigned[key] = (a.executor_id, a.attempt, now, True)
            bump("restart_assignment_restored")

    def _restore_spec_rows(self, rows, now: float, bump) -> None:
        """Reload surviving speculation-ledger rows (ISSUE 11): a duplicate
        is valid while the primary is still RUNNING at a LOWER attempt
        (exactly attempt-1 for a single speculation; further behind after
        re-speculation, ISSUE 15) — the pair's completions then resolve
        through the normal first-completion-wins path. Anything else is a
        leftover record to sweep."""
        for k, v in rows:
            tail = k.rsplit("/", 3)
            key = (tail[1], int(tail[2]), int(tail[3]))
            a = pb.Assignment()
            a.ParseFromString(v)
            cur = self.get_task_status(*key)
            if (
                cur is None
                or cur.WhichOneof("status") != "running"
                or cur.attempt >= a.attempt
            ):
                self.kv.delete(self._spec_key(key))
                continue
            self._speculative[key] = (a.executor_id, a.attempt, now, False, True)
            # rebuild the launch bound from attempt arithmetic (the
            # superseded set died with the old process; the requeue
            # numbering floor covers its late reports regardless)
            self._spec_launches[key] = max(1, a.attempt - cur.attempt)
            _record_speculation("restored")
            bump("restart_speculation_restored")

    def recover(self, jobs=None) -> Dict[str, int]:
        """Scheduler-restart recovery — and, scoped by `jobs`, peer
        FAILOVER (ISSUE 20: adopting a dead replica's jobs runs exactly
        this, restricted to them, with no generation bump — no process
        died, the store's restart count is unchanged).

        Full mode (jobs=None), called once before serving (the caller
        holds no lock yet — nothing else can touch this state):

        - A job still QUEUED was never committed: planning publishes
          stages, tasks, and the `running` flip in ONE atomic put_all, and
          the logical plan lived only in the dead scheduler's memory — so
          the job is failed cleanly ("resubmit") instead of hanging the
          client forever. With live PEER leases in the namespace the
          queued job may be a peer's in-flight planning, so it is left
          alone — the housekeeping queued-grace sweep fails truly
          abandoned ones after a couple of lease TTLs.
        - RUNNING jobs owned by a LIVE peer lease are skipped entirely
          (theirs to run); our own surviving or expired leases are
          re-minted with a fresh fencing generation.
        - The assignment ledger reloads with a FRESH grace window: entries
          whose KV task status no longer matches are dropped; the rest
          wait for their owner's running_echo — re-adopted on the first
          vouching poll, requeued through the normal retry path if nobody
          vouches in time.

        Returns the recovery counters (also fed into ops.runtime so
        bench.py's `recovery` field picks them up). A fresh store returns
        {} without recording anything."""
        stats: Dict[str, int] = {}

        def bump(event: str) -> None:
            _record_recovery(event)
            stats[event] = stats.get(event, 0) + 1

        now = time.monotonic()
        if jobs is not None:
            # scoped failover: adopt exactly these (already re-leased) jobs
            for job_id in sorted(jobs):
                js = self.get_job_metadata(job_id)
                if js is None or js.WhichOneof("status") != "running":
                    continue
                bump("restart_job_resumed")
                self._restore_ledger_rows(
                    list(self.kv.get_prefix(self._key("assignments", job_id) + "/")),
                    now, bump,
                )
                self._restore_spec_rows(
                    list(self.kv.get_prefix(self._key("speculation", job_id) + "/")),
                    now, bump,
                )
                self._job_tenant_full(job_id)
            # adopted tasks enter this replica's (and every peer's) task
            # index through the epoch read-through, not a private reseed
            self._bump_plan_epoch()
            if stats:
                log.warning("failover adoption recovery: %s", stats)
            return stats
        job_rows = list(self.kv.get_prefix(self._key("jobs")))
        ledger = list(self.kv.get_prefix(self._key("assignments")))
        spec_ledger = list(self.kv.get_prefix(self._key("speculation")))
        if not job_rows and not ledger and not spec_ledger:
            return {}
        bump("scheduler_restart")
        gen_key = self._key("meta", "restart_generation")
        prior = self.kv.get(gen_key)
        self.generation = (int(prior) if prior else 0) + 1
        self.kv.put(gen_key, str(self.generation).encode())
        lease_rows: Dict[str, bytes] = {
            k.rsplit("/", 1)[1]: v
            for k, v in self.kv.get_prefix(self._key("leases"))
        }
        peers_alive = False
        for raw in lease_rows.values():
            jl = pb.JobLease()
            jl.ParseFromString(raw)
            if jl.replica_id != self.replica_id:
                peers_alive = True
                break
        running_jobs: List[str] = []
        foreign: set = set()
        for k, v in job_rows:
            job_id = k.rsplit("/", 1)[1]
            js = pb.JobStatus()
            js.ParseFromString(v)
            w = js.WhichOneof("status")
            if w == "queued":
                if peers_alive:
                    # plausibly a live peer's planning in flight; the
                    # housekeeping queued-grace sweep owns the verdict
                    continue
                failed = pb.JobStatus()
                failed.failed.error = (
                    "scheduler restarted before planning committed; the job "
                    "was never submitted to executors — resubmit it"
                )
                self.save_job_metadata(job_id, failed)
                self.kv.delete(self._key("settings", job_id))
                self.kv.delete(self._key("tenants", job_id))
                self.kv.delete(self._key("jobfp", job_id))
                self.kv.delete_prefix(self._key("stages", job_id) + "/")
                self.kv.delete_prefix(self._key("tasks", job_id) + "/")
                bump("torn_job_discarded")
                log.warning("discarded torn (uncommitted) job %s", job_id)
            elif w == "running":
                raw = lease_rows.get(job_id)
                if raw is not None:
                    jl = pb.JobLease()
                    jl.ParseFromString(raw)
                    if jl.replica_id != self.replica_id:
                        foreign.add(job_id)  # a live peer's job; not ours
                        continue
                if self._reclaim_lease(job_id, raw):
                    running_jobs.append(job_id)
                    bump("restart_job_resumed")
                else:
                    foreign.add(job_id)  # a peer claimed it meanwhile
        self._restore_ledger_rows(
            [(k, v) for k, v in ledger if k.rsplit("/", 3)[1] not in foreign],
            now, bump,
        )
        self._restore_spec_rows(
            [(k, v) for k, v in spec_ledger if k.rsplit("/", 3)[1] not in foreign],
            now, bump,
        )
        # warm every derived structure from KV truth before serving
        # (ISSUE 18: each derived(<rebuild-fn>) classification promises its
        # rebuild is reachable from here — the durability analyzer checks
        # that promise statically, the crash-recovery property test checks
        # it at runtime): the task index reseeds from the tasks/ scan, the
        # resumed jobs' immutable tenant records re-enter the read-through
        # cache, and the result-cache count reseeds from its authoritative
        # prefix scan instead of on the first at-cap put.
        self._ensure_task_index()
        for job_id in running_jobs:
            self._job_tenant_full(job_id)
        self._ensure_rc_count()
        if stats:
            log.warning("scheduler restart recovery: %s", stats)
        return stats

    # -- executors ----------------------------------------------------------
    def save_executor_metadata(self, meta: pb.ExecutorMetadata) -> None:
        self.kv.put(
            self._key("executors", meta.id),
            meta.SerializeToString(),
            lease_seconds=EXECUTOR_LEASE_SECS,
        )

    def get_executors_metadata(self) -> List[pb.ExecutorMetadata]:
        out = []
        for _k, v in self.kv.get_prefix(self._key("executors")):
            m = pb.ExecutorMetadata()
            m.ParseFromString(v)
            out.append(m)
        return out

    def get_executor_metadata(self, executor_id: str) -> Optional[pb.ExecutorMetadata]:
        v = self.kv.get(self._key("executors", executor_id))
        if v is None:
            return None
        m = pb.ExecutorMetadata()
        m.ParseFromString(v)
        return m

    # -- jobs -----------------------------------------------------------------
    def save_job_metadata(self, job_id: str, status: pb.JobStatus) -> bool:
        """Write the job status, fenced by the ownership lease (ISSUE 20).
        False = a peer adopted the job and the write was rejected whole;
        subscribers are only notified of writes that actually landed."""
        if not self._fenced_put(
            job_id, self._key("jobs", job_id), status.SerializeToString()
        ):
            return False
        self._notify_job_status(job_id, status)
        return True

    def get_job_metadata(self, job_id: str) -> Optional[pb.JobStatus]:
        v = self.kv.get(self._key("jobs", job_id))
        if v is None:
            return None
        s = pb.JobStatus()
        s.ParseFromString(v)
        return s

    def save_job_settings(self, job_id: str, settings: Dict[str, str]) -> None:
        """Client-supplied per-job settings, attached to every
        TaskDefinition for this job so executors honor them."""
        msg = pb.JobSettings()
        for k, v in settings.items():
            msg.settings.add(key=k, value=v)
        self.kv.put(self._key("settings", job_id), msg.SerializeToString())

    def get_job_settings(self, job_id: str) -> Dict[str, str]:
        v = self.kv.get(self._key("settings", job_id))
        if v is None:
            return {}
        msg = pb.JobSettings()
        msg.ParseFromString(v)
        return {kv.key: kv.value for kv in msg.settings}

    # -- tenancy (ISSUE 7) ----------------------------------------------------
    def save_job_tenant(
        self, job_id: str, tenant: str, priority: int,
        created_at: Optional[float] = None,
    ) -> None:
        """Durable per-job tenant record: admission quotas, fair-share
        accounting, priority ordering, and the SLO-deadline anchor
        (created_at, ISSUE 11) survive a scheduler restart."""
        created = time.time() if created_at is None else created_at
        msg = pb.JobTenant(tenant=tenant, priority=priority, created_at=created)
        self.kv.put(self._key("tenants", job_id), msg.SerializeToString())
        with self._tenant_mu:
            self._tenant_cache[job_id] = (tenant, priority, created)

    def _job_tenant_full(self, job_id: str) -> Tuple[str, int, float]:
        """(tenant, priority, created_at) of a job; ("", 0, 0.0) for
        pre-tenancy jobs. Read-through cached — the record is immutable."""
        with self._tenant_mu:
            hit = self._tenant_cache.get(job_id)
            if hit is not None:
                return hit
            if len(self._tenant_cache) > 10_000:
                # jobs are short-lived; a long-lived scheduler must not
                # accumulate every job id it ever saw
                self._tenant_cache.clear()
        v = self.kv.get(self._key("tenants", job_id))
        out = ("", 0, 0.0)
        if v is not None:
            msg = pb.JobTenant()
            msg.ParseFromString(v)
            out = (msg.tenant, msg.priority, msg.created_at)
        with self._tenant_mu:
            self._tenant_cache[job_id] = out
        return out

    def job_tenant(self, job_id: str) -> Tuple[str, int]:
        """(tenant, priority) of a job; ("", 0) for pre-tenancy jobs."""
        return self._job_tenant_full(job_id)[:2]

    def job_created_at(self, job_id: str) -> float:
        """Submission time (unix seconds; 0.0 when unknown) — the anchor
        for the per-tenant SLO deadline (ISSUE 11)."""
        return self._job_tenant_full(job_id)[2]

    def note_tenant_assigned(self, tenant: str) -> None:
        with self._tenant_mu:
            self.tenant_assigned[tenant] = self.tenant_assigned.get(tenant, 0) + 1

    def tenant_task_shares(self) -> Dict[str, int]:
        """Per-tenant totals of tasks assigned by this scheduler instance —
        the fairness denominator bench's multi-tenant scenario reports."""
        with self._tenant_mu:
            return dict(self.tenant_assigned)

    # -- plan-fingerprint result cache (ISSUE 7) ------------------------------
    def save_job_fingerprint(self, job_id: str, fingerprint: str) -> None:
        """Remember which result-cache key a job completes into (and which
        entry a lost cached result invalidates)."""
        self.kv.put(self._key("jobfp", job_id), fingerprint.encode())

    def get_job_fingerprint(self, job_id: str) -> Optional[str]:
        v = self.kv.get(self._key("jobfp", job_id))
        return v.decode() if v is not None else None

    # -- incremental execution (ISSUE 19) -------------------------------------
    def save_job_facts(
        self, job_id: str, content_key: str, facts: List[str]
    ) -> None:
        """The plan's content key + the scan-file facts its result_key was
        built over, recorded at submission so the completion-time cache put
        can stamp them onto the entry — the identity a LATER submission's
        advancement probe matches against."""
        body = "\n".join([content_key] + list(facts))
        self.kv.put(self._key("jobfacts", job_id), body.encode())

    def get_job_facts(self, job_id: str) -> Optional[Tuple[str, List[str]]]:
        v = self.kv.get(self._key("jobfacts", job_id))
        if v is None:
            return None
        lines = v.decode().split("\n")
        return lines[0], lines[1:]

    def result_cache_put(
        self, fingerprint: str, completed, job_id: Optional[str] = None
    ) -> bool:
        """Best-effort publish of a completed job's result partition
        locations under resultcache/{fingerprint}. The write passes the
        `cache.put` chaos site (keyed on the content-derived fingerprint —
        a plan coordinate, never a job id): a torn write is recorded and
        SKIPPED, never retried here — the cache is an accelerator, and the
        job completion that triggered the put stands either way. The
        size-bound eviction (ISSUE 8) runs BEFORE the insert, so the cache
        never exceeds max_entries even transiently."""
        from ballista_tpu.utils.chaos import ChaosInjected

        entry = pb.ResultCacheEntry(
            fingerprint=fingerprint, created_at=time.time()
        )
        for pl in completed.partition_location:
            entry.partition_location.add().CopyFrom(pl)
        if job_id is not None:
            # advancement identity (ISSUE 19): stamp the content key + the
            # scan-file facts recorded at submission, so a later submission
            # over a GROWN file set can find this entry as its fold base
            jf = self.get_job_facts(job_id)
            if jf is not None:
                entry.content_key = jf[0]
                entry.scan_fact.extend(jf[1])
        try:
            if self._chaos is not None:
                self._chaos.maybe_fail("cache.put", f"fp:{fingerprint[:16]}")
            self._result_cache_evict_for(fingerprint)
            key = self._key("resultcache", fingerprint)
            # an overwrite orphans the PRIOR job's result pieces: sweep
            # them once the new entry is durably in (ISSUE 16 GC), keeping
            # anything the replacement still points at
            prior = self.kv.get(key)
            self.kv.put(key, entry.SerializeToString())
            if prior is not None:
                self._gc_cached_result(
                    prior,
                    keep_uris=[
                        pl.storage_uri for pl in entry.partition_location
                    ],
                )
        except ChaosInjected:
            _record_recovery("chaos_injected")
            _record_tenancy("cache_put_torn")
            log.warning("result-cache put torn by chaos (fp=%s...)",
                        fingerprint[:16])
            return False
        _record_tenancy("cache_put")
        return True

    def _ensure_rc_count(self) -> int:
        """Seed the best-effort result-cache entry count from one
        authoritative prefix scan (idempotent; the at-cap eviction path
        re-derives it). The derived(_ensure_rc_count) rebuild recover()
        runs so a restarted replica starts with a true count instead of
        paying the seed scan on its first at-cap put.

        Generation-stamped read-through (ISSUE 20): peers deleting entries
        bump the durable rc epoch; seeing it move invalidates the cached
        count, so the next capacity check re-derives instead of trusting a
        figure a peer already made stale."""
        epoch_raw = self.kv.get(self._key("meta", "rc_epoch"))
        epoch = int(epoch_raw) if epoch_raw else 0
        if self._rc_epoch_seen is not None and epoch != self._rc_epoch_seen:
            self._rc_count = None
        self._rc_epoch_seen = epoch
        if self._rc_count is None:
            self._rc_count = len(
                self.kv.get_prefix(self._key("resultcache") + "/")
            )
        return self._rc_count

    def _result_cache_delete(self, fingerprint: str) -> None:
        """Delete one entry, keeping the best-effort count in step (and
        sweeping its storage-homed result pieces, ISSUE 16 GC)."""
        key = self._key("resultcache", fingerprint)
        self._gc_cached_result(self.kv.get(key))
        self.kv.delete(key)
        if self._rc_count is not None:
            self._rc_count = max(0, self._rc_count - 1)
        self._bump_rc_epoch()

    def _result_cache_evict_for(self, incoming_fp: str) -> int:
        """Make room for one incoming entry under the
        ballista.cache.results.max_entries bound: evict least-recently-HIT
        entries (never-hit entries rank by created_at) until the insert
        fits. The recency lives in the KV value (ResultCacheEntry.last_hit,
        refreshed on every lookup hit), so eviction order survives a
        scheduler restart. 0 = unbounded. Returns the eviction count.

        The full prefix scan runs only when the maintained count says the
        cap is actually reached; under-cap puts pay at most one extra
        kv.get (is this an overwrite?)."""
        cap = self.config.result_cache_max_entries()
        if cap <= 0:
            return 0
        incoming_key = self._key("resultcache", incoming_fp)
        self._ensure_rc_count()
        overwrite = self.kv.get(incoming_key) is not None
        if not overwrite and self._rc_count < cap:
            self._rc_count += 1  # the caller's put inserts a fresh key
            return 0
        if overwrite and self._rc_count <= cap:
            return 0  # in-place refresh; no new slot consumed
        live = []
        for k, v in self.kv.get_prefix(self._key("resultcache") + "/"):
            if k == incoming_key:
                continue  # overwrite in place; no eviction needed for it
            e = pb.ResultCacheEntry()
            try:
                e.ParseFromString(v)
            except Exception:
                self.kv.delete(k)  # unreadable entry: reclaim the slot
                continue
            live.append((e.last_hit or e.created_at, k, v))
        evicted = 0
        if len(live) >= cap:
            live.sort(key=lambda t: t[:2])
            for _recency, k, v in live[: len(live) - cap + 1]:
                # evicted entry = last reference to its storage-homed
                # result pieces (ISSUE 16 GC)
                self._gc_cached_result(v)
                self.kv.delete(k)
                evicted += 1
                _record_tenancy("cache_evicted")
        # authoritative re-derivation: surviving others + the incoming entry
        self._rc_count = (len(live) - evicted) + 1
        if evicted:
            self._bump_rc_epoch()
            log.info("result cache evicted %d entries (cap %d)", evicted, cap)
        return evicted

    def _result_cache_expired(self, entry: pb.ResultCacheEntry) -> bool:
        ttl = self.config.result_cache_ttl_s()
        return ttl > 0 and time.time() - entry.created_at > ttl

    def result_cache_lookup(self, fingerprint: str):
        """CompletedJob (cached=True) for a live entry, else None.

        Liveness: every executor referenced by the entry must still hold a
        live lease — the result partitions live in executor work dirs, so
        an entry naming a dead executor is deleted and reported as a miss
        (the lazy half of invalidation; the eager half is the
        ReportLostPartition path for leases that outlive the data)."""
        key = self._key("resultcache", fingerprint)
        v = self.kv.get(key)
        if v is None:
            _record_tenancy("cache_miss")
            return None
        entry = pb.ResultCacheEntry()
        entry.ParseFromString(v)
        if self._result_cache_expired(entry):
            # TTL bound (ISSUE 8): age is measured from creation, not last
            # hit — a hot entry over stale-but-mtime-identical data still
            # re-executes once per TTL window
            self._result_cache_delete(fingerprint)
            _record_tenancy("cache_expired")
            log.info("result-cache entry %s... expired (ttl %.0fs)",
                     fingerprint[:16], self.config.result_cache_ttl_s())
            return None
        # advanced entries (ISSUE 19) are self-contained: the folded
        # aggregate state rides the KV value itself, so no executor lease
        # (or storage mount) gates serving them
        if entry.state_ipc:
            completed = pb.CompletedJob(
                cached=True, inline_result=entry.state_ipc
            )
            entry.last_hit = time.time()
            self.kv.put(key, entry.SerializeToString())
            _record_tenancy("cache_hit")
            return completed
        # storage-homed locations (ISSUE 15) outlive their producer: only
        # locations whose pieces live in an executor work dir need the
        # owner's lease alive for the entry to stay servable
        for eid in {
            pl.executor_meta.id
            for pl in entry.partition_location
            if not pl.storage_uri
        }:
            if self.get_executor_metadata(eid) is None:
                self._result_cache_delete(fingerprint)
                _record_tenancy("cache_invalidated")
                log.info(
                    "result-cache entry %s... invalidated (executor %s gone)",
                    fingerprint[:16], eid,
                )
                return None
        completed = pb.CompletedJob(cached=True)
        for pl in entry.partition_location:
            completed.partition_location.add().CopyFrom(pl)
        # refresh LRU recency IN the KV value so the eviction order is as
        # durable as the cache itself (scheduler restarts keep it)
        entry.last_hit = time.time()
        self.kv.put(key, entry.SerializeToString())
        _record_tenancy("cache_hit")
        return completed

    def result_cache_invalidate(self, fingerprint: str) -> None:
        self._result_cache_delete(fingerprint)
        _record_tenancy("cache_invalidated")

    # -- result-cache advancement (ISSUE 19) ----------------------------------
    def result_cache_probe_advance(self, content_key: str, facts: List[str]):
        """Best advancement base for a submission whose result_key missed:
        a live same-content entry whose scan-fact set is a strict subset
        of `facts` (the file set GREW — a moved base-file identity
        disqualifies). Among candidates the one covering the most files
        wins (smallest delta). Returns the ResultCacheEntry or None.

        O(entries ≤ max_entries) scan — it runs only on a result-cache
        MISS with advancement enabled, never on the hit path."""
        from ballista_tpu.scheduler.delta import new_scan_files

        best = None
        best_n = -1
        for k, v in self.kv.get_prefix(self._key("resultcache") + "/"):
            e = pb.ResultCacheEntry()
            try:
                e.ParseFromString(v)
            except Exception:
                continue
            if e.content_key != content_key or not e.scan_fact:
                continue
            if self._result_cache_expired(e):
                continue
            if new_scan_files(facts, list(e.scan_fact)) is None:
                continue
            # same liveness rule as lookup: an entry whose work-dir-homed
            # pieces lost their executor cannot be fetched as a fold base
            # (state-carrying entries are self-contained)
            if not e.state_ipc and any(
                self.get_executor_metadata(pl.executor_meta.id) is None
                for pl in e.partition_location
                if not pl.storage_uri
            ):
                continue
            if len(e.scan_fact) > best_n:
                best, best_n = e, len(e.scan_fact)
        return best

    def result_cache_put_advanced(
        self,
        result_key: str,
        content_key: str,
        facts: List[str],
        state_ipc: bytes,
        base_epoch: int,
    ) -> bool:
        """Publish an ADVANCED entry: the folded aggregate state inline
        under the grown file set's result_key. Passes the `cache.advance`
        chaos site — a torn publish is recorded and declined (the caller
        falls back to a full recompute), never retried here and never
        half-written: like cache.put, the site fires before any KV write."""
        from ballista_tpu.utils.chaos import ChaosInjected

        entry = pb.ResultCacheEntry(
            fingerprint=result_key,
            created_at=time.time(),
            content_key=content_key,
            state_ipc=state_ipc,
            advance_epoch=base_epoch + 1,
        )
        entry.scan_fact.extend(facts)
        try:
            if self._chaos is not None:
                self._chaos.maybe_fail("cache.advance", f"fp:{result_key[:16]}")
            self._result_cache_evict_for(result_key)
            key = self._key("resultcache", result_key)
            prior = self.kv.get(key)
            self.kv.put(key, entry.SerializeToString())
            if prior is not None:
                self._gc_cached_result(prior)
        except ChaosInjected:
            _record_recovery("chaos_injected")
            log.warning("result-cache advancement torn by chaos (fp=%s...)",
                        result_key[:16])
            return False
        _record_tenancy("cache_put")
        return True

    # -- shared-store GC (ISSUE 16 satellite) -------------------------------
    @staticmethod
    def _gc_piece_dir(uri: str, stage_id: int, partition: int,
                      job_id: Optional[str] = None) -> int:
        """rmtree ONE published piece-set dir — but only when the path's
        tail spells the scheduler-known plan coordinates
        (<job>/)<stage>/<partition>, the layout shuffle_output_base
        publishes under. The uri is executor-reported: the structural
        check means a report can only ever steer a delete to the piece
        home it announced at completion, never an arbitrary host path.
        Empty stage/job parents prune with it."""
        d = os.path.normpath(uri)
        tail = [str(stage_id), str(partition)]
        if job_id is not None:
            tail.insert(0, job_id)
        if d.split(os.sep)[-len(tail):] != tail or not os.path.isdir(d):
            return 0
        shutil.rmtree(d, ignore_errors=True)
        for parent in (os.path.dirname(d),
                       os.path.dirname(os.path.dirname(d))):
            try:
                os.rmdir(parent)
            except OSError:
                break
        return 1

    def _gc_shared_store_job(
        self, job_id: str, keep_final: Optional[int], tasks
    ) -> int:
        """Sweep a terminal job's storage-homed shuffle pieces — the dirs
        its own completed tasks REPORTED as their storage_uri homes, so
        per-job tier opt-ins GC without the scheduler needing the mount
        configured itself.

        Refcount view: every intermediate stage's pieces are referenced
        only by the job's own downstream tasks, so the job's terminal
        transition IS the refcount release for them — they sweep
        immediately. The FINAL stage is still referenced by the client
        fetch and (when fingerprintable) the result cache, so it stays
        behind `keep_final` until its cache entry leaves the cache
        (_gc_cached_result); never-cached finals are the ISSUE 15 TTL
        sweeper's to reclaim — it stays on as the backstop for everything
        this eager path misses. A failed job releases every stage at once
        (keep_final None), and a completed-job restart
        (restart_completed_job) recomputes swept intermediates through
        the ordinary fetch_failed lineage ladder."""
        swept = 0
        for t in tasks:
            if t.WhichOneof("status") != "completed":
                continue
            uri = t.completed.storage_uri
            stage = t.partition_id.stage_id
            if not uri or (keep_final is not None and stage == keep_final):
                continue
            swept += self._gc_piece_dir(
                uri, stage, t.partition_id.partition_id, job_id=job_id
            )
        if swept:
            _record_shuffle_tier("gc_stage_swept", swept)
            log.info(
                "shared-store GC: swept %d piece dir(s) of job %s", swept,
                job_id,
            )
        return swept

    def _gc_cached_result(self, raw, keep_uris=()) -> None:
        """A result-cache entry leaving the cache (TTL expiry, LRU
        eviction, invalidation, or overwrite by a newer same-fingerprint
        job) drops the last reference to its storage-homed final-stage
        pieces — sweep them (same structural check as above; the job
        component is unknown here, the stage/partition coordinates are
        the entry's own). `raw` is the serialized ResultCacheEntry
        (None/unparseable = nothing to do); `keep_uris` names
        storage_uris a replacing entry still references (the overwrite
        case must not sweep its successor's pieces). Work-dir-homed
        locations are untouched — executor work dirs are their owners'
        to reclaim."""
        if not raw:
            return
        entry = pb.ResultCacheEntry()
        try:
            entry.ParseFromString(raw)
        except Exception:
            return
        keep = {os.path.normpath(u) for u in keep_uris if u}
        swept = 0
        for pl in entry.partition_location:
            uri = pl.storage_uri
            if not uri or os.path.normpath(uri) in keep:
                continue
            swept += self._gc_piece_dir(
                uri, pl.partition_id.stage_id, pl.partition_id.partition_id
            )
        if swept:
            _record_shuffle_tier("gc_result_swept", swept)
            log.info(
                "shared-store GC: swept %d cached-result piece dir(s)", swept
            )

    # -- stage plans ----------------------------------------------------------
    def stage_job_plan(self, job_id: str, attempt: int = 0) -> JobPlanBatch:
        """Start an atomic planning publish for job_id (see JobPlanBatch)."""
        return JobPlanBatch(self, job_id, attempt)

    def save_stage_plan(self, job_id: str, stage_id: int, plan) -> None:
        msg = phys_plan_to_proto(plan)
        self.kv.put(
            self._key("stages", job_id, str(stage_id)), msg.SerializeToString()
        )

    def get_stage_plan(self, job_id: str, stage_id: int):
        v = self.kv.get(self._key("stages", job_id, str(stage_id)))
        if v is None:
            return None
        n = pb.PhysicalPlanNode()
        n.ParseFromString(v)
        return phys_plan_from_proto(n)

    # -- tasks ------------------------------------------------------------------
    def save_task_status(self, status: pb.TaskStatus) -> bool:
        """Write the task status, fenced by the job's ownership lease
        (ISSUE 20). False = a peer adopted the job and the write was
        rejected whole — the index observes only writes that landed (the
        watch maps were already purged by the deposition)."""
        pid = status.partition_id
        key = self._key("tasks", pid.job_id, str(pid.stage_id), str(pid.partition_id))
        # maintain the running-task watch (ISSUE 11): the straggler monitor
        # compares each entry's elapsed time against its cost prediction,
        # and completions observe their duration into the cost store
        key3 = (pid.job_id, pid.stage_id, pid.partition_id)
        if status.WhichOneof("status") == "running":
            cur = self._running_since.get(key3)
            if (
                cur is None
                or cur[0] != status.running.executor_id
                or cur[1] != status.attempt
            ):
                import heapq

                t0 = time.monotonic()
                self._running_since[key3] = (
                    status.running.executor_id, status.attempt, t0,
                )
                # elapsed-ordered straggler heap: superseded entries for
                # the same key invalidate lazily (start-time mismatch)
                heapq.heappush(self._running_heap, (t0, key3))
        else:
            self._running_since.pop(key3, None)
        if not self._fenced_put(pid.job_id, key, status.SerializeToString()):
            return False
        if self._task_index is not None:
            self._task_index.observe(status)
        return True

    def accept_task_status(self, status: pb.TaskStatus) -> bool:
        """Gate for executor-reported statuses: drop stale reports from
        attempts the scheduler already reset (a requeued task's old executor
        completing late must not clobber the retry's state), and carry the
        KV-side attempt history forward over the report (executors don't
        know it). Returns True when the status was applied."""
        if self._chaos is not None:
            # the kv.put site lives HERE (the executor-report path, not the
            # planning writes): a faulted write raises out of PollWork, the
            # executor requeues the report, and the next poll retries the
            # delivery. Keyed on a write counter because a same-key verdict
            # would fail that redelivery forever; the seeded verdict
            # SEQUENCE (which k-th report write faults) stays reproducible.
            self._chaos_puts += 1
            self._chaos.maybe_fail("kv.put", f"put{self._chaos_puts}")
        pid = status.partition_id
        key3 = (pid.job_id, pid.stage_id, pid.partition_id)
        current = self.get_task_status(pid.job_id, pid.stage_id, pid.partition_id)
        w = status.WhichOneof("status")
        spec = self._speculative.get(key3)
        if current is not None and current.WhichOneof("status") == "completed":
            # first completion wins (ISSUE 11): once any attempt's result
            # stands, every DIFFERENT later report — a speculation pair's
            # losing sibling included — is stale, whatever its attempt
            # number (the duplicate runs attempt N+1, so the numeric guard
            # below alone would let it clobber the primary's published
            # locations). A redelivery of the SAME completion (same
            # attempt, same executor) stays accepted: PollWork requeues
            # undelivered statuses after a crash, and the accept must stay
            # idempotent or the redelivery never re-enters the job-sync
            # set and the job wedges in running.
            if not (
                w == "completed"
                and status.attempt == current.attempt
                and status.completed.executor_id == current.completed.executor_id
            ):
                _record_recovery("stale_status_dropped")
                log.info(
                    "dropping late status for resolved task %s/%s/%s "
                    "(attempt %d%s; completion already stands)",
                    pid.job_id, pid.stage_id, pid.partition_id,
                    status.attempt,
                    " speculative" if status.speculative else "",
                )
                return False
        if current is not None and status.attempt < current.attempt:
            _record_recovery("stale_status_dropped")
            log.info(
                "dropping stale status for %s/%s/%s (attempt %d < %d)",
                pid.job_id, pid.stage_id, pid.partition_id,
                status.attempt, current.attempt,
            )
            return False
        sup = self._spec_superseded.get(key3)
        superseded_completion = False
        if (
            sup
            and status.attempt in sup
            and (spec is None or status.attempt != spec[1])
        ):
            # a report from an ABANDONED (re-speculated-over) duplicate
            # (ISSUE 15 satellite): its failure touches nothing — the
            # primary (and possibly a live successor duplicate) still runs
            # — while its completion is as good as anyone's (first
            # completion wins, whoever crosses the line) and falls through
            # to the normal accept below.
            sup.discard(status.attempt)
            if not sup:
                self._spec_superseded.pop(key3, None)
            if w in ("failed", "fetch_failed"):
                _record_speculation("superseded_failed")
                if w == "fetch_failed":
                    # like a live duplicate's fetch failure: the named map
                    # output is gone for EVERY future consumer — recompute
                    # it now (the reporter itself needs no requeue)
                    self._recompute_lost_map(
                        pid.job_id, status.fetch_failed,
                        self.retry_limit(pid.job_id),
                        "superseded speculative attempt",
                    )
                log.info(
                    "superseded speculative attempt %d of %s/%s/%s failed; "
                    "nothing to do", status.attempt,
                    pid.job_id, pid.stage_id, pid.partition_id,
                )
                return False
            if w == "completed":
                superseded_completion = True
                _record_speculation("superseded_won")
        if spec is not None:
            spec_exec, spec_attempt, spec_t0, _v, _r = spec
            if status.attempt == spec_attempt and w in ("failed", "fetch_failed"):
                # the DUPLICATE itself died; the primary still runs — retire
                # the speculation without touching the task (a failed
                # duplicate never consumes the task's retry budget)
                self._spec_del(key3)
                _record_speculation("failed")
                if w == "fetch_failed":
                    # the report still carries actionable lineage: the named
                    # map output is gone for EVERY future consumer. Recompute
                    # it now instead of waiting for the next consumer (the
                    # primary included) to trip on it a full failure
                    # round-trip later. The reporter itself needs no requeue
                    # — the primary still runs.
                    self._recompute_lost_map(
                        pid.job_id, status.fetch_failed,
                        self.retry_limit(pid.job_id),
                        f"speculative attempt on {spec_exec}",
                    )
                log.warning(
                    "speculative attempt %d of %s/%s/%s failed on %s; "
                    "primary continues", spec_attempt,
                    pid.job_id, pid.stage_id, pid.partition_id, spec_exec,
                )
                return False
            if w == "completed":
                # a completion resolves the race NOW; the sibling's late
                # report is dropped by the guards above
                now = time.monotonic()
                if status.attempt == spec_attempt:
                    prim = self._running_since.get(key3)
                    _record_speculation("won")
                    _record_speculation(
                        "wasted_seconds",
                        now - (prim[2] if prim is not None else spec_t0),
                    )
                elif superseded_completion:
                    # an ABANDONED duplicate crossed the line first: still
                    # a speculative WIN (the duplicate rescued the task) —
                    # the live successor's effort is what got wasted
                    _record_speculation("won")
                    _record_speculation("wasted_seconds", now - spec_t0)
                else:
                    _record_speculation("lost")
                    _record_speculation("wasted_seconds", now - spec_t0)
                self._spec_del(key3)
                log.info(
                    "speculation resolved for %s/%s/%s: %s attempt %d won",
                    pid.job_id, pid.stage_id, pid.partition_id,
                    "speculative"
                    if status.attempt == spec_attempt or superseded_completion
                    else "primary", status.attempt,
                )
        if w == "completed":
            # observe the attempt's duration under the stage's
            # job-independent task.run op — the rates the straggler monitor
            # predicts from (sibling completions warm it within one job).
            # A shared-scan batch member (ISSUE 13) instead folds into its
            # batch's stage.batch observation: its own wall time IS the
            # batch's wall time and would corrupt the solo rates the
            # evidence gate compares against.
            batched = key3 in self._batch_members
            self._note_batch_member_done(key3, clean=True)
            rs = self._running_since.get(key3)
            if not batched and rs is not None and rs[1] == status.attempt:
                self._observe_task_run(
                    pid.job_id, pid.stage_id, time.monotonic() - rs[2]
                )
        merged = pb.TaskStatus()
        merged.CopyFrom(status)
        if current is not None and current.history:
            merged.ClearField("history")
            merged.history.MergeFrom(current.history)
        if not self.save_task_status(merged):
            # fenced out (ISSUE 20): a peer adopted the job mid-report. The
            # durable ledger rows below are the ADOPTER's now — bail before
            # the deletes, and report the status as not-applied so the
            # server never folds it into job synchronization.
            return False
        if merged.WhichOneof("status") in ("completed", "failed", "fetch_failed"):
            # the assignment resolved; stop watching for orphaning
            self._ledger_del((pid.job_id, pid.stage_id, pid.partition_id))
        if merged.WhichOneof("status") == "completed":
            # an accepted completion ends the speculation episode for good:
            # superseded bookkeeping included (their late reports are
            # dropped by the completion-stands guard from here on)
            self._spec_resolve(key3)
        return True

    def _ensure_task_index(self) -> _TaskIndex:
        """Seed the per-stage task index from one full scan, then keep it
        current through save_task_status — and RE-seed at most every
        TASK_INDEX_RESEED_SECS so peer-scheduler writes (new jobs, lost-task
        resets) are discovered with bounded delay instead of never.
        Assignment additionally re-verifies the chosen task's pending state
        and every upstream status from the KV before acting on them.

        Generation-stamped read-through (ISSUE 20): peer task-set mutations
        (plan commits, failover adoptions) bump the durable plan epoch, and
        seeing it move forces a reseed NOW instead of after the wall-clock
        backstop — a replica's index lags a peer's commit by one epoch
        read, not by up to TASK_INDEX_RESEED_SECS."""
        now = time.monotonic()
        epoch_raw = self.kv.get(self._key("meta", "plan_epoch"))
        epoch = int(epoch_raw) if epoch_raw else 0
        if self._plan_epoch_seen is not None and epoch != self._plan_epoch_seen:
            self._task_index = None
        self._plan_epoch_seen = epoch
        if (
            self._task_index is None
            or now - self._task_index_seeded_at > TASK_INDEX_RESEED_SECS
        ):
            idx = _TaskIndex()
            for t in self.get_all_tasks():
                idx.observe(t)
            self._task_index = idx
            self._task_index_seeded_at = now
        return self._task_index

    def get_task_status(self, job_id: str, stage_id: int, partition: int) -> Optional[pb.TaskStatus]:
        v = self.kv.get(self._key("tasks", job_id, str(stage_id), str(partition)))
        if v is None:
            return None
        t = pb.TaskStatus()
        t.ParseFromString(v)
        return t

    def get_job_tasks(self, job_id: str) -> List[pb.TaskStatus]:
        out = []
        for _k, v in self.kv.get_prefix(self._key("tasks", job_id)):
            t = pb.TaskStatus()
            t.ParseFromString(v)
            out.append(t)
        return out

    def get_stage_tasks(self, job_id: str, stage_id: int) -> List[pb.TaskStatus]:
        # trailing "/": the bare prefix "tasks/j/2" would also match stage 20
        out = []
        for _k, v in self.kv.get_prefix(self._key("tasks", job_id, str(stage_id)) + "/"):
            t = pb.TaskStatus()
            t.ParseFromString(v)
            out.append(t)
        return out

    def get_all_tasks(self) -> List[pb.TaskStatus]:
        out = []
        for _k, v in self.kv.get_prefix(self._key("tasks")):
            t = pb.TaskStatus()
            t.ParseFromString(v)
            out.append(t)
        return out

    # -- failure recovery ---------------------------------------------------
    def retry_limit(self, job_id: str) -> int:
        """Max requeues per task: the job's own setting if the client sent
        one, else the scheduler's config default."""
        settings = self.get_job_settings(job_id)
        raw = settings.get(BALLISTA_MAX_TASK_RETRIES)
        if raw is not None:
            try:
                return max(0, int(raw))
            except ValueError:
                log.warning("job %s: bad %s=%r, using scheduler default",
                            job_id, BALLISTA_MAX_TASK_RETRIES, raw)
        return self.config.max_task_retries()

    def requeue_task(
        self, t: pb.TaskStatus, executor_id: str, error: str, limit: int,
        promote: bool = True,
    ) -> bool:
        """Put a failed/lost task back to pending for attempt N+1, recording
        attempt N (executor + error) in the history. Returns False without
        writing when the retry budget is exhausted — the caller fails the
        job with the full history instead.

        Speculation-aware (ISSUE 11): when the PRIMARY attempt dies while
        its speculative duplicate is still in flight, the duplicate IS the
        retry — it is promoted to the task's current attempt (running, on
        its executor, with the failure recorded in the history) instead of
        requeueing fresh work. A promotion consumes no retry budget: the
        duplicate was already dispatched and attempt numbering already
        advanced when it launched. Callers requeueing because the task's
        UPSTREAM locations went stale (lineage invalidation, fetch_failed)
        pass promote=False — the duplicate was bound to the same dead
        locations, so it is retired below instead of promoted into a
        doomed attempt."""
        pid0 = t.partition_id
        key3 = (pid0.job_id, pid0.stage_id, pid0.partition_id)
        # a batched member leaving its attempt (failure, loss, lineage
        # reset) dirties its batch accounting: a partial batch's wall time
        # is not a clean stage.batch observation (ISSUE 13)
        self._note_batch_member_done(key3, clean=False)
        spec = self._speculative.get(key3)
        if (
            promote
            and spec is not None
            # any LATER attempt qualifies: re-speculation (ISSUE 15) may
            # have advanced the duplicate past attempt+1
            and spec[1] > t.attempt
            and spec[0] != executor_id
            # same budget bound as a normal requeue: a task already AT its
            # final allowed attempt must fail the job, not ride promotion
            # to attempt numbers past the configured limit
            and t.attempt < limit
            and t.WhichOneof("status") in ("running", "failed", "fetch_failed")
        ):
            promoted = pb.TaskStatus()
            promoted.partition_id.CopyFrom(t.partition_id)
            promoted.attempt = spec[1]
            promoted.speculative = True
            promoted.history.MergeFrom(t.history)
            h = promoted.history.add()
            h.attempt = t.attempt
            h.executor_id = executor_id
            h.error = error
            promoted.running.executor_id = spec[0]
            if not self.save_task_status(promoted):
                # fenced out (ISSUE 20): the adopter owns the retry now —
                # leave its durable ledger rows alone and report the task
                # as handled (nothing for the caller to fail)
                return True
            self._ledger_del(key3)  # superseded primary assignment
            # the duplicate has been RUNNING since its launch, not since
            # this promotion — keep the watch clock honest (save_task_
            # status just re-stamped it with now) or its completion would
            # observe an understated duration into the task.run rates and
            # teach the monitor to over-speculate on this shape
            import heapq

            self._running_since[key3] = (spec[0], spec[1], spec[2])
            # re-stamping orphans the heap entry save_task_status just
            # pushed (start-time mismatch); push the honest clock so the
            # promoted attempt stays visible to the straggler monitor
            heapq.heappush(self._running_heap, (spec[2], key3))
            # the promoted attempt enters the normal assignment ledger:
            # its owner's next echo vouches for it, and a restart re-adopts
            # it like any in-flight assignment
            self._ledger_put(key3, spec[0], spec[1])
            self._spec_del(key3)
            _record_speculation("promoted")
            log.warning(
                "promoted speculative attempt %d of %s/%s/%s on %s "
                "(primary attempt %d lost: %s)",
                promoted.attempt, pid0.job_id, pid0.stage_id,
                pid0.partition_id, promoted.running.executor_id,
                t.attempt, error,
            )
            return True
        if t.attempt >= limit:
            # exhausted: the job fails — retire any in-flight duplicate's
            # record with it (its late report is dropped by the guards)
            if spec is not None:
                _record_speculation("failed")
            self._spec_resolve(key3)
            return False
        # any in-flight assignment of the superseded attempt is now stale;
        # clearing it here keeps the durable ledger from carrying entries a
        # restarted scheduler would have to re-validate and discard — a
        # stale speculation record included (the requeued attempt would
        # collide with the duplicate's attempt number). The fresh attempt
        # numbers PAST every speculative attempt ever minted for the task
        # (the abandoned ones included), so no late duplicate report can
        # impersonate it.
        floor = self._spec_attempt_floor(key3)
        pending = pb.TaskStatus()
        pending.partition_id.CopyFrom(t.partition_id)
        pending.attempt = max(t.attempt, floor) + 1
        pending.history.MergeFrom(t.history)
        h = pending.history.add()
        h.attempt = t.attempt
        h.executor_id = executor_id
        h.error = error
        # the fenced status write goes FIRST (ISSUE 20): a rejected write
        # means a peer adopted the job, and its restored ledger rows must
        # not be deleted by this (deposed) replica's cleanup below
        if not self.save_task_status(pending):
            return True
        self._ledger_del((pid0.job_id, pid0.stage_id, pid0.partition_id))
        if spec is not None:
            _record_speculation("failed")
        self._spec_resolve(key3)
        _record_recovery("task_retry")
        pid = t.partition_id
        log.warning(
            "requeued task %s/%s/%s for attempt %d (%s)",
            pid.job_id, pid.stage_id, pid.partition_id, pending.attempt, error,
        )
        return True

    def _fail_job(self, job_id: str, error: str) -> None:
        failed = pb.JobStatus()
        failed.failed.error = error
        self.save_job_metadata(job_id, failed)
        _record_recovery("job_failed_exhausted")
        log.error("job %s failed: %s", job_id, error)

    def get_job_stage_ids(self, job_id: str) -> List[int]:
        out = []
        for k, _v in self.kv.get_prefix(self._key("stages", job_id) + "/"):
            try:
                out.append(int(k.rsplit("/", 1)[1]))
            except ValueError:
                continue
        return out

    def _downstream_stages(self, job_id: str, lost_stages: Set[int]) -> Set[int]:
        """Stage ids whose plans read (via UnresolvedShuffle) any stage in
        lost_stages — the consumers a lost map output invalidates."""
        out: Set[int] = set()
        for sid in self.get_job_stage_ids(job_id):
            plan = self.get_stage_plan(job_id, sid)
            if plan is None:
                continue
            if any(u.stage_id in lost_stages for u in find_unresolved_shuffles(plan)):
                out.add(sid)
        return out

    def reset_lost_tasks(self) -> int:
        """Re-schedule work lost to dead executors (beyond the reference,
        which loses in-flight work permanently — SURVEY §5 'no retry').

        A task RUNNING on an executor whose lease expired goes back to
        pending; a COMPLETED task whose output lives on a dead executor also
        goes back to pending (its shuffle files are unreachable). Lineage
        pass: downstream stage tasks RUNNING against those lost locations
        are invalidated too (their in-flight fetches would only fetch_fail
        later), and the normal runnability check blocks them until the map
        partitions are recomputed. Every reset consumes one retry from the
        task's budget; a task out of budget fails the job with its full
        attempt history. Returns the number of tasks reset."""
        alive = {m.id for m in self.get_executors_metadata()}
        finished_jobs: Dict[str, bool] = {}
        limits: Dict[str, int] = {}
        # job -> stages whose COMPLETED outputs were lost (lineage roots)
        lost_outputs: Dict[str, Set[int]] = {}
        reset = 0

        def job_finished(job_id: str) -> bool:
            if job_id not in finished_jobs:
                js = self.get_job_metadata(job_id)
                finished_jobs[job_id] = js is not None and js.WhichOneof(
                    "status"
                ) in ("completed", "failed")
            return finished_jobs[job_id]

        def limit_of(job_id: str) -> int:
            if job_id not in limits:
                limits[job_id] = self.retry_limit(job_id)
            return limits[job_id]

        touch_memo: Dict[str, bool] = {}

        def may_touch(job_id: str) -> bool:
            # ownership filter (ISSUE 20): leased jobs are reset by their
            # owner — a live foreign lease means a peer's sweep covers it,
            # an expired one means adoption (not this sweep) picks it up.
            # Never-leased jobs keep the legacy single-scheduler behavior.
            if job_id in self._owned:
                return True
            if job_id not in touch_memo:
                touch_memo[job_id] = (
                    self.kv.get(self._lease_key(job_id)) is None
                    and self.kv.get(self._leasegen_key(job_id)) is None
                )
            return touch_memo[job_id]

        for t in self.get_all_tasks():
            job_id = t.partition_id.job_id
            if job_finished(job_id):
                continue  # don't resurrect finished jobs
            if not may_touch(job_id):
                continue  # a peer replica's job (ISSUE 20)
            w = t.WhichOneof("status")
            owner = None
            if w == "running":
                owner = t.running.executor_id
            elif w == "completed":
                owner = t.completed.executor_id
            if owner is None or owner in alive:
                continue
            if w == "completed" and t.completed.storage_uri:
                # disaggregated tier (ISSUE 15): the output's home is a
                # PATH in shared storage, not the dead executor — the
                # pieces are still readable, so executor death after map
                # completion is a NON-EVENT: no requeue, no lineage
                # invalidation, no task retries. (A piece that really did
                # vanish from storage surfaces later as a reader's
                # fetch_failed and recovers through lineage as usual.)
                _record_recovery("storage_home_retained")
                continue
            error = (
                f"executor {owner} lease expired while the task ran"
                if w == "running"
                else f"completed shuffle output lost with executor {owner}"
            )
            if not self.requeue_task(t, owner, error, limit_of(job_id)):
                exhausted = pb.TaskStatus()
                exhausted.CopyFrom(t)
                exhausted.failed.error = error
                exhausted.failed.executor_id = owner
                self._fail_job(job_id, _attempts_error(exhausted))
                finished_jobs[job_id] = True
                continue
            _record_recovery("lost_task_reset")
            reset += 1
            if w == "completed":
                lost_outputs.setdefault(job_id, set()).add(t.partition_id.stage_id)

        # lineage invalidation: running consumers of the lost outputs
        for job_id, stages in lost_outputs.items():
            for sid in self._downstream_stages(job_id, stages):
                # an exhausted requeue below fails the job; stop touching
                # its remaining stages/tasks (a failed job must not keep
                # accumulating fresh pending work)
                if job_finished(job_id):
                    break
                for t in self.get_stage_tasks(job_id, sid):
                    if t.WhichOneof("status") != "running":
                        continue
                    error = (
                        f"upstream shuffle locations of stage(s) "
                        f"{sorted(stages)} lost mid-run"
                    )
                    if not self.requeue_task(
                        t, t.running.executor_id, error, limit_of(job_id),
                        # the task's upstream bindings are what died — a
                        # speculative duplicate carries the same dead
                        # locations and must not be promoted into them
                        promote=False,
                    ):
                        exhausted = pb.TaskStatus()
                        exhausted.CopyFrom(t)
                        exhausted.failed.error = error
                        exhausted.failed.executor_id = t.running.executor_id
                        self._fail_job(job_id, _attempts_error(exhausted))
                        finished_jobs[job_id] = True
                        break
                    _record_recovery("downstream_invalidated")
                    reset += 1
        # prune watch entries of finished jobs (ISSUE 11): a job that
        # failed with tasks still marked running would otherwise pin its
        # entries (and any speculation records) forever
        for key in list(self._running_since):
            if job_finished(key[0]):
                self._running_since.pop(key, None)
        for key in list(self._speculative):
            if job_finished(key[0]):
                self._spec_resolve(key)
        for key in list(self._spec_superseded):
            if job_finished(key[0]):
                self._spec_superseded.pop(key, None)
        for key in list(self._batch_members):
            if job_finished(key[0]):
                self._note_batch_member_done(key, clean=False)
        return reset

    def handle_fetch_failed(self, t: pb.TaskStatus, limit: int) -> bool:
        """Lineage-based recovery for one fetch_failed report: requeue the
        reporting (reduce) task AND recompute the named lost map partition,
        instead of failing the job. Returns False when the reporter's retry
        budget is exhausted (caller fails the job)."""
        ff = t.fetch_failed
        pid = t.partition_id
        _record_recovery("fetch_failed")
        reporter_error = (
            f"fetch_failed: shuffle output {ff.map_executor_id}:{ff.path} "
            f"(map {ff.map_stage_id}/{ff.map_partition_id}) unreachable: {ff.error}"
        )
        # promote=False: the reporter's duplicate (if any) was bound to the
        # same lost shuffle location — retire it rather than promote it
        # into a fetch that is known to fail
        if not self.requeue_task(
            t, ff.executor_id, reporter_error, limit, promote=False
        ):
            return False
        self._recompute_lost_map(pid.job_id, ff, limit, ff.executor_id)
        return True

    def _recompute_lost_map(self, job_id: str, ff, limit: int,
                            reporter: str) -> None:
        """Recompute ONLY the named lost map partition — and only if its
        current completed output is the one reported lost (a concurrent
        reset or recompute may already have moved it). Shared by the
        primary fetch_failed path and the speculative-duplicate report
        (ISSUE 11), so the recompute rule cannot silently diverge. When
        the map partition is out of budget its data is gone for good: the
        consumers' retries will exhaust and fail the job with the full
        lineage in the error."""
        mt = self.get_task_status(job_id, ff.map_stage_id, ff.map_partition_id)
        if (
            mt is not None
            and mt.WhichOneof("status") == "completed"
            and mt.completed.executor_id == ff.map_executor_id
        ):
            if self.requeue_task(
                mt,
                ff.map_executor_id,
                f"shuffle output lost (fetch_failed reported by {reporter})",
                limit,
            ):
                _record_recovery("map_recomputed")

    def restart_completed_job(self, job_id: str, executor_id: str) -> int:
        """Restart a job whose result partitions died with their executor
        before the client fetched them (PR 5 residue): the client reports
        the lost location (ReportLostPartition) and the final-stage tasks
        completed on that executor requeue through the normal retry/lineage
        machinery — upstream outputs lost with the same executor recover
        via the fetch_failed path when the re-run fetches them. For a
        COMPLETED job the status flips back to running so the client's
        GetJobStatus poll waits for the fresh locations; a job still
        RUNNING (ISSUE 8: a streaming client fetches partial_location
        entries mid-job, and one died) requeues the named tasks the same
        way — without it the dead location would be republished on every
        status fold until the lease machinery caught up, and the streaming
        client would spin on it. Each restart consumes retry budget;
        exhaustion fails the job (the client gets an error instead of an
        eternal fetch loop). Returns the number of restarted tasks; 0
        declines the report (job terminal-failed/queued, or nothing on
        that executor — e.g. a concurrent restart already moved the
        partitions)."""
        js = self.get_job_metadata(job_id)
        if js is None or js.WhichOneof("status") not in ("completed", "running"):
            return 0
        was_completed = js.WhichOneof("status") == "completed"
        tasks = self.get_job_tasks(job_id)
        if not tasks:
            return 0
        final_stage = max(t.partition_id.stage_id for t in tasks)
        limit = self.retry_limit(job_id)
        restarted = 0
        for t in tasks:
            if (
                t.partition_id.stage_id != final_stage
                or t.WhichOneof("status") != "completed"
                or t.completed.executor_id != executor_id
            ):
                continue
            error = (
                f"result partition lost with executor {executor_id} "
                "before the client fetched it"
            )
            if not self.requeue_task(t, executor_id, error, limit):
                exhausted = pb.TaskStatus()
                exhausted.CopyFrom(t)
                exhausted.failed.error = error
                exhausted.failed.executor_id = executor_id
                self._fail_job(job_id, _attempts_error(exhausted))
                return restarted
            _record_recovery("result_partition_restarted")
            restarted += 1
        if restarted and was_completed:
            running = pb.JobStatus()
            running.running.SetInParent()
            self.save_job_metadata(job_id, running)
            _record_recovery("completed_job_restarted")
        if restarted:
            log.warning(
                "restarting job %s: %d result partition(s) lost with "
                "executor %s", job_id, restarted, executor_id,
            )
        return restarted

    # -- scheduling ---------------------------------------------------------
    def _bound_stage_plan(self, job_id: str, stage_id: int, idx: _TaskIndex):
        """The stage plan with upstream shuffle locations bound, or None
        while any upstream stage is incomplete (or the plan is missing).
        Factored out of assign_next_schedulable_task so speculative
        duplicates (ISSUE 11) bind EXACTLY like first attempts."""
        plan = self.get_stage_plan(job_id, stage_id)
        if plan is None:
            return None
        unresolved = find_unresolved_shuffles(plan)
        locations: Dict[int, List[ShuffleLocation]] = {}
        for u in unresolved:
            # O(1) screen: stages the index knows are incomplete skip
            # the KV read entirely (staleness toward "peer completed
            # it" is bounded by the periodic reseed)
            if not idx.stage_done(job_id, u.stage_id):
                return None
            # the locations are built from FRESH KV statuses with a
            # final completeness check — a peer's lost-task reset
            # (completed -> pending, unseen by this index) must block
            # the stage, not hand out empty executor/path locations
            upstream = self.get_stage_tasks(job_id, u.stage_id)
            for t in upstream:
                idx.observe(t)
            if not upstream or any(
                t.WhichOneof("status") != "completed" for t in upstream
            ):
                return None
            locs = []
            for t in sorted(upstream, key=lambda t: t.partition_id.partition_id):
                meta = self.get_executor_metadata(t.completed.executor_id)
                host, port = (meta.host, meta.port) if meta else ("", 0)
                locs.append(
                    ShuffleLocation(
                        t.completed.executor_id,
                        host,
                        port,
                        t.completed.path,
                        stage_id=u.stage_id,
                        map_partition=t.partition_id.partition_id,
                        # shared tier (ISSUE 15): a storage-homed piece set
                        # binds even when its producer's lease lapsed —
                        # readers resolve it from the mount (host/port stay
                        # the fallback transport while the producer lives)
                        storage_uri=t.completed.storage_uri,
                        # HBM-resident exchange hint + size (ISSUE 16):
                        # advisory — a consumer landing elsewhere (or after
                        # eviction) just walks the ordinary piece ladder
                        resident=t.completed.resident,
                        nbytes=t.completed.stats.num_bytes,
                    )
                )
            locations[u.stage_id] = locs
        return remove_unresolved_shuffles(plan, locations) if unresolved else plan

    def _locality_partition_order(
        self, bound, parts, executor_id: str
    ) -> Tuple[list, Set]:
        """Visit order for a chosen stage's pending partitions, preferring
        partitions whose HBM-resident shuffle inputs live on THIS executor
        (ISSUE 16). Strictly a reorder WITHIN the stage the fair-share /
        SLO / blacklist machinery already chose — tenant order, quota, and
        the per-task executor blacklist all apply before and after exactly
        as without residency. The preference is cost-model-sized: each
        resident input contributes its predicted readback+re-upload saving
        (exchange.predicted_transfer_saving_s over the producer-reported
        piece bytes), so a partition backed by large resident pieces beats
        one backed by crumbs. Only identity readers differentiate
        partitions (consumer p reads exactly map output p); a hash reader
        consumes a slice of EVERY map output, so its saving is uniform
        across partitions and cannot reorder anything. Ties (and the
        no-residency case) keep the deterministic sorted-by-str order the
        fair-share identity tests pin. Returns (ordered partitions, the
        set with a positive predicted saving on this executor)."""
        saving: Dict[object, float] = {}
        stack = [bound]
        while stack:
            node = stack.pop()
            if isinstance(node, ShuffleReaderExec) and node.identity:
                from ballista_tpu.ops import exchange

                for p in parts:
                    if not isinstance(p, int) or p >= len(node.locations):
                        continue
                    loc = node.locations[p]
                    if loc.resident and loc.executor_id == executor_id:
                        saving[p] = saving.get(p, 0.0) + (
                            exchange.predicted_transfer_saving_s(loc.nbytes)
                        )
            # getattr: scheduler tests bind stub plans with no tree API —
            # no residency signal there means no reorder, by construction
            stack.extend(getattr(node, "children", list)())
        base = sorted(parts, key=str)
        preferred = {p for p, s in saving.items() if s > 0.0}
        if not preferred:
            return base, preferred
        return sorted(base, key=lambda p: -saving.get(p, 0.0)), preferred

    # -- speculative execution (ISSUE 11) -----------------------------------
    def _task_run_op(self, job_id: str, stage_id: int) -> str:
        """Job-independent cost-store op for this stage's task durations:
        sha1 of the stage plan's display with the job id scrubbed, so
        repeated queries of the same shape share one rate across jobs (and
        sibling tasks within one job warm it past MIN_OBSERVATIONS)."""
        k = (job_id, stage_id)
        op = self._task_op_cache.get(k)
        if op is None:
            from ballista_tpu.ops import costmodel

            plan = self.get_stage_plan(job_id, stage_id)
            shape = (
                plan.display_indent() if plan is not None else f"s{stage_id}"
            ).replace(job_id, "")
            op = costmodel.task_run_op(shape)
            if len(self._task_op_cache) > 10_000:
                self._task_op_cache.clear()
            self._task_op_cache[k] = op
        return op

    def _observe_task_run(self, job_id: str, stage_id: int, seconds: float) -> None:
        from ballista_tpu.ops import costmodel

        op = self._task_run_op(job_id, stage_id)
        s_, n_ = self._task_rates.get(op, (0.0, 0))
        if n_ >= 32:  # forget like the store: follow the current cluster
            s_, n_ = s_ / 2.0, n_ // 2
        if len(self._task_rates) > 10_000:
            self._task_rates.clear()
        self._task_rates[op] = (s_ + seconds, n_ + 1)
        costmodel.observe(op, 1.0, seconds, engine="task")

    def _predict_task_run(self, job_id: str, stage_id: int) -> Optional[float]:
        """Predicted seconds for one task of this stage shape: the
        scheduler-owned rates first (immune to cost-store rebinds), the
        cost store as fallback (a restarted scheduler reloads persisted
        rates before re-learning its own)."""
        from ballista_tpu.ops import costmodel

        op = self._task_run_op(job_id, stage_id)
        local = self._task_rates.get(op)
        if local is not None and local[1] >= costmodel.MIN_OBSERVATIONS:
            return local[0] / local[1]
        return costmodel.predict(op, 1.0, engine="task")

    def has_running_tasks(self) -> bool:
        """True while any task is RUNNING in a live job — the autoscaler's
        idle check (a drain must never start under in-flight work it can
        see coming). Caller holds the global KV lock, like every index
        consumer."""
        idx = self._ensure_task_index()
        return any(parts for parts in idx.running.values())

    def predicted_backlog_seconds(self) -> float:
        """Cost-model-predicted seconds of PENDING work across live jobs —
        the autoscaling signal (ISSUE 15): the same task.run rates the
        straggler monitor predicts from, summed over every pending task of
        every non-terminal job. Stages the model has never observed count a
        small cold prior each (a deep cold queue still registers as
        backlog; the prior is deliberately below any task worth scaling
        for, so an idle-ish cluster never grows on priors alone). Caller
        holds the global KV lock, like every index consumer."""
        idx = self._ensure_task_index()
        job_live: Dict[str, bool] = {}
        total = 0.0
        for (job_id, stage_id), parts in list(idx.pending.items()):
            if not parts:
                continue
            if job_id not in job_live:
                js = self.get_job_metadata(job_id)
                job_live[job_id] = js is not None and js.WhichOneof(
                    "status"
                ) == "running"
            if not job_live[job_id]:
                continue
            pred = self._predict_task_run(job_id, stage_id)
            total += (
                pred if pred is not None else BACKLOG_COLD_TASK_SECONDS
            ) * len(parts)
        return total

    def _straggler_candidates(
        self, now: float
    ) -> List[Tuple[str, int, int]]:
        """Running-task keys past the speculation floor, MOST-ELAPSED
        first, from the elapsed-ordered heap (ISSUE 13 satellite, PR 11
        residue: the monitor used to linearly scan EVERY running task under
        the global KV lock on each idle slot). The watch map is the
        authority: a heap entry for a resolved task drops on sight, and an
        entry whose start time disagrees with the map (superseded attempt,
        or a re-stamped clock) RECONCILES in place — replaced with the
        map's time so it re-sorts correctly. Because the heap orders by
        start time, the walk stops at the first entry younger than the
        floor — the common idle-slot case (every running task young) does
        O(1) work instead of a 10k-entry sweep. Floor-passing entries pop
        and re-push, so the heap stays consistent for the next slot.

        INVARIANT the early exit relies on: every watch-map entry has at
        least one heap entry carrying its EXACT clock — save_task_status
        pushes at stamp time and the promotion re-stamp pushes the
        corrected clock, so code that rewrites a watch clock directly must
        push the corrected entry too (the reconcile above only repairs
        entries the walk reaches before the break).
        tests/test_speculation.py asserts the heap and a linear scan
        agree."""
        import heapq

        heap = self._running_heap
        if len(heap) > 4 * len(self._running_since) + 64:
            # compact: superseded-attempt entries accumulate on busy
            # schedulers; rebuild from the authoritative watch map
            heap = self._running_heap = [
                (e[2], k) for k, e in self._running_since.items()
            ]
            heapq.heapify(heap)
        out: List[Tuple[str, int, int]] = []
        seen: set = set()
        popped: List[Tuple[float, Tuple[str, int, int]]] = []
        while heap:
            t0, key = heap[0]
            cur = self._running_since.get(key)
            if cur is None or key in seen:
                heapq.heappop(heap)  # resolved, or a duplicate entry
                continue
            if cur[2] != t0:
                # reconcile to the authoritative clock and re-sort
                heapq.heapreplace(heap, (cur[2], key))
                continue
            if now - t0 < self._spec_floor_s:
                break  # t0-ordered: everything below is younger still
            heapq.heappop(heap)
            seen.add(key)
            popped.append((t0, key))
            out.append(key)
        for item in popped:
            heapq.heappush(heap, item)
        return out

    # -- shared-scan batching (ISSUE 13) ------------------------------------
    def _note_batch_member_done(self, key3: Tuple[str, int, int],
                                clean: bool) -> None:
        """Fold one member's outcome into its batch's accounting. When the
        LAST member completes cleanly, the batch's wall duration lands in
        the cost store as a `stage.batch` observation (units = member
        count) and the decision is recorded against the formation-time
        prediction — the evidence form_shared_batch's gate consults. A
        member failing or requeueing dirties the batch: a partial batch's
        wall time is not a batch cost."""
        bid = self._batch_members.pop(key3, None)
        if bid is None:
            return
        b = self._batches.get(bid)
        if b is None:
            return
        b["remaining"].discard(key3)
        if not clean:
            b["dirty"] = True
        if b["remaining"]:
            return
        del self._batches[bid]
        if b["dirty"]:
            _record_routing("batch", "stage.batch")
            return
        wall = time.monotonic() - b["t0"]
        from ballista_tpu.ops import costmodel

        costmodel.observe("stage.batch", float(b["k"]), wall, engine="task")
        _record_routing("batch", "stage.batch", b["predicted"], wall)

    def _shared_scan_signature(self, plan) -> Optional[tuple]:
        """Cheap scan-sharing signature of one bound stage plan: non-None
        for a fused-aggregate-shaped stage over a file-backed scan, keyed
        on (scan type, file list, merge coverage, scan partition count) —
        two stages with equal signatures dispatched for the same partition
        read the same rows. A HEURISTIC only: the executor re-derives
        compatibility authoritatively (mtimes, dtypes, dictionaries,
        cardinality) and degrades mismatches to solo execution, so a false
        positive here costs a little batching overhead, never a wrong
        answer."""
        from ballista_tpu.ops.sharedscan import _find_aggregate
        from ballista_tpu.physical.basic import (
            CoalesceBatchesExec,
            FilterExec,
            MergeExec,
            ProjectionExec,
        )
        from ballista_tpu.physical.scan import CsvScanExec, ParquetScanExec

        # the ONE spine walk (ops/sharedscan.py): the executor's
        # authoritative compatibility check and this heuristic must find
        # the same aggregate or batches silently stop grouping
        node = _find_aggregate(plan)
        if node is None:
            return None
        n = node.input
        merged = False
        while isinstance(n, (FilterExec, ProjectionExec, CoalesceBatchesExec,
                             MergeExec)):
            merged = merged or isinstance(n, MergeExec)
            n = n.input
        if not isinstance(n, (ParquetScanExec, CsvScanExec)):
            return None
        files = tuple(getattr(n.source, "files", ()) or ())
        if not files:
            return None
        return (
            type(n).__name__, files, merged,
            n.output_partitioning().partition_count(),
        )

    def _cached_stage_signature(self, job_id: str, stage_id: int):
        """Scan-sharing signature of a PLANNED stage, computed once per
        (job, stage) from the stored stage plan — leaf fused-aggregate
        stages read no shuffles, so the raw plan and the bound plan carry
        the same signature. None = not batchable (cached too)."""
        k = (job_id, stage_id)
        if k in self._shared_sig_cache:
            return self._shared_sig_cache[k]
        try:
            plan = self.get_stage_plan(job_id, stage_id)
            sig = None if plan is None else self._shared_scan_signature(plan)
        except Exception:
            sig = None
        if len(self._shared_sig_cache) > 10_000:
            self._shared_sig_cache.clear()
        self._shared_sig_cache[k] = sig
        return sig

    def form_shared_batch(
        self, primary: pb.TaskStatus, plan, executor_id: str
    ) -> List[Tuple[pb.TaskStatus, object]]:
        """Scan-sharing pass (ISSUE 13): after `primary` was assigned, pull
        OTHER jobs' co-pending compatible stage tasks for the SAME
        partition into one batched dispatch. Each sibling flips to Running
        through the exact assignment machinery (status write, durable
        ledger entry, tenant accounting), so every recovery path — orphan
        reconciliation, lease expiry, scheduler restart — sees N ordinary
        in-flight tasks. Returns the (status, bound plan) siblings to ride
        the primary's TaskDefinition; [] dispatches solo.

        Evidence gate: with warm `stage.batch` rates AND solo task.run
        predictions for every member, a batch predicted no faster than the
        members' solo sum dispatches solo (recorded, never silent). Cold
        models batch optimistically — the batch is bit-identical to solo
        by construction, so the only risk is time, which the observation
        then measures. The `scheduler.batch` chaos site tears formation
        BEFORE any sibling is flipped: a torn formation degrades to solo
        dispatch with nothing written. Never raises; any failure degrades
        to solo."""
        from ballista_tpu.utils.chaos import ChaosInjected

        if not self._shared_scan:
            return []
        pid = primary.partition_id
        sig = self._cached_stage_signature(pid.job_id, pid.stage_id)
        if sig is None:
            return []
        partition = pid.partition_id
        idx = self._ensure_task_index()
        if len(self._batch_members) > 100_000:
            # safety valve for a leak (normal resolution + the finished-job
            # prune keep this at the in-flight batched count). Clearing
            # mid-flight members means their completions observe their
            # batch wall time into the SOLO task.run rates — a one-time
            # pollution the store's forgetting/retier self-heals — so the
            # bound sits far above any real in-flight population and the
            # drop is counted, never silent.
            _record_routing("batch", "stage.batch.accounting_dropped")
            log.warning(
                "shared-scan batch accounting overflowed (%d members); "
                "dropped — solo task.run rates may be briefly polluted",
                len(self._batch_members),
            )
            self._batch_members.clear()
            self._batches.clear()
        job_live: Dict[str, bool] = {}
        alive_others = {
            m.id for m in self.get_executors_metadata()
        } - {executor_id}
        candidates: List[Tuple[str, int, object]] = []
        # weighted fair-share sibling ordering (ISSUE 14 satellite, PR 13
        # residue): candidates are visited lightest-tenant-first by the
        # SAME smallest in_flight/weight key assign_next_schedulable_task
        # uses, re-ranked as this batch claims slots — one heavy tenant
        # can no longer fill every sibling slot of a shared batch while a
        # lighter tenant has co-pending compatible work. Untenanted
        # deployments (one "" tenant) reduce to a stable (job, stage)
        # order. The same running+claimed counts enforce the in-flight
        # quota, so a whole batch can never claim past the bound.
        weights = self._tenant_weights
        rank_inflight = self._tenant_inflight(idx)
        remaining = [
            (key, parts) for key, parts in idx.pending.items()
            if key[0] != pid.job_id and partition in parts
        ]

        def fair_key(item):
            (job_id, stage_id), _parts = item
            tenant = self.job_tenant(job_id)[0]
            return (
                rank_inflight.get(tenant, 0) / weights.get(tenant, 1),
                tenant, job_id, str(stage_id),
            )

        while remaining and len(candidates) < self._shared_max_batch - 1:
            remaining.sort(key=fair_key)
            (job_id, stage_id), parts = remaining.pop(0)
            if job_id not in job_live:
                js = self.get_job_metadata(job_id)
                job_live[job_id] = js is not None and js.WhichOneof(
                    "status"
                ) == "running"
            if not job_live[job_id]:
                continue
            tenant = self.job_tenant(job_id)[0]
            if self._tenant_quota > 0 and \
                    rank_inflight.get(tenant, 0) >= self._tenant_quota:
                continue
            # cheap screen first: the cached per-(job, stage) signature —
            # only a MATCH pays the plan bind (which the dispatched
            # sibling TaskDefinition needs anyway)
            if self._cached_stage_signature(job_id, stage_id) != sig:
                continue
            try:
                bound = self._bound_stage_plan(job_id, stage_id, idx)
                if bound is None:
                    continue
            except Exception:
                continue
            candidates.append((job_id, stage_id, bound))
            rank_inflight[tenant] = rank_inflight.get(tenant, 0) + 1
        if not candidates:
            return []
        # evidence gate (cost model, ISSUE 13): predicted batch wall vs the
        # members' predicted solo sum — both under engine "task" beside the
        # straggler monitor's rates
        from ballista_tpu.ops import costmodel

        k = len(candidates) + 1
        predicted = costmodel.predict("stage.batch", float(k), engine="task")
        solo = [self._predict_task_run(pid.job_id, pid.stage_id)] + [
            self._predict_task_run(j, s) for j, s, _b in candidates
        ]
        if predicted is not None and all(s is not None for s in solo):
            if predicted >= sum(solo):
                _record_shared_scan("batch_gate_solo")
                _record_routing("solo", "stage.batch")
                log.info(
                    "shared-scan gate: batch of %d predicted %.4fs >= solo "
                    "sum %.4fs; dispatching solo", k, predicted, sum(solo),
                )
                return []
        if self._chaos is not None:
            self._batch_seq += 1
            try:
                self._chaos.maybe_fail(
                    "scheduler.batch",
                    f"g{self.generation}/batch{self._batch_seq}",
                )
            except ChaosInjected:
                # torn BEFORE any write: the primary dispatches solo and
                # the would-be siblings stay pending for the next slot
                _record_shared_scan("batch_chaos_solo")
                log.warning(
                    "chaos[scheduler.batch]: batch formation torn; "
                    "dispatching %s/%s/%s solo",
                    pid.job_id, pid.stage_id, partition,
                )
                return []
        out: List[Tuple[pb.TaskStatus, object]] = []
        keys = [(pid.job_id, pid.stage_id, partition)]
        for job_id, stage_id, bound in candidates:
            # re-verify from the KV before claiming, exactly like
            # assignment (the index is local; a peer may have moved on)
            current = self.get_task_status(job_id, stage_id, partition)
            if current is None or current.WhichOneof("status") is not None:
                if current is not None:
                    idx.observe(current)
                continue
            if (
                current.history
                and current.history[-1].executor_id == executor_id
                and alive_others
            ):
                continue  # blacklist: this executor failed its last attempt
            running = pb.TaskStatus()
            running.CopyFrom(current)  # keep attempt + history
            running.running.executor_id = executor_id
            if not self.save_task_status(running):
                continue  # fenced out: a peer adopted the sibling's job
            self._ledger_put(
                (job_id, stage_id, partition), executor_id, running.attempt
            )
            self.note_tenant_assigned(self.job_tenant(job_id)[0])
            keys.append((job_id, stage_id, partition))
            out.append((running, bound))
        if not out:
            return []
        bid = self._batch_next_id
        self._batch_next_id += 1
        k = len(out) + 1
        self._batches[bid] = {
            "k": k,
            "t0": time.monotonic(),
            "remaining": set(keys),
            "predicted": costmodel.predict(
                "stage.batch", float(k), engine="task"
            ),
            "dirty": False,
        }
        for key in keys:
            self._batch_members[key] = bid
        _record_shared_scan("batches_formed")
        _record_shared_scan("batched_stages", k)
        log.info(
            "shared-scan batch %d: %d stages over one scan -> %s "
            "(primary %s/%s/%s)", bid, k, executor_id,
            pid.job_id, pid.stage_id, partition,
        )
        return out

    def maybe_speculate(
        self, executor_id: str
    ) -> Optional[Tuple[pb.TaskStatus, object]]:
        """Cost-model straggler detection (ISSUE 11): pick ONE running task
        whose elapsed time grossly exceeds its task.run prediction (slack
        multiplier x predicted, past the minimum-runtime floor) and whose
        owner is NOT `executor_id`, and hand back a speculative duplicate
        (attempt N+1) for dispatch to this executor — recorded in the
        durable speculation ledger, never in the task's own status (the
        primary stays the current attempt; first completion wins). Returns
        (status, bound plan) like assign_next_schedulable_task, or None.

        Never speculates twice on one task, never on an executor that
        failed a previous attempt of it, and never while the model has no
        prediction (a cold store reproduces pre-speculation scheduling
        exactly — which is also why fault-free runs with the default floor
        launch nothing)."""
        if not self._spec_enabled or not self._running_since:
            return None
        now = time.monotonic()
        if self._speculative:
            # sweep: a duplicate whose executor's lease lapsed is dead
            # weight — the primary still runs, so just drop the record
            alive = {m.id for m in self.get_executors_metadata()}
            for k, entry in list(self._speculative.items()):
                if entry[0] not in alive:
                    self._spec_del(k)
                    _record_speculation("executor_lost")
        job_live: Dict[str, bool] = {}
        inflight: Optional[Dict[str, int]] = None
        for key3 in self._straggler_candidates(now):
            entry = self._running_since.get(key3)
            if entry is None:
                continue
            owner, attempt, t0 = entry
            if owner == executor_id:
                continue
            spec = self._speculative.get(key3)
            if spec is not None:
                # re-speculation (ISSUE 15 satellite, PR 11 residue): the
                # live duplicate may ITSELF straggle past the same
                # cost-model threshold (floor included — its own clock,
                # not the primary's). Bounded by speculation.max_attempts
                # launches per episode; the straggler is superseded in the
                # ledger, its late reports retired via _spec_superseded.
                if spec[0] == executor_id:
                    continue
                if self._spec_launches.get(key3, 1) >= self._spec_max:
                    continue
                if now - spec[2] < self._spec_floor_s:
                    continue
            if key3 in self._batch_members:
                # a shared-scan batch member (ISSUE 13) is co-scheduled
                # with its siblings: its wall time is the BATCH's, not a
                # straggler signal against its solo task.run rate —
                # duplicating it would re-run work the batch is already
                # finishing (real batch loss is covered by the normal
                # lease/orphan machinery)
                continue
            # the straggler under judgment: the primary on a first
            # speculation, the LIVE DUPLICATE on a re-speculation
            elapsed = now - (t0 if spec is None else spec[2])
            pred = self._predict_task_run(key3[0], key3[1])
            if pred is None or elapsed <= self._spec_multiplier * max(pred, 1e-6):
                continue
            job_id, stage_id, partition = key3
            if job_id not in job_live:
                js = self.get_job_metadata(job_id)
                job_live[job_id] = (
                    js is not None and js.WhichOneof("status") == "running"
                )
            if not job_live[job_id]:
                continue
            if self._tenant_quota > 0:
                # the rescue must not grant a saturated tenant an extra
                # physical slot past its max_inflight bound (the duplicate
                # writes no tasks/ status, so it is invisible to the
                # in-flight accounting — gate on the primaries' count)
                tenant = self.job_tenant(job_id)[0]
                if inflight is None:
                    inflight = self._tenant_inflight(self._ensure_task_index())
                if inflight.get(tenant, 0) >= self._tenant_quota:
                    _record_tenancy("speculate_quota_deferred")
                    continue
            # re-verify from the KV before dispatching: the watch map is
            # in-memory and a peer (or a racing status) may have moved on
            cur = self.get_task_status(*key3)
            if (
                cur is None
                or cur.WhichOneof("status") != "running"
                or cur.attempt != attempt
                or cur.running.executor_id != owner
            ):
                self._running_since.pop(key3, None)
                continue
            if any(h.executor_id == executor_id for h in cur.history):
                # this executor already failed an attempt of the task;
                # don't bet the tail-latency rescue on it
                continue
            idx = self._ensure_task_index()
            bound = self._bound_stage_plan(job_id, stage_id, idx)
            if bound is None:
                continue
            dup = pb.TaskStatus()
            dup.partition_id.CopyFrom(cur.partition_id)
            dup.speculative = True
            if spec is not None:
                # supersede the straggling duplicate: it keeps running
                # (first completion wins, whoever crosses the line), but
                # the ledger now tracks its successor and its own late
                # reports retire against the superseded set
                dup.attempt = spec[1] + 1
                self._spec_superseded.setdefault(key3, set()).add(spec[1])
                self._spec_launches[key3] = self._spec_launches.get(key3, 1) + 1
                _record_speculation("relaunched")
            else:
                dup.attempt = cur.attempt + 1
                self._spec_launches[key3] = 1
            self._spec_put(key3, executor_id, dup.attempt)
            self.note_tenant_assigned(self.job_tenant(job_id)[0])
            _record_speculation("launched")
            log.warning(
                "speculating %s/%s/%s on %s (attempt %d%s): elapsed %.3fs > "
                "%.1fx predicted %.3fs (primary %s)",
                job_id, stage_id, partition, executor_id, dup.attempt,
                " re-speculated" if spec is not None else "",
                elapsed, self._spec_multiplier, pred, owner,
            )
            return dup, bound
        return None

    def _note_job_slo(self, job_id: str) -> None:
        """SLO accounting at job completion (ISSUE 11): a job finishing
        past its tenant's ballista.tenant.slo_ms deadline counts one
        slo_misses event. Once per job, enforced here — restart_completed_
        job can un-terminate a job (lost result partitions), and the
        second fold must not count the same job's outcome twice."""
        if not self._tenant_slos:
            return
        if job_id in self._slo_noted:
            return
        if len(self._slo_noted) > 10_000:
            self._slo_noted.clear()
        self._slo_noted.add(job_id)
        tenant, _prio, created = self._job_tenant_full(job_id)
        slo = self._tenant_slos.get(tenant)
        if slo is None or created <= 0.0:
            return
        if (time.time() - created) * 1000.0 > slo:
            _record_speculation("slo_misses")
            log.warning(
                "job %s (tenant %s) missed its %.0fms SLO", job_id, tenant, slo
            )
        else:
            _record_speculation("slo_met")

    def _tenant_inflight(self, idx: _TaskIndex) -> Dict[str, int]:
        """Per-tenant totals of currently RUNNING tasks, via the index's
        per-stage running sets and the job->tenant map."""
        out: Dict[str, int] = {}
        for (job_id, _stage), parts in idx.running.items():
            if not parts:
                continue
            tenant, _prio = self.job_tenant(job_id)
            out[tenant] = out.get(tenant, 0) + len(parts)
        return out

    def _tenant_candidate_order(
        self, idx: _TaskIndex
    ) -> List[Tuple[str, int]]:
        """Pending (job, stage) candidates in admission order (ISSUE 7).

        Tenants are visited by weighted fair share — smallest
        in_flight/weight first (ties by tenant name), so a tenant hogging
        the cluster yields the next slot to lighter tenants the moment they
        have runnable work. A tenant at its in-flight quota
        (ballista.tenant.max_inflight > 0) is skipped entirely: its pending
        work stays queued until its running tasks drain, which is exactly
        the starvation bound the quota promises other tenants. Within a
        tenant, higher-priority jobs come first; the final tie-break is the
        pre-tenancy (job, str(stage)) KV order, so single-tenant
        deployments see the EXACT historical candidate order
        (tests/test_scheduler_state.py asserts identity vs the linear
        scan)."""
        quota = self._tenant_quota
        weights = self._tenant_weights
        inflight = self._tenant_inflight(idx)
        by_tenant: Dict[str, List[Tuple[str, int]]] = {}
        prios: Dict[str, int] = {}
        for key in idx.pending:
            tenant, prio = self.job_tenant(key[0])
            by_tenant.setdefault(tenant, []).append(key)
            prios[key[0]] = prio
        order: List[Tuple[str, int]] = []
        # deadline-aware layer (ISSUE 11): a tenant whose oldest pending
        # job has blown its ballista.tenant.slo_ms deadline jumps ahead of
        # the fair-share order (most overdue first); everyone else — and
        # every deployment with no SLOs configured — keeps the exact
        # weighted fair-share ranking below.
        overdue: Dict[str, float] = {}
        if self._tenant_slos:
            now = time.time()
            for tenant, keys in by_tenant.items():
                slo = self._tenant_slos.get(tenant)
                if slo is None:
                    continue
                headrooms = [
                    created + slo / 1000.0 - now
                    for created in (
                        self.job_created_at(j) for j in {k[0] for k in keys}
                    )
                    if created > 0.0
                ]
                if headrooms and min(headrooms) <= 0.0:
                    overdue[tenant] = min(headrooms)
                    last = self._slo_boosted.get(tenant)
                    if last is None or now - last > 5.0:
                        # a fresh episode: never boosted, or unseen for
                        # long enough that the prior episode ended (a
                        # sub-5s gap is a stage boundary draining the
                        # pending set, not relief)
                        _record_tenancy("admit_slo_boosted")
                    self._slo_boosted[tenant] = now
            for t in by_tenant:
                # evaluated this scan and NOT overdue: episode over
                if t not in overdue:
                    self._slo_boosted.pop(t, None)
        tenant_rank = sorted(
            by_tenant,
            key=lambda t: (
                (0, overdue[t]) if t in overdue
                else (1, inflight.get(t, 0) / weights.get(t, 1)),
                t,
            ),
        )
        for tenant in tenant_rank:
            if quota > 0 and inflight.get(tenant, 0) >= quota:
                _record_tenancy("admit_quota_deferred")
                continue
            order.extend(sorted(
                by_tenant[tenant],
                key=lambda k: (-prios[k[0]], k[0], str(k[1])),
            ))
        return order

    def assign_next_schedulable_task(
        self, executor_id: str
    ) -> Optional[Tuple[pb.TaskStatus, object]]:
        """Index-driven pick of a runnable pending task: a task is runnable
        when every upstream stage it reads from has all tasks completed
        (ref state/mod.rs:182-260 does this as a linear scan over every
        task). The per-stage index narrows the work to stages that actually
        have pending tasks, with O(1) upstream-completeness checks; only a
        chosen stage's upstream statuses are read back from the KV (for
        shuffle locations). Candidates are visited in weighted fair-share
        tenant order with per-tenant in-flight quotas (ISSUE 7,
        _tenant_candidate_order); with no tenants configured this reduces
        to the linear scan's KV key order — tests/test_scheduler_state.py
        asserts identity on randomized DAGs. Marks the pick Running and
        returns (status, bound plan)."""
        idx = self._ensure_task_index()
        # per-task executor blacklist: attempt N+1 must not land on the
        # executor that failed attempt N — unless it is the only executor
        # left alive (progress beats placement when there is no choice)
        alive_others = {
            m.id for m in self.get_executors_metadata()
        } - {executor_id}
        # pending tasks of a terminal job must not be handed out (a failed
        # job can leave requeued-then-exhausted pending work behind)
        job_live: Dict[str, bool] = {}
        for job_id, stage_id in self._tenant_candidate_order(idx):
            # .get: an earlier iteration's upstream KV refresh may have
            # drained (and dropped) this stage's entry mid-iteration
            parts = idx.pending.get((job_id, stage_id))
            if not parts:
                continue
            if job_id not in job_live:
                js = self.get_job_metadata(job_id)
                # queued = planning not yet COMMITTED (the atomic publish
                # flips the job to running with its tasks): tasks visible
                # under a queued job can only be leakage from a torn write
                # on a non-transactional backend and must not be handed out
                job_live[job_id] = (
                    js is None
                    or js.WhichOneof("status") not in (
                        "completed", "failed", "queued",
                    )
                    # ownership gate (ISSUE 20): only the lease holder hands
                    # the job's tasks out — adopting on the spot when the
                    # previous owner's lease expired (thread-free failover)
                ) and self._may_schedule(job_id)
            if not job_live[job_id]:
                continue
            bound = self._bound_stage_plan(job_id, stage_id, idx)
            if bound is None:
                continue
            ordered, resident_pref = self._locality_partition_order(
                bound, parts, executor_id
            )
            for partition in ordered:
                # re-verify from the KV before claiming: the index is local
                # to this SchedulerState; a peer scheduler (or an expired
                # write) must not lead to a double assignment
                current = self.get_task_status(job_id, stage_id, partition)
                if current is None or current.WhichOneof("status") is not None:
                    if current is None:
                        idx.pending[(job_id, stage_id)].discard(partition)
                    else:
                        idx.observe(current)
                    continue
                if (
                    current.history
                    and current.history[-1].executor_id == executor_id
                    and alive_others
                ):
                    # blacklist: this executor failed the previous attempt;
                    # leave the task for a peer (another partition may still
                    # fit this executor)
                    continue
                if self._chaos is not None:
                    # admission chaos (ISSUE 7): abort the PollWork BEFORE
                    # the Running flip — nothing is written, the executor's
                    # poll fails transiently and retries, and the rotated
                    # sequence key gives the retry a fresh verdict. Keyed on
                    # a per-process admission counter (like kv.put's write
                    # counter): the seeded verdict SEQUENCE is reproducible,
                    # while a same-key verdict would refuse this admission
                    # forever.
                    self._admit_seq += 1
                    self._chaos.maybe_fail(
                        "scheduler.admit", f"admit{self._admit_seq}"
                    )
                running = pb.TaskStatus()
                running.CopyFrom(current)  # keep attempt + history
                running.running.executor_id = executor_id
                if partition in resident_pref:
                    # the pick landed where its inputs are HBM-resident
                    from ballista_tpu.ops.runtime import record_exchange

                    record_exchange("locality_preferred")
                if not self.save_task_status(running):
                    # fenced out mid-assignment (ISSUE 20): a peer adopted
                    # the job between the liveness check and the claim —
                    # nothing was written; stop offering this job's tasks
                    job_live[job_id] = False
                    break
                self._ledger_put(
                    (job_id, stage_id, partition), executor_id, running.attempt
                )
                self.note_tenant_assigned(self.job_tenant(job_id)[0])
                return running, bound
        return None

    def reconcile_running_tasks(self, executor_id: str, running) -> int:
        """Fold one executor's in-flight echo against the assignment ledger.

        An entry the owner echoes (with a matching attempt when the echo
        carries one) is CONFIRMED: the assignment reached the executor, so
        the entry retires from the ledger and the normal status/lease
        machinery takes over — after a scheduler restart this is the
        re-adoption path (the restarted scheduler never re-executes a task
        an executor still owns). An entry past the grace period that the
        owner's poll does not echo means the PollWork response carrying the
        assignment never arrived — requeue it through the retry path
        (without this the task is orphaned forever: the owner's lease stays
        fresh, so reset_lost_tasks never fires).

        `running` accepts both echo forms: RunningTaskEcho (partition +
        attempt) and bare PartitionId (wire compat; vouches for whatever
        attempt the ledger holds). Returns the number of RECLAIMED
        (requeued) assignments."""
        now = time.monotonic()
        echo: Dict[Tuple[str, int, int], Optional[int]] = {}
        for p in running:
            if hasattr(p, "job_id"):  # bare PartitionId
                echo[(p.job_id, p.stage_id, p.partition_id)] = None
            else:  # RunningTaskEcho
                pid = p.partition_id
                echo[(pid.job_id, pid.stage_id, pid.partition_id)] = p.attempt
        reclaimed = 0
        # speculative-duplicate reconciliation (ISSUE 11): the duplicate
        # has no tasks/ status, so the ledger entry under speculation/ is
        # the only thing that notices a lost-in-transit delivery. The
        # owner's echo with the speculative attempt confirms it (and, after
        # a restart, re-adopts it); an unvouched entry past the grace
        # window is simply dropped — the primary still runs, so there is
        # nothing to requeue.
        for key, entry in list(self._speculative.items()):
            if key not in self._speculative:
                continue  # purged mid-loop (deposition, ISSUE 20)
            ex, at, t0, vouched, restored = entry
            if ex != executor_id:
                continue
            if key in echo and echo[key] in (None, at):
                if not vouched:
                    self._speculative[key] = (ex, at, t0, True, restored)
                    if restored:
                        _record_recovery("restart_speculation_readopted")
                continue
            if not vouched and now - t0 > ORPHANED_ASSIGNMENT_GRACE_SECS:
                self._spec_del(key)
                _record_speculation("orphaned")
                log.warning(
                    "speculative attempt %d of %s/%s/%s never reached %s; "
                    "dropped (primary still runs)",
                    at, key[0], key[1], key[2], ex,
                )
        # in-memory screens first (owner, echo confirmation, grace window):
        # the KV read + proto parse happens ONLY for entries actually up
        # for requeue — this loop runs under the global lock on every poll,
        # so O(in-flight) KV reads per heartbeat would tax every executor.
        # Entries of other owners (incl. ones superseded elsewhere) are
        # cleaned on their owner's polls or by accept_task_status.
        for key, (owner, attempt, t0, restored) in list(self._assigned.items()):
            if key not in self._assigned:
                continue  # purged mid-loop (deposition, ISSUE 20)
            if owner != executor_id:
                continue  # only the owner's polls can vouch for it
            if key in echo and echo[key] in (None, attempt):
                # confirmed started (a stale-attempt echo does NOT count);
                # status/lease machinery takes over from here
                self._ledger_del(key)
                if restored:
                    _record_recovery("restart_readopted")
                    log.info(
                        "restart reconciliation: executor %s re-adopted "
                        "task %s/%s/%s (attempt %d)",
                        owner, key[0], key[1], key[2], attempt,
                    )
                continue
            if now - t0 < ORPHANED_ASSIGNMENT_GRACE_SECS:
                continue
            # destructive path ahead: re-verify the ownership lease first
            # (ISSUE 20). A peer may have adopted the job while this
            # replica sat paused past its TTL — its restored ledger rows
            # must not be deleted by the deposed owner's reconciliation.
            if key[0] in self._owned:
                held = self.kv.get(self._lease_key(key[0]))
                if held is not None and held != self._owned[key[0]]:
                    self._deposed(key[0])
                    continue
            cur = self.get_task_status(*key)
            if (
                cur is None
                or cur.WhichOneof("status") != "running"
                or cur.attempt != attempt
                or cur.running.executor_id != owner
            ):
                self._ledger_del(key)  # resolved or superseded elsewhere
                continue
            self._ledger_del(key)
            error = (
                f"assignment never reached executor {owner} "
                "(PollWork response lost in transit)"
            )
            if self.requeue_task(cur, owner, error, self.retry_limit(key[0])):
                _record_recovery("orphan_reassigned")
                reclaimed += 1
            else:
                exhausted = pb.TaskStatus()
                exhausted.CopyFrom(cur)
                exhausted.failed.error = error
                exhausted.failed.executor_id = owner
                self._fail_job(key[0], _attempts_error(exhausted))
        return reclaimed

    # -- job status fold ------------------------------------------------------
    def synchronize_job_status(self, job_id: str) -> None:
        """Fold task statuses into the job status (ref state/mod.rs:267-358)
        — through the retry policy: a failed task inside its retry budget is
        requeued (with the attempt recorded in its history) instead of
        failing the job, a fetch_failed task additionally recomputes the
        lost map partition (lineage), and only an exhausted task fails the
        job — with every attempt listed in the error."""
        current = self.get_job_metadata(job_id)
        which_job = current.WhichOneof("status") if current is not None else None
        if which_job == "queued":
            # still being planned; tasks not yet created
            return
        if which_job in ("completed", "failed"):
            # terminal: late task reports must not resurrect the job
            return
        tasks = self.get_job_tasks(job_id)
        if not tasks:
            return
        limit = self.retry_limit(job_id)
        status = pb.JobStatus()
        any_failed = None
        all_completed = True
        for t in tasks:
            w = t.WhichOneof("status")
            if w == "failed":
                if self.requeue_task(
                    t, t.failed.executor_id, t.failed.error, limit
                ):
                    all_completed = False
                    continue
                any_failed = _attempts_error(t)
                break
            if w == "fetch_failed":
                if self.handle_fetch_failed(t, limit):
                    all_completed = False
                    continue
                any_failed = _attempts_error(t)
                break
            if w != "completed":
                all_completed = False
        if any_failed is not None:
            status.failed.error = any_failed
            _record_recovery("job_failed_exhausted")
        elif all_completed:
            final_stage = max(t.partition_id.stage_id for t in tasks)
            for t in sorted(tasks, key=lambda t: t.partition_id.partition_id):
                if t.partition_id.stage_id != final_stage:
                    continue
                pl = status.completed.partition_location.add()
                pl.partition_id.CopyFrom(t.partition_id)
                meta = self.get_executor_metadata(t.completed.executor_id)
                if meta is not None:
                    pl.executor_meta.CopyFrom(meta)
                pl.path = t.completed.path
                pl.partition_stats.CopyFrom(t.completed.stats)
                pl.storage_uri = t.completed.storage_uri
                pl.resident = t.completed.resident
        else:
            status.running.SetInParent()
            # per-partition completion notifications (ISSUE 8): publish the
            # final-stage result partitions completed SO FAR on the running
            # status, so a streaming client starts fetching before the last
            # partition lands. Built exactly like the completed list above —
            # same location shape, same partition order — and re-derived on
            # every fold, so a requeued partition simply drops out until its
            # retry completes again.
            final_stage = max(t.partition_id.stage_id for t in tasks)
            for t in sorted(tasks, key=lambda t: t.partition_id.partition_id):
                if (
                    t.partition_id.stage_id != final_stage
                    or t.WhichOneof("status") != "completed"
                ):
                    continue
                pl = status.running.partial_location.add()
                pl.partition_id.CopyFrom(t.partition_id)
                meta = self.get_executor_metadata(t.completed.executor_id)
                if meta is not None:
                    pl.executor_meta.CopyFrom(meta)
                pl.path = t.completed.path
                pl.partition_stats.CopyFrom(t.completed.stats)
                pl.storage_uri = t.completed.storage_uri
                pl.resident = t.completed.resident
        if not self.save_job_metadata(job_id, status):
            # fenced out (ISSUE 20): a peer adopted the job mid-fold — its
            # own synchronization owns the terminal transition, the GC
            # release, the SLO note, and the result-cache publish
            return
        which_new = status.WhichOneof("status")
        if which_new in ("completed", "failed"):
            # shared-store GC (ISSUE 16 satellite): the terminal transition
            # happens exactly ONCE (the already-terminal early return above
            # guards re-entry), so this is the refcount-release point for
            # the job's intermediate shuffle pieces — completed keeps its
            # final stage for the client/result cache, failed releases all
            self._gc_shared_store_job(
                job_id,
                max(t.partition_id.stage_id for t in tasks)
                if which_new == "completed" else None,
                tasks,
            )
        if which_new == "completed":
            self._note_job_slo(job_id)
            # publish into the plan-fingerprint result cache (ISSUE 7).
            # jobfp/{job} exists only when the submission was fingerprintable
            # AND caching was enabled for it — so this is already gated.
            fp = self.get_job_fingerprint(job_id)
            if fp is not None:
                self.result_cache_put(fp, status.completed, job_id=job_id)

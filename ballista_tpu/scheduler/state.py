"""Scheduler cluster state over a KV backend.

Mirrors the reference's SchedulerState (rust/scheduler/src/state/mod.rs):
every piece of cluster state is a protobuf value under
/ballista/{namespace}/... keys, so a restarted scheduler on a durable
backend resumes mid-job. Key layout (ref state/mod.rs:387-434):

    executors/{id}                  ExecutorMetadata (60s lease)
    jobs/{job_id}                   JobStatus
    stages/{job_id}/{stage_id}      PhysicalPlanNode (the stage plan)
    tasks/{job_id}/{stage_id}/{p}   TaskStatus (empty oneof = pending)
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

from ballista_tpu.distributed.planner import (
    find_unresolved_shuffles,
    remove_unresolved_shuffles,
)
from ballista_tpu.distributed.stages import ShuffleLocation, ShuffleWriterExec
from ballista_tpu.proto import ballista_pb2 as pb
from ballista_tpu.scheduler.kv import KvBackend
from ballista_tpu.serde.physical import phys_plan_from_proto, phys_plan_to_proto

EXECUTOR_LEASE_SECS = 60.0  # ref state/mod.rs:42


class _TaskIndex:
    """Per-stage pending/incomplete index over task statuses.

    assign_next_schedulable_task previously re-scanned (and re-parsed) EVERY
    task protobuf in the KV under the global scheduler lock on every poll —
    O(total tasks) per idle poll. The index keeps, per (job_id, stage_id):
    the pending partitions (status oneof unset), the not-yet-completed
    partitions (answers "is this upstream stage fully done" in O(1)), and
    the total task count (a stage with NO tasks is never a satisfied
    dependency). It is seeded lazily from one full scan — a restarted
    scheduler on a durable backend resumes correctly — and then maintained
    on every save_task_status transition, which is the single write path
    for task state (planning, poll updates, lost-task resets)."""

    def __init__(self) -> None:
        self.pending: Dict[Tuple[str, int], set] = {}
        self.incomplete: Dict[Tuple[str, int], set] = {}
        self.total: Dict[Tuple[str, int], set] = {}

    def observe(self, status: pb.TaskStatus) -> None:
        pid = status.partition_id
        key = (pid.job_id, pid.stage_id)
        part = pid.partition_id
        self.total.setdefault(key, set()).add(part)
        w = status.WhichOneof("status")
        if w is None:
            self.pending.setdefault(key, set()).add(part)
        else:
            self._drop(self.pending, key, part)
        if w == "completed":
            self._drop(self.incomplete, key, part)
        else:
            self.incomplete.setdefault(key, set()).add(part)

    @staticmethod
    def _drop(index: Dict[Tuple[str, int], set], key, part) -> None:
        """Remove part from index[key], deleting drained entries — a
        long-lived scheduler must not re-sort every stage it ever saw on
        each poll."""
        s = index.get(key)
        if s is None:
            return
        s.discard(part)
        if not s:
            del index[key]

    def stage_done(self, job_id: str, stage_id: int) -> bool:
        key = (job_id, stage_id)
        return bool(self.total.get(key)) and not self.incomplete.get(key)


# a peer scheduler sharing the namespace writes tasks this instance's index
# never observes; re-seed from a full scan at most this often so peer-
# submitted jobs are discovered within a bounded delay (single-scheduler
# deployments see every write through save_task_status and never need it,
# but still pay at most one scan per interval instead of one per poll)
TASK_INDEX_RESEED_SECS = 5.0


class SchedulerState:
    def __init__(self, kv: KvBackend, namespace: str = "default") -> None:
        self.kv = kv
        self.namespace = namespace
        self._task_index: Optional[_TaskIndex] = None
        self._task_index_seeded_at = 0.0

    def _key(self, *parts: str) -> str:
        return "/".join(("/ballista", self.namespace) + parts)

    # -- executors ----------------------------------------------------------
    def save_executor_metadata(self, meta: pb.ExecutorMetadata) -> None:
        self.kv.put(
            self._key("executors", meta.id),
            meta.SerializeToString(),
            lease_seconds=EXECUTOR_LEASE_SECS,
        )

    def get_executors_metadata(self) -> List[pb.ExecutorMetadata]:
        out = []
        for _k, v in self.kv.get_prefix(self._key("executors")):
            m = pb.ExecutorMetadata()
            m.ParseFromString(v)
            out.append(m)
        return out

    def get_executor_metadata(self, executor_id: str) -> Optional[pb.ExecutorMetadata]:
        v = self.kv.get(self._key("executors", executor_id))
        if v is None:
            return None
        m = pb.ExecutorMetadata()
        m.ParseFromString(v)
        return m

    # -- jobs -----------------------------------------------------------------
    def save_job_metadata(self, job_id: str, status: pb.JobStatus) -> None:
        self.kv.put(self._key("jobs", job_id), status.SerializeToString())

    def get_job_metadata(self, job_id: str) -> Optional[pb.JobStatus]:
        v = self.kv.get(self._key("jobs", job_id))
        if v is None:
            return None
        s = pb.JobStatus()
        s.ParseFromString(v)
        return s

    def save_job_settings(self, job_id: str, settings: Dict[str, str]) -> None:
        """Client-supplied per-job settings, attached to every
        TaskDefinition for this job so executors honor them."""
        msg = pb.JobSettings()
        for k, v in settings.items():
            msg.settings.add(key=k, value=v)
        self.kv.put(self._key("settings", job_id), msg.SerializeToString())

    def get_job_settings(self, job_id: str) -> Dict[str, str]:
        v = self.kv.get(self._key("settings", job_id))
        if v is None:
            return {}
        msg = pb.JobSettings()
        msg.ParseFromString(v)
        return {kv.key: kv.value for kv in msg.settings}

    # -- stage plans ----------------------------------------------------------
    def save_stage_plan(self, job_id: str, stage_id: int, plan) -> None:
        msg = phys_plan_to_proto(plan)
        self.kv.put(
            self._key("stages", job_id, str(stage_id)), msg.SerializeToString()
        )

    def get_stage_plan(self, job_id: str, stage_id: int):
        v = self.kv.get(self._key("stages", job_id, str(stage_id)))
        if v is None:
            return None
        n = pb.PhysicalPlanNode()
        n.ParseFromString(v)
        return phys_plan_from_proto(n)

    # -- tasks ------------------------------------------------------------------
    def save_task_status(self, status: pb.TaskStatus) -> None:
        pid = status.partition_id
        self.kv.put(
            self._key("tasks", pid.job_id, str(pid.stage_id), str(pid.partition_id)),
            status.SerializeToString(),
        )
        if self._task_index is not None:
            self._task_index.observe(status)

    def _ensure_task_index(self) -> _TaskIndex:
        """Seed the per-stage task index from one full scan, then keep it
        current through save_task_status — and RE-seed at most every
        TASK_INDEX_RESEED_SECS so peer-scheduler writes (new jobs, lost-task
        resets) are discovered with bounded delay instead of never.
        Assignment additionally re-verifies the chosen task's pending state
        and every upstream status from the KV before acting on them."""
        now = time.monotonic()
        if (
            self._task_index is None
            or now - self._task_index_seeded_at > TASK_INDEX_RESEED_SECS
        ):
            idx = _TaskIndex()
            for t in self.get_all_tasks():
                idx.observe(t)
            self._task_index = idx
            self._task_index_seeded_at = now
        return self._task_index

    def get_task_status(self, job_id: str, stage_id: int, partition: int) -> Optional[pb.TaskStatus]:
        v = self.kv.get(self._key("tasks", job_id, str(stage_id), str(partition)))
        if v is None:
            return None
        t = pb.TaskStatus()
        t.ParseFromString(v)
        return t

    def get_job_tasks(self, job_id: str) -> List[pb.TaskStatus]:
        out = []
        for _k, v in self.kv.get_prefix(self._key("tasks", job_id)):
            t = pb.TaskStatus()
            t.ParseFromString(v)
            out.append(t)
        return out

    def get_stage_tasks(self, job_id: str, stage_id: int) -> List[pb.TaskStatus]:
        # trailing "/": the bare prefix "tasks/j/2" would also match stage 20
        out = []
        for _k, v in self.kv.get_prefix(self._key("tasks", job_id, str(stage_id)) + "/"):
            t = pb.TaskStatus()
            t.ParseFromString(v)
            out.append(t)
        return out

    def get_all_tasks(self) -> List[pb.TaskStatus]:
        out = []
        for _k, v in self.kv.get_prefix(self._key("tasks")):
            t = pb.TaskStatus()
            t.ParseFromString(v)
            out.append(t)
        return out

    # -- failure recovery ---------------------------------------------------
    def reset_lost_tasks(self) -> int:
        """Re-schedule work lost to dead executors (beyond the reference,
        which loses in-flight work permanently — SURVEY §5 'no retry').

        A task RUNNING on an executor whose lease expired goes back to
        pending; a COMPLETED task whose output lives on a dead executor also
        goes back to pending (its shuffle files are unreachable), which
        recursively invalidates dependents via the normal runnability check.
        Returns the number of tasks reset."""
        alive = {m.id for m in self.get_executors_metadata()}
        finished_jobs: Dict[str, bool] = {}
        reset = 0
        for t in self.get_all_tasks():
            job_id = t.partition_id.job_id
            if job_id not in finished_jobs:
                js = self.get_job_metadata(job_id)
                finished_jobs[job_id] = js is not None and js.WhichOneof("status") in (
                    "completed",
                    "failed",
                )
            if finished_jobs[job_id]:
                continue  # don't resurrect finished jobs
            w = t.WhichOneof("status")
            owner = None
            if w == "running":
                owner = t.running.executor_id
            elif w == "completed":
                owner = t.completed.executor_id
            if owner is not None and owner not in alive:
                pending = pb.TaskStatus()
                pending.partition_id.CopyFrom(t.partition_id)
                self.save_task_status(pending)
                reset += 1
        return reset

    # -- scheduling ---------------------------------------------------------
    def assign_next_schedulable_task(
        self, executor_id: str
    ) -> Optional[Tuple[pb.TaskStatus, object]]:
        """Index-driven pick of a runnable pending task: a task is runnable
        when every upstream stage it reads from has all tasks completed
        (ref state/mod.rs:182-260 does this as a linear scan over every
        task). The per-stage index narrows the work to stages that actually
        have pending tasks, with O(1) upstream-completeness checks; only a
        chosen stage's upstream statuses are read back from the KV (for
        shuffle locations). Candidate order matches the linear scan's KV
        key order — tests/test_scheduler_state.py asserts identity on
        randomized DAGs. Marks the pick Running and returns
        (status, bound plan)."""
        idx = self._ensure_task_index()
        # KV keys order stage/partition ids as STRINGS ("10" < "2"); the
        # scan this replaces inherited that order from get_prefix
        for job_id, stage_id in sorted(
            idx.pending, key=lambda k: (k[0], str(k[1]))
        ):
            # .get: an earlier iteration's upstream KV refresh may have
            # drained (and dropped) this stage's entry mid-iteration
            parts = idx.pending.get((job_id, stage_id))
            if not parts:
                continue
            plan = self.get_stage_plan(job_id, stage_id)
            if plan is None:
                continue
            unresolved = find_unresolved_shuffles(plan)
            locations: Dict[int, List[ShuffleLocation]] = {}
            blocked = False
            for u in unresolved:
                # O(1) screen: stages the index knows are incomplete skip
                # the KV read entirely (staleness toward "peer completed
                # it" is bounded by the periodic reseed)
                if not idx.stage_done(job_id, u.stage_id):
                    blocked = True
                    break
                # the locations are built from FRESH KV statuses with a
                # final completeness check — a peer's lost-task reset
                # (completed -> pending, unseen by this index) must block
                # the stage, not hand out empty executor/path locations
                upstream = self.get_stage_tasks(job_id, u.stage_id)
                for t in upstream:
                    idx.observe(t)
                if not upstream or any(
                    t.WhichOneof("status") != "completed" for t in upstream
                ):
                    blocked = True
                    break
                locs = []
                for t in sorted(upstream, key=lambda t: t.partition_id.partition_id):
                    meta = self.get_executor_metadata(t.completed.executor_id)
                    host, port = (meta.host, meta.port) if meta else ("", 0)
                    locs.append(
                        ShuffleLocation(
                            t.completed.executor_id, host, port, t.completed.path
                        )
                    )
                locations[u.stage_id] = locs
            if blocked:
                continue
            bound = remove_unresolved_shuffles(plan, locations) if unresolved else plan
            partition = min(parts, key=str)
            # re-verify from the KV before claiming: the index is local to
            # this SchedulerState; a peer scheduler (or an expired write)
            # must not lead to a double assignment
            current = self.get_task_status(job_id, stage_id, partition)
            if current is None or current.WhichOneof("status") is not None:
                if current is None:
                    idx.pending[(job_id, stage_id)].discard(partition)
                else:
                    idx.observe(current)
                continue
            running = pb.TaskStatus()
            running.partition_id.CopyFrom(current.partition_id)
            running.running.executor_id = executor_id
            self.save_task_status(running)
            return running, bound
        return None

    # -- job status fold ------------------------------------------------------
    def synchronize_job_status(self, job_id: str) -> None:
        """Fold task statuses into the job status (ref state/mod.rs:267-358)."""
        current = self.get_job_metadata(job_id)
        if current is not None and current.WhichOneof("status") == "queued":
            # still being planned; tasks not yet created
            return
        tasks = self.get_job_tasks(job_id)
        if not tasks:
            return
        status = pb.JobStatus()
        any_failed = None
        all_completed = True
        for t in tasks:
            w = t.WhichOneof("status")
            if w == "failed":
                any_failed = t.failed.error
                break
            if w != "completed":
                all_completed = False
        if any_failed is not None:
            status.failed.error = any_failed
        elif all_completed:
            final_stage = max(t.partition_id.stage_id for t in tasks)
            for t in sorted(tasks, key=lambda t: t.partition_id.partition_id):
                if t.partition_id.stage_id != final_stage:
                    continue
                pl = status.completed.partition_location.add()
                pl.partition_id.CopyFrom(t.partition_id)
                meta = self.get_executor_metadata(t.completed.executor_id)
                if meta is not None:
                    pl.executor_meta.CopyFrom(meta)
                pl.path = t.completed.path
                pl.partition_stats.CopyFrom(t.completed.stats)
        else:
            status.running.SetInParent()
        self.save_job_metadata(job_id, status)

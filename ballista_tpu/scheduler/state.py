"""Scheduler cluster state over a KV backend.

Mirrors the reference's SchedulerState (rust/scheduler/src/state/mod.rs):
every piece of cluster state is a protobuf value under
/ballista/{namespace}/... keys, so a restarted scheduler on a durable
backend resumes mid-job. Key layout (ref state/mod.rs:387-434):

    executors/{id}                  ExecutorMetadata (60s lease)
    jobs/{job_id}                   JobStatus
    stages/{job_id}/{stage_id}      PhysicalPlanNode (the stage plan)
    tasks/{job_id}/{stage_id}/{p}   TaskStatus (empty oneof = pending)
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

from ballista_tpu.distributed.planner import (
    find_unresolved_shuffles,
    remove_unresolved_shuffles,
)
from ballista_tpu.distributed.stages import ShuffleLocation, ShuffleWriterExec
from ballista_tpu.proto import ballista_pb2 as pb
from ballista_tpu.scheduler.kv import KvBackend
from ballista_tpu.serde.physical import phys_plan_from_proto, phys_plan_to_proto

EXECUTOR_LEASE_SECS = 60.0  # ref state/mod.rs:42


class SchedulerState:
    def __init__(self, kv: KvBackend, namespace: str = "default") -> None:
        self.kv = kv
        self.namespace = namespace

    def _key(self, *parts: str) -> str:
        return "/".join(("/ballista", self.namespace) + parts)

    # -- executors ----------------------------------------------------------
    def save_executor_metadata(self, meta: pb.ExecutorMetadata) -> None:
        self.kv.put(
            self._key("executors", meta.id),
            meta.SerializeToString(),
            lease_seconds=EXECUTOR_LEASE_SECS,
        )

    def get_executors_metadata(self) -> List[pb.ExecutorMetadata]:
        out = []
        for _k, v in self.kv.get_prefix(self._key("executors")):
            m = pb.ExecutorMetadata()
            m.ParseFromString(v)
            out.append(m)
        return out

    def get_executor_metadata(self, executor_id: str) -> Optional[pb.ExecutorMetadata]:
        v = self.kv.get(self._key("executors", executor_id))
        if v is None:
            return None
        m = pb.ExecutorMetadata()
        m.ParseFromString(v)
        return m

    # -- jobs -----------------------------------------------------------------
    def save_job_metadata(self, job_id: str, status: pb.JobStatus) -> None:
        self.kv.put(self._key("jobs", job_id), status.SerializeToString())

    def get_job_metadata(self, job_id: str) -> Optional[pb.JobStatus]:
        v = self.kv.get(self._key("jobs", job_id))
        if v is None:
            return None
        s = pb.JobStatus()
        s.ParseFromString(v)
        return s

    def save_job_settings(self, job_id: str, settings: Dict[str, str]) -> None:
        """Client-supplied per-job settings, attached to every
        TaskDefinition for this job so executors honor them."""
        msg = pb.JobSettings()
        for k, v in settings.items():
            msg.settings.add(key=k, value=v)
        self.kv.put(self._key("settings", job_id), msg.SerializeToString())

    def get_job_settings(self, job_id: str) -> Dict[str, str]:
        v = self.kv.get(self._key("settings", job_id))
        if v is None:
            return {}
        msg = pb.JobSettings()
        msg.ParseFromString(v)
        return {kv.key: kv.value for kv in msg.settings}

    # -- stage plans ----------------------------------------------------------
    def save_stage_plan(self, job_id: str, stage_id: int, plan) -> None:
        msg = phys_plan_to_proto(plan)
        self.kv.put(
            self._key("stages", job_id, str(stage_id)), msg.SerializeToString()
        )

    def get_stage_plan(self, job_id: str, stage_id: int):
        v = self.kv.get(self._key("stages", job_id, str(stage_id)))
        if v is None:
            return None
        n = pb.PhysicalPlanNode()
        n.ParseFromString(v)
        return phys_plan_from_proto(n)

    # -- tasks ------------------------------------------------------------------
    def save_task_status(self, status: pb.TaskStatus) -> None:
        pid = status.partition_id
        self.kv.put(
            self._key("tasks", pid.job_id, str(pid.stage_id), str(pid.partition_id)),
            status.SerializeToString(),
        )

    def get_task_status(self, job_id: str, stage_id: int, partition: int) -> Optional[pb.TaskStatus]:
        v = self.kv.get(self._key("tasks", job_id, str(stage_id), str(partition)))
        if v is None:
            return None
        t = pb.TaskStatus()
        t.ParseFromString(v)
        return t

    def get_job_tasks(self, job_id: str) -> List[pb.TaskStatus]:
        out = []
        for _k, v in self.kv.get_prefix(self._key("tasks", job_id)):
            t = pb.TaskStatus()
            t.ParseFromString(v)
            out.append(t)
        return out

    def get_all_tasks(self) -> List[pb.TaskStatus]:
        out = []
        for _k, v in self.kv.get_prefix(self._key("tasks")):
            t = pb.TaskStatus()
            t.ParseFromString(v)
            out.append(t)
        return out

    # -- failure recovery ---------------------------------------------------
    def reset_lost_tasks(self) -> int:
        """Re-schedule work lost to dead executors (beyond the reference,
        which loses in-flight work permanently — SURVEY §5 'no retry').

        A task RUNNING on an executor whose lease expired goes back to
        pending; a COMPLETED task whose output lives on a dead executor also
        goes back to pending (its shuffle files are unreachable), which
        recursively invalidates dependents via the normal runnability check.
        Returns the number of tasks reset."""
        alive = {m.id for m in self.get_executors_metadata()}
        finished_jobs: Dict[str, bool] = {}
        reset = 0
        for t in self.get_all_tasks():
            job_id = t.partition_id.job_id
            if job_id not in finished_jobs:
                js = self.get_job_metadata(job_id)
                finished_jobs[job_id] = js is not None and js.WhichOneof("status") in (
                    "completed",
                    "failed",
                )
            if finished_jobs[job_id]:
                continue  # don't resurrect finished jobs
            w = t.WhichOneof("status")
            owner = None
            if w == "running":
                owner = t.running.executor_id
            elif w == "completed":
                owner = t.completed.executor_id
            if owner is not None and owner not in alive:
                pending = pb.TaskStatus()
                pending.partition_id.CopyFrom(t.partition_id)
                self.save_task_status(pending)
                reset += 1
        return reset

    # -- scheduling ---------------------------------------------------------
    def assign_next_schedulable_task(
        self, executor_id: str
    ) -> Optional[Tuple[pb.TaskStatus, object]]:
        """Linear scan for a runnable pending task (ref state/mod.rs:182-260):
        a task is runnable when every upstream stage it reads from has all
        tasks completed. Marks it Running and returns (status, bound plan)."""
        tasks = self.get_all_tasks()
        by_stage: Dict[Tuple[str, int], List[pb.TaskStatus]] = {}
        for t in tasks:
            by_stage.setdefault(
                (t.partition_id.job_id, t.partition_id.stage_id), []
            ).append(t)

        for task in tasks:
            if task.WhichOneof("status") is not None:
                continue  # already running/completed/failed
            job_id = task.partition_id.job_id
            stage_id = task.partition_id.stage_id
            plan = self.get_stage_plan(job_id, stage_id)
            if plan is None:
                continue
            unresolved = find_unresolved_shuffles(plan)
            locations: Dict[int, List[ShuffleLocation]] = {}
            runnable = True
            for u in unresolved:
                upstream = by_stage.get((job_id, u.stage_id), [])
                if not upstream or any(
                    t.WhichOneof("status") != "completed" for t in upstream
                ):
                    runnable = False
                    break
                locs = []
                for t in sorted(upstream, key=lambda t: t.partition_id.partition_id):
                    meta = self.get_executor_metadata(t.completed.executor_id)
                    host, port = (meta.host, meta.port) if meta else ("", 0)
                    locs.append(
                        ShuffleLocation(
                            t.completed.executor_id, host, port, t.completed.path
                        )
                    )
                locations[u.stage_id] = locs
            if not runnable:
                continue
            bound = remove_unresolved_shuffles(plan, locations) if unresolved else plan
            # mark running
            running = pb.TaskStatus()
            running.partition_id.CopyFrom(task.partition_id)
            running.running.executor_id = executor_id
            self.save_task_status(running)
            return running, bound
        return None

    # -- job status fold ------------------------------------------------------
    def synchronize_job_status(self, job_id: str) -> None:
        """Fold task statuses into the job status (ref state/mod.rs:267-358)."""
        current = self.get_job_metadata(job_id)
        if current is not None and current.WhichOneof("status") == "queued":
            # still being planned; tasks not yet created
            return
        tasks = self.get_job_tasks(job_id)
        if not tasks:
            return
        status = pb.JobStatus()
        any_failed = None
        all_completed = True
        for t in tasks:
            w = t.WhichOneof("status")
            if w == "failed":
                any_failed = t.failed.error
                break
            if w != "completed":
                all_completed = False
        if any_failed is not None:
            status.failed.error = any_failed
        elif all_completed:
            final_stage = max(t.partition_id.stage_id for t in tasks)
            for t in sorted(tasks, key=lambda t: t.partition_id.partition_id):
                if t.partition_id.stage_id != final_stage:
                    continue
                pl = status.completed.partition_location.add()
                pl.partition_id.CopyFrom(t.partition_id)
                meta = self.get_executor_metadata(t.completed.executor_id)
                if meta is not None:
                    pl.executor_meta.CopyFrom(meta)
                pl.path = t.completed.path
                pl.partition_stats.CopyFrom(t.completed.stats)
        else:
            status.running.SetInParent()
        self.save_job_metadata(job_id, status)

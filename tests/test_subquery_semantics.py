"""Regression tests for subquery decorrelation semantics (code-review findings)."""

import pyarrow as pa
import pytest

from ballista_tpu.engine import ExecutionContext
from ballista_tpu.errors import SqlError


@pytest.fixture
def ctx():
    c = ExecutionContext()
    c.register_record_batches("t", pa.table({"a": [1, 2, 3], "b": [10, 20, 30]}))
    c.register_record_batches("s", pa.table({"x": [1, 2], "y": [1, 1]}))
    c.register_record_batches("s_null", pa.table({"x": [1, None]}))
    return c


def test_correlated_count_empty_group_is_zero(ctx):
    # a=3 has no matching s rows; COUNT over the empty group is 0, so the
    # predicate 0 = count(...) must KEEP that row
    out = ctx.sql(
        "select a from t where 0 = (select count(*) from s where s.x = t.a) order by a"
    ).collect()
    assert out.column("a").to_pylist() == [3]


def test_correlated_sum_empty_group_is_null(ctx):
    # SUM over the empty group is NULL; comparison with NULL is unknown -> drop
    out = ctx.sql(
        "select a from t where 1 <= (select sum(y) from s where s.x = t.a) order by a"
    ).collect()
    assert out.column("a").to_pylist() == [1, 2]


def test_not_in_with_null_in_subquery_returns_nothing(ctx):
    out = ctx.sql("select a from t where a not in (select x from s_null)").collect()
    assert out.num_rows == 0


def test_not_in_without_nulls(ctx):
    out = ctx.sql(
        "select a from t where a not in (select x from s) order by a"
    ).collect()
    assert out.column("a").to_pylist() == [3]


def test_not_in_select_star_stays_clean(ctx):
    # the null-guard helper column must not leak into SELECT *
    out = ctx.sql("select * from t where a not in (select x from s)").collect()
    assert out.column_names == ["a", "b"]


def test_correlated_in_subquery(ctx):
    out = ctx.sql(
        "select a from t where a in (select y from s where s.x = t.a) order by a"
    ).collect()
    # s rows: (x=1,y=1), (x=2,y=1); for t.a=1 the group is {y=1} -> 1 in it;
    # for t.a=2 the group is {y=1} -> 2 not in it
    assert out.column("a").to_pylist() == [1]


def test_union_mismatched_columns_rejected(ctx):
    with pytest.raises(SqlError, match="column counts"):
        ctx.sql("select a from t union all select x, y from s")

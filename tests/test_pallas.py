"""Pallas kernel tests (interpret mode — semantics identical on TPU;
real-chip correctness is exercised by the bench/verify flow)."""

import numpy as np
import pytest

from ballista_tpu.ops.pallas_kernels import grouped_aggregate, pallas_available


pytestmark = pytest.mark.skipif(
    not pallas_available(), reason="pallas not importable"
)


def _ref(codes, vals, mask, G):
    ref = np.zeros((G, vals.shape[1]), dtype=np.float64)
    np.add.at(ref, codes[mask], vals[mask].astype(np.float64))
    return ref


def test_grouped_aggregate_matches_reference():
    rng = np.random.default_rng(1)
    N, G, A = 4096, 6, 4
    codes = rng.integers(0, G, N).astype(np.int32)
    vals = rng.uniform(-5, 5, (N, A)).astype(np.float32)
    mask = rng.random(N) > 0.4
    out = grouped_aggregate(codes, vals, mask, G, interpret=True)
    assert out is not None
    np.testing.assert_allclose(out, _ref(codes, vals, mask, G), rtol=1e-4, atol=1e-3)


def test_grouped_aggregate_unaligned_length():
    rng = np.random.default_rng(2)
    N, G, A = 3001, 5, 2  # not a multiple of the block size
    codes = rng.integers(0, G, N).astype(np.int32)
    vals = rng.uniform(0, 1, (N, A)).astype(np.float32)
    mask = np.ones(N, dtype=bool)
    out = grouped_aggregate(codes, vals, mask, G, interpret=True)
    np.testing.assert_allclose(out, _ref(codes, vals, mask, G), rtol=1e-4, atol=1e-3)


def test_declines_large_group_count():
    codes = np.zeros(10, dtype=np.int32)
    vals = np.zeros((10, 1), dtype=np.float32)
    mask = np.ones(10, dtype=bool)
    assert grouped_aggregate(codes, vals, mask, 1000, interpret=True) is None


def test_empty_input_returns_zeros():
    out = grouped_aggregate(
        np.zeros(0, dtype=np.int32),
        np.zeros((0, 3), dtype=np.float32),
        np.zeros(0, dtype=bool),
        4,
        interpret=True,
    )
    assert out.shape == (4, 3) and (out == 0).all()

"""End-to-end single-process engine tests (host/Arrow backend oracle)."""

import pyarrow as pa
import pytest

from ballista_tpu.logical import col, functions as F, lit


def _register(ctx, sales_table, n_partitions=1):
    ctx.register_record_batches("sales", sales_table, n_partitions=n_partitions)


def test_filter_project(ctx, sales_table):
    _register(ctx, sales_table)
    df = (
        ctx.table("sales")
        .filter(col("amount") > lit(20.0))
        .select(col("id"), (col("amount") * lit(2.0)).alias("double_amount"))
    )
    out = df.collect()
    assert out.column_names == ["id", "double_amount"]
    assert out.num_rows == 6
    assert out.column("double_amount").to_pylist() == [60.0, 50.0, 70.0, 90.0, 110.0, 130.0]


@pytest.mark.parametrize("n_partitions", [1, 3])
def test_aggregate_partial_final(ctx, sales_table, n_partitions):
    _register(ctx, sales_table, n_partitions)
    df = ctx.table("sales").aggregate(
        [col("region")],
        [
            F.sum(col("amount")).alias("total"),
            F.avg(col("amount")).alias("avg_amount"),
            F.count(col("id")).alias("n"),
            F.min(col("qty")).alias("min_qty"),
            F.max(col("qty")).alias("max_qty"),
        ],
    ).sort(col("region").sort())
    out = df.collect()
    assert out.column("region").to_pylist() == ["east", "north", "west"]
    assert out.column("total").to_pylist() == [120.0, 40.0, 145.0]
    assert out.column("n").to_pylist() == [4, 2, 4]
    assert out.column("min_qty").to_pylist() == [1, 4, 2]
    assert out.column("max_qty").to_pylist() == [9, 7, 10]
    avg = out.column("avg_amount").to_pylist()
    assert avg[0] == pytest.approx(30.0)


def test_scalar_aggregate_no_groups(ctx, sales_table):
    _register(ctx, sales_table, 2)
    out = ctx.table("sales").aggregate(
        [], [F.sum(col("amount")).alias("s"), F.count(col("id")).alias("c")]
    ).collect()
    assert out.num_rows == 1
    assert out.column("s").to_pylist() == [305.0]
    assert out.column("c").to_pylist() == [10]


def test_sort_limit(ctx, sales_table):
    _register(ctx, sales_table, 2)
    out = (
        ctx.table("sales")
        .select(col("id"), col("amount"))
        .sort(col("amount").sort(ascending=False))
        .limit(3)
        .collect()
    )
    assert out.column("amount").to_pylist() == [65.0, 55.0, 45.0]


def test_join(ctx, sales_table):
    _register(ctx, sales_table)
    regions = pa.table(
        {
            "name": pa.array(["east", "west", "north", "south"]),
            "manager": pa.array(["alice", "bob", "carol", "dan"]),
        }
    )
    ctx.register_record_batches("regions", regions)
    out = (
        ctx.table("sales")
        .join(ctx.table("regions"), ["region"], ["name"])
        .select(col("id"), col("manager"))
        .sort(col("id").sort())
        .collect()
    )
    assert out.num_rows == 10
    assert out.column("manager").to_pylist()[:4] == ["alice", "bob", "alice", "carol"]


def test_join_left_outer(ctx):
    left = pa.table({"k": [1, 2, 3], "v": ["a", "b", "c"]})
    right = pa.table({"k2": [2, 3, 4], "w": [20, 30, 40]})
    from ballista_tpu.engine import ExecutionContext

    c = ExecutionContext()
    c.register_record_batches("l", left)
    c.register_record_batches("r", right)
    out = (
        c.table("l")
        .join(c.table("r"), ["k"], ["k2"], how="left")
        .sort(col("k").sort())
        .collect()
    )
    assert out.num_rows == 3
    assert out.column("w").to_pylist() == [None, 20, 30]


def test_repartition_roundtrip(ctx, sales_table):
    _register(ctx, sales_table)
    out = (
        ctx.table("sales")
        .repartition(4, col("region"))
        .aggregate([col("region")], [F.sum(col("amount")).alias("t")])
        .sort(col("region").sort())
        .collect()
    )
    assert out.column("t").to_pylist() == [120.0, 40.0, 145.0]


def test_distinct(ctx, sales_table):
    _register(ctx, sales_table, 2)
    out = ctx.table("sales").select(col("region")).distinct().sort(col("region").sort()).collect()
    assert out.column("region").to_pylist() == ["east", "north", "west"]


def test_union(ctx, sales_table):
    _register(ctx, sales_table)
    a = ctx.table("sales").select(col("id")).filter(col("id") < lit(3))
    b = ctx.table("sales").select(col("id")).filter(col("id") >= lit(8))
    out = a.union(b).sort(col("id").sort()).collect()
    assert out.column("id").to_pylist() == [0, 1, 2, 8, 9]


def test_case_expr(ctx, sales_table):
    _register(ctx, sales_table)
    from ballista_tpu.logical.expr import Case

    e = Case(
        None,
        [(col("amount") > lit(30.0), lit("big"))],
        lit("small"),
    ).alias("size")
    out = ctx.table("sales").select(col("id"), e).sort(col("id").sort()).collect()
    assert out.column("size").to_pylist()[:4] == ["small", "small", "small", "small"]
    assert out.column("size").to_pylist()[7] == "big"


def test_projection_pushdown_narrows_scan(ctx, sales_table):
    _register(ctx, sales_table)
    df = ctx.table("sales").select(col("id"))
    plan = ctx.optimize(df.logical_plan())
    scan = plan
    while scan.children():
        scan = scan.children()[0]
    assert scan.projection == [0]


def test_explain(ctx, sales_table):
    _register(ctx, sales_table)
    text = ctx.table("sales").select(col("id")).explain()
    assert "Logical Plan" in text and "ProjectionExec" in text


def test_left_join_multi_partition_no_merge():
    """LEFT/FULL joins with multi-partition inputs run co-partitioned (both
    sides hash-repartitioned on the join keys) instead of collapsing the
    probe side through MergeExec — outer rows stay correct because every
    key lands in exactly one partition."""
    import numpy as np

    from ballista_tpu.engine import ExecutionContext
    from ballista_tpu.physical.basic import MergeExec
    from ballista_tpu.physical.join import HashJoinExec

    rng = np.random.default_rng(21)
    n = 5000
    left = pa.table(
        {
            "k": pa.array(rng.integers(0, 800, n), type=pa.int64()),
            "v": pa.array(rng.uniform(0, 10, n)),
        }
    )
    right = pa.table(
        {
            "k2": pa.array(np.arange(0, 1200, 2), type=pa.int64()),  # evens
            "w": pa.array(np.arange(600) * 1.5),
        }
    )
    c = ExecutionContext()
    c.register_record_batches("l", left, n_partitions=4)
    c.register_record_batches("r", right, n_partitions=3)
    df = c.table("l").join(c.table("r"), ["k"], ["k2"], how="left")
    phys = c.create_physical_plan(df.logical_plan())

    def nodes(p):
        yield p
        for ch in p.children():
            yield from nodes(ch)

    join = next(x for x in nodes(phys) if isinstance(x, HashJoinExec))
    assert join.partitioned
    assert join.output_partitioning().partition_count() > 1
    assert not any(isinstance(x, MergeExec) for x in nodes(join))

    out = df.collect()
    import pandas as pd

    oracle = left.to_pandas().merge(
        right.to_pandas(), left_on="k", right_on="k2", how="left"
    )
    assert out.num_rows == len(oracle)
    got_w = sorted((x if x is not None else -1.0) for x in out.column("w").to_pylist())
    exp_w = sorted(oracle["w"].fillna(-1.0).tolist())
    assert got_w == exp_w
    # unmatched rows (odd keys) survive exactly once
    assert got_w.count(-1.0) == int(oracle["w"].isna().sum()) > 0


def test_full_join_multi_partition():
    """FULL join: unmatched rows from BOTH sides survive co-partitioning."""
    import numpy as np

    from ballista_tpu.engine import ExecutionContext

    left = pa.table({"k": [1, 2, 3, 5, 7], "v": ["a", "b", "c", "e", "g"]})
    right = pa.table({"k2": [2, 3, 4, 6], "w": [20, 30, 40, 60]})
    c = ExecutionContext()
    c.register_record_batches("l", left, n_partitions=3)
    c.register_record_batches("r", right, n_partitions=2)
    out = (
        c.table("l")
        .join(c.table("r"), ["k"], ["k2"], how="full")
        .collect()
    )
    # 2,3 match; 1,5,7 left-only; 4,6 right-only
    assert out.num_rows == 7
    ks = out.column("k").to_pylist()
    assert sorted(k for k in ks if k is not None) == [1, 2, 3, 5, 7]
    ws = out.column("w").to_pylist()
    assert sorted(w for w in ws if w is not None) == [20, 30, 40, 60]


def test_outer_join_expression_keys_rejected_at_planning():
    """r3 Weak #7: outer joins whose inputs cannot be hash-co-partitioned
    (expression keys, residual conditions) never reach execution — the SQL
    front end rejects them with a clear error, so the PlanError fallback in
    physical/join.py is purely defensive."""
    import pyarrow as pa
    import pytest as _pytest

    from ballista_tpu.engine import ExecutionContext
    from ballista_tpu.errors import SqlError

    c = ExecutionContext()
    c.register_record_batches(
        "l", pa.table({"a": [1, 2], "x": [1.0, 2.0]}), n_partitions=2
    )
    c.register_record_batches(
        "r", pa.table({"b": [2, 3], "y": [9.0, 8.0]}), n_partitions=2
    )
    with _pytest.raises(SqlError, match="unsupported ON condition"):
        c.sql("select * from l left join r on a + 1 = b").collect()
    with _pytest.raises(SqlError, match="unsupported ON condition"):
        c.sql("select * from l full join r on a = b and x > y").collect()

"""Logical expression and plan tests."""

import pyarrow as pa
import pytest

from ballista_tpu.logical import (
    Aggregate,
    Column,
    Filter,
    Projection,
    TableScan,
    col,
    functions as F,
    lit,
)
from ballista_tpu.datasource import MemoryTableSource
from ballista_tpu.errors import SchemaError


SCHEMA = pa.schema(
    [
        pa.field("a", pa.int64()),
        pa.field("b", pa.float64()),
        pa.field("c", pa.string()),
    ]
)


def _scan():
    src = MemoryTableSource(SCHEMA, [[]])
    return TableScan("t", src)


def test_column_type_resolution():
    assert col("a").data_type(SCHEMA) == pa.int64()
    assert col("b").data_type(SCHEMA) == pa.float64()
    with pytest.raises(SchemaError):
        col("nope").data_type(SCHEMA)


def test_binary_expr_types():
    e = col("a") + col("b")
    assert e.data_type(SCHEMA) == pa.float64()
    cmp = col("a") > lit(5)
    assert cmp.data_type(SCHEMA) == pa.bool_()
    assert str(cmp) == "(#a > 5)"


def test_aggregate_types():
    assert F.sum(col("a")).data_type(SCHEMA) == pa.int64()
    assert F.sum(col("b")).data_type(SCHEMA) == pa.float64()
    assert F.avg(col("a")).data_type(SCHEMA) == pa.float64()
    assert F.count(col("c")).data_type(SCHEMA) == pa.int64()


def test_alias_output_name():
    e = (col("a") * lit(2)).alias("doubled")
    assert e.output_name() == "doubled"
    assert e.data_type(SCHEMA) == pa.int64()


def test_plan_schemas():
    scan = _scan()
    proj = Projection(scan, [col("a"), (col("b") * lit(2.0)).alias("b2")])
    assert proj.schema().names == ["a", "b2"]
    filt = Filter(proj, col("a") > lit(1))
    assert filt.schema().names == ["a", "b2"]
    agg = Aggregate(scan, [col("c")], [F.sum(col("a")).alias("total")])
    assert agg.schema().names == ["c", "total"]
    assert agg.schema().field("total").type == pa.int64()


def test_qualified_column_resolution():
    schema = pa.schema([pa.field("t.a", pa.int64()), pa.field("u.a", pa.int32())])
    assert Column("a", "t").data_type(schema) == pa.int64()
    assert Column("a", "u").data_type(schema) == pa.int32()
    with pytest.raises(SchemaError):
        Column("a").data_type(schema)  # ambiguous

"""Randomized crash-recovery property test (ISSUE 18 satellite).

A seeded workload (jobs, tenants, assignments, completions, a speculative
mint, a result-cache publish) drives a SchedulerState; the process is
"killed" at a seeded accepted-status point by abandoning the instance,
and a FRESH SchedulerState recovers over the same store. Every attribute
the durability analyzer classifies `derived(<rebuild-fn>)` in
dev/analysis/durability.toml must rebuild EQUAL to the never-crashed
control's incrementally-maintained copy — the runtime half of the static
recover()-reachability check. The comparator table is asserted to cover
exactly the manifest's derived set, so classifying a new attribute
derived without extending this test fails loudly."""

import pathlib
import random

import pyarrow as pa
import pytest

try:  # py3.11+
    import tomllib as _toml
except ImportError:  # pragma: no cover - py3.10 fallback
    import tomli as _toml  # type: ignore

from ballista_tpu.proto import ballista_pb2 as pb
from ballista_tpu.scheduler.kv import MemoryBackend
from ballista_tpu.scheduler.state import SchedulerState

REPO = pathlib.Path(__file__).resolve().parent.parent
MANIFEST = REPO / "dev" / "analysis" / "durability.toml"

SEEDS = range(6)


# -- seeded workload ---------------------------------------------------------

def _running_job(s, job):
    running = pb.JobStatus()
    running.running.SetInParent()
    s.save_job_metadata(job, running)


def _pending(job, stage, part):
    t = pb.TaskStatus()
    t.partition_id.job_id = job
    t.partition_id.stage_id = stage
    t.partition_id.partition_id = part
    return t


def _stage_plan(s, job, stage=1):
    from ballista_tpu.physical.basic import EmptyExec

    s.save_stage_plan(job, stage, EmptyExec(True, pa.schema([("a", pa.int64())])))


def _drive(s, seed):
    """Apply the seeded operation sequence up to its crash point (a seeded
    accepted-status count); returns the job ids. Deterministic given the
    seed — the control and nothing else defines the expected state."""
    rng = random.Random(seed)
    jobs = [f"j{i}" for i in range(rng.randint(2, 3))]
    for i, job in enumerate(jobs):
        _running_job(s, job)
        s.save_job_tenant(job, f"tenant{i % 2}", rng.randint(0, 3))
        _stage_plan(s, job)
        for p in range(3):
            s.save_task_status(_pending(job, 1, p))
    for e in ("e1", "e2"):
        s.save_executor_metadata(pb.ExecutorMetadata(id=e, host="h", port=1))
    running = []
    accepted = 0
    crash_at = rng.randint(2, 5)  # the seeded accepted-status crash point
    minted_spec = cached = False
    for _ in range(200):
        if accepted >= crash_at:
            break
        roll = rng.random()
        if roll < 0.5 or not running:
            ex = rng.choice(("e1", "e2"))
            got = s.assign_next_schedulable_task(ex)
            if got is None:
                if not running:
                    break
                continue
            status, _meta = got
            pid = status.partition_id
            key = (pid.job_id, pid.stage_id, pid.partition_id)
            running.append((key, ex, status.attempt))
        elif roll < 0.8:
            key, ex, attempt = running.pop(rng.randrange(len(running)))
            done = pb.TaskStatus()
            done.partition_id.job_id = key[0]
            done.partition_id.stage_id = key[1]
            done.partition_id.partition_id = key[2]
            done.attempt = attempt
            done.completed.executor_id = ex
            done.completed.path = f"/out/{key[0]}/{key[1]}/{key[2]}"
            if s.accept_task_status(done):
                accepted += 1
        elif not minted_spec:
            # mint a speculative duplicate the way maybe_speculate does:
            # launch accounting + the durable spec-ledger write-through
            key, ex, attempt = rng.choice(running)
            other = "e2" if ex == "e1" else "e1"
            s._spec_launches[key] = s._spec_launches.get(key, 0) + 1
            s._spec_put(key, other, attempt + 1)
            minted_spec = True
        elif not cached:
            done_job = pb.JobStatus()
            done_job.completed.SetInParent()
            s.result_cache_put(f"fp{rng.randrange(10)}", done_job.completed)
            cached = True
    return jobs


# -- comparators: one per analyzer-classified derived attribute --------------

def _index_view(idx):
    return {
        "pending": idx.pending,
        "incomplete": idx.incomplete,
        "total": idx.total,
        "running": idx.running,
    }


COMPARATORS = {
    "_task_index": lambda ctl, rec, jobs: (
        _index_view(ctl._ensure_task_index()) == _index_view(rec._task_index)
    ),
    # a timestamp can't equal across processes; rebuilt means re-seeded
    "_task_index_seeded_at": lambda ctl, rec, jobs: (
        rec._task_index_seeded_at > 0
    ),
    "_tenant_cache": lambda ctl, rec, jobs: all(
        rec._tenant_cache.get(j) == ctl._job_tenant_full(j) for j in jobs
    ),
    "_rc_count": lambda ctl, rec, jobs: (
        rec._rc_count == ctl._ensure_rc_count()
    ),
    "_spec_launches": lambda ctl, rec, jobs: (
        rec._spec_launches == ctl._spec_launches
    ),
    # ISSUE 20 generation-stamped read-throughs: recovery must leave the
    # replica tracking the SAME durable epoch the control sees, so the
    # next peer mutation (an epoch bump) re-derives the cached view
    "_plan_epoch_seen": lambda ctl, rec, jobs: (
        ctl._ensure_task_index() is not None
        and rec._plan_epoch_seen == ctl._plan_epoch_seen
    ),
    "_rc_epoch_seen": lambda ctl, rec, jobs: (
        ctl._ensure_rc_count() is not None
        and rec._rc_epoch_seen == ctl._rc_epoch_seen
    ),
}


def _manifest_derived():
    with open(MANIFEST, "rb") as f:
        man = _toml.load(f)
    return {
        key.rsplit(".", 1)[1]
        for key, row in man.get("attrs", {}).items()
        if key.startswith("scheduler.state.SchedulerState.")
        and row.startswith("derived(")
    }


def test_comparators_cover_every_derived_attr():
    """The comparator table and the manifest's derived classification must
    stay in lockstep: a new derived attribute needs a runtime rebuild
    check here, a dropped one needs its comparator retired."""
    assert set(COMPARATORS) == _manifest_derived()


@pytest.mark.parametrize("seed", SEEDS)
def test_derived_state_rebuilds_equal_to_never_crashed_control(seed):
    kv = MemoryBackend()
    control = SchedulerState(kv, "t")
    jobs = _drive(control, seed)
    # crash: the control instance is abandoned mid-flight; a fresh replica
    # recovers from the same store
    replica = SchedulerState(kv, "t")
    stats = replica.recover()
    assert stats.get("scheduler_restart") == 1, stats
    failed = [
        name for name in sorted(COMPARATORS)
        if not COMPARATORS[name](control, replica, jobs)
    ]
    assert failed == [], (
        f"derived attribute(s) did not rebuild to the control state: {failed}"
    )

"""HBM residency accounting: LRU eviction replaces first-come streaming.

When the budget fills, the least-recently-touched pins of OTHER stages are
evicted (their stages re-prepare on next touch); an entry that cannot fit
even after eviction streams. First-come residency would have made every
query after the budget filled stream per iteration — fatal for the SF=100
suite where one stage's lineitem residency is most of the chip.
"""

import numpy as np
import pytest

from ballista_tpu.ops import runtime


class _FakeStage:
    def __init__(self):
        self._device_cache = {}


@pytest.fixture(autouse=True)
def _clean_residency():
    runtime.reset_residency()
    yield
    runtime.reset_residency()


def test_lru_evicts_oldest_other_stage():
    a, b, c = _FakeStage(), _FakeStage(), _FakeStage()
    budget = 100
    assert runtime.reserve_and_pin(a, 0, {"x": 1}, a._device_cache, 40, budget)
    assert runtime.reserve_and_pin(b, 0, {"x": 2}, b._device_cache, 40, budget)
    runtime.touch_residency(a, 0)  # a is now more recent than b
    # c needs 40: evicting b (oldest) suffices; a must survive
    assert runtime.reserve_and_pin(c, 0, {"x": 3}, c._device_cache, 40, budget)
    assert 0 in a._device_cache
    assert 0 not in b._device_cache, "LRU victim must be dropped"
    assert 0 in c._device_cache
    assert runtime.resident_bytes() == 80


def test_own_partitions_never_victims():
    a = _FakeStage()
    budget = 100
    assert runtime.reserve_and_pin(a, 0, {"x": 1}, a._device_cache, 60, budget)
    # a second partition of the SAME stage must not evict the first; it
    # simply fails to pin (streams per query)
    assert not runtime.reserve_and_pin(a, 1, {"x": 2}, a._device_cache, 60, budget)
    assert 0 in a._device_cache and 1 not in a._device_cache
    assert runtime.resident_bytes() == 60


def test_oversized_entry_streams_without_evicting():
    a, b = _FakeStage(), _FakeStage()
    budget = 100
    assert runtime.reserve_and_pin(a, 0, {"x": 1}, a._device_cache, 50, budget)
    # b can NEVER fit: it must stream without disturbing a's pin (an
    # eviction sweep here would repeat on every one of b's queries)
    assert not runtime.reserve_and_pin(b, 0, {"x": 2}, b._device_cache, 150, budget)
    assert runtime.resident_bytes() == 50
    assert 0 in a._device_cache


def test_huge_victim_not_evicted_for_small_need():
    """Evicting a pin much larger than the request costs more re-upload
    than the newcomer streaming ever would (A/B alternation thrash)."""
    a, b = _FakeStage(), _FakeStage()
    budget = 100
    assert runtime.reserve_and_pin(a, 0, {"x": 1}, a._device_cache, 95, budget)
    # b needs 10; the only victim holds 95 > 4x10 — b streams, a survives
    assert not runtime.reserve_and_pin(b, 0, {"x": 2}, b._device_cache, 10, budget)
    assert 0 in a._device_cache
    assert runtime.resident_bytes() == 95


def test_multi_victim_eviction_plan():
    a, b, c = _FakeStage(), _FakeStage(), _FakeStage()
    budget = 100
    assert runtime.reserve_and_pin(a, 0, {"x": 1}, a._device_cache, 30, budget)
    assert runtime.reserve_and_pin(b, 0, {"x": 2}, b._device_cache, 30, budget)
    # c needs 80: both victims (60 total <= 4x80) go, oldest first
    assert runtime.reserve_and_pin(c, 0, {"x": 3}, c._device_cache, 80, budget)
    assert 0 not in a._device_cache and 0 not in b._device_cache
    assert runtime.resident_bytes() == 80


def test_release_stage_clears_lru_bookkeeping():
    a = _FakeStage()
    assert runtime.reserve_and_pin(a, 0, {"x": 1}, a._device_cache, 10, 100)
    runtime.release_stage_residency(a)
    assert runtime.resident_bytes() == 0
    assert not runtime._pinned and not runtime._last_used
    # retired stages refuse new pins
    assert not runtime.reserve_and_pin(a, 0, {"x": 1}, a._device_cache, 10, 100)


def test_stage_past_budget_declines_to_host(tmp_path):
    """A stage whose tiles cannot fit the HBM budget must decline BEFORE
    device allocation (host fallback), not OOM the chip — and results stay
    correct via the host path."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from ballista_tpu.config import BallistaConfig
    from ballista_tpu.engine import ExecutionContext

    rng = np.random.default_rng(2)
    n = 60_000
    t = pa.table(
        {
            "k": pa.array(rng.choice(["x", "y", "z"], n)),
            "v": pa.array(rng.uniform(0, 1e6, n)),  # high-card: stays f32
        }
    )
    pq.write_table(t, tmp_path / "t.parquet")
    results = {}
    for budget in ("32", str(1 << 30)):  # 32 bytes: nothing fits
        ctx = ExecutionContext(
            BallistaConfig(
                {
                    "ballista.executor.backend": "tpu",
                    "ballista.tpu.hbm_budget_bytes": budget,
                }
            )
        )
        ctx.register_parquet("t", str(tmp_path))
        out = ctx.sql(
            "select k, sum(v) as s, count(*) as c from t group by k order by k"
        ).collect()
        results[budget] = out.to_pydict()
    assert results["32"]["k"] == results[str(1 << 30)]["k"]
    assert results["32"]["c"] == results[str(1 << 30)]["c"]
    np.testing.assert_allclose(
        results["32"]["s"], results[str(1 << 30)]["s"], rtol=1e-5
    )


def test_eviction_preserves_running_consumers():
    """An evicted entry's arrays stay alive for a thread already holding
    them (Python references) — eviction only drops the cache slot."""
    a, b = _FakeStage(), _FakeStage()
    arr = np.arange(8)
    assert runtime.reserve_and_pin(a, 0, {"arr": arr}, a._device_cache, 60, 100)
    held = a._device_cache[0]["arr"]  # a task thread's reference
    assert runtime.reserve_and_pin(b, 0, {"x": 1}, b._device_cache, 60, 100)
    assert 0 not in a._device_cache
    np.testing.assert_array_equal(held, np.arange(8))


def test_two_real_stages_under_pressure_reach_steady_state(tmp_path):
    """VERDICT r3 #7: two real parquet-backed sorted stages alternating
    under a budget that fits either but not both. The thrash guards must
    converge: after one thrash cycle the cooldown pins a survivor and the
    other stage streams — NOT the A,B,A,B full re-prepare ping-pong plain
    LRU would give. Prepares (each one h2d upload on this path) are counted
    per stage; results must stay correct throughout."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from ballista_tpu.config import BallistaConfig
    from ballista_tpu.engine import ExecutionContext
    from ballista_tpu.ops import kernels
    from ballista_tpu.ops.stage import FusedAggregateStage

    rng = np.random.default_rng(11)
    n, g = 120_000, 2500  # >1024 groups: the sorted (one-upload) path
    for name, seed in (("ta", 1), ("tb", 2)):
        r = np.random.default_rng(seed)
        pq.write_table(
            pa.table(
                {
                    "k": pa.array(r.integers(0, g, n), type=pa.int64()),
                    "v": pa.array(r.uniform(-10, 10, n)),
                }
            ),
            str(tmp_path / f"{name}.parquet"),
        )

    def make_ctx(budget):
        ctx = ExecutionContext(
            BallistaConfig(
                {
                    "ballista.executor.backend": "tpu",
                    "ballista.tpu.hbm_budget_bytes": str(budget),
                }
            )
        )
        for name in ("ta", "tb"):
            ctx.register_parquet(name, str(tmp_path / f"{name}.parquet"))
        return ctx

    def q(t):
        return f"select k, sum(v) as s from {t} group by k order by k"

    # size the stages with an unconstrained run
    kernels._stage_cache.clear()
    runtime.reset_residency()
    big = make_ctx(1 << 30)
    oracle = {t: big.sql(q(t)).collect() for t in ("ta", "tb")}
    per_stage = runtime.resident_bytes() / 2
    assert per_stage > 0
    budget = int(per_stage * 1.25)  # fits either stage, not both

    kernels._stage_cache.clear()
    runtime.reset_residency()

    prepares = {}
    orig = FusedAggregateStage._prepare_partition_sorted

    def counting(self, partition, ctx):
        prepares[id(self)] = prepares.get(id(self), 0) + 1
        return orig(self, partition, ctx)

    FusedAggregateStage._prepare_partition_sorted = counting
    try:
        ctx = make_ctx(budget)
        history = []
        for cycle in range(4):
            for t in ("ta", "tb"):
                out = ctx.sql(q(t)).collect()
                assert out.equals(oracle[t]), f"cycle {cycle} {t} wrong"
            history.append(dict(prepares))
    finally:
        FusedAggregateStage._prepare_partition_sorted = orig

    assert runtime.resident_bytes() <= budget
    # steady state by cycle 3: exactly one stage re-prepares per cycle (the
    # streamer), the survivor stays pinned with zero further prepares
    deltas = []
    for c in (2, 3):
        d = {
            sid: history[c][sid] - history[c - 1][sid]
            for sid in history[c]
        }
        deltas.append(sorted(d.values()))
    assert deltas == [[0, 1], [0, 1]], (
        f"expected survivor+streamer steady state, got per-cycle prepare "
        f"deltas {deltas} (history {history})"
    )
    # and the ping-pong phase was bounded: no stage prepared more than twice
    # before steady state plus once per later cycle
    assert max(history[-1].values()) <= 2 + 2

"""JAX backend tests: fused aggregate stage vs host Arrow oracle.

Run on CPU jax (conftest forces JAX_PLATFORMS=cpu); semantics are identical
on TPU, modulo float32 accumulation order.
"""

import numpy as np
import pathlib

import pyarrow as pa
import pytest

from ballista_tpu.config import BallistaConfig
from ballista_tpu.engine import ExecutionContext


def make_ctx(backend: str) -> ExecutionContext:
    return ExecutionContext(BallistaConfig({"ballista.executor.backend": backend}))


@pytest.fixture(scope="module")
def tpch_dir(tmp_path_factory):
    from benchmarks.tpch.datagen import generate

    d = tmp_path_factory.mktemp("tpch_tpu")
    generate(str(d), sf=0.002, parts=2)
    return str(d)


def both(sql: str, tpch_dir):
    from benchmarks.tpch.datagen import register_all

    out = {}
    for backend in ("cpu", "tpu"):
        ctx = make_ctx(backend)
        register_all(ctx, tpch_dir)
        out[backend] = ctx.sql(sql).collect().to_pandas()
    return out["cpu"], out["tpu"]


def assert_close(cpu, tpu, rtol=2e-5):
    assert len(cpu) == len(tpu)
    assert list(cpu.columns) == list(tpu.columns)
    for c in cpu.columns:
        g, w = tpu[c].to_numpy(), cpu[c].to_numpy()
        if np.issubdtype(w.dtype, np.floating):
            np.testing.assert_allclose(
                g.astype(float), w.astype(float), rtol=rtol, err_msg=c
            )
        else:
            assert list(g) == list(w), c


def test_q6_scalar_agg(tpch_dir):
    sql = pathlib.Path("benchmarks/tpch/queries/q6.sql").read_text()
    cpu, tpu = both(sql, tpch_dir)
    assert_close(cpu, tpu)


def test_q1_group_agg(tpch_dir):
    sql = pathlib.Path("benchmarks/tpch/queries/q1.sql").read_text()
    cpu, tpu = both(sql, tpch_dir)
    assert_close(cpu, tpu)


def test_q12_in_list_and_case(tpch_dir):
    sql = pathlib.Path("benchmarks/tpch/queries/q12.sql").read_text()
    cpu, tpu = both(sql, tpch_dir)
    assert_close(cpu, tpu)


def test_count_min_max_avg(tpch_dir):
    sql = """
        select l_returnflag,
               count(*) as n,
               min(l_quantity) as mn,
               max(l_quantity) as mx,
               avg(l_extendedprice) as av
        from lineitem
        where l_shipdate > date '1995-01-01'
        group by l_returnflag
        order by l_returnflag
    """
    cpu, tpu = both(sql, tpch_dir)
    assert_close(cpu, tpu)


def test_like_predicate_on_device(tpch_dir):
    sql = """
        select count(*) as n
        from part
        where p_type like '%BRASS'
    """
    cpu, tpu = both(sql, tpch_dir)
    assert_close(cpu, tpu)


def test_extract_year_on_device(tpch_dir):
    sql = """
        select extract(year from o_orderdate) as y, count(*) as n
        from orders
        group by extract(year from o_orderdate)
        order by y
    """
    cpu, tpu = both(sql, tpch_dir)
    assert_close(cpu, tpu)


def test_unfusable_falls_back(tpch_dir):
    # join under the aggregate: not fusable -> host path, results still correct
    sql = """
        select n_name, count(*) as cnt
        from supplier, nation
        where s_nationkey = n_nationkey
        group by n_name
        order by cnt desc, n_name
    """
    cpu, tpu = both(sql, tpch_dir)
    assert_close(cpu, tpu)


def test_civil_from_days():
    import datetime
    import jax.numpy as jnp

    from ballista_tpu.ops.jaxexpr import _civil_from_days

    dates = [
        datetime.date(1970, 1, 1),
        datetime.date(1992, 2, 29),
        datetime.date(1998, 12, 31),
        datetime.date(2000, 3, 1),
        datetime.date(1969, 12, 31),
    ]
    days = jnp.asarray(
        [(d - datetime.date(1970, 1, 1)).days for d in dates], dtype=jnp.int32
    )
    y, m, dd = _civil_from_days(days)
    assert list(np.asarray(y)) == [d.year for d in dates]
    assert list(np.asarray(m)) == [d.month for d in dates]
    assert list(np.asarray(dd)) == [d.day for d in dates]


def test_taxi_high_cardinality_groupby(tmp_path_factory):
    """BASELINE config #4: heavy-tailed 265-zone group-by (medium-G device
    path) matches the host backend."""
    from benchmarks.taxi.datagen import TRIP_AGG_QUERY, generate

    d = str(tmp_path_factory.mktemp("taxi"))
    generate(d, sf=0.01, parts=2)
    out = {}
    for backend in ("cpu", "tpu"):
        ctx = make_ctx(backend)
        ctx.register_parquet("trips", f"{d}/trips")
        out[backend] = ctx.sql(TRIP_AGG_QUERY).collect().to_pandas()
    assert_close(out["cpu"], out["tpu"], rtol=1e-5)


def test_hbm_budget_streams_beyond_cap(tpch_dir):
    """Partitions past the residency budget stream per query instead of
    pinning; results are identical either way (SF=100's path on a 16GB
    chip). The budget is global across stages."""
    from ballista_tpu.ops import kernels, runtime
    from benchmarks.tpch.datagen import register_all

    sql = (
        "select l_returnflag, sum(l_quantity) as sq, count(*) as n "
        "from lineitem group by l_returnflag order by l_returnflag"
    )

    def run_with_budget(budget):
        kernels._stage_cache.clear()
        runtime.reset_residency()
        ctx = ExecutionContext(
            BallistaConfig(
                {
                    "ballista.executor.backend": "tpu",
                    "ballista.tpu.hbm_budget_bytes": str(budget),
                }
            )
        )
        register_all(ctx, tpch_dir)
        out = ctx.sql(sql).collect()
        from ballista_tpu.ops.stage import FusedAggregateStage

        stages = [
            s for s in kernels._stage_cache.values()
            if isinstance(s, FusedAggregateStage)
        ]
        cached = sum(len(s._device_cache) for s in stages)
        return out, cached, runtime.resident_bytes()

    full, cached_full, rb_full = run_with_budget(12 << 30)
    tiny, cached_tiny, rb_tiny = run_with_budget(1)
    assert cached_full > 0 and rb_full > 0  # default: partitions pinned
    assert cached_tiny == 0 and rb_tiny == 0  # budget 1 byte: all stream
    assert full.to_pylist() == tiny.to_pylist()
    runtime.reset_residency()


def test_coalesced_aggregate_single_stage(tpch_dir):
    """Multi-partition input + tpu backend plans SINGLE over Merge (one
    device dispatch + one readback instead of per-partition Partials), with
    identical results; cpu backend keeps the Partial/Final split."""
    from ballista_tpu.physical.aggregate import AggregateMode, HashAggregateExec
    from benchmarks.tpch.datagen import register_all

    sql = "select l_returnflag, sum(l_quantity) as s from lineitem group by l_returnflag"

    def agg_modes(plan):
        out = []
        def walk(n):
            if isinstance(n, HashAggregateExec):
                out.append(n.mode)
            for c in n.children():
                walk(c)
        walk(plan)
        return out

    ctx_tpu = make_ctx("tpu")
    register_all(ctx_tpu, tpch_dir)
    df = ctx_tpu.sql(sql)
    phys = ctx_tpu.create_physical_plan(df.logical_plan())
    assert agg_modes(phys) == [AggregateMode.SINGLE]

    ctx_cpu = make_ctx("cpu")
    register_all(ctx_cpu, tpch_dir)
    df_c = ctx_cpu.sql(sql)
    phys_c = ctx_cpu.create_physical_plan(df_c.logical_plan())
    assert AggregateMode.PARTIAL in agg_modes(phys_c)

    cpu, tpu = both(sql, tpch_dir)
    assert_close(cpu.sort_values("l_returnflag").reset_index(drop=True),
                 tpu.sort_values("l_returnflag").reset_index(drop=True))


def test_coalesced_factagg_topk(tpch_dir):
    """q3-shaped aggregate-over-join with ORDER BY sum LIMIT: the coalesced
    single-partition plan re-enables the device top-k readback pushdown
    over multi-partition fact files, and results match the host path.
    Asserts the device fact-agg stage with top-k actually RAN (a silent
    host fallback would also produce matching results)."""
    from ballista_tpu.ops import kernels, runtime
    from ballista_tpu.ops.factagg import FactAggregateStage

    kernels._stage_cache.clear()
    kernels._stage_cache_pins.clear()
    kernels._stage_latest.clear()
    runtime.reset_residency()
    sql = pathlib.Path("benchmarks/tpch/queries/q3.sql").read_text()
    cpu, tpu = both(sql, tpch_dir)
    assert_close(cpu, tpu)
    ran = [
        s for s in kernels._stage_cache.values()
        if isinstance(s, FactAggregateStage) and s._prepared
    ]
    assert ran, "device fact-agg stage did not run (silent host fallback)"
    assert any(s.topk is not None and s.inner.scan_stride == 1 for s in ran)


def test_concurrent_partition_runs_share_stage_safely(tpch_dir):
    """Executor task threads run different partitions of one cached stage
    concurrently; prepare (growing dictionaries, compiled-step slots) is
    serialized per stage, so concurrent runs must produce exactly the
    sequential results."""
    import threading

    from ballista_tpu.config import BallistaConfig
    from ballista_tpu.engine import ExecutionContext
    from ballista_tpu.physical.aggregate import AggregateMode, HashAggregateExec
    from ballista_tpu.physical.plan import TaskContext, collect_partition
    from benchmarks.tpch.datagen import register_all

    # keep the Partial/Final split so several driven partitions exist
    cfg = BallistaConfig({
        "ballista.executor.backend": "tpu",
        "ballista.tpu.coalesce_aggregates": "false",
    })
    ctx = ExecutionContext(cfg)
    register_all(ctx, tpch_dir)
    df = ctx.sql(
        "select l_returnflag, sum(l_quantity) as s, count(*) as c "
        "from lineitem group by l_returnflag"
    )
    phys = ctx.create_physical_plan(df.logical_plan())

    def find_partial(n):
        if isinstance(n, HashAggregateExec) and n.mode == AggregateMode.PARTIAL:
            return n
        for ch in n.children():
            r = find_partial(ch)
            if r is not None:
                return r
        return None

    partial = find_partial(phys)
    assert partial is not None
    nparts = partial.output_partitioning().partition_count()
    assert nparts >= 2
    tctx = TaskContext(config=cfg)
    sequential = [collect_partition(partial, p, tctx) for p in range(nparts)]

    from ballista_tpu.ops import kernels, runtime

    kernels._stage_cache.clear()
    kernels._stage_cache_pins.clear()
    kernels._stage_latest.clear()
    runtime.reset_residency()
    results = [None] * nparts
    errors = []

    def work(p):
        try:
            results[p] = collect_partition(partial, p, tctx)
        except Exception as e:  # noqa: BLE001
            errors.append((p, e))

    threads = [threading.Thread(target=work, args=(p,)) for p in range(nparts)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    for p in range(nparts):
        a = sequential[p].to_pandas().sort_values("l_returnflag").reset_index(drop=True)
        b = results[p].to_pandas().sort_values("l_returnflag").reset_index(drop=True)
        assert (a == b).all().all(), p


def test_global_count_over_empty_input(tpch_dir):
    """COUNT is never NULL: a global aggregate whose input has no rows
    finalizes to 0 on both backends (the NOT IN null-guard relies on it —
    a NULL count made q16 return zero rows on the tpu backend)."""
    for sql, col, want in [
        ("select count(*) as c from supplier where s_suppkey is null", "c", 0),
        ("select count(*) as c from supplier where s_suppkey < 0", "c", 0),
        ("select sum(s_acctbal) as s from supplier where s_suppkey < 0", "s", None),
    ]:
        cpu, tpu = both(sql, tpch_dir)
        for name, df in (("cpu", cpu), ("tpu", tpu)):
            assert len(df) == 1, (name, sql)
            got = df[col][0]
            if want is None:
                assert got is None or (isinstance(got, float) and np.isnan(got)), (name, sql, got)
            else:
                assert got == want, (name, sql, got)


def test_null_string_predicates_device(tmp_path):
    """Dictionary-encoded string columns carry nulls as -1 codes on device.
    IS [NOT] NULL tests the code; =, <>, LIKE, NOT LIKE, IN, NOT IN follow
    three-valued logic (NULL rows excluded, even under negation — a -1
    gather would otherwise wrap to the table's last entry). Asserts the
    device stage actually ran."""
    import pyarrow.parquet as pq

    from ballista_tpu.ops import kernels, runtime
    from ballista_tpu.ops.stage import FusedAggregateStage

    t = pa.table({
        "k": pa.array(["a", None, "b", None, "a", "c"]),
        "v": pa.array([1.0, 2.0, 3.0, 4.0, 5.0, 6.0]),
    })
    (tmp_path / "t").mkdir()
    pq.write_table(t, str(tmp_path / "t" / "p0.parquet"))
    cases = [
        ("k is null", 2),
        ("k is not null", 4),
        ("k = 'a'", 2),
        ("k <> 'a'", 2),       # NULL rows excluded
        ("k like 'a%'", 2),
        ("k not like 'a%'", 2),  # NULL rows excluded
        ("k in ('a', 'c')", 3),
        ("k not in ('a', 'c')", 1),  # only 'b'; NULL rows excluded
        ("not (k = 'a')", 2),   # Kleene NOT: NULL stays NULL -> excluded
        ("not (k <> 'a' or k = 'c')", 2),  # NOT over Kleene OR
        ("coalesce(k, 'x') = 'x'", 2),  # NULL coalesces to 'x' -> matches
        ("coalesce(k, 'a') <> 'a'", 2),  # b, c
    ]
    kernels._stage_cache.clear()
    kernels._stage_cache_pins.clear()
    kernels._stage_latest.clear()
    runtime.reset_residency()
    for backend in ("cpu", "tpu"):
        ctx = make_ctx(backend)
        ctx.register_parquet("t", str(tmp_path / "t"))
        for pred, want in cases:
            out = ctx.sql(f"select count(*) as c from t where {pred}").collect()
            assert out.column("c").to_pylist() == [want], (backend, pred)
        # COUNT(k) counts only non-null values; the device declines (host
        # fallback) rather than counting -1 codes
        out = ctx.sql("select count(k) as c from t").collect()
        assert out.column("c").to_pylist() == [4], backend
    # EVERY predicate query must have taken the device path — a silent
    # host fallback (cache value False) would also produce correct counts
    declined = [k for k, v in kernels._stage_cache.items()
                if v is False and "COUNT(k@0)" not in k]
    assert not declined, f"silent host fallback for: {declined[:2]}"
    ran = [
        s for s in kernels._stage_cache.values()
        if isinstance(s, FusedAggregateStage) and s._device_cache
    ]
    assert len(ran) >= len(cases), (len(ran), len(cases))

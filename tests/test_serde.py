"""Serde roundtrip tests — the reference's largest test surface
(rust/core/src/serde/logical_plan/mod.rs roundtrip_test! macro cases and
physical_plan/mod.rs). Equality by display-string comparison, like the
reference's format!-based assertion (mod.rs:43-46)."""

import datetime

import pyarrow as pa
import pytest

from ballista_tpu.datasource import MemoryTableSource
from ballista_tpu.logical import expr as lx
from ballista_tpu.logical import plan as lp
from ballista_tpu.logical.builder import LogicalPlanBuilder
from ballista_tpu.serde.logical import (
    expr_from_proto,
    expr_to_proto,
    plan_from_proto,
    plan_to_proto,
)
from ballista_tpu.logical.expr import col, functions as F, lit

SCHEMA = pa.schema(
    [
        pa.field("a", pa.int64()),
        pa.field("b", pa.float64()),
        pa.field("c", pa.string()),
        pa.field("d", pa.date32()),
    ]
)


def roundtrip_expr(e: lx.Expr):
    msg = expr_to_proto(e)
    data = msg.SerializeToString()
    from ballista_tpu.proto import ballista_pb2 as pb

    decoded = pb.LogicalExprNode()
    decoded.ParseFromString(data)
    e2 = expr_from_proto(decoded)
    assert str(e2) == str(e), f"{e2} != {e}"
    return e2


EXPR_CASES = [
    col("a"),
    lx.Column("x", "t"),
    lit(42),
    lit(3.5),
    lit("hello"),
    lit(True),
    lit(None),
    lx.Literal(datetime.date(1994, 1, 1), pa.date32()),
    lx.Literal(datetime.datetime(1994, 1, 1, 12, 30), pa.timestamp("us")),
    col("a") + lit(1),
    col("a") - lit(1),
    (col("a") * lit(2)) / col("b"),
    col("a") == lit(5),
    (col("a") > lit(1)) & (col("b") < lit(2.0)),
    (col("a") >= lit(1)) | (col("b") <= lit(2.0)),
    ~(col("a") != lit(0)),
    -col("b"),
    col("c").like("%foo%"),
    col("c").not_like("bar%"),
    lx.Like(col("c"), lit("x_%"), True, "\\"),
    col("a").is_null(),
    col("a").is_not_null(),
    col("a").between(lit(1), lit(10)),
    col("a").between(lit(1), lit(10), negated=True),
    col("c").isin(["x", "y"]),
    col("a").isin([1, 2, 3], negated=True),
    lx.Case(None, [(col("a") > lit(0), lit("pos"))], lit("neg")),
    lx.Case(col("a"), [(lit(1), lit("one")), (lit(2), lit("two"))], None),
    col("a").cast(pa.float32()),
    lx.TryCast(col("c"), pa.int64()),
    lx.ScalarFunction("sqrt", [col("b")]),
    lx.ScalarFunction("substring", [col("c"), lit(1), lit(2)]),
    lx.ScalarFunction("extract", [lit("year"), col("d")]),
    F.sum(col("a")),
    F.avg(col("b")),
    F.min(col("a")),
    F.max(col("a")),
    F.count(col("c")),
    F.count(distinct=True),
    lx.AggregateExpr("count", col("c"), distinct=True),
    col("a").sort(ascending=False, nulls_first=True),
    lx.Wildcard(),
]


@pytest.mark.parametrize("e", EXPR_CASES, ids=lambda e: str(e)[:40])
def test_expr_roundtrip(e):
    roundtrip_expr(e)


def _scan() -> LogicalPlanBuilder:
    table = pa.table(
        {
            "a": pa.array([1, 2, 3], type=pa.int64()),
            "b": pa.array([1.0, 2.0, 3.0]),
            "c": pa.array(["x", "y", "z"]),
            "d": pa.array([datetime.date(2020, 1, 1)] * 3),
        }
    )
    return LogicalPlanBuilder.scan("t", MemoryTableSource.from_table(table, 2))


def roundtrip_plan(plan: lp.LogicalPlan):
    msg = plan_to_proto(plan)
    decoded_bytes = msg.SerializeToString()
    from ballista_tpu.proto import ballista_pb2 as pb

    decoded = pb.LogicalPlanNode()
    decoded.ParseFromString(decoded_bytes)
    p2 = plan_from_proto(decoded)
    assert str(p2) == str(plan)
    assert p2.schema().equals(plan.schema())
    return p2


def test_roundtrip_scan_projection_filter():
    plan = (
        _scan()
        .filter(col("a") > lit(1))
        .project([col("a"), (col("b") * lit(2.0)).alias("b2")])
        .build()
    )
    roundtrip_plan(plan)


def test_roundtrip_aggregate_sort_limit():
    plan = (
        _scan()
        .aggregate([col("c")], [F.sum(col("a")).alias("s"), F.avg(col("b")).alias("m")])
        .sort([col("s").sort(ascending=False)])
        .limit(5)
        .build()
    )
    roundtrip_plan(plan)


def test_roundtrip_joins():
    left = _scan().alias("l")
    right = _scan().alias("r")
    plan = left.join(
        right,
        [(lx.Column("a", "l"), lx.Column("a", "r"))],
        lp.JoinType.INNER,
    ).build()
    roundtrip_plan(plan)

    semi = left.join(
        _scan().alias("r2"),
        [(lx.Column("a", "l"), lx.Column("a", "r2"))],
        lp.JoinType.SEMI,
        filter=lx.Column("b", "l") > lit(1.0),
    ).build()
    roundtrip_plan(semi)


def test_roundtrip_repartition_union_distinct():
    plan = (
        _scan()
        .repartition_hash([col("a")], 4)
        .distinct()
        .build()
    )
    roundtrip_plan(plan)
    u = _scan().union([_scan()]).build()
    roundtrip_plan(u)


def test_roundtrip_empty_and_ddl():
    roundtrip_plan(lp.EmptyRelation(True, pa.schema([pa.field("x", pa.int32())])))
    roundtrip_plan(
        lp.CreateExternalTable("t2", "/tmp/x", "csv", True, SCHEMA)
    )


def test_roundtrip_memory_scan_preserves_data():
    plan = _scan().build()
    p2 = roundtrip_plan(plan)
    # memory partitions carry actual rows over the wire (IPC)
    assert p2.source.num_partitions() == 2
    total = sum(b.num_rows for part in p2.source.partitions for b in part)
    assert total == 3


class TestPhysicalRoundtrip:
    def _physical(self, df_builder):
        from ballista_tpu.engine import ExecutionContext

        ctx = ExecutionContext()
        return ctx.create_physical_plan(df_builder.build())

    def roundtrip(self, plan):
        from ballista_tpu.proto import ballista_pb2 as pb
        from ballista_tpu.serde.physical import (
            phys_plan_from_proto,
            phys_plan_to_proto,
        )

        msg = phys_plan_to_proto(plan)
        decoded = pb.PhysicalPlanNode()
        decoded.ParseFromString(msg.SerializeToString())
        p2 = phys_plan_from_proto(decoded)
        if "mode=final" not in str(plan):
            # FINAL aggregates deserialize with positional placeholder
            # expressions (they never re-evaluate inputs), so display
            # equality is only guaranteed elsewhere
            assert str(p2) == str(plan)
        assert p2.schema().equals(plan.schema())
        return p2

    def test_filter_project(self):
        plan = self._physical(
            _scan().filter(col("a") > lit(1)).project([col("a"), col("c")])
        )
        self.roundtrip(plan)

    def test_aggregate_two_phase(self):
        plan = self._physical(
            _scan().aggregate([col("c")], [F.sum(col("a")).alias("s"),
                                           F.avg(col("b")).alias("m"),
                                           F.count(col("a")).alias("n")])
        )
        p2 = self.roundtrip(plan)
        # execution equivalence after roundtrip
        from ballista_tpu.physical.plan import TaskContext, collect_all

        t1 = collect_all(plan, TaskContext()).sort_by("c")
        t2 = collect_all(p2, TaskContext()).sort_by("c")
        assert t1.equals(t2)

    def test_join_sort_limit(self):
        left = _scan().alias("l")
        right = _scan().alias("r")
        df = left.join(right, [(lx.Column("a", "l"), lx.Column("a", "r"))]).sort(
            [lx.Column("a", "l").sort()]
        ).limit(2)
        plan = self._physical(df)
        self.roundtrip(plan)

    def test_shuffle_nodes(self):
        from ballista_tpu.distributed.stages import (
            ShuffleLocation,
            ShuffleReaderExec,
            ShuffleWriterExec,
            UnresolvedShuffleExec,
        )
        from ballista_tpu.physical.plan import Partitioning

        inner = self._physical(_scan())
        w = ShuffleWriterExec(
            "job1", 3, inner, Partitioning.hash([__import__("ballista_tpu.physical.expr", fromlist=["ColumnExpr"]).ColumnExpr("a", 0)], 4)
        )
        self.roundtrip(w)
        r = ShuffleReaderExec(
            [ShuffleLocation("e1", "h", 50051, "/tmp/x")],
            SCHEMA,
            4,
        )
        self.roundtrip(r)
        u = UnresolvedShuffleExec(7, SCHEMA, 2)
        self.roundtrip(u)

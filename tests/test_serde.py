"""Serde roundtrip tests — the reference's largest test surface
(rust/core/src/serde/logical_plan/mod.rs roundtrip_test! macro cases and
physical_plan/mod.rs). Equality by display-string comparison, like the
reference's format!-based assertion (mod.rs:43-46)."""

import datetime

import pyarrow as pa
import pytest

from ballista_tpu.datasource import MemoryTableSource
from ballista_tpu.logical import expr as lx
from ballista_tpu.logical import plan as lp
from ballista_tpu.logical.builder import LogicalPlanBuilder
from ballista_tpu.serde.logical import (
    expr_from_proto,
    expr_to_proto,
    plan_from_proto,
    plan_to_proto,
)
from ballista_tpu.logical.expr import col, functions as F, lit

SCHEMA = pa.schema(
    [
        pa.field("a", pa.int64()),
        pa.field("b", pa.float64()),
        pa.field("c", pa.string()),
        pa.field("d", pa.date32()),
    ]
)


def roundtrip_expr(e: lx.Expr):
    msg = expr_to_proto(e)
    data = msg.SerializeToString()
    from ballista_tpu.proto import ballista_pb2 as pb

    decoded = pb.LogicalExprNode()
    decoded.ParseFromString(data)
    e2 = expr_from_proto(decoded)
    assert str(e2) == str(e), f"{e2} != {e}"
    return e2


EXPR_CASES = [
    col("a"),
    lx.Column("x", "t"),
    lit(42),
    lit(3.5),
    lit("hello"),
    lit(True),
    lit(None),
    lx.Literal(datetime.date(1994, 1, 1), pa.date32()),
    lx.Literal(datetime.datetime(1994, 1, 1, 12, 30), pa.timestamp("us")),
    col("a") + lit(1),
    col("a") - lit(1),
    (col("a") * lit(2)) / col("b"),
    col("a") == lit(5),
    (col("a") > lit(1)) & (col("b") < lit(2.0)),
    (col("a") >= lit(1)) | (col("b") <= lit(2.0)),
    ~(col("a") != lit(0)),
    -col("b"),
    col("c").like("%foo%"),
    col("c").not_like("bar%"),
    lx.Like(col("c"), lit("x_%"), True, "\\"),
    col("a").is_null(),
    col("a").is_not_null(),
    col("a").between(lit(1), lit(10)),
    col("a").between(lit(1), lit(10), negated=True),
    col("c").isin(["x", "y"]),
    col("a").isin([1, 2, 3], negated=True),
    lx.Case(None, [(col("a") > lit(0), lit("pos"))], lit("neg")),
    lx.Case(col("a"), [(lit(1), lit("one")), (lit(2), lit("two"))], None),
    col("a").cast(pa.float32()),
    lx.TryCast(col("c"), pa.int64()),
    lx.ScalarFunction("sqrt", [col("b")]),
    lx.ScalarFunction("substring", [col("c"), lit(1), lit(2)]),
    lx.ScalarFunction("extract", [lit("year"), col("d")]),
    F.sum(col("a")),
    F.avg(col("b")),
    F.min(col("a")),
    F.max(col("a")),
    F.count(col("c")),
    F.count(distinct=True),
    lx.AggregateExpr("count", col("c"), distinct=True),
    col("a").sort(ascending=False, nulls_first=True),
    lx.Wildcard(),
]


@pytest.mark.parametrize("e", EXPR_CASES, ids=lambda e: str(e)[:40])
def test_expr_roundtrip(e):
    roundtrip_expr(e)


# scalar edge values, mirroring the reference's ScalarValue matrix
# (rust/core/src/serde/logical_plan/mod.rs:58-920 covers every variant with
# boundary values)
SCALAR_EDGE_CASES = [
    lit(0),
    lit(-1),
    lit(2**63 - 1),
    lit(-(2**63)),
    lit(2**31),          # beyond int32
    lit(0.0),
    lit(-0.0),
    lit(float("inf")),
    lit(float("-inf")),
    lit(float("nan")),
    lit(5e-324),         # smallest subnormal double
    lit(1.7976931348623157e308),
    lit(""),
    lit("unicode ✓ ☃ 日本語"),
    lit("embedded 'quotes' and \"doubles\""),
    lit("newline\nand\ttab"),
    lx.Literal(datetime.date(1970, 1, 1), pa.date32()),
    lx.Literal(datetime.date(1904, 2, 29), pa.date32()),   # pre-epoch leap day
    lx.Literal(datetime.date(2262, 4, 11), pa.date32()),
    lx.Literal(datetime.datetime(1969, 12, 31, 23, 59, 59, 999999),
               pa.timestamp("us")),  # negative epoch micros
    lx.Literal(False, pa.bool_()),
]


@pytest.mark.parametrize("e", SCALAR_EDGE_CASES, ids=lambda e: repr(str(e))[:48])
def test_scalar_edge_roundtrip(e):
    roundtrip_expr(e)


def test_scalar_edge_values_survive_exactly():
    """Beyond display equality: the decoded literal VALUE must be bit-equal
    (display strings can hide float rounding)."""
    import math

    for e in SCALAR_EDGE_CASES:
        msg = expr_to_proto(e)
        from ballista_tpu.proto import ballista_pb2 as pb

        decoded = pb.LogicalExprNode()
        decoded.ParseFromString(msg.SerializeToString())
        e2 = expr_from_proto(decoded)
        v1, v2 = e.value, e2.value
        if isinstance(v1, float) and math.isnan(v1):
            assert math.isnan(v2)
        else:
            assert v1 == v2 and type(v1) is type(v2), (v1, v2)
            if isinstance(v1, float):
                assert math.copysign(1, v1) == math.copysign(1, v2)


def _scan() -> LogicalPlanBuilder:
    table = pa.table(
        {
            "a": pa.array([1, 2, 3], type=pa.int64()),
            "b": pa.array([1.0, 2.0, 3.0]),
            "c": pa.array(["x", "y", "z"]),
            "d": pa.array([datetime.date(2020, 1, 1)] * 3),
        }
    )
    return LogicalPlanBuilder.scan("t", MemoryTableSource.from_table(table, 2))


def roundtrip_plan(plan: lp.LogicalPlan):
    msg = plan_to_proto(plan)
    decoded_bytes = msg.SerializeToString()
    from ballista_tpu.proto import ballista_pb2 as pb

    decoded = pb.LogicalPlanNode()
    decoded.ParseFromString(decoded_bytes)
    p2 = plan_from_proto(decoded)
    assert str(p2) == str(plan)
    assert p2.schema().equals(plan.schema())
    return p2


def test_roundtrip_scan_projection_filter():
    plan = (
        _scan()
        .filter(col("a") > lit(1))
        .project([col("a"), (col("b") * lit(2.0)).alias("b2")])
        .build()
    )
    roundtrip_plan(plan)


def test_roundtrip_aggregate_sort_limit():
    plan = (
        _scan()
        .aggregate([col("c")], [F.sum(col("a")).alias("s"), F.avg(col("b")).alias("m")])
        .sort([col("s").sort(ascending=False)])
        .limit(5)
        .build()
    )
    roundtrip_plan(plan)


def test_roundtrip_joins():
    left = _scan().alias("l")
    right = _scan().alias("r")
    plan = left.join(
        right,
        [(lx.Column("a", "l"), lx.Column("a", "r"))],
        lp.JoinType.INNER,
    ).build()
    roundtrip_plan(plan)

    semi = left.join(
        _scan().alias("r2"),
        [(lx.Column("a", "l"), lx.Column("a", "r2"))],
        lp.JoinType.SEMI,
        filter=lx.Column("b", "l") > lit(1.0),
    ).build()
    roundtrip_plan(semi)


def test_roundtrip_repartition_union_distinct():
    plan = (
        _scan()
        .repartition_hash([col("a")], 4)
        .distinct()
        .build()
    )
    roundtrip_plan(plan)
    u = _scan().union([_scan()]).build()
    roundtrip_plan(u)


def test_roundtrip_empty_and_ddl():
    roundtrip_plan(lp.EmptyRelation(True, pa.schema([pa.field("x", pa.int32())])))
    roundtrip_plan(
        lp.CreateExternalTable("t2", "/tmp/x", "csv", True, SCHEMA)
    )


def test_roundtrip_memory_scan_preserves_data():
    plan = _scan().build()
    p2 = roundtrip_plan(plan)
    # memory partitions carry actual rows over the wire (IPC)
    assert p2.source.num_partitions() == 2
    total = sum(b.num_rows for part in p2.source.partitions for b in part)
    assert total == 3


class TestPhysicalRoundtrip:
    def _physical(self, df_builder):
        from ballista_tpu.engine import ExecutionContext

        ctx = ExecutionContext()
        return ctx.create_physical_plan(df_builder.build())

    def roundtrip(self, plan):
        from ballista_tpu.proto import ballista_pb2 as pb
        from ballista_tpu.serde.physical import (
            phys_plan_from_proto,
            phys_plan_to_proto,
        )

        msg = phys_plan_to_proto(plan)
        decoded = pb.PhysicalPlanNode()
        decoded.ParseFromString(msg.SerializeToString())
        p2 = phys_plan_from_proto(decoded)
        if "mode=final" not in str(plan):
            # FINAL aggregates deserialize with positional placeholder
            # expressions (they never re-evaluate inputs), so display
            # equality is only guaranteed elsewhere
            assert str(p2) == str(plan)
        assert p2.schema().equals(plan.schema())
        return p2

    def test_filter_project(self):
        plan = self._physical(
            _scan().filter(col("a") > lit(1)).project([col("a"), col("c")])
        )
        self.roundtrip(plan)

    def test_aggregate_two_phase(self):
        plan = self._physical(
            _scan().aggregate([col("c")], [F.sum(col("a")).alias("s"),
                                           F.avg(col("b")).alias("m"),
                                           F.count(col("a")).alias("n")])
        )
        p2 = self.roundtrip(plan)
        # execution equivalence after roundtrip
        from ballista_tpu.physical.plan import TaskContext, collect_all

        t1 = collect_all(plan, TaskContext()).sort_by("c")
        t2 = collect_all(p2, TaskContext()).sort_by("c")
        assert t1.equals(t2)

    def test_join_sort_limit(self):
        left = _scan().alias("l")
        right = _scan().alias("r")
        df = left.join(right, [(lx.Column("a", "l"), lx.Column("a", "r"))]).sort(
            [lx.Column("a", "l").sort()]
        ).limit(2)
        plan = self._physical(df)
        self.roundtrip(plan)

    def test_shuffle_nodes(self):
        from ballista_tpu.distributed.stages import (
            ShuffleLocation,
            ShuffleReaderExec,
            ShuffleWriterExec,
            UnresolvedShuffleExec,
        )
        from ballista_tpu.physical.plan import Partitioning

        inner = self._physical(_scan())
        w = ShuffleWriterExec(
            "job1", 3, inner, Partitioning.hash([__import__("ballista_tpu.physical.expr", fromlist=["ColumnExpr"]).ColumnExpr("a", 0)], 4)
        )
        self.roundtrip(w)
        r = ShuffleReaderExec(
            [ShuffleLocation("e1", "h", 50051, "/tmp/x",
                             stage_id=3, map_partition=1)],
            SCHEMA,
            4,
        )
        r2 = self.roundtrip(r)
        # the producing map task's lineage survives the wire: fetch_failed
        # reports name it so the scheduler can recompute the lost partition
        loc = r2.locations[0]
        assert (loc.stage_id, loc.map_partition) == (3, 1)
        assert (loc.executor_id, loc.host, loc.port) == ("e1", "h", 50051)
        u = UnresolvedShuffleExec(7, SCHEMA, 2)
        self.roundtrip(u)

    def test_cross_join_union_coalesce_empty(self):
        """Remaining node variants (ref from_proto.rs:58-345 covers all 15)."""
        from ballista_tpu.physical.basic import (
            CoalesceBatchesExec,
            EmptyExec,
            LocalLimitExec,
            MergeExec,
        )
        from ballista_tpu.physical.join import CrossJoinExec
        from ballista_tpu.physical.union import UnionExec

        a = self._physical(_scan())
        b = self._physical(_scan())
        self.roundtrip(CrossJoinExec(a, b))
        self.roundtrip(UnionExec([a, b]))
        self.roundtrip(CoalesceBatchesExec(a, 4096))
        self.roundtrip(MergeExec(a))
        self.roundtrip(LocalLimitExec(a, 7))
        self.roundtrip(EmptyExec(False, SCHEMA))
        self.roundtrip(EmptyExec(True, SCHEMA))

    def test_repartition_variants(self):
        from ballista_tpu.physical.expr import ColumnExpr
        from ballista_tpu.physical.plan import Partitioning
        from ballista_tpu.physical.repartition import RepartitionExec

        a = self._physical(_scan())
        self.roundtrip(
            RepartitionExec(a, Partitioning.hash([ColumnExpr("a", 0)], 8))
        )
        self.roundtrip(RepartitionExec(a, Partitioning.round_robin(3)))

    def test_window_exec(self):
        from ballista_tpu.physical.expr import ColumnExpr
        from ballista_tpu.physical.window import WindowExec, WindowFuncDesc

        a = self._physical(_scan())
        w = WindowExec(
            a,
            [
                WindowFuncDesc(
                    "row_number", None, [ColumnExpr("c", 2)],
                    [(ColumnExpr("a", 0), True)], "rn", pa.int64(),
                ),
                WindowFuncDesc(
                    "sum", ColumnExpr("b", 1), [], [(ColumnExpr("a", 0), False)],
                    "running", pa.float64(),
                ),
            ],
        )
        self.roundtrip(w)

    def test_spmd_aggregate_node(self):
        from ballista_tpu.config import BallistaConfig
        from ballista_tpu.distributed.planner import DistributedPlanner
        from ballista_tpu.engine import ExecutionContext
        from ballista_tpu.parallel.spmd_stage import SpmdAggregateExec

        ctx = ExecutionContext()
        ctx.register_record_batches(
            "t",
            pa.table({"k": pa.array([1, 2, 1]), "v": pa.array([1.0, 2.0, 3.0])}),
            n_partitions=2,
        )
        df = ctx.table("t").aggregate([col("k")], [F.sum(col("v")).alias("s")])
        phys = ctx.create_physical_plan(df.logical_plan())
        cfg = BallistaConfig({"ballista.tpu.spmd_stages": "true"})
        stages = DistributedPlanner(cfg).plan_query_stages("j", phys)

        def find(n):
            if isinstance(n, SpmdAggregateExec):
                return n
            for c in n.children():
                r = find(c)
                if r is not None:
                    return r
            return None

        spmd = next((find(s) for s in stages if find(s) is not None), None)
        assert spmd is not None
        self.roundtrip(spmd)

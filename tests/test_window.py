"""Window function tests: SQL surface vs pandas oracle, serde roundtrip,
distributed execution."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from ballista_tpu.engine import ExecutionContext


@pytest.fixture
def ctx():
    c = ExecutionContext()
    rng = np.random.default_rng(5)
    t = pa.table(
        {
            "g": pa.array(rng.choice(["a", "b", "c"], 50).tolist()),
            "v": pa.array(np.round(rng.uniform(0, 100, 50), 2)),
            "k": pa.array(rng.integers(0, 10, 50)),
        }
    )
    c.register_record_batches("t", t, n_partitions=2)
    return c, t.to_pandas()


def test_row_number_and_ranks_vs_pandas(ctx):
    c, df = ctx
    out = c.sql(
        """
        select g, v,
               row_number() over (partition by g order by v desc) as rn,
               rank() over (partition by g order by k) as rk,
               dense_rank() over (partition by g order by k) as dr
        from t order by g, v desc
        """
    ).collect().to_pandas()
    want = df.sort_values(["g", "v"], ascending=[True, False]).reset_index(drop=True)
    want["rn"] = df.groupby("g").v.rank(method="first", ascending=False).astype(int)[
        want.index if False else df.sort_values(["g", "v"], ascending=[True, False]).index
    ].to_numpy()
    # recompute oracle directly on the sorted frame
    g = df.copy()
    g["rn"] = g.sort_values("v", ascending=False).groupby("g").cumcount() + 1
    g["rk"] = g.groupby("g").k.rank(method="min").astype(int)
    g["dr"] = g.groupby("g").k.rank(method="dense").astype(int)
    g = g.sort_values(["g", "v"], ascending=[True, False]).reset_index(drop=True)
    assert out.g.tolist() == g.g.tolist()
    np.testing.assert_allclose(out.v, g.v)
    assert out.rn.tolist() == g.rn.tolist()
    assert out.rk.tolist() == g.rk.tolist()
    assert out.dr.tolist() == g.dr.tolist()


def test_window_aggregates_vs_pandas(ctx):
    c, df = ctx
    out = c.sql(
        """
        select g, v,
               sum(v) over (partition by g) as total,
               avg(v) over (partition by g) as mean,
               min(v) over (partition by g) as lo,
               max(v) over (partition by g) as hi,
               count(v) over (partition by g) as n
        from t order by g, v
        """
    ).collect().to_pandas()
    g = df.copy()
    for fn, name in [("sum", "total"), ("mean", "mean"), ("min", "lo"),
                     ("max", "hi"), ("count", "n")]:
        g[name] = g.groupby("g").v.transform(fn)
    g = g.sort_values(["g", "v"]).reset_index(drop=True)
    np.testing.assert_allclose(out.total, g.total)
    np.testing.assert_allclose(out["mean"], g["mean"])
    np.testing.assert_allclose(out.lo, g.lo)
    np.testing.assert_allclose(out.hi, g.hi)
    assert out.n.tolist() == g.n.astype(int).tolist()


def test_window_no_partition(ctx):
    c, df = ctx
    out = c.sql(
        "select v, row_number() over (order by v) as rn, sum(v) over () as total "
        "from t order by v limit 5"
    ).collect().to_pandas()
    assert out.rn.tolist() == [1, 2, 3, 4, 5]
    np.testing.assert_allclose(out.total, df.v.sum())


def test_window_expr_serde_roundtrip():
    from ballista_tpu.logical import expr as lx
    from ballista_tpu.logical.expr import col
    from ballista_tpu.serde.logical import expr_from_proto, expr_to_proto

    e = lx.WindowExpr(
        "sum", col("v"), [col("g")], [lx.SortExpr(col("k"), False, False)]
    )
    msg = expr_to_proto(e)
    e2 = expr_from_proto(type(msg).FromString(msg.SerializeToString()))
    assert str(e2) == str(e)


def test_window_distributed(sales_table):
    from ballista_tpu.client import BallistaContext
    from ballista_tpu.executor.runtime import StandaloneCluster

    cluster = StandaloneCluster(n_executors=2)
    try:
        ctx = BallistaContext(*cluster.scheduler_addr)
        ctx.register_record_batches("sales", sales_table, n_partitions=3)
        out = ctx.sql(
            "select region, amount, "
            "rank() over (partition by region order by amount desc) as r "
            "from sales order by region, r"
        ).collect().to_pandas()
        east = out[out.region == "east"]
        assert east.amount.tolist() == [55.0, 30.0, 25.0, 10.0]
        assert east.r.tolist() == [1, 2, 3, 4]
        ctx.close()
    finally:
        cluster.shutdown()


def test_running_default_frame_with_order_by(ctx):
    """Aggregate + ORDER BY and no frame clause = SQL's running default
    (UNBOUNDED PRECEDING .. CURRENT ROW)."""
    c, df = ctx
    out = c.sql(
        "select g, v, sum(v) over (partition by g order by v) as rs from t "
        "order by g, v"
    ).collect().to_pandas()
    exp = (
        df.sort_values(["g", "v"])
        .groupby("g")["v"].cumsum()
        .reset_index(drop=True)
    )
    np.testing.assert_allclose(out["rs"].to_numpy(), exp.to_numpy(), rtol=1e-9)


def test_rows_between_moving_window(ctx):
    c, df = ctx
    out = c.sql(
        "select g, v, "
        "sum(v) over (partition by g order by v rows between 2 preceding and current row) as ms, "
        "avg(v) over (partition by g order by v rows between 1 preceding and 1 following) as ma, "
        "min(v) over (partition by g order by v rows between 2 preceding and current row) as mn, "
        "max(v) over (partition by g order by v rows between 1 preceding and 1 following) as mx "
        "from t order by g, v"
    ).collect().to_pandas()
    s = df.sort_values(["g", "v"]).reset_index(drop=True)
    gb = s.groupby("g")["v"]
    np.testing.assert_allclose(
        out["ms"].to_numpy(),
        gb.rolling(3, min_periods=1).sum().reset_index(drop=True).to_numpy(),
        rtol=1e-9,
    )
    np.testing.assert_allclose(
        out["ma"].to_numpy(),
        gb.rolling(3, min_periods=1, center=True).mean().reset_index(drop=True).to_numpy(),
        rtol=1e-9,
    )
    np.testing.assert_allclose(
        out["mn"].to_numpy(),
        gb.rolling(3, min_periods=1).min().reset_index(drop=True).to_numpy(),
        rtol=1e-9,
    )
    np.testing.assert_allclose(
        out["mx"].to_numpy(),
        gb.rolling(3, min_periods=1, center=True).max().reset_index(drop=True).to_numpy(),
        rtol=1e-9,
    )


def test_rows_unbounded_following(ctx):
    """Suffix frame: CURRENT ROW .. UNBOUNDED FOLLOWING."""
    c, df = ctx
    out = c.sql(
        "select g, v, sum(v) over (partition by g order by v "
        "rows between current row and unbounded following) as tail from t "
        "order by g, v"
    ).collect().to_pandas()
    s = df.sort_values(["g", "v"]).reset_index(drop=True)
    exp = (
        s.iloc[::-1].groupby("g")["v"].cumsum().iloc[::-1].reset_index(drop=True)
    )
    np.testing.assert_allclose(out["tail"].to_numpy(), exp.to_numpy(), rtol=1e-9)


def test_frame_serde_roundtrip(ctx):
    from ballista_tpu.serde.physical import phys_plan_from_proto, phys_plan_to_proto
    from ballista_tpu.physical.window import WindowExec

    c, _ = ctx
    df = c.sql(
        "select sum(v) over (order by v rows between 3 preceding and 1 following) as s from t"
    )
    phys = c.create_physical_plan(df.logical_plan())
    back = phys_plan_from_proto(phys_plan_to_proto(phys))

    def find(n):
        if isinstance(n, WindowExec):
            return n
        for ch in n.children():
            r = find(ch)
            if r is not None:
                return r
        return None

    w = find(back)
    assert w is not None and w.funcs[0].frame == ("rows", -3, 1)


def test_frame_errors(ctx):
    c, _ = ctx
    from ballista_tpu.errors import BallistaError

    with pytest.raises(BallistaError):
        c.sql("select row_number() over (order by v rows between 1 preceding and current row) as s from t")


def test_running_default_includes_peers():
    """SQL's default frame with ORDER BY is RANGE-based: rows tied on the
    order key are peers and all see the same running value."""
    c = ExecutionContext()
    t = pa.table({"k": pa.array([1, 1, 2]), "v": pa.array([10.0, 20.0, 5.0])})
    c.register_record_batches("t2", t)
    out = c.sql("select k, sum(v) over (order by k) as s from t2 order by k").collect()
    assert out.column("s").to_pylist() == [30.0, 30.0, 35.0]


def test_frame_survives_group_by_rewrite():
    """Window frames inside a GROUP BY query must survive the planner's
    expression rewrite (review regression: frame silently dropped)."""
    c = ExecutionContext()
    t = pa.table({"g": pa.array(["a"] * 4), "k": pa.array([1, 2, 3, 4])})
    c.register_record_batches("t3", t)
    out = c.sql(
        "select g, k, sum(k) over (partition by g order by k "
        "rows between 1 preceding and current row) as ms "
        "from t3 group by g, k order by k"
    ).collect()
    assert out.column("ms").to_pylist() == [1, 3, 5, 7]


def test_huge_frame_offsets_clamped():
    """Giant ROWS offsets must cost O(partition), not O(offset)."""
    c = ExecutionContext()
    t = pa.table({"v": pa.array([3.0, 1.0, 2.0])})
    c.register_record_batches("t4", t)
    out = c.sql(
        "select v, min(v) over (order by v rows between 1000000000 preceding "
        "and current row) as m from t4 order by v"
    ).collect()
    assert out.column("m").to_pylist() == [1.0, 1.0, 1.0]


def test_range_frame_numeric_offsets(ctx):
    """RANGE frames window by order-key VALUE, peers included."""
    c, df = ctx
    out = c.sql(
        "select g, k, sum(k) over (partition by g order by k "
        "range between 2 preceding and current row) as rs, "
        "min(k) over (partition by g order by k "
        "range between 1 preceding and 1 following) as mn "
        "from t order by g, k"
    ).collect().to_pandas()
    s = df.sort_values(["g", "k"]).reset_index(drop=True)

    def oracle_rs(grp):
        return [grp[(grp >= kk - 2) & (grp <= kk)].sum() for kk in grp]

    def oracle_mn(grp):
        return [grp[(grp >= kk - 1) & (grp <= kk + 1)].min() for kk in grp]

    exp_rs = s.groupby("g")["k"].transform(lambda x: pd.Series(oracle_rs(x), index=x.index))
    exp_mn = s.groupby("g")["k"].transform(lambda x: pd.Series(oracle_mn(x), index=x.index))
    np.testing.assert_allclose(out["rs"].to_numpy(), exp_rs.to_numpy())
    np.testing.assert_allclose(out["mn"].to_numpy(), exp_mn.to_numpy())


def test_range_frame_desc_ordering(ctx):
    """PRECEDING follows the ordering direction under DESC."""
    c, df = ctx
    out = c.sql(
        "select k, sum(k) over (order by k desc range between 1 preceding "
        "and current row) as rs from t order by k desc"
    ).collect().to_pandas()
    s = df.sort_values("k", ascending=False).reset_index(drop=True)
    exp = [df["k"][(df["k"] <= kk + 1) & (df["k"] >= kk)].sum() for kk in s["k"]]
    np.testing.assert_allclose(out["rs"].to_numpy(), np.array(exp))


def test_range_frame_requires_one_order_key(ctx):
    c, _ = ctx
    from ballista_tpu.errors import BallistaError

    with pytest.raises(BallistaError):
        c.sql("select sum(v) over (order by g, k range between 1 preceding "
              "and current row) as s from t")


def test_short_partition_same_side_minmax_frame():
    """A same-side min/max ROWS frame wider than the partition yields NULLs
    (empty frames), not a crash (ADVICE r2: negative sliding-window width)."""
    c = ExecutionContext()
    t = pa.table({"v": pa.array([3.0, 1.0, 2.0])})
    c.register_record_batches("t5", t)
    out = c.sql(
        "select v, min(v) over (order by v rows between 5 following "
        "and 10 following) as mf, "
        "max(v) over (order by v rows between 10 preceding "
        "and 5 preceding) as mp from t5 order by v"
    ).collect()
    assert out.column("mf").to_pylist() == [None, None, None]
    assert out.column("mp").to_pylist() == [None, None, None]
    # partially-overlapping same-side frame still works
    out = c.sql(
        "select v, min(v) over (order by v rows between 1 following "
        "and 10 following) as m from t5 order by v"
    ).collect()
    assert out.column("m").to_pylist() == [2.0, 3.0, None]


def test_range_frame_null_order_keys():
    """NULL order keys are one trailing peer group (standard semantics):
    offset bounds resolve to the peer run, UNBOUNDED keeps the edge."""
    c = ExecutionContext()
    t = pa.table(
        {
            "k": pa.array([1.0, 2.0, None, 4.0, None]),
            "v": pa.array([10.0, 20.0, 30.0, 40.0, 50.0]),
        }
    )
    c.register_record_batches("t6", t)
    out = c.sql(
        "select k, v, sum(v) over (order by k range between 1 preceding "
        "and current row) as rs, "
        "sum(v) over (order by k range between unbounded preceding "
        "and current row) as run from t6 order by k nulls last, v"
    ).collect()
    # sorted rows: k=1(v=10), k=2(v=20), k=4(v=40), NULL(v=30), NULL(v=50)
    # rs: offset frame -> nulls see only the null peer group (30+50)
    assert out.column("rs").to_pylist() == [10.0, 30.0, 40.0, 80.0, 80.0]
    # running default (unbounded preceding .. current row incl peers):
    # nulls include everything before plus their peer run
    assert out.column("run").to_pylist() == [10.0, 30.0, 70.0, 150.0, 150.0]

"""Window function tests: SQL surface vs pandas oracle, serde roundtrip,
distributed execution."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from ballista_tpu.engine import ExecutionContext


@pytest.fixture
def ctx():
    c = ExecutionContext()
    rng = np.random.default_rng(5)
    t = pa.table(
        {
            "g": pa.array(rng.choice(["a", "b", "c"], 50).tolist()),
            "v": pa.array(np.round(rng.uniform(0, 100, 50), 2)),
            "k": pa.array(rng.integers(0, 10, 50)),
        }
    )
    c.register_record_batches("t", t, n_partitions=2)
    return c, t.to_pandas()


def test_row_number_and_ranks_vs_pandas(ctx):
    c, df = ctx
    out = c.sql(
        """
        select g, v,
               row_number() over (partition by g order by v desc) as rn,
               rank() over (partition by g order by k) as rk,
               dense_rank() over (partition by g order by k) as dr
        from t order by g, v desc
        """
    ).collect().to_pandas()
    want = df.sort_values(["g", "v"], ascending=[True, False]).reset_index(drop=True)
    want["rn"] = df.groupby("g").v.rank(method="first", ascending=False).astype(int)[
        want.index if False else df.sort_values(["g", "v"], ascending=[True, False]).index
    ].to_numpy()
    # recompute oracle directly on the sorted frame
    g = df.copy()
    g["rn"] = g.sort_values("v", ascending=False).groupby("g").cumcount() + 1
    g["rk"] = g.groupby("g").k.rank(method="min").astype(int)
    g["dr"] = g.groupby("g").k.rank(method="dense").astype(int)
    g = g.sort_values(["g", "v"], ascending=[True, False]).reset_index(drop=True)
    assert out.g.tolist() == g.g.tolist()
    np.testing.assert_allclose(out.v, g.v)
    assert out.rn.tolist() == g.rn.tolist()
    assert out.rk.tolist() == g.rk.tolist()
    assert out.dr.tolist() == g.dr.tolist()


def test_window_aggregates_vs_pandas(ctx):
    c, df = ctx
    out = c.sql(
        """
        select g, v,
               sum(v) over (partition by g) as total,
               avg(v) over (partition by g) as mean,
               min(v) over (partition by g) as lo,
               max(v) over (partition by g) as hi,
               count(v) over (partition by g) as n
        from t order by g, v
        """
    ).collect().to_pandas()
    g = df.copy()
    for fn, name in [("sum", "total"), ("mean", "mean"), ("min", "lo"),
                     ("max", "hi"), ("count", "n")]:
        g[name] = g.groupby("g").v.transform(fn)
    g = g.sort_values(["g", "v"]).reset_index(drop=True)
    np.testing.assert_allclose(out.total, g.total)
    np.testing.assert_allclose(out["mean"], g["mean"])
    np.testing.assert_allclose(out.lo, g.lo)
    np.testing.assert_allclose(out.hi, g.hi)
    assert out.n.tolist() == g.n.astype(int).tolist()


def test_window_no_partition(ctx):
    c, df = ctx
    out = c.sql(
        "select v, row_number() over (order by v) as rn, sum(v) over () as total "
        "from t order by v limit 5"
    ).collect().to_pandas()
    assert out.rn.tolist() == [1, 2, 3, 4, 5]
    np.testing.assert_allclose(out.total, df.v.sum())


def test_window_expr_serde_roundtrip():
    from ballista_tpu.logical import expr as lx
    from ballista_tpu.logical.expr import col
    from ballista_tpu.serde.logical import expr_from_proto, expr_to_proto

    e = lx.WindowExpr(
        "sum", col("v"), [col("g")], [lx.SortExpr(col("k"), False, False)]
    )
    msg = expr_to_proto(e)
    e2 = expr_from_proto(type(msg).FromString(msg.SerializeToString()))
    assert str(e2) == str(e)


def test_window_distributed(sales_table):
    from ballista_tpu.client import BallistaContext
    from ballista_tpu.executor.runtime import StandaloneCluster

    cluster = StandaloneCluster(n_executors=2)
    try:
        ctx = BallistaContext(*cluster.scheduler_addr)
        ctx.register_record_batches("sales", sales_table, n_partitions=3)
        out = ctx.sql(
            "select region, amount, "
            "rank() over (partition by region order by amount desc) as r "
            "from sales order by region, r"
        ).collect().to_pandas()
        east = out[out.region == "east"]
        assert east.amount.tolist() == [55.0, 30.0, 25.0, 10.0]
        assert east.r.tolist() == [1, 2, 3, 4]
        ctx.close()
    finally:
        cluster.shutdown()

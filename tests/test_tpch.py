"""TPC-H correctness tests against independent pandas oracles.

The reference's integration strategy runs q1,3,5,6,10,12 and eyeballs output
(docs/integration-testing.md, rust/benchmarks/tpch/run.sh:5-8); here ALL 22
queries are asserted programmatically against the shared pandas
re-implementations in benchmarks/tpch/oracles.py on the same generated data.
"""

import pathlib

import numpy as np
import pandas as pd
import pyarrow.parquet as pq
import pytest

from ballista_tpu.engine import ExecutionContext
from benchmarks.tpch.datagen import generate, register_all
from benchmarks.tpch import oracles

QUERIES = pathlib.Path(__file__).parent.parent / "benchmarks" / "tpch" / "queries"

# queries whose single scalar output is NULL when the aggregate input is
# empty (the oracle returns NaN there)
SCALAR_QUERIES = {"q6", "q14", "q17", "q19"}


@pytest.fixture(scope="session")
def tpch_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("tpch")
    generate(str(d), sf=0.005, parts=2)
    return str(d)


@pytest.fixture(scope="session")
def tables(tpch_dir):
    names = ["lineitem", "orders", "customer", "supplier", "nation", "region",
             "part", "partsupp"]
    return {t: pq.read_table(f"{tpch_dir}/{t}").to_pandas() for t in names}


@pytest.fixture(params=["cpu", "tpu"])
def ctx(request, tpch_dir):
    # BOTH backends face the same oracles: the q2 regression (f32 device
    # MIN breaking an equality-joined subquery) passed a cpu-only suite
    from ballista_tpu.config import BallistaConfig

    global _rtol
    _rtol = _FLOAT_RTOL[request.param]
    c = ExecutionContext(
        BallistaConfig({"ballista.executor.backend": request.param})
    )
    register_all(c, tpch_dir)
    return c


def run(ctx, name):
    sql = (QUERIES / f"{name}.sql").read_text()
    return ctx.sql(sql).collect().to_pandas()


# host arithmetic is f64 (rel 1e-9); device aggregation accumulates in f32
# by design (BASELINE.md) — semantics identical, last-bits differ
_FLOAT_RTOL = {"cpu": 1e-9, "tpu": 5e-4}
_rtol = 1e-9


def assert_frames_close(got: pd.DataFrame, want: pd.DataFrame):
    assert len(got) == len(want), f"row count {len(got)} != {len(want)}"
    assert list(got.columns) == list(want.columns), (got.columns, want.columns)
    for c in want.columns:
        g, w = got[c].to_numpy(), want[c].to_numpy()
        if np.issubdtype(w.dtype, np.floating):
            np.testing.assert_allclose(
                g.astype(float), w.astype(float), rtol=_rtol, atol=_rtol
            )
        else:
            assert list(g) == list(w), f"column {c}: {g[:5]} != {w[:5]}"


def assert_scalar_close(got: pd.DataFrame, want: pd.DataFrame):
    """One-row single-value result; NaN in the oracle means SQL NULL."""
    assert list(got.columns) == list(want.columns)
    col = want.columns[0]
    w = want[col][0]
    g = got[col][0]
    if w is None or (isinstance(w, float) and np.isnan(w)):
        assert g is None or (isinstance(g, float) and np.isnan(g)), g
    else:
        assert g == pytest.approx(w, rel=_rtol)


def check(ctx, tables, name):
    got = run(ctx, name)
    want = oracles.ORACLES[name](tables)
    if name in SCALAR_QUERIES:
        assert_scalar_close(got, want)
    elif name == "q11":
        # ORDER BY value desc leaves ties unordered: compare in a total order
        got = got.sort_values(["value", "ps_partkey"],
                              ascending=[False, True]).reset_index(drop=True)
        assert_frames_close(got, want)
    else:
        assert_frames_close(got, want)


@pytest.mark.parametrize("name", [f"q{i}" for i in range(1, 23)])
def test_query_oracle(ctx, tables, name):
    check(ctx, tables, name)


def test_q18_lowered_threshold_nonempty(ctx, tables):
    """The official 300 cutoff can be empty at tiny SF; a lowered cutoff
    proves the semi-join + group-by shape end to end on real rows."""
    sql = (QUERIES / "q18.sql").read_text().replace("> 300", "> 150")
    got = ctx.sql(sql).collect().to_pandas()
    w = oracles.q18(tables, 150)
    assert len(w) > 0
    assert_frames_close(got, w)


def test_all_queries_execute(ctx):
    for i in range(1, 23):
        out = run(ctx, f"q{i}")
        assert out is not None
